"""Black-box flight recorder: the last ~N structured events, always.

Every observability layer before this one either needs a run dir
(telemetry spans — attached only when an entry point asked for one) or
answers "what is happening NOW" (the /metrics scrape). Neither answers
the incident question: *what happened in the seconds BEFORE the
breaker opened / the SLO started burning / the snapshot quarantined?*
By the time an operator scrapes, the evidence is gone.

This module is the aviation answer: a bounded, lock-guarded, in-memory
ring of the last ``LFM_FLIGHT`` structured events (default 1024) that
is ALWAYS on — no run dir required — and cheap enough to leave on
(one lock, one ``deque.append``; the ring recycles storage, so memory
is bounded by construction). Two feeds fill it:

* **telemetry instants** — :func:`note` is called by
  ``utils/telemetry.py instant()`` BEFORE its run-active gate, so every
  marker the codebase already emits (``circuit_open``/``circuit_closed``
  breaker transitions, ``zoo_swap`` publishes, ``fault_injected``
  chaos injections, ``restore_quarantine`` verdicts, ``drift_veto``,
  ``batcher_died``, fold/run stops) lands in the ring even when no
  telemetry run is attached — the black-box property;
* **explicit serve events** — the micro-batcher records the hot-path
  outcomes that deliberately have no instant (per-batch dispatches,
  sheds, deadline drops, retries) via :func:`record`.

The ring is dumped crash-safely (:func:`dump`: temp file + fsync +
rename, one JSON line per event, non-finite floats nulled) into every
incident bundle (``serve/incident.py``, DESIGN.md §21) — the captured
evidence of the seconds before a degradation.

Knob: ``LFM_FLIGHT`` — ``0`` disables (exact no-op: one cached read +
a None test per event), unset/``1`` = the 1024-event default, any
other integer ≥ 2 sets the ring capacity. Like ``LFM_FAULTS``, the env
is resolved once on first use; tests re-resolve via :func:`configure`.

Non-interference: nothing here touches a device, takes the admission
lock, or allocates beyond one small dict per event; the measured
zero-trace / zero-panel-H2D / one-sync-per-epoch contracts are re-pinned
with the recorder fully on in ``tests/test_incident.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: Default ring capacity (events) when ``LFM_FLIGHT`` is unset/``1``.
DEFAULT_CAPACITY = 1024


def flight_capacity() -> int:
    """Resolve ``LFM_FLIGHT``: 0 = off, unset/1 = the default capacity,
    N >= 2 = that capacity. Loud on garbage — a flight recorder that
    silently recorded nothing would be worse than none."""
    raw = os.environ.get("LFM_FLIGHT", "").strip()
    if raw in ("", "1"):
        return DEFAULT_CAPACITY
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"LFM_FLIGHT must be an integer (0=off, 1=default "
            f"{DEFAULT_CAPACITY}, N>=2=capacity), got {raw!r}") from None
    if n <= 0:
        return 0
    return max(2, n)


class FlightRecorder:
    """One bounded event ring. ``record`` is the O(1) hot path; every
    reader (:meth:`snapshot`, :meth:`dump`) copies under the lock and
    serializes outside it."""

    __slots__ = ("capacity", "_ring", "_lock", "_seq", "_dropped")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(2, int(capacity))
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._dropped = 0  # events pushed out of the ring (bounded-ness
        #                    made visible: total seen = seq, kept = ring)

    def record(self, kind: str, cat: str = "serve",
               **fields: Any) -> None:
        """Append one event: ``{seq, ts, kind, cat, **fields}``. O(1):
        one dict build, one lock, one deque append (which recycles the
        evicted slot — bounded memory by construction)."""
        ev = {"ts": time.time(), "kind": kind, "cat": cat}
        if fields:
            ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(ev)

    def note(self, name: str, cat: str, args: Dict[str, Any]) -> None:
        """The ``telemetry.instant`` adapter: same event shape, args
        folded in (reserved keys never clobbered — an instant arg named
        ``ts`` would otherwise corrupt the event's own timestamp)."""
        ev = {"ts": time.time(), "kind": name, "cat": cat}
        for k, v in args.items():
            if k not in ("ts", "kind", "cat", "seq"):
                ev[k] = v
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(ev)

    def snapshot(self) -> List[Dict[str, Any]]:
        """The ring's events, oldest first (copies: callers may mutate)."""
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"capacity": self.capacity, "events": len(self._ring),
                    "total_seen": self._seq, "dropped": self._dropped}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dropped = 0

    def dump(self, path: str) -> int:
        """Crash-safe dump: every ring event as one strict-JSON line
        (non-finite floats nulled — the spans.jsonl policy), written to
        a temp file, fsync'd, then atomically renamed over ``path`` —
        a reader never sees a torn dump. Returns the event count."""
        from lfm_quant_tpu.utils.logging import _finite

        events = self.snapshot()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            for ev in events:
                fh.write(json.dumps({k: _finite(v) for k, v in ev.items()},
                                    default=str) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return len(events)


#: Sentinel: env not yet resolved (the ``utils/faults.py`` pattern —
#: one env read on first use, re-resolved only via :func:`configure`).
_UNSET = object()
_RECORDER: Any = _UNSET
_LOCK = threading.Lock()


def configure(capacity: Optional[int] = None) -> Optional[FlightRecorder]:
    """(Re)build the process recorder. ``capacity=None`` re-reads the
    ``LFM_FLIGHT`` knob (what tests that monkeypatch the env call); an
    explicit int configures directly (0 disables). Returns the active
    recorder, or None when disabled."""
    global _RECORDER
    cap = flight_capacity() if capacity is None else int(capacity)
    rec = FlightRecorder(cap) if cap > 0 else None
    with _LOCK:
        _RECORDER = rec
    return rec


def recorder() -> Optional[FlightRecorder]:
    """The active process recorder (None when ``LFM_FLIGHT=0``)."""
    rec = _RECORDER
    if rec is _UNSET:
        rec = configure()
    return rec


def enabled() -> bool:
    """Whether the flight recorder is on (the manifest probe)."""
    return recorder() is not None


def record(kind: str, cat: str = "serve", **fields: Any) -> None:
    """Module-level hot-path append (the serve layer's entry point):
    exact no-op — one global read + a None test — when disabled."""
    rec = _RECORDER
    if rec is _UNSET:
        rec = configure()
    if rec is not None:
        rec.record(kind, cat=cat, **fields)


def note(name: str, cat: str, args: Dict[str, Any]) -> None:
    """The ``telemetry.instant`` feed (see module docstring)."""
    rec = _RECORDER
    if rec is _UNSET:
        rec = configure()
    if rec is not None:
        rec.note(name, cat, args)


def snapshot() -> List[Dict[str, Any]]:
    rec = recorder()
    return rec.snapshot() if rec is not None else []


def dump(path: str) -> int:
    """Dump the active ring to ``path`` (0 events when disabled — the
    file is still written, so an incident bundle is always complete)."""
    rec = recorder()
    if rec is None:
        rec = FlightRecorder(2)  # empty dump: complete, explicit
    return rec.dump(path)
