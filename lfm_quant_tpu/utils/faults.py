"""Deterministic fault injection: make failures happen on demand.

Every PERF property in this repo is pinned by a tier-1 lane (reuse,
pipeline, serve, ...); until this module the FAILURE-path properties —
shed-on-overload, retry-then-recover, circuit breaking, preemption-safe
checkpoint flushes — were pinned by nothing, because there was no way
to produce a dispatch failure, a wedged H2D or a mid-epoch SIGTERM on
demand, reproducibly, in a unit test. This module is that lever: named
injection points (the *fault sites* below) call :func:`check` on their
hot path, and an ``LFM_FAULTS`` spec string turns specific calls at
specific sites into seeded, schedulable failures.

Fault sites (the map lives in DESIGN.md §18):

* ``serve_dispatch`` — the micro-batcher's scoring dispatch
  (serve/batcher.py), the site the retry + circuit-breaker layer guards;
* ``panel_h2d``      — the device-panel transfer (data/windows.py
  ``device_panel``), the residency layer's only H2D;
* ``zoo_lease``      — taking a serving lease on a zoo entry
  (serve/zoo.py ``ModelZoo.lease``);
* ``ckpt_write``     — staging an Orbax save (train/checkpoint.py
  ``CheckpointManager.save``), the preemption test's rendezvous;
* ``device_get``     — the counted blocking device→host fetch
  (utils/profiling.py ``timed_device_get``);
* ``zoo_persist``    — staging a durable zoo snapshot (serve/persist.py
  ``ZooStore.record_publish``: panel/params/probe/exec artifacts);
* ``manifest_write`` — committing the durable zoo manifest
  (serve/persist.py, checked TWICE per commit: even call indices fire
  immediately BEFORE the atomic rename, odd indices immediately AFTER
  it — so a scheduled crash lands on either side of the commit point;
  the SIGKILL-mid-publish crash-consistency test's rendezvous).

Spec grammar (``LFM_FAULTS``)::

    site:key=val[,key=val...][;site2:...]

    kind=transient|permanent|sigterm|sigkill   (default transient)
    at=I[+J+...]   fire on exactly these 0-based call indices
    p=F            else fire per call with probability F (seeded RNG)
    seed=N         the p-mode RNG seed (default 0)
    n=N            cap total injections at N (p-mode/every-call bound)

With neither ``at`` nor ``p`` the site fires on EVERY call (bounded by
``n``). Examples: ``serve_dispatch:n=3`` (first three dispatches fail
transiently), ``ckpt_write:at=2,kind=sigterm`` (deliver SIGTERM to self
at the third checkpoint write — the kill-mid-epoch preemption test),
``panel_h2d:p=0.2,seed=7,kind=permanent``.

Kinds: ``transient`` raises :class:`TransientFault` (the retry layer's
"worth retrying" classification — serve/errors.py ``is_transient``),
``permanent`` raises :class:`PermanentFault` (fail fast, trip the
breaker), ``sigterm`` delivers SIGTERM to the current process at the
site and RETURNS (the grace handler in train/preempt.py turns it into a
clean stop at the next epoch boundary) — deterministic preemption.
``sigkill`` delivers SIGKILL: the process dies INSTANTLY at the site —
no handler, no cleanup, no atexit — which is exactly the "crash at ANY
instant" a crash-consistency proof needs (the durable-zoo
SIGKILL-mid-publish subprocess test schedules it at ``zoo_persist`` /
``manifest_write``).

Determinism: each site keeps a call counter and (for ``p``) a private
``random.Random(seed)``; given the same call order, two runs inject the
identical schedule. Counters are lock-guarded, so concurrent callers
(the serving threads) each consume distinct call indices; cross-thread
interleaving order is the only nondeterminism, exactly as for the real
failures being modeled.

Non-interference contract (telemetry-style, MEASURED): with
``LFM_FAULTS`` unset, :func:`check` is one module-global read plus a
None test — no lock, no env read after the first call, no telemetry, no
device work. tests/test_chaos.py pins that a warm fit with the fault
layer wired but unconfigured pays zero jit traces, zero panel H2D and
exactly one host sync per epoch — the same numbers as before the layer
existed. Every injection bumps ``faults_injected`` / ``fault_<site>``
in the telemetry counter registry and emits a ``fault_injected``
instant, so chaos runs are attributable from the run dir alone.
"""

from __future__ import annotations

import os
import random
import signal
import threading
from typing import Any, Dict, Optional

#: The named injection points (the only valid spec sites — a typo'd
#: site must fail loudly, not silently never fire).
SITES = ("serve_dispatch", "panel_h2d", "zoo_lease", "ckpt_write",
         "device_get", "zoo_persist", "manifest_write")

#: The supported failure kinds.
KINDS = ("transient", "permanent", "sigterm", "sigkill")


class FaultError(RuntimeError):
    """Base class of injected failures. ``transient`` is the retry
    layer's classification hook (serve/errors.py ``is_transient``)."""

    transient = False

    def __init__(self, site: str, call: int):
        super().__init__(
            f"injected {type(self).__name__} at fault site {site!r} "
            f"(call #{call}, LFM_FAULTS)")
        self.site = site
        self.call = call


class TransientFault(FaultError):
    """An injected failure the caller SHOULD retry (a flaky dispatch,
    a dropped tunnel packet)."""

    transient = True


class PermanentFault(FaultError):
    """An injected failure retrying cannot fix (a poisoned program, a
    corrupt panel) — the circuit breaker's food."""


class _SitePlan:
    """One site's parsed schedule. ``fire`` is called under the module
    lock: it consumes one call index and returns it when the call
    should fail (None otherwise)."""

    __slots__ = ("site", "kind", "prob", "at", "limit", "rng", "calls",
                 "injected")

    def __init__(self, site: str, kind: str, prob: Optional[float],
                 at: Optional[frozenset], limit: Optional[int], seed: int):
        self.site = site
        self.kind = kind
        self.prob = prob
        self.at = at
        self.limit = limit
        self.rng = random.Random(seed)
        self.calls = 0
        self.injected = 0

    def fire(self) -> Optional[int]:
        idx = self.calls
        self.calls += 1
        if self.limit is not None and self.injected >= self.limit:
            return None
        if self.at is not None:
            hit = idx in self.at
        elif self.prob is not None:
            # Drawn once per call regardless of outcome, so the schedule
            # is a pure function of (seed, call index).
            hit = self.rng.random() < self.prob
        else:
            hit = True
        if not hit:
            return None
        self.injected += 1
        return idx


def parse_spec(spec: str) -> Dict[str, _SitePlan]:
    """Parse an ``LFM_FAULTS`` spec into per-site plans. Loud on any
    unknown site/kind/key — a chaos experiment that silently never
    fires is worse than no experiment."""
    plans: Dict[str, _SitePlan] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        site, sep, body = part.partition(":")
        site = site.strip()
        if site not in SITES:
            raise ValueError(
                f"LFM_FAULTS: unknown fault site {site!r} "
                f"(valid: {', '.join(SITES)})")
        if site in plans:
            raise ValueError(f"LFM_FAULTS: duplicate site {site!r}")
        kind, prob, at, limit, seed = "transient", None, None, None, 0
        if sep:
            for kv in body.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                key, sep2, val = kv.partition("=")
                if not sep2:
                    raise ValueError(
                        f"LFM_FAULTS: {site}: expected key=val, got {kv!r}")
                key = key.strip()
                val = val.strip()
                try:
                    if key == "kind":
                        if val not in KINDS:
                            raise ValueError(
                                f"kind must be one of {KINDS}, got {val!r}")
                        kind = val
                    elif key == "p":
                        prob = float(val)
                        if not 0.0 <= prob <= 1.0:
                            raise ValueError(f"p must be in [0, 1], got {prob}")
                    elif key == "at":
                        at = frozenset(int(tok) for tok in val.split("+"))
                    elif key == "n":
                        limit = int(val)
                    elif key == "seed":
                        seed = int(val)
                    else:
                        raise ValueError(f"unknown key {key!r} "
                                         "(kind|p|at|n|seed)")
                except ValueError as e:
                    raise ValueError(f"LFM_FAULTS: {site}: {e}") from None
        plans[site] = _SitePlan(site, kind, prob, at, limit, seed)
    return plans


#: Sentinel: spec not yet resolved — the first :func:`check`/:func:`active`
#: reads the env exactly once. ``None`` means "no faults configured".
_UNSET = object()
_PLANS: Any = _UNSET
_LOCK = threading.Lock()


def configure(spec: Optional[str] = None) -> Optional[Dict[str, _SitePlan]]:
    """(Re)configure the fault schedules. ``spec=None`` re-reads the
    ``LFM_FAULTS`` env knob (what tests that monkeypatch the env call);
    an explicit string configures directly (``""`` disables). Returns
    the active plans dict, or None when no faults are configured.
    Every configure RESETS call counters — schedules restart."""
    global _PLANS
    if spec is None:
        spec = os.environ.get("LFM_FAULTS", "")
    plans = parse_spec(spec) if spec.strip() else None
    with _LOCK:
        _PLANS = plans
    return plans


def active() -> bool:
    """Whether any fault schedule is configured."""
    plans = _PLANS
    if plans is _UNSET:
        plans = configure()
    return bool(plans)


def check(site: str, **ctx) -> None:
    """The injection point every fault site calls. EXACT no-op when no
    spec is configured (one global read + a None test); with a schedule
    hit it bumps the fault counters, emits a ``fault_injected``
    telemetry instant (``ctx`` lands in the instant's args) and raises
    the scheduled :class:`FaultError` — or delivers SIGTERM to the own
    process for ``kind=sigterm``."""
    plans = _PLANS
    if plans is _UNSET:
        plans = configure()
    if not plans:
        return
    plan = plans.get(site)
    if plan is None:
        return
    with _LOCK:
        idx = plan.fire()
    if idx is None:
        return
    from lfm_quant_tpu.utils import telemetry

    telemetry.COUNTERS.bump("faults_injected")
    telemetry.COUNTERS.bump(f"fault_{site}")
    telemetry.instant("fault_injected", cat="fault", site=site,
                      kind=plan.kind, call=idx, **ctx)
    if plan.kind == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if plan.kind == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
        return  # unreachable: SIGKILL is not deliverable-later, it kills
    cls = TransientFault if plan.kind == "transient" else PermanentFault
    raise cls(site, idx)
