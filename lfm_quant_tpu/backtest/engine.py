"""Backtest engine — parity with the reference's ``backtest.py`` entry
(SURVEY.md §3, §4.3; BASELINE.json:5): trained model(s) → forecasts →
monthly cross-sectional ranks → top-quantile portfolio → CAGR/Sharpe/IC
report. Lookahead-factor lineage: rank the cross-section each month by the
forecast factor, hold the top quantile, rebalance monthly (SURVEY.md §1
[BACKGROUND]).

This is the cold evaluation path — plain numpy, runs on host. The hot
forecast generation lives in Trainer.predict_panel / the ensemble trainer.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

import numpy as np

from lfm_quant_tpu.data.panel import Panel


@dataclasses.dataclass
class BacktestReport:
    """Monthly-rebalance portfolio simulation results.

    All rates are per-month unless suffixed _ann; months with no tradeable
    universe are skipped (recorded in ``n_skipped_months``).
    """

    cagr: float
    sharpe_ann: float
    mean_ic: float           # per-month Spearman(forecast, realized target)
    mean_ret_ic: float       # per-month Spearman(forecast, forward return)
    max_drawdown: float
    turnover: float          # mean fraction of portfolio replaced per month
    hit_rate: float          # fraction of months with positive return
    n_months: int
    n_skipped_months: int
    # Benchmark-relative block (benchmark = equal-weight tradeable
    # universe, the standard LFM-lineage comparison point):
    bench_cagr: float
    excess_cagr: float       # portfolio CAGR − benchmark CAGR
    ir_ann: float            # annualized IR of (portfolio − benchmark)
    t_stat: float            # t-stat of the mean monthly portfolio return
    monthly_returns: np.ndarray  # [T_used]
    monthly_ic: np.ndarray       # [T_used]
    monthly_bench: np.ndarray    # [T_used] universe EW forward return
    dates: np.ndarray            # [T_used] YYYYMM of formation months
    # Mean forward return per forecast-rank bucket, bottom → top — the
    # monotonicity evidence (a real signal shows increasing buckets).
    quantile_profile: np.ndarray  # [profile_buckets]

    def yearly(self) -> dict:
        """Calendar-year breakdown: {year: {"ret", "bench", "mean_ic",
        "n_months"}} with returns compounded within the year.

        Vectorized with ``np.ufunc.reduceat`` over year-boundary indices
        (dates are sorted formation months, so each year is one contiguous
        segment) — ``multiply.reduceat`` applies the SAME left-to-right
        reduction order as the old per-year ``np.prod`` loop, so the
        numbers are bit-identical while a 50-year report stops paying one
        Python iteration (plus boolean scans over the full series) per
        year."""
        years = np.asarray(self.dates) // 100
        starts = np.flatnonzero(np.r_[True, years[1:] != years[:-1]])
        counts = np.diff(np.r_[starts, years.size])
        # Same dtype promotion as the old per-year np.prod loop (multiply
        # .reduce is sequential, so each segment reduces in the identical
        # order) — ret/bench stay bit-compatible with prior reports;
        # mean_ic deliberately accumulates in float64 (≈1e-9 more
        # accurate than the old float32 .mean()).
        ret = np.multiply.reduceat(1.0 + np.asarray(self.monthly_returns), starts) - 1.0
        bench = np.multiply.reduceat(1.0 + np.asarray(self.monthly_bench), starts) - 1.0
        ic = np.add.reduceat(np.asarray(self.monthly_ic, np.float64), starts) / counts
        return {
            int(years[s]): {
                "ret": float(ret[i]),
                "bench": float(bench[i]),
                "mean_ic": float(ic[i]),
                "n_months": int(counts[i]),
            }
            for i, s in enumerate(starts)
        }

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        for k in ("monthly_returns", "monthly_ic", "monthly_bench", "dates",
                  "quantile_profile"):
            d[k] = np.asarray(d[k]).tolist()
        d["yearly"] = self.yearly()
        return json.dumps(d, indent=2)

    def summary(self) -> str:
        return (
            f"CAGR {self.cagr:+.2%} (bench {self.bench_cagr:+.2%}, excess "
            f"{self.excess_cagr:+.2%}, IR {self.ir_ann:.2f}) | "
            f"Sharpe {self.sharpe_ann:.2f} | t {self.t_stat:.1f} | "
            f"IC {self.mean_ic:+.3f} | retIC {self.mean_ret_ic:+.3f} | "
            f"maxDD {self.max_drawdown:.2%} | turnover {self.turnover:.2f} | "
            f"months {self.n_months}"
        )


#: Known aggregation modes (shared vocabulary of the numpy reference,
#: the device-resident jax_engine, and the CLIs).
ENSEMBLE_MODES = ("mean", "mean_minus_std", "mean_minus_total_std")


def normalize_modes(modes, risk_lambda: float = 1.0):
    """Mode specs → [(mode, λ)]: each entry is a mode name (taking the
    default ``risk_lambda``) or an explicit ``(mode, λ)`` pair — the λ
    grid of the uncertainty_aggregation sweep. Lives on the numpy side
    so mode vocabulary needs no jax import."""
    specs = []
    for m in modes:
        mode, lam = m if isinstance(m, tuple) else (m, risk_lambda)
        if mode not in ENSEMBLE_MODES:
            raise ValueError(f"unknown ensemble mode {mode!r}")
        specs.append((mode, float(lam)))
    return specs


def mode_label(mode: str, lam: float) -> str:
    """Stable dict key for a (mode, λ) spec; the plain mode name when λ
    is irrelevant (mean), matching the single-mode CLI vocabulary."""
    return mode if mode == "mean" else f"{mode}@{lam:g}"


def _spearman(a: np.ndarray, b: np.ndarray) -> float:
    # kind="stable": ties rank in index order — a DEFINED tie-break the
    # fused JAX engine (stable argsort by construction) reproduces
    # exactly; the default introsort's tie order is implementation-
    # arbitrary, which would make engine parity untestable on ties.
    ra = np.argsort(np.argsort(a, kind="stable"),
                    kind="stable").astype(np.float64)
    rb = np.argsort(np.argsort(b, kind="stable"),
                    kind="stable").astype(np.float64)
    ra -= ra.mean()
    rb -= rb.mean()
    denom = np.sqrt((ra * ra).sum() * (rb * rb).sum())
    return float((ra * rb).sum() / denom) if denom > 0 else 0.0


def aggregate_ensemble(
    forecasts: np.ndarray,
    fc_valid: np.ndarray,
    mode: str = "mean",
    risk_lambda: float = 1.0,
    aleatoric_var: Optional[np.ndarray] = None,
):
    """Combine stacked per-seed forecasts [S, N, T] → ([N, T], [N, T] valid).

    ``mode``:
      * "mean"           — ensemble average (the reference's multi-seed
        aggregation, SURVEY.md §4.3).
      * "mean_minus_std" — uncertainty-penalized score ``mean − λ·std``
        over the seed axis (epistemic only; uncertainty-aware LFM
        lineage, SURVEY.md §1 [BACKGROUND]).
      * "mean_minus_total_std" — ``mean − λ·sqrt(Var_seeds(mean_s) +
        mean_s(var_s))``: the deep-ensemble mixture's total predictive
        std (law of total variance — epistemic seed spread + mean
        aleatoric head variance). Needs ``aleatoric_var`` [S, N, T] from
        ``predict(return_variance=True)`` on heteroscedastic members.
    ``fc_valid`` may be [N, T] (shared) or [S, N, T] (per-seed; a cell is
    valid if ALL seeds predicted it).
    """
    if forecasts.ndim != 3:
        raise ValueError(f"expected [S, N, T] forecasts, got {forecasts.shape}")
    valid = fc_valid.all(axis=0) if fc_valid.ndim == 3 else fc_valid
    mean = forecasts.mean(axis=0)
    if mode == "mean":
        score = mean
    elif mode == "mean_minus_std":
        score = mean - risk_lambda * forecasts.std(axis=0)
    elif mode == "mean_minus_total_std":
        if aleatoric_var is None:
            raise ValueError(
                "mean_minus_total_std needs aleatoric_var (predict with "
                "return_variance=True on a heteroscedastic model)")
        if aleatoric_var.shape != forecasts.shape:
            raise ValueError(
                f"aleatoric_var {aleatoric_var.shape} must match "
                f"forecasts {forecasts.shape}")
        total_var = forecasts.var(axis=0) + aleatoric_var.mean(axis=0)
        score = mean - risk_lambda * np.sqrt(np.maximum(total_var, 0.0))
    else:
        raise ValueError(f"unknown ensemble mode {mode!r}")
    return np.where(valid, score, 0.0).astype(np.float32), valid


def run_backtest(
    forecast: np.ndarray,
    fc_valid: np.ndarray,
    panel: Panel,
    quantile: float = 0.1,
    long_short: bool = False,
    min_universe: int = 20,
    periods_per_year: int = 12,
    rf_monthly: float = 0.0,
    costs_bps: float = 0.0,
    profile_buckets: int = 10,
) -> BacktestReport:
    """Monthly-rebalance quantile portfolio simulation.

    Each month t with ≥ ``min_universe`` forecastable firms: rank the
    cross-section by ``forecast[:, t]``, go long the top ``quantile``
    (equal-weight); with ``long_short`` also short the bottom quantile.
    The position earns the forward 1-month return ``panel.returns[:, t]``.
    ``costs_bps`` charges that many basis points on each month's turnover.
    The report also carries the equal-weight-universe benchmark
    (excess CAGR, annualized IR) and a ``profile_buckets``-bucket mean
    forward return profile over the forecast ranking.
    """
    n, t_len = forecast.shape
    if panel.returns.shape != (n, t_len):
        raise ValueError("forecast and panel shapes disagree")
    rets, ics, ret_ics, dates, turns, benches = [], [], [], [], [], []
    profile_sum = np.zeros(profile_buckets, np.float64)
    profile_cnt = np.zeros(profile_buckets, np.int64)
    prev_long: Optional[set] = None
    skipped = 0
    # tradeable() excludes firms whose forward return is unobserved (e.g.
    # delisting at t+1) — crediting them 0% would mask delisting losses.
    tradeable = panel.tradeable()
    for t in range(t_len):
        uni = np.nonzero(fc_valid[:, t] & tradeable[:, t])[0]
        if uni.size < min_universe:
            skipped += 1
            continue
        f = forecast[uni, t]
        k = max(1, int(round(uni.size * quantile)))
        # Stable sort: tied forecasts keep firm-index order, so the
        # portfolio boundary is well-defined and the fused JAX engine
        # (backtest/jax_engine.py) forms bit-identical portfolios.
        order = np.argsort(f, kind="stable")
        long_ix = uni[order[-k:]]
        port_ret = float(panel.returns[long_ix, t].mean())
        if long_short:
            short_ix = uni[order[:k]]
            port_ret -= float(panel.returns[short_ix, t].mean())
        cur = set(long_ix.tolist())
        if prev_long is not None:
            turn = 1.0 - len(cur & prev_long) / max(len(cur), 1)
            turns.append(turn)
            port_ret -= costs_bps * 1e-4 * turn
        prev_long = cur
        rets.append(port_ret)
        benches.append(float(panel.returns[uni, t].mean()))
        month_rets = panel.returns[uni[order], t]  # sorted by forecast
        # Map each sorted name to bucket floor(rank*B/n): in thin months
        # (n < profile_buckets) names keep their forecast-rank position —
        # the top-forecast name lands in the highest REACHABLE bucket,
        # floor((n-1)*B/n) (e.g. bucket 8 of 9 at n=6), rank order is
        # preserved, and only unreached buckets go empty, so the
        # monotonicity profile stays honest.
        bucket_of = (np.arange(uni.size) * profile_buckets) // uni.size
        for b in np.unique(bucket_of):
            profile_sum[b] += float(month_rets[bucket_of == b].mean())
            profile_cnt[b] += 1
        ics.append(_spearman(f, panel.targets[uni, t])
                   if panel.target_valid[uni, t].any() else 0.0)
        ret_ics.append(_spearman(f, panel.returns[uni, t]))
        dates.append(int(panel.dates[t]))

    return assemble_report(
        rets, ics, ret_ics, benches, turns, dates, skipped,
        profile_sum, profile_cnt, min_universe=min_universe,
        periods_per_year=periods_per_year, rf_monthly=rf_monthly,
    )


def assemble_report(rets, ics, ret_ics, benches, turns, dates, skipped,
                    profile_sum, profile_cnt, *, min_universe: int,
                    periods_per_year: int = 12, rf_monthly: float = 0.0,
                    ) -> BacktestReport:
    """Per-month series → :class:`BacktestReport` summary statistics.

    The ONE place the portfolio statistics (CAGR/Sharpe/IR/t-stat/max-DD)
    are computed: both the numpy reference engine and the fused JAX
    engine (backtest/jax_engine.py) hand their per-used-month series to
    this function, so the two paths can only diverge in the per-month
    numbers — which the parity suite pins — never in the report math.
    All inputs are sequences over USED months (thin months already
    dropped); ``turns`` has one fewer entry (no predecessor portfolio in
    the first used month).
    """
    rets = np.asarray(rets, np.float64)
    if rets.size == 0:
        raise ValueError(
            f"no month had a universe of >= {min_universe} forecastable firms"
        )
    r = rets
    b = np.asarray(benches, np.float64)
    turns = np.asarray(turns, np.float64)
    excess = r - rf_monthly
    growth = np.cumprod(1.0 + r)
    years = len(r) / periods_per_year
    cagr = float(growth[-1] ** (1.0 / years) - 1.0) if years > 0 else 0.0
    vol = float(excess.std(ddof=1)) if len(r) > 1 else 0.0
    sharpe = float(excess.mean() / vol * np.sqrt(periods_per_year)) if vol > 0 else 0.0
    peak = np.maximum.accumulate(growth)
    max_dd = float(((growth - peak) / peak).min())
    bench_growth = np.cumprod(1.0 + b)
    bench_cagr = (float(bench_growth[-1] ** (1.0 / years) - 1.0)
                  if years > 0 else 0.0)
    active = r - b
    a_vol = float(active.std(ddof=1)) if len(r) > 1 else 0.0
    ir = (float(active.mean() / a_vol * np.sqrt(periods_per_year))
          if a_vol > 0 else 0.0)
    t_stat = (float(r.mean() / r.std(ddof=1) * np.sqrt(len(r)))
              if len(r) > 1 and r.std(ddof=1) > 0 else 0.0)
    return BacktestReport(
        cagr=cagr,
        sharpe_ann=sharpe,
        mean_ic=float(np.mean(ics)),
        mean_ret_ic=float(np.mean(ret_ics)),
        max_drawdown=max_dd,
        turnover=float(turns.mean()) if turns.size else 0.0,
        hit_rate=float((r > 0).mean()),
        n_months=len(r),
        n_skipped_months=int(skipped),
        bench_cagr=bench_cagr,
        excess_cagr=cagr - bench_cagr,
        ir_ann=ir,
        t_stat=t_stat,
        monthly_returns=r.astype(np.float32),
        monthly_ic=np.asarray(ics, np.float32),
        monthly_bench=b.astype(np.float32),
        dates=np.asarray(dates, np.int32),
        quantile_profile=(np.asarray(profile_sum, np.float64)
                          / np.maximum(profile_cnt, 1)).astype(np.float32),
    )
