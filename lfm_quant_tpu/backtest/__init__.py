"""Backtest / evaluation layer: forecasts → portfolio → performance report.

Two engines, one contract:

* ``engine`` — the numpy reference (host loop). Golden for parity.
* ``jax_engine`` — the fused device-resident path (all months in one
  jitted dispatch; multi-mode aggregation from one stacked tensor).

``resolve_backtest()`` picks the engine for the CLIs/walk-forward:
the fused path is the default, ``LFM_JAX_BACKTEST=0`` (or a jax import
failure) falls back to the numpy reference.
"""

import os

from lfm_quant_tpu.backtest.engine import (
    BacktestReport,
    aggregate_ensemble,
    assemble_report,
    run_backtest,
)


def jax_backtest_enabled() -> bool:
    """The fused-scoring knob: ``LFM_JAX_BACKTEST`` (default ON)."""
    return os.environ.get("LFM_JAX_BACKTEST", "1") != "0"


def resolve_backtest():
    """The backtest callable the serving paths should dispatch through:
    ``jax_engine.run_backtest_jax`` when the knob is on and jax imports,
    else the numpy ``run_backtest`` reference (same signature, same
    report — the fused path is an optimization, never a requirement)."""
    if jax_backtest_enabled():
        try:
            from lfm_quant_tpu.backtest.jax_engine import run_backtest_jax

            return run_backtest_jax
        except ImportError:
            pass
    return run_backtest


__all__ = [
    "BacktestReport",
    "run_backtest",
    "aggregate_ensemble",
    "assemble_report",
    "jax_backtest_enabled",
    "resolve_backtest",
]
