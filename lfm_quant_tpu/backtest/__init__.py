"""Backtest / evaluation layer: forecasts → portfolio → performance report."""

from lfm_quant_tpu.backtest.engine import (
    BacktestReport,
    aggregate_ensemble,
    run_backtest,
)

__all__ = ["BacktestReport", "run_backtest", "aggregate_ensemble"]
