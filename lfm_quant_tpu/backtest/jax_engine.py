"""Device-resident scoring: the fused predict → aggregate → backtest path.

The numpy engine (backtest/engine.py) iterates ``for t in range(T)`` with
a nested per-bucket loop and a double-argsort Spearman per month — a
serial host loop that dominates end-to-end latency for the serving
workload (walk-forward re-scoring and the ``uncertainty_aggregation``
sweep: 171 OOS months × seeds × aggregation modes) once training is
warm. The same lesson the training path learned from the related work
applies on the TIME axis: what looks sequential is batchable
(PAPERS.md — "Large-Batch Training for LSTM and Beyond" for throughput
scaling, "Parallelizing Linear Recurrent Neural Nets Over Sequence
Length" for parallelism over the sequence dimension). Months are
independent given the forecast panel, so the whole monthly loop is one
``vmap``; only the turnover chain is truly sequential, and that is a
[T]-step ``lax.scan`` over an [N]-bool carry, not a Python loop.

Shape of the fused path (a handful of dispatches, not O(T·K·modes)
Python iterations):

* ``run_backtest_jax`` — drop-in twin of ``engine.run_backtest``: ONE
  jitted dispatch computes every month's portfolio formation (stable
  masked argsort ranks + exact ``k``-of-``n`` selection via a
  precomputed k-table), monthly rank-IC (``ops/metrics.spearman_ic`` —
  the same tie-handling as the reference's double argsort), the
  equal-weight benchmark, the decile profile (``segment_sum`` over
  forecast-rank buckets) and the turnover/cost chain. Host work is one
  small D2H of [T]-shaped series plus the shared
  ``engine.assemble_report`` summary math — the numpy engine stays the
  golden reference the parity suite compares against.
* ``aggregate_scores_device`` — evaluates ALL aggregation modes
  (mean, mean−λ·std, mean−λ·total_std, any λ grid) from one stacked
  [S, N, T] forecast tensor in one dispatch, without re-materializing
  the stack per mode.
* ``run_scoring_pipeline`` — aggregate + backtest for a whole mode
  sweep in ONE core dispatch (modes ride a leading vmap axis of the
  same compiled program).

Parity discipline (pinned by tests/test_jax_backtest.py):

* Selection count: numpy uses ``max(1, int(round(n * quantile)))`` in
  float64. Recomputing ``n · quantile`` in on-device float32 could round
  the other way across the .5 boundary, so ``k`` comes from a
  host-precomputed ``k_table[n]`` with the exact numpy semantics.
* Ordering: ``jnp.argsort`` is stable, and invalid slots are pushed to
  ``+inf``, so valid entries keep exactly the relative order numpy's
  stable subset argsort produces — ties land in the same buckets and
  portfolios on both engines.
* Everything aggregate-shaped (profile sums, report statistics) is
  accumulated on host in float64 via the shared ``assemble_report``.

The engine selection knob is ``LFM_JAX_BACKTEST`` (default ON; ``0``
falls back to the numpy engine) — see ``resolve_backtest`` in
``backtest/__init__.py``.
"""

from __future__ import annotations

import functools
import threading
import weakref
from typing import Dict, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from lfm_quant_tpu.backtest.engine import (
    BacktestReport,
    assemble_report,
    mode_label,
    normalize_modes,
)
from lfm_quant_tpu.data.panel import Panel
from lfm_quant_tpu.ops.metrics import hard_ranks, pearson_ic
from lfm_quant_tpu.utils import telemetry

# Mode name → which uncertainty tensor the λ-penalty scales (static
# program structure; λ itself is a traced argument, so a λ grid reuses
# one compiled program).
_MODE_KINDS = {"mean": 0, "mean_minus_std": 1, "mean_minus_total_std": 2}

ModeSpec = Union[str, Tuple[str, float]]


# ---- device residency ---------------------------------------------------
#
# The backtest-side panel arrays (forward returns, targets, validity,
# tradeability) are not part of the training device panel
# (data/windows.py keeps returns host-side on purpose — training never
# reads them). The scoring pipeline is called many times per panel
# (every fold × every mode sweep), so they get their own residency
# cache: one H2D per panel object, month-major ([T, N]) because the
# fused core vmaps over months. Same identity-keyed + weakref-evicted
# contract as the training panel cache.

_SCORE_PANEL_LOCK = threading.Lock()
_SCORE_PANEL_CACHE: dict = {}


def _device_score_panel(panel: Panel) -> dict:
    # Lock-guarded like the training residency cache (data/windows.py):
    # the serving process backtests from request/refresh threads, and a
    # cold-panel race must pay ONE transfer, not two aliased entries.
    key = id(panel)
    with _SCORE_PANEL_LOCK:
        hit = _SCORE_PANEL_CACHE.get(key)
        if hit is not None:
            return hit
        dev = {
            "returns": jnp.asarray(np.ascontiguousarray(panel.returns.T)),
            "targets": jnp.asarray(np.ascontiguousarray(panel.targets.T)),
            "target_valid": jnp.asarray(
                np.ascontiguousarray(panel.target_valid.T)),
            "tradeable": jnp.asarray(
                np.ascontiguousarray(panel.tradeable().T)),
        }
        _SCORE_PANEL_CACHE[key] = dev
        weakref.finalize(panel, _gc_pop_score, key)
        return dev


def _gc_pop_score(key) -> None:
    with _SCORE_PANEL_LOCK:
        _SCORE_PANEL_CACHE.pop(key, None)


def clear_score_panel_cache() -> None:
    """Drop all device-resident scoring panels (tests / memory pressure)."""
    with _SCORE_PANEL_LOCK:
        _SCORE_PANEL_CACHE.clear()


def invalidate_score_panel(panel: Panel) -> int:
    """Drop this panel's device-resident scoring arrays. Called by
    ``data/windows.invalidate_panel`` so ONE invalidation hook covers
    both residency caches — a panel mutated in place must never be
    scored against stale device returns/targets. Returns entries
    dropped. (Dispatches already in flight hold Python references to
    the arrays, so dropping the dict entry can never tear a live
    dispatch — same contract as the training cache's deferred drop.)"""
    with _SCORE_PANEL_LOCK:
        if id(panel) in _SCORE_PANEL_CACHE:
            del _SCORE_PANEL_CACHE[id(panel)]
            return 1
        return 0


@functools.lru_cache(maxsize=32)
def _k_table(n_firms: int, quantile: float) -> jnp.ndarray:
    """Exact numpy portfolio sizes for every possible universe count:
    ``k_table[n] = max(1, int(round(n * quantile)))`` computed in host
    float64 (round-half-even, like the reference engine) — on-device
    float32 could land on the other side of a .5 boundary. Cached so the
    hot scoring path (per fold × per mode sweep) pays the build + H2D
    once per (universe size, quantile), like the panel residency cache."""
    n = np.arange(n_firms + 1, dtype=np.float64)
    return jnp.asarray(np.maximum(1, np.round(n * quantile)).astype(np.int32))


# ---- the fused core -----------------------------------------------------


@functools.lru_cache(maxsize=8)
def _core_for(n_buckets: int):
    """Build (and cache) the jitted all-months backtest core for a
    profile-bucket count. One program serves every call with the same
    bucket count and array shapes — quantile (k-table), min_universe,
    costs and long/short arrive as traced arguments, so a mode/λ/cost
    sweep pays ZERO recompiles after the first dispatch."""
    from lfm_quant_tpu.train.reuse import ledger_jit

    def month_stats(f, u, r, rank_tgt, rank_r, tv_any, n, k):
        """One month's cross-section × one mode's scores → portfolio/IC/
        profile stats; mirrors one iteration of the numpy engine's month
        loop. ``rank_tgt``/``rank_r`` are the month's PRECOMPUTED target/
        return ranks — they don't depend on the scores, so the mode sweep
        shares them and each (mode, month) pays exactly ONE sort: the
        portfolio argsort below, whose scatter-of-iota is simultaneously
        the forecast rank vector (ops/metrics.hard_ranks is the same
        construction — sorts are the whole cost of this core on CPU)."""
        n_slots = f.shape[0]
        # Stable ascending sort with invalid slots pushed past every real
        # score: slots 0..n-1 are the universe in forecast order, exactly
        # numpy's stable argsort over the subset (ties keep index order).
        # This sort + one inverse-permutation scatter are the ONLY
        # per-(mode, month) O(N log N) ops: portfolio membership, IC
        # ranks and profile buckets all derive elementwise from rank_f
        # (XLA CPU scatters/gathers/segment-reduces cost more than the
        # arithmetic they'd save).
        order = jnp.argsort(jnp.where(u, f, jnp.inf))
        slot = jnp.arange(n_slots)
        rank_f = jnp.zeros(n_slots, f.dtype).at[order].set(
            slot.astype(f.dtype))
        ranki = rank_f.astype(jnp.int32)
        memb = u & (ranki >= n - k)       # long leg, firm order
        short_memb = u & (ranki < jnp.minimum(k, n))
        kf = jnp.maximum(k, 1).astype(r.dtype)
        long_ret = (r * memb).sum() / kf
        short_ret = (r * short_memb).sum() / kf
        # Rank-based Spearman (ops/metrics.py spearman_ic ≡ pearson over
        # hard ranks): identical tie handling to the reference's stable
        # double argsort; IC is defined 0 when no target in the month's
        # universe is observable.
        ic = jnp.where(tv_any, pearson_ic(rank_f, rank_tgt, u), 0.0)
        ret_ic = pearson_ic(rank_f, rank_r, u)
        # Decile profile: bucket = floor(rank·B/n) per firm; per-bucket
        # sums via a one-hot contraction (a [N, B] compare + reduce beats
        # segment_sum's scatter-add on every backend tried).
        bucket = (ranki * n_buckets) // jnp.maximum(n, 1)
        onehot = (bucket[:, None] == jnp.arange(n_buckets)[None]) \
            & u[:, None]
        bsum = (r[:, None] * onehot).sum(axis=0)
        bcnt = onehot.sum(axis=0)
        bmean = jnp.where(bcnt > 0, bsum / jnp.maximum(bcnt, 1), 0.0)
        return {"long_ret": long_ret, "short_ret": short_ret, "ic": ic,
                "ret_ic": ret_ic, "bmean": bmean, "bhas": bcnt > 0,
                "memb": memb}

    def turnover_chain(memb, k, used, prev_idx):
        """Prev-portfolio overlap across USED months (skipped months keep
        the previous portfolio, exactly like the numpy engine's
        ``prev_long`` carry). Looks sequential but isn't: each used
        month's predecessor is resolved OUTSIDE by a cummax over used
        month indices (``prev_idx``), so the whole chain is one gather +
        one reduction — a T-step ``lax.scan`` here measured ~150 ms of
        pure per-iteration overhead on the CPU backend."""
        prev_memb = memb[jnp.maximum(prev_idx, 0)]          # [T, N]
        inter = (memb & prev_memb).sum(axis=-1)
        turn = 1.0 - inter / jnp.maximum(k, 1).astype(jnp.float32)
        turn_has = used & (prev_idx >= 0)
        return jnp.where(turn_has, turn, 0.0), turn_has

    def core(scores, u, r, tgt, tv, k_table, min_uni, costs_bps, long_short):
        """All months × all modes in one dispatch. ``scores`` [G, T, N]
        (G aggregation modes over a shared universe ``u`` [T, N]). The
        mode-independent month quantities — universe count, portfolio
        size, benchmark, target/return ranks — are computed ONCE and
        broadcast into the per-mode vmap."""
        n = u.sum(axis=-1)                      # [T]
        k = k_table[n]
        used = n >= min_uni
        bench = (r * u).sum(axis=-1) / jnp.maximum(n, 1).astype(r.dtype)
        rank_tgt = hard_ranks(tgt, u)           # [T, N], shared by modes
        rank_r = hard_ranks(r, u)
        tv_any = (tv & u).any(axis=-1)          # [T]
        per_month = jax.vmap(month_stats)
        st = jax.vmap(lambda f: per_month(f, u, r, rank_tgt, rank_r,
                                          tv_any, n, k))(scores)
        port = st["long_ret"] - jnp.where(long_short, st["short_ret"], 0.0)
        # Predecessor used-month index via exclusive cummax: the
        # vectorized form of the numpy engine's prev_long carry.
        t_len = used.shape[0]
        idx = jnp.where(used, jnp.arange(t_len), -1)
        run = jax.lax.cummax(idx)
        prev_idx = jnp.concatenate([jnp.full((1,), -1, idx.dtype), run[:-1]])
        turn, turn_has = jax.vmap(turnover_chain,
                                  in_axes=(0, None, None, None))(
            st["memb"], k, used, prev_idx)
        port = port - costs_bps * 1e-4 * turn * turn_has
        return {"used": used, "n": n, "k": k, "port": port,
                "bench": bench, "ic": st["ic"],
                "ret_ic": st["ret_ic"], "turn": turn, "turn_has": turn_has,
                "bmean": st["bmean"], "bhas": st["bhas"]}

    return ledger_jit(f"backtest_core_b{n_buckets}", core)


def _dispatch_core(scores, u, panel: Panel, quantile: float,
                   long_short: bool, min_universe: int, costs_bps: float,
                   profile_buckets: int) -> dict:
    """Stage inputs and run the jitted core; returns the host-fetched
    per-month output dict (one small D2H for everything)."""
    with telemetry.span("score_dispatch", cat="score",
                        modes=int(scores.shape[0]),
                        months=int(scores.shape[1])):
        dev = _device_score_panel(panel)
        out = _core_for(profile_buckets)(
            scores, u, dev["returns"], dev["targets"], dev["target_valid"],
            _k_table(panel.n_firms, quantile),
            jnp.asarray(min_universe, jnp.int32),
            jnp.asarray(costs_bps, jnp.float32),
            jnp.asarray(bool(long_short)),
        )
        return jax.device_get(out)


def _report_for_mode(out: dict, g: int, dates: np.ndarray, *,
                     min_universe: int, periods_per_year: int,
                     rf_monthly: float) -> BacktestReport:
    """Slice one mode's per-month series out of the core output and hand
    them to the SHARED report assembly (float64, same as numpy engine)."""
    used = out["used"]
    turn_has = out["turn_has"][g]
    profile = np.where(out["bhas"][g], out["bmean"][g], 0.0)[used]
    return assemble_report(
        rets=out["port"][g][used],
        ics=out["ic"][g][used],
        ret_ics=out["ret_ic"][g][used],
        benches=out["bench"][used],
        turns=out["turn"][g][turn_has],
        dates=dates[used],
        skipped=int((~used).sum()),
        profile_sum=profile.astype(np.float64).sum(axis=0),
        profile_cnt=out["bhas"][g][used].sum(axis=0),
        min_universe=min_universe,
        periods_per_year=periods_per_year,
        rf_monthly=rf_monthly,
    )


def run_backtest_jax(
    forecast: np.ndarray,
    fc_valid: np.ndarray,
    panel: Panel,
    quantile: float = 0.1,
    long_short: bool = False,
    min_universe: int = 20,
    periods_per_year: int = 12,
    rf_monthly: float = 0.0,
    costs_bps: float = 0.0,
    profile_buckets: int = 10,
) -> BacktestReport:
    """Drop-in fused twin of :func:`engine.run_backtest`: all T months in
    one jitted dispatch, report math shared with the numpy reference.
    Matches the numpy engine within float32 tolerance (pinned by the
    ``backtest``-marked parity suite)."""
    n, t_len = forecast.shape
    if panel.returns.shape != (n, t_len):
        raise ValueError("forecast and panel shapes disagree")
    dev = _device_score_panel(panel)
    u = jnp.asarray(np.ascontiguousarray(fc_valid.T)) & dev["tradeable"]
    scores = jnp.asarray(np.ascontiguousarray(forecast.T))[None]
    out = _dispatch_core(scores, u, panel, quantile, long_short,
                         min_universe, costs_bps, profile_buckets)
    return _report_for_mode(out, 0, panel.dates,
                            min_universe=min_universe,
                            periods_per_year=periods_per_year,
                            rf_monthly=rf_monthly)


# ---- device-resident multi-mode aggregation -----------------------------


@functools.partial(jax.jit, static_argnames=("kinds",))
def _aggregate_modes(forecasts, valid, lams, aleatoric_var, kinds):
    """[S, N, T] stacked forecasts → [G, N, T] scores for every mode in
    one dispatch. ``kinds`` is the static per-mode penalty selector; λ
    is traced so a λ sweep reuses the program."""
    mean = forecasts.mean(axis=0)
    zeros = jnp.zeros_like(mean)
    std = tstd = None
    if any(k == 1 for k in kinds):
        std = forecasts.std(axis=0)
    if any(k == 2 for k in kinds):
        total_var = (forecasts.var(axis=0)
                     + aleatoric_var.mean(axis=0))
        tstd = jnp.sqrt(jnp.maximum(total_var, 0.0))
    penalty = jnp.stack([zeros if k == 0 else (std if k == 1 else tstd)
                         for k in kinds])
    scores = mean[None] - lams[:, None, None] * penalty
    return jnp.where(valid[None], scores, 0.0).astype(jnp.float32)


def aggregate_scores_device(
    forecasts,
    fc_valid,
    modes: Sequence[ModeSpec],
    risk_lambda: float = 1.0,
    aleatoric_var=None,
):
    """Device-resident twin of :func:`engine.aggregate_ensemble` that
    evaluates ALL aggregation modes from ONE stacked [S, N, T] forecast
    tensor without re-materializing it per mode.

    Returns ``(scores [G, N, T] device array, valid [N, T] numpy,
    specs [(mode, λ)])`` — same validation rules and numerics (float32)
    as the numpy reference, which remains the golden comparison point.
    """
    forecasts = jnp.asarray(forecasts)
    if forecasts.ndim != 3:
        raise ValueError(f"expected [S, N, T] forecasts, got {forecasts.shape}")
    specs = normalize_modes(modes, risk_lambda)
    fc_valid = np.asarray(fc_valid)
    valid = fc_valid.all(axis=0) if fc_valid.ndim == 3 else fc_valid
    kinds = tuple(_MODE_KINDS[m] for m, _ in specs)
    if any(k == 2 for k in kinds):
        if aleatoric_var is None:
            raise ValueError(
                "mean_minus_total_std needs aleatoric_var (predict with "
                "return_variance=True on a heteroscedastic model)")
        if aleatoric_var.shape != forecasts.shape:
            raise ValueError(
                f"aleatoric_var {aleatoric_var.shape} must match "
                f"forecasts {forecasts.shape}")
        avar = jnp.asarray(aleatoric_var)
    else:
        # Static zero placeholder: keeps the jitted signature fixed so
        # mean/std-only sweeps don't re-trace when avar is absent.
        avar = jnp.zeros((1,) + forecasts.shape[1:], forecasts.dtype)
    lams = jnp.asarray([lam for _, lam in specs], jnp.float32)
    with telemetry.span("aggregate", cat="score", modes=len(specs)):
        scores = _aggregate_modes(forecasts, jnp.asarray(valid), lams, avar,
                                  kinds)
    return scores, valid, specs


def run_scoring_pipeline(
    forecasts,
    fc_valid,
    panel: Panel,
    modes: Sequence[ModeSpec] = ("mean",),
    risk_lambda: float = 1.0,
    aleatoric_var=None,
    quantile: float = 0.1,
    long_short: bool = False,
    min_universe: int = 20,
    periods_per_year: int = 12,
    rf_monthly: float = 0.0,
    costs_bps: float = 0.0,
    profile_buckets: int = 10,
) -> Dict[str, BacktestReport]:
    """Fused aggregate → backtest for a whole mode sweep: ONE aggregation
    dispatch builds every mode's score panel from the stacked [S, N, T]
    forecasts, ONE core dispatch backtests all modes × all months, one
    small D2H fetches the per-month series. Returns {label: report} in
    spec order (see :func:`mode_label`).

    ``forecasts`` may be [S, N, T] (ensemble seeds / MC-dropout samples)
    or [N, T] (a single already-aggregated panel: ``mean_minus_std``
    is rejected there — the seed axis is degenerate, so every λ would
    silently reproduce "mean" under a penalized label; matches the
    backtest.py CLI's validation. ``mean_minus_total_std`` stays legal
    WITH ``aleatoric_var`` — the single-heteroscedastic-model case).
    """
    if forecasts.ndim == 2:
        bad = [m for m, _ in normalize_modes(modes, risk_lambda)
               if m == "mean_minus_std"]
        if bad:
            raise ValueError(
                "mean_minus_std needs stacked forecasts (ensemble seeds "
                "or MC-dropout samples); this is a single already-"
                "aggregated [N, T] panel — its seed-axis std is "
                "identically 0, so every λ would just relabel 'mean'")
        forecasts = forecasts[None]
        if aleatoric_var is not None and aleatoric_var.ndim == 2:
            aleatoric_var = aleatoric_var[None]
    scores, valid, specs = aggregate_scores_device(
        forecasts, fc_valid, modes, risk_lambda, aleatoric_var)
    dev = _device_score_panel(panel)
    u = jnp.asarray(np.ascontiguousarray(valid.T)) & dev["tradeable"]
    out = _dispatch_core(jnp.swapaxes(scores, 1, 2), u, panel, quantile,
                         long_short, min_universe, costs_bps,
                         profile_buckets)
    return {
        mode_label(mode, lam): _report_for_mode(
            out, g, panel.dates, min_universe=min_universe,
            periods_per_year=periods_per_year, rf_monthly=rf_monthly)
        for g, (mode, lam) in enumerate(specs)
    }
