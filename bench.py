#!/usr/bin/env python
"""Benchmark harness: ladder configs on one chip.

Prints one JSON line PER METRIC, each {"metric", "value", "unit",
"vs_baseline", ...extras}:

  * train_throughput_c2_lstm — single-seed LSTM, 20 features, 60-month
    lookback (BASELINE.json:8) training throughput.
  * train_throughput_c5_ensemble — the c5-geometry seed-vmapped LSTM
    ensemble (BASELINE.json:11), as many seeds as fit one chip
    (LFM_BENCH_SEEDS overrides). This is the evidence stream for the
    primary ensemble wall-clock metric (BASELINE.json:2): per-chip
    ensemble throughput × chips ≈ pod throughput, since seeds scale
    embarrassingly over the mesh seed axis.

Metric: firm-months/sec/chip (BASELINE.json:2) — firm-month observations
consumed by training per second (real windows × window length; padded
slots excluded). No reference number exists (BASELINE.json:13
"published": {} — see BASELINE.md), so vs_baseline is reported against the
round-1 recorded values in BENCH_BASELINE.json when present, else 1.0.

Each record carries ``mfu_pct``: analytic model FLOPs per firm-month
(training ≈ 3× forward: fwd + ~2× backward) × measured throughput,
against the v5e bf16 peak (197 TFLOP/s). The LSTM forward per firm-month
is dominated by the hoisted input projection + recurrent matmul
(2·F·H + 16·H² FLOPs at gate width 4H).
"""

import io
import json
import os
import sys
import threading
import time

# Guards the preempted-watcher re-arm: the watchdog's fire path and
# main()'s finally can race, and a double re-arm would leave two watchers
# fighting over the serialized chip.
_REARM_LOCK = threading.Lock()

V5E_BF16_PEAK = 197e12  # FLOP/s per chip


def _lstm_train_flops_per_fm(hidden: int, features: int) -> float:
    """Training FLOPs per firm-month for the framework's LSTM: embed GEMM
    (F→H) + hoisted input projection (H→4H) + recurrent matmul (H→4H),
    each 2·in·out FLOPs per step; backward ≈ 2× forward. Head and
    elementwise gate math are O(H) noise and excluded."""
    fwd = 2 * features * hidden + 2 * hidden * 4 * hidden * 2
    return 3.0 * fwd


def _baseline(name: str) -> float:
    """Recorded baseline value for a metric (BENCH_BASELINE.json carries
    either the round-1 single-value form {"value": x} — the c2 metric —
    or a {metric: value} map)."""
    path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    try:
        with open(path) as fh:
            base = json.load(fh)
    except Exception:
        return 0.0
    if name in base:
        return float(base[name])
    if name == "train_throughput_c2_lstm":
        return float(base.get("value", 0.0))
    return 0.0


def persist_row(rec: dict) -> None:
    """Append a measured record to BENCH_ROWS.jsonl AT MEASUREMENT TIME.

    Round 3's lesson: campaign results only lived in a /tmp log plus a
    hand-updated BASELINE.md, so a mid-campaign re-wedge (or session end)
    would have lost every captured row. Now each record is durable the
    moment it exists; `scripts/regen_baseline.py` rebuilds BASELINE.md's
    measured table from this ledger. Never raises — a full disk or
    read-only checkout must not kill a measurement run holding scarce
    chip results in memory. No jax import/init here: in the wedged-tunnel
    path a backend query would itself hang at claim."""
    if os.environ.get("LFM_BENCH_NO_PERSIST") == "1":
        return
    path = os.environ.get("LFM_BENCH_ROWS") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_ROWS.jsonl")
    row = dict(rec)
    row.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    # Deliberately NO jax/backend query here — persist_row runs on the
    # watchdog's fire path while the main thread may be wedged INSIDE
    # backend init holding jax's _backend_lock; any backend call (even on
    # a "mostly initialized" registry) can block on that lock and break
    # the watchdog's os._exit contract. Callers that just finished a
    # measurement tag the backend themselves via _backend_name().
    try:
        with open(path, "a") as fh:
            fh.write(json.dumps(row) + "\n")
    except OSError as e:
        print(f"[bench] WARNING: could not persist row to {path}: {e}",
              file=sys.stderr, flush=True)


def _median(vals):
    """Middle-averaging median — the same protocol as
    measure_with_spread: a nearest-element "median" on an even rep
    count would just be the luckier rep."""
    vals = sorted(vals)
    mid = len(vals) // 2
    return (vals[mid] if len(vals) % 2
            else 0.5 * (vals[mid - 1] + vals[mid]))


def _backend_name() -> str:
    """The backend a JUST-COMPLETED measurement ran on. Only safe to call
    where a measurement has finished — the backend is initialized and
    idle, so default_backend() is a dictionary lookup, not an init that
    could hang at tunnel claim (see persist_row)."""
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — a tag, never worth crashing for
        return "unknown"


def _precision_name() -> str:
    """The resolved compute-precision lane a JUST-COMPLETED measurement
    ran under (LFM_PRECISION env resolution — stage-local config
    overrides tag their rows explicitly via the ``dtype`` extra). Pure
    env read, no jax import — safe on every emit path including the
    wedged-tunnel status records."""
    try:
        from lfm_quant_tpu.config import resolve_precision

        return resolve_precision()
    except Exception:  # noqa: BLE001 — a tag, never worth crashing for
        return "unknown"


def _flight_on() -> bool:
    """Whether the black-box flight recorder (DESIGN.md §21) was live
    for this row — a provenance tag, never worth crashing for."""
    try:
        from lfm_quant_tpu.utils import flight

        return flight.enabled()
    except Exception:  # noqa: BLE001
        return False


def _emit(metric: str, value: float, mfu_pct: float, **extras) -> None:
    base = _baseline(metric)
    rec = {
        "metric": metric,
        "value": round(value, 1),
        "unit": "firm-months/sec/chip",
        "vs_baseline": round(value / base, 3) if base > 0 else 1.0,
        "mfu_pct": round(mfu_pct, 2),
        "backend": _backend_name(),
        # Compute precision of the measurement (DESIGN.md §17): makes
        # mixed-precision rows distinguishable from the f32 trajectory
        # in the same ledger. Stages that flip the lane per phase
        # override this via an explicit ``dtype`` extra.
        "dtype": _precision_name(),
    }
    rec.update(extras)
    print(json.dumps(rec), flush=True)
    persist_row(rec)


_RTT_PROBE = None


def dispatch_rtt_ms(reps: int = 5):
    """Tunnel-health covariate: median round-trip of a tiny pre-compiled
    dispatch + scalar readback. The round-4 c2 captures drifted 41.7→55.4M
    between harness runs minutes apart with bit-identical geometry — the
    spread was attributed to tunnel/server state but nothing RECORDED it.
    Stamped on every measurement row, this lets a later analysis correlate
    throughput with tunnel latency instead of arguing about it. Cost: one
    tiny compile + ``reps`` ~25-30 ms round-trips. Never raises — a
    covariate must not kill a measurement run.

    PLACEMENT CONTRACT: call this BEFORE the measurement it annotates,
    never between a completed measurement and its persist_row — a
    post-measurement wedge inside this probe would hang/exit the process
    holding an unpersisted row, exactly the loss mode persist-at-
    measurement-time exists to prevent."""
    global _RTT_PROBE
    try:
        import jax
        import jax.numpy as jnp

        if _RTT_PROBE is None:
            # Compile once per process: the jit cache keys on shape/dtype,
            # but holding the pair explicitly documents that every call
            # after the first costs only ~reps round-trips (the first
            # costs one small tunnel compile).
            _RTT_PROBE = (jax.jit(lambda a: (a @ a).sum()),
                          jnp.ones((128, 128), jnp.bfloat16))
        f, x = _RTT_PROBE
        float(f(x))  # compile + first round-trip outside the timing
        vals = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(f(x))
            vals.append((time.perf_counter() - t0) * 1e3)
        vals.sort()
        return round(vals[len(vals) // 2], 2)
    except Exception:  # noqa: BLE001 — diagnostic only
        return None


def measure_with_spread(fn, outer_reps: int = 0):
    """Round-4 verdict (Weak #1): the same geometry measured 55.4M and
    41.7M fm/s minutes apart — absolute numbers need error bars. Run a
    complete measurement callable ``outer_reps`` times (each inner call
    keeps its own warmup/sync discipline untouched) and return
    ``(median, extras)`` where extras carries the spread AND the rtt_ms
    tunnel-latency covariate for the ledger row. The covariate is probed
    HERE, before the first measurement pass, so the placement contract
    (dispatch_rtt_ms docstring: never between a measurement and its
    persist) holds structurally at every call site — no row that rides
    this chokepoint can ship without it. LFM_BENCH_OUTER_REPS overrides
    (default 3; 1 = legacy single shot, no spread fields). The median is
    robust to one tunnel hiccup; the recorded spread keeps the headline
    honest."""
    outer_reps = outer_reps or int(os.environ.get("LFM_BENCH_OUTER_REPS",
                                                  "3"))
    rtt = dispatch_rtt_ms()
    vals = [fn() for _ in range(max(1, outer_reps))]
    vals.sort()
    med = vals[len(vals) // 2] if len(vals) % 2 else (
        0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]))
    extras = {"rtt_ms": rtt} if rtt is not None else {}
    # Always tag the rep count: the campaign's `--has n_reps` resume
    # guards key on the field's PRESENCE, so a deliberate single-shot
    # run (LFM_BENCH_OUTER_REPS=1) must satisfy them too.
    extras["n_reps"] = len(vals)
    if len(vals) >= 2:
        extras["spread_pct"] = round(100.0 * (vals[-1] - vals[0]) / med, 1)
        extras["rep_values"] = [round(v, 1) for v in vals]
    return med, extras


def measure_trainer(trainer, k: int = 30, reps: int = 3) -> float:
    """Measured training throughput (firm-months/sec) of a built Trainer:
    k steps of one epoch scanned inside a single jit dispatch — per-
    dispatch tunnel latency is excluded by design, and the final float()
    readback forces a true device sync (on the tunneled axon device,
    block_until_ready alone does not wait)."""
    import dataclasses as _dc

    state = trainer.init_state()
    b = trainer.train_sampler.stacked_epoch(0)
    k = min(k, b.firm_idx.shape[0])
    b = _dc.replace(b, firm_idx=b.firm_idx[:k], time_idx=b.time_idx[:k],
                    weight=b.weight[:k])
    fi, ti, w = trainer._batch_args(b, train=True, steps=True)
    fm = float(b.weight.sum()) * trainer.window

    # The multi-step wrapper DONATES its input state (train/reuse.py):
    # every dispatch, warmup included, must consume the PREVIOUS
    # dispatch's output — re-dispatching a donated state is an error.
    st, ms = trainer._jit_multi_step(state, trainer.dev, fi, ti, w)
    _ = float(ms["loss"][-1])  # warmup: compile + one full pass

    t0 = time.perf_counter()
    for _ in range(reps):
        st, ms = trainer._jit_multi_step(st, trainer.dev, fi, ti, w)
    _ = float(ms["loss"][-1])
    dt = (time.perf_counter() - t0) / reps
    return fm / dt


def measure_ensemble_trainer(trainer, k: int = 10, reps: int = 3) -> float:
    """measure_trainer's twin for an EnsembleTrainer: k vmapped steps of
    the [K, S, D, Bf] stacked epoch per dispatch, all seeds counted in the
    firm-month total, device sync via scalar readback (see
    measure_trainer's docstring for why)."""
    import numpy as np

    state = trainer.init_state()
    fi, ti, w = trainer._stacked_epoch(0)
    k = min(k, fi.shape[0])
    fi, ti, w = fi[:k], ti[:k], w[:k]
    fm = float(np.asarray(w).sum()) * trainer.window  # all seeds

    # Donation discipline: see measure_trainer — thread the returned
    # state, never re-dispatch a donated one.
    st, ms = trainer._jit_multi_step(state, trainer.dev, fi, ti, w)
    _ = float(np.asarray(ms["loss"])[-1].mean())  # warmup

    t0 = time.perf_counter()
    for _ in range(reps):
        st, ms = trainer._jit_multi_step(st, trainer.dev, fi, ti, w)
    _ = float(np.asarray(ms["loss"])[-1].mean())
    dt = (time.perf_counter() - t0) / reps
    return fm / dt


def eval_path(trainer) -> str:
    """Which eval dispatch measure_eval will time for this trainer —
    recorded in the bench row so a multi-chip capture says what it
    measured (the month-sharded and replicated paths are identical work
    on one chip but different programs under a data mesh)."""
    return ("month_sharded" if getattr(trainer, "_eval_sharded", False)
            else "replicated")


def measure_eval(trainer, reps: int = 5) -> float:
    """Inference/backtest-path throughput (firm-months/sec): the stacked
    cross-section eval sweep — EVERY val month's full cross-section in one
    dispatch, the same forward the backtest's predict path uses
    (SURVEY.md §4.3). Works for both Trainer ([M, bf] batch) and
    EnsembleTrainer (seed-vmapped forward; firm-months counted across the
    whole seed stack — per-chip ensemble inference). Under a data mesh the
    PRODUCTION path is the month-sharded _forward_eval — that is what gets
    timed there (round-3 advisor: timing the replicated forward would
    substantiate the wrong program on a multi-chip host). Sync discipline
    matches measure_trainer: scalar readback, not block_until_ready."""
    import numpy as np

    state = getattr(trainer, "state", None)
    params = state.params if state is not None else trainer.init_state().params
    b = trainer.val_sampler.stacked_cross_sections()
    fm = (float(b.weight.sum()) * trainer.window
          * getattr(trainer, "n_seeds", 1))

    if eval_path(trainer) == "month_sharded":
        # Hoist the one-time host prep (pad + device placement) out of the
        # timed loop — both branches must time ONLY queued dispatches.
        args = trainer._eval_batch_args(b)

        def run():
            pred, _, _ = trainer._jit_fwd_det(params, trainer.dev, *args)
            return pred
    else:
        # EnsembleTrainer delegates batch prep to its inner Trainer.
        fi, ti, w = getattr(trainer, "inner", trainer)._batch_args(b)

        def run():
            pred, _, _ = trainer._jit_forward(params, trainer.dev, fi, ti, w)
            return pred

    def sync(pred):
        return float(np.asarray(pred).ravel()[0])  # true device sync

    sync(run())  # warmup: compile + one full pass

    # Dispatches queue back-to-back; ONE readback at the end forces the
    # whole pipeline (per-dispatch sync would add ~25-30 ms of tunnel
    # latency to every rep — see measure_trainer).
    t0 = time.perf_counter()
    for _ in range(reps):
        pred = run()
    sync(pred)
    dt = (time.perf_counter() - t0) / reps
    return fm / dt


def _scan_impl_override(cfg):
    """LFM_BENCH_SCAN_IMPL=xla|pallas|pallas_fused overrides the RNN scan
    implementation — the on-chip validation/measurement hook for kernel
    variants (README "kernel caveat": new BlockSpecs/grids must run on a
    real chip once before they count)."""
    import dataclasses as _dc

    impl = os.environ.get("LFM_BENCH_SCAN_IMPL")
    if not impl:
        return cfg
    kw = dict(cfg.model.kwargs)
    kw["scan_impl"] = impl
    return _dc.replace(cfg, model=_dc.replace(cfg.model, kwargs=kw))


def bench_c2() -> None:
    from lfm_quant_tpu.config import get_preset
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    cfg = _scan_impl_override(get_preset("c2"))
    # Bench panel: full config-2 feature/window geometry, trimmed months so
    # panel generation isn't the bench bottleneck.
    d = cfg.data
    panel = synthetic_panel(
        n_firms=d.n_firms, n_months=240, n_features=d.n_features,
        horizon=d.horizon, seed=0,
    )
    splits = PanelSplits.by_date(panel, 198601, 198801)
    trainer = Trainer(cfg, splits)
    value, spread = measure_with_spread(lambda: measure_trainer(
        trainer, k=int(os.environ.get("LFM_BENCH_STEPS", "30"))))
    flops = _lstm_train_flops_per_fm(
        cfg.model.kwargs.get("hidden", 128), d.n_features)
    # RESOLVED impls, so A/B runs (LFM_BENCH_SCAN_IMPL / _GATHER_IMPL)
    # land on distinct ledger keys instead of overwriting each other.
    _emit("train_throughput_c2_lstm", value,
          100.0 * value * flops / V5E_BF16_PEAK,
          scan_impl=trainer.model.scan_impl,
          gather_impl=trainer._gather_impl, **spread)


def bench_c5_ensemble() -> None:
    import dataclasses as _dc

    from lfm_quant_tpu.config import get_preset
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    cfg = _scan_impl_override(get_preset("c5"))
    n_seeds = int(os.environ.get("LFM_BENCH_SEEDS", "16"))
    # LFM_BENCH_SEED_BLOCK: scan the seed stack in blocks of this size
    # (HBM-fit fallback for the full 64-seed stack on one chip).
    seed_block = int(os.environ.get("LFM_BENCH_SEED_BLOCK", "0"))
    cfg = _dc.replace(cfg, n_seeds=n_seeds, seed_block=seed_block)
    d = cfg.data
    # Full c5 firm cross-section (8000) and feature/window geometry;
    # months trimmed (throughput is O(batch), not O(panel), once the
    # panel is HBM-resident — and the tunnel transfer isn't the metric).
    panel = synthetic_panel(
        n_firms=d.n_firms, n_months=240, n_features=d.n_features,
        horizon=d.horizon, seed=0,
    )
    splits = PanelSplits.by_date(panel, 198601, 198801)
    trainer = EnsembleTrainer(cfg, splits)
    value, spread = measure_with_spread(lambda: measure_ensemble_trainer(
        trainer, k=int(os.environ.get("LFM_BENCH_STEPS", "10"))))
    # value counts all seeds; one chip hosts the whole seed stack.
    flops = _lstm_train_flops_per_fm(
        cfg.model.kwargs.get("hidden", 128), d.n_features)
    _emit("train_throughput_c5_ensemble", value,
          100.0 * value * flops / V5E_BF16_PEAK,
          n_seeds=n_seeds,
          per_seed_fm_s=round(value / n_seeds, 1),
          scan_impl=trainer.inner.model.scan_impl,
          gather_impl=trainer.inner._gather_impl,
          **({"seed_block": seed_block} if seed_block else {}),
          **spread)


def bench_walkforward_reuse() -> None:
    """walkforward_reuse — the cross-fold reuse layer's ledger metric:
    folds/hour at the WARM-fold rate plus compiles-per-fold, measured on
    a same-shape toy walk-forward (train/reuse.py).

    Each fold is timed as its own incremental ``run_walkforward`` call
    (``resume=True`` continues the sweep; the in-process program/panel
    caches persist across calls exactly as they do across folds), so the
    row separates fold 1 — which pays tracing, XLA compilation and the
    panel H2D once — from the warm folds that must pay neither:
    ``compiles_per_warm_fold`` and ``transfers_per_warm_fold`` are 0 by
    the reuse layer's contract (tests/test_reuse.py asserts it; this row
    MEASURES it per backend), and ``fold2_speedup`` is the wall-clock win
    the amortization argument predicts. Toy MLP geometry on purpose: the
    metric prices the FIXED costs, not model throughput — c2/c5 own that.
    """
    import shutil
    import tempfile

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.walkforward import run_walkforward

    n_folds = max(2, int(os.environ.get("LFM_BENCH_WF_FOLDS", "3")))
    cfg = RunConfig(
        name="wf_reuse_bench",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=2, warmup_steps=5, loss="mse"),
        seed=0,
    )
    panel = synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)
    rtt = dispatch_rtt_ms()
    out = tempfile.mkdtemp(prefix="lfm_wf_reuse_bench_")
    try:
        fold_s = []
        for k in range(1, n_folds + 1):
            t0 = time.perf_counter()
            _, _, summary = run_walkforward(
                cfg, panel, start=198001, step_months=12, val_months=24,
                n_folds=k, out_dir=out, resume=k > 1, train_months=72)
            fold_s.append(round(time.perf_counter() - t0, 2))
    finally:
        shutil.rmtree(out, ignore_errors=True)
    reuse = [r["reuse"] for r in summary["folds"]]
    warm, warm_s = reuse[1:], fold_s[1:]
    warm_rate = 3600.0 * len(warm_s) / max(sum(warm_s), 1e-9)
    extras = {
        "unit": "folds/hour",
        "n_folds": n_folds,
        "fold_s": fold_s,
        "fold2_speedup": round(fold_s[0] / max(fold_s[1], 1e-9), 2),
        "compiles_fold1": reuse[0]["jit_traces"],
        "compiles_per_warm_fold": round(
            sum(r["jit_traces"] for r in warm) / len(warm), 2),
        "transfers_per_warm_fold": round(
            sum(r["panel_transfers"] for r in warm) / len(warm), 2),
        "panel_mb": round(reuse[0]["panel_bytes"] / 2**20, 1),
    }
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("walkforward_reuse", warm_rate, 0.0, **extras)


def bench_walkforward_foldstack() -> None:
    """walkforward_foldstack — the fold-vectorized walk-forward metric:
    folds/hour with the whole sweep trained as ONE fold-stacked,
    pipelined program (train/foldstack.py, LFM_FOLDSTACK) vs the
    sequential per-fold fits, on the SAME fold set.

    Both passes run warm (a throwaway pass per mode first pays tracing /
    XLA compilation through the reuse caches), so the ratio prices
    exactly what fold-stacking removes: F-1 sequential walks through the
    per-epoch fixed costs — metric syncs (one per stacked epoch instead
    of one per fold-epoch), host sampling windows, dispatch latency —
    plus the mesh's idle fold axis. The stacked stitched forecasts are
    checked against the sequential ones (max_abs_diff on the row): the
    speedup must not come from computing something else. Toy MLP
    geometry on purpose — the metric prices SWEEP STRUCTURE, not model
    throughput (c2/c5 own that), which also makes the CPU fallback
    meaningful when the tunnel is wedged.
    """
    import shutil
    import tempfile

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.walkforward import run_walkforward

    n_folds = int(os.environ.get("LFM_BENCH_WF_STACK_FOLDS", "4"))
    n_epochs = int(os.environ.get("LFM_BENCH_WF_STACK_EPOCHS", "4"))
    if n_folds < 2 or n_epochs < 1:
        # Honor the operator's geometry; only the structural minimums
        # are enforced, loudly (stacking needs >= 2 folds to mean
        # anything, and a 0-epoch fit prices nothing).
        print(f"[bench] walkforward_foldstack geometry clamped: "
              f"folds {n_folds}->{max(2, n_folds)}, "
              f"epochs {n_epochs}->{max(1, n_epochs)}",
              file=sys.stderr, flush=True)
        n_folds, n_epochs = max(2, n_folds), max(1, n_epochs)
    cfg = RunConfig(
        name="wf_foldstack_bench",
        data=DataConfig(n_firms=100, n_months=240, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=n_epochs, warmup_steps=5,
                          early_stop_patience=n_epochs + 1, loss="mse"),
        seed=0,
    )
    panel = synthetic_panel(n_firms=100, n_months=240, n_features=5, seed=5)
    rtt = dispatch_rtt_ms()
    kw = dict(start=197801, step_months=12, val_months=24, n_folds=n_folds,
              train_months=72)

    def one(stacked: bool, out: str):
        t0 = time.perf_counter()
        fc, _, summary = run_walkforward(cfg, panel, out_dir=out,
                                         foldstack=stacked, **kw)
        return time.perf_counter() - t0, fc, summary

    root = tempfile.mkdtemp(prefix="lfm_wf_foldstack_bench_")
    try:
        # Warmup passes compile both modes' programs (shared reuse
        # caches); the timed passes then price the loop, not XLA.
        one(False, os.path.join(root, "wseq"))
        one(True, os.path.join(root, "wstk"))
        t_seq, fc_seq, _ = one(False, os.path.join(root, "seq"))
        t_stk, fc_stk, summary = one(True, os.path.join(root, "stk"))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    import numpy as np

    stack = summary.get("foldstack") or {}
    if not stack.get("enabled"):
        # The stacked pass silently degraded to the sequential path
        # (FoldstackUnavailable warning) — emitting would bank a
        # seq-vs-seq row indistinguishable from a real unsharded-stack
        # measurement. Fail through the bench_error record instead.
        raise RuntimeError(
            "fold-stacking degraded to the sequential path — no "
            "walkforward_foldstack metric to record")
    max_abs_diff = float(np.abs(fc_seq - fc_stk).max())
    if not (max_abs_diff <= 1e-4):
        # The speedup must come from removing fixed costs, not from
        # computing something else: the foldstack lane pins stacked
        # forecasts to sequential within float32 reduction-order
        # tolerance, and a row that fails that bound must not be banked
        # as a performance number. Inverted compare: a NaN diff (a
        # diverged fit) must fail CLOSED, and `nan > 1e-4` is False.
        raise RuntimeError(
            f"stacked forecasts diverged from sequential "
            f"(max_abs_diff={max_abs_diff:g} > 1e-4) — parity broken, "
            "row not recorded")
    extras = {
        "unit": "folds/hour",
        "n_folds": n_folds,
        "n_epochs": n_epochs,
        "seq_folds_per_hour": round(3600.0 * n_folds / max(t_seq, 1e-9), 1),
        "speedup": round(t_seq / max(t_stk, 1e-9), 2),
        "seq_s": round(t_seq, 2),
        "stack_s": round(t_stk, 2),
        "fold_mesh": stack.get("fold_mesh"),
        "max_abs_diff": max_abs_diff,
    }
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("walkforward_foldstack", 3600.0 * n_folds / max(t_stk, 1e-9),
          0.0, **extras)


def bench_config_sweep() -> None:
    """config_sweep — the stacked-run engine's hyperparameter-grid
    metric: configs/hour with the whole LR × weight-decay grid trained
    as ONE stacked compiled program (train/stacked.py ``StackedRuns``,
    per-config hyperparameters as vmapped per-run operands) vs warm
    sequential per-config fits on the SAME grid.

    Both passes run warm (a throwaway pass per mode first pays tracing /
    XLA compilation through the reuse caches — note the sequential mode
    compiles once PER CONFIG: lr/weight_decay are baked constants in
    ``trainer_program_key``, which is exactly the fixed cost the operand
    threading removes), so the timed ratio prices the per-config fixed
    costs the stack amortizes: R-1 walks through per-epoch sampling
    windows, dispatch latency and metric syncs (one per stacked epoch
    instead of one per config-epoch) plus trainer construction. The
    stacked per-config best val ICs are parity-checked against the
    sequential ones first (bit-equal on a pure-vmap stack; ≤1e-4 under
    a stack mesh, the sharded reduction-order allowance — the test
    lanes own the strict bit-identity contract) — the speedup must not
    come from computing something else. Median-of-3 per the BASELINE.md
    error-bar protocol.
    Toy MLP geometry on purpose — the metric prices SWEEP STRUCTURE,
    not model throughput, which also makes the CPU fallback meaningful
    when the tunnel is wedged.
    """
    import shutil
    import tempfile

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.stacked import run_config_sweep

    n_epochs = int(os.environ.get("LFM_BENCH_SWEEP_EPOCHS", "4"))
    n_lr = int(os.environ.get("LFM_BENCH_SWEEP_LRS", "4"))
    n_wd = int(os.environ.get("LFM_BENCH_SWEEP_WDS", "2"))
    n_epochs, n_lr, n_wd = max(1, n_epochs), max(2, n_lr), max(1, n_wd)
    grid = [{"lr": 1e-3 * (0.5 ** i), "weight_decay": 1e-4 * (0.1 ** j)}
            for i in range(n_lr) for j in range(n_wd)]
    cfg = RunConfig(
        name="config_sweep_bench",
        data=DataConfig(n_firms=100, n_months=240, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=n_epochs, warmup_steps=5,
                          early_stop_patience=n_epochs + 1, loss="mse"),
        seed=0,
    )
    panel = synthetic_panel(n_firms=100, n_months=240, n_features=5, seed=5)
    R = len(grid)

    def one(stacked: bool, out: str):
        t0 = time.perf_counter()
        summary = run_config_sweep(cfg, grid, panel=panel, out_dir=out,
                                   stacked=stacked)
        return time.perf_counter() - t0, summary

    root = tempfile.mkdtemp(prefix="lfm_config_sweep_bench_")
    try:
        # Warmup passes compile both modes' programs (shared reuse
        # caches; the sequential pass caches all R per-config bundles);
        # the timed passes then price the loop, not XLA.
        one(False, os.path.join(root, "wseq"))
        _, warm_stk = one(True, os.path.join(root, "wstk"))
        if not (warm_stk.get("stacked") or {}).get("enabled"):
            # The stacked pass silently degraded to the sequential path
            # — emitting would bank a seq-vs-seq row indistinguishable
            # from a real measurement.
            raise RuntimeError(
                "config-sweep stacking degraded to the sequential path "
                "— no config_sweep metric to record")
        rtt = dispatch_rtt_ms()
        reps = max(1, int(os.environ.get("LFM_BENCH_OUTER_REPS", "3")))
        pairs = []
        for r in range(reps):
            t_seq, sum_seq = one(False, os.path.join(root, f"seq{r}"))
            t_stk, sum_stk = one(True, os.path.join(root, f"stk{r}"))
            ics_seq = [x["best_val_ic"] for x in sum_seq["runs"]]
            ics_stk = [x["best_val_ic"] for x in sum_stk["runs"]]
            if ics_seq != ics_stk:
                # Shards=auto may legitimately differ at last-ulp under
                # a stack mesh; anything beyond that is a parity break
                # that must not be banked as a performance number.
                import numpy as np

                diff = float(np.max(np.abs(
                    np.asarray(ics_seq) - np.asarray(ics_stk))))
                # Inverted compare: a NaN diff (diverged grid point)
                # must fail CLOSED — `nan > 1e-4` is False.
                if not (diff <= 1e-4):
                    raise RuntimeError(
                        f"stacked sweep diverged from sequential "
                        f"(max_abs_diff={diff:g} > 1e-4) — parity "
                        "broken, row not recorded")
            pairs.append((t_seq, t_stk))
        last_stack = sum_stk["stacked"]
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # Each mode gets its OWN median — pairing them by rep would let one
    # transient hiccup on the seq side inflate the banked speedup.
    t_seq = _median(p[0] for p in pairs)
    t_stk = _median(p[1] for p in pairs)
    rates = sorted(3600.0 * R / max(p[1], 1e-9) for p in pairs)
    med_rate = 3600.0 * R / max(t_stk, 1e-9)
    extras = {
        "unit": "configs/hour",
        "n_configs": R,
        "n_epochs": n_epochs,
        "seq_configs_per_hour": round(3600.0 * R / max(t_seq, 1e-9), 1),
        "speedup": round(t_seq / max(t_stk, 1e-9), 2),
        "seq_s": round(t_seq, 2),
        "stack_s": round(t_stk, 2),
        "stack_mesh": last_stack.get("stack_mesh"),
        "stack_block": last_stack.get("stack_block"),
        "n_reps": len(pairs),
    }
    if len(rates) >= 2:
        extras["spread_pct"] = round(
            100.0 * (rates[-1] - rates[0]) / max(med_rate, 1e-9), 1)
        extras["rep_values"] = [round(v, 1) for v in rates]
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("config_sweep", med_rate, 0.0, **extras)


def bench_bucketed_train() -> None:
    """bucketed_train — the geometry-bucket metric (LFM_BUCKETS,
    DESIGN.md §16): epochs/hour with training batches quantized to the
    (lookback-rows × cross-section-width) ladder vs max-shape padding,
    on a synthetic MIXED-GEOMETRY panel, plus the padded-FLOP fraction
    each mode dispatches.

    The panel stitches two regimes: a LARGE universe (wide
    cross-sections, deep history) over the first ``cut`` months, then a
    SMALL-CAP SHORT-HISTORY cohort (few firms, all listed at ``cut``)
    over the rest — so max-shape padding bills every cohort batch at
    the large universe's width and the full lookback window, which is
    exactly the tax ROADMAP item 5a describes for international /
    small-cap / short-history panels. Bucketed mode trains the SAME
    anchor set (different batch grouping — that is the Khomenko trade),
    so the ratio prices geometry, not data. A GRU model on purpose: the
    lookback rung savings scale the serial scan, not just the GEMM
    width. Parity gate: the bucketed PREDICT of the max-shape-trained
    params must be BIT-identical to the max-shape sweep before any row
    is recorded (the speedup must not come from computing something
    else). Median-of-3 per BASELINE.md; CPU fallback when the tunnel is
    wedged (the metric prices padding structure, not chips).
    """
    import dataclasses as _dc

    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.data.panel import PanelSplits
    from lfm_quant_tpu.train import reuse
    from lfm_quant_tpu.train.loop import Trainer
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS
    from lfm_quant_tpu.utils.telemetry import COUNTERS

    n_epochs = int(os.environ.get("LFM_BENCH_BUCKET_EPOCHS", "3"))
    n_big = int(os.environ.get("LFM_BENCH_BUCKET_BIG", "96"))
    n_small = int(os.environ.get("LFM_BENCH_BUCKET_SMALL", "24"))
    n_months, cut, window = 120, 84, 24

    base = synthetic_panel(n_firms=n_big + n_small, n_months=n_months,
                           n_features=5, seed=7, min_history=24)
    valid = base.valid.copy()
    valid[:n_big, cut:] = False    # large universe delists at the cut
    valid[n_big:, :cut] = False    # small-cap cohort lists AT the cut
    tv = base.target_valid & valid
    h = base.horizon
    tv[:, :-h] &= valid[:, h:]     # target month must still be listed
    rv = base.ret_valid
    if rv is not None:
        rv = rv & valid
        rv[:, :-1] &= valid[:, 1:]
    panel = _dc.replace(base, valid=valid, target_valid=tv, ret_valid=rv)

    cfg = RunConfig(
        name="bucketed_train_bench",
        data=DataConfig(n_firms=n_big + n_small, n_months=n_months,
                        n_features=5, window=window, dates_per_batch=4,
                        firms_per_date=n_big, min_valid_months=8),
        model=ModelConfig(kind="gru", kwargs={"hidden": 16}),
        optim=OptimConfig(lr=1e-3, epochs=n_epochs, warmup_steps=5,
                          early_stop_patience=n_epochs + 1, loss="mse"),
        seed=0,
    )
    splits = PanelSplits.by_date(panel, int(panel.dates[70]),
                                 int(panel.dates[94]))

    prev = os.environ.get("LFM_BUCKETS")

    def one(bucketed: bool):
        os.environ["LFM_BUCKETS"] = "1" if bucketed else "0"
        try:
            tr = Trainer(cfg, splits)
            t0 = time.perf_counter()
            tr.fit()
            return time.perf_counter() - t0, tr
        finally:
            if prev is None:
                os.environ.pop("LFM_BUCKETS", None)
            else:
                os.environ["LFM_BUCKETS"] = prev

    try:
        # Warmup passes compile both modes' programs through the shared
        # reuse caches; the timed passes then price the loop, not XLA.
        _, tr_max = one(False)
        snap = REUSE_COUNTERS.snapshot()
        cnt0 = {k: COUNTERS.get(k) for k in
                ("bucket_cells_dispatched", "bucket_cells_real",
                 "bucket_cells_max_shape")}
        _, tr_bkt = one(True)
        if REUSE_COUNTERS.delta(snap).get("panel_transfers"):
            raise RuntimeError(
                "bucketed warmup re-transferred the panel — the "
                "residency-cache contract broke; row not recorded")
        cnt = {k: COUNTERS.get(k) - cnt0[k] for k in cnt0}
        # Parity gate: same params, bucketed vs max-shape inference.
        tr_bkt.state = tr_max.state
        os.environ["LFM_BUCKETS"] = "1"
        try:
            pred_b, valid_b = tr_bkt.predict()
        finally:
            if prev is None:
                os.environ.pop("LFM_BUCKETS", None)
            else:
                os.environ["LFM_BUCKETS"] = prev
        pred_m, valid_m = tr_max.predict()
        if not (np.array_equal(pred_b, pred_m)
                and np.array_equal(valid_b, valid_m)):
            raise RuntimeError(
                "bucketed predict diverged from the max-shape sweep — "
                "parity broken, row not recorded")
        rtt = dispatch_rtt_ms()
        reps = max(1, int(os.environ.get("LFM_BENCH_OUTER_REPS", "3")))
        pairs = []
        for _ in range(reps):
            t_max, _ = one(False)
            t_bkt, _ = one(True)
            pairs.append((t_max, t_bkt))
    finally:
        reuse.clear_program_cache()

    t_max = _median(p[0] for p in pairs)
    t_bkt = _median(p[1] for p in pairs)
    rates = sorted(3600.0 * n_epochs / max(p[1], 1e-9) for p in pairs)
    med_rate = 3600.0 * n_epochs / max(t_bkt, 1e-9)
    # Padded-FLOP fractions: bucketed from the per-epoch counters; the
    # max-shape twin from one host-side stacked epoch (weights are
    # deterministic in (seed, epoch)).
    disp_b, real_b = cnt["bucket_cells_dispatched"], cnt["bucket_cells_real"]
    b0 = tr_max.train_sampler.stacked_epoch(0)
    k, d, bf = b0.firm_idx.shape
    disp_m = k * d * bf * window
    real_m = float(b0.weight.sum()) * window
    extras = {
        "unit": "epochs/hour",
        "n_epochs": n_epochs,
        "max_shape_epochs_per_hour": round(
            3600.0 * n_epochs / max(t_max, 1e-9), 1),
        "speedup": round(t_max / max(t_bkt, 1e-9), 3),
        "padded_flop_fraction_bucketed": (
            round(1.0 - real_b / disp_b, 4) if disp_b else None),
        "padded_flop_fraction_max_shape": round(1.0 - real_m / disp_m, 4),
        "cells_saved_vs_max_shape": (
            round(1.0 - disp_b / cnt["bucket_cells_max_shape"], 4)
            if cnt["bucket_cells_max_shape"] else None),
        "ladder": tr_bkt.train_sampler.bucket_geometry().summary(
            cfg.data.dates_per_batch)["ladder"],
        "max_s": round(t_max, 2),
        "bucketed_s": round(t_bkt, 2),
        "n_reps": len(pairs),
    }
    if len(rates) >= 2:
        extras["spread_pct"] = round(
            100.0 * (rates[-1] - rates[0]) / max(med_rate, 1e-9), 1)
        extras["rep_values"] = [round(v, 1) for v in rates]
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("bucketed_train", med_rate, 0.0, **extras)


def bench_mixed_precision() -> None:
    """mixed_precision — the LFM_PRECISION lane metric (DESIGN.md §17):
    epochs/hour and measured params/panel/opt-state bytes with the
    whole-stack bf16 lane ON vs the f32 reference, on the same panel and
    seeds.

    What the row must prove, each gated before anything is recorded:

    * **footprint** — the resident working set (master params + Adam
      moments + packed device panel) drops ≥1.8× measured from the live
      arrays' avals, AND the ledger's ``arg_bytes`` for the traced
      multi-step program shrinks (the 2× panel drop seen by the actual
      compiled dispatch — "ledger-verified"). Params and moments bytes
      are reported UNCHANGED on purpose: equal numbers are the
      masters-stay-f32 invariant made visible; the reduction comes from
      the panel, which dominates any production working set and every
      serve-zoo residency budget.
    * **parity** — best val IC within the pre-registered tolerance
      (``LFM_BENCH_AMP_IC_TOL``, default 0.02) of the f32 fit, with the
      early-stop DECISIONS exact (same best epoch, same stop epoch):
      f32 reductions + f32 head boundary keep decision numerics off the
      bf16 path entirely.
    * **reuse** — warm bf16 fits pay zero jit traces and zero panel H2D
      (the reuse-lane contract with the knob ON).

    Median-of-3 per BASELINE.md. CPU fallback when the tunnel is wedged:
    the footprint/parity/reuse halves are backend-independent;
    epochs/hour on CPU prices loop structure only (XLA CPU emulates
    bf16, so the speed column is a real-chip claim — the row's backend
    says which it was)."""
    import jax
    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.data.panel import PanelSplits
    from lfm_quant_tpu.train import reuse
    from lfm_quant_tpu.utils import telemetry
    from lfm_quant_tpu.train.loop import Trainer
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    n_epochs = int(os.environ.get("LFM_BENCH_AMP_EPOCHS", "8"))
    ic_tol = float(os.environ.get("LFM_BENCH_AMP_IC_TOL", "0.02"))
    cfg = RunConfig(
        name="mixed_precision_bench",
        data=DataConfig(n_firms=400, n_months=160, n_features=20,
                        window=12, dates_per_batch=4, firms_per_date=64),
        model=ModelConfig(kind="gru", kwargs={"hidden": 8}),
        optim=OptimConfig(lr=1e-3, epochs=n_epochs, warmup_steps=5,
                          early_stop_patience=2, loss="mse"),
        seed=0,
    )
    panel = synthetic_panel(n_firms=400, n_months=160, n_features=20,
                            seed=11)
    splits = PanelSplits.by_date(panel, int(panel.dates[100]),
                                 int(panel.dates[124]))

    def tree_bytes(tree):
        return int(sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(tree)
                       if hasattr(x, "size") and hasattr(x, "dtype")))

    prev = os.environ.get("LFM_PRECISION")

    def lane(precision: str):
        """One precision lane: warmup fit (compiles), timed warm fits,
        byte accounting, ledger arg_bytes of the traced multi-step."""
        os.environ["LFM_PRECISION"] = precision
        try:
            led0 = len(telemetry.program_ledger())
            tr = Trainer(cfg, splits)
            summary = tr.fit()  # warmup: compile + the parity fit
            multi = [e for e in telemetry.program_ledger()[led0:]
                     if e["program"].startswith("multi_step")]
            arg_bytes = max((e.get("arg_bytes") or 0) for e in multi) \
                if multi else None
            snap = REUSE_COUNTERS.snapshot()
            times = []
            reps = max(1, int(os.environ.get("LFM_BENCH_OUTER_REPS", "3")))
            for _ in range(reps):
                t0 = time.perf_counter()
                tr.fit()
                times.append(time.perf_counter() - t0)
            warm = REUSE_COUNTERS.delta(snap)
            return {
                "summary": summary,
                "times": times,
                "warm_traces": int(warm.get("jit_traces", 0)),
                "warm_h2d": int(warm.get("panel_transfers", 0)),
                "params_bytes": tree_bytes(tr.state.params),
                "opt_bytes": tree_bytes(tr.state.opt_state),
                "panel_bytes": tree_bytes(tr.dev),
                "arg_bytes": arg_bytes,
                "epochs_run": summary["epochs_run"],
            }
        finally:
            if prev is None:
                os.environ.pop("LFM_PRECISION", None)
            else:
                os.environ["LFM_PRECISION"] = prev

    rtt = dispatch_rtt_ms()
    try:
        f32 = lane("f32")
        b16 = lane("bf16")
    finally:
        reuse.clear_program_cache()

    # ---- gates (nothing recorded unless every one holds) -------------
    s32, s16 = f32["summary"], b16["summary"]
    if (s16["best_epoch"] != s32["best_epoch"]
            or s16["epochs_run"] != s32["epochs_run"]):
        raise RuntimeError(
            f"mixed-precision early-stop decisions diverged from f32 "
            f"(best {s32['best_epoch']}→{s16['best_epoch']}, stop "
            f"{s32['epochs_run']}→{s16['epochs_run']}) — row not recorded")
    ic_diff = abs(float(s16["best_val_ic"]) - float(s32["best_val_ic"]))
    if not np.isfinite(ic_diff) or ic_diff > ic_tol:
        raise RuntimeError(
            f"mixed-precision val IC off by {ic_diff:.4f} > tol {ic_tol} "
            "— row not recorded")
    if b16["warm_traces"] or b16["warm_h2d"]:
        raise RuntimeError(
            f"warm bf16 fits paid {b16['warm_traces']} traces / "
            f"{b16['warm_h2d']} panel H2D — reuse contract broke with "
            "LFM_PRECISION=bf16; row not recorded")
    if b16["params_bytes"] != f32["params_bytes"] \
            or b16["opt_bytes"] != f32["opt_bytes"]:
        raise RuntimeError(
            "master params / optimizer moments changed size under bf16 "
            "— the f32-masters invariant broke; row not recorded")
    tot32 = f32["params_bytes"] + f32["opt_bytes"] + f32["panel_bytes"]
    tot16 = b16["params_bytes"] + b16["opt_bytes"] + b16["panel_bytes"]
    reduction = tot32 / max(tot16, 1)
    if reduction < 1.8:
        raise RuntimeError(
            f"measured footprint reduction {reduction:.2f}x < 1.8x — "
            "row not recorded")
    if (f32["arg_bytes"] and b16["arg_bytes"]
            and not b16["arg_bytes"] < f32["arg_bytes"]):
        raise RuntimeError(
            "ledger arg_bytes did not shrink under bf16 — the compiled "
            "dispatch never saw the footprint drop; row not recorded")

    t16 = _median(b16["times"])
    t32 = _median(f32["times"])
    rates = sorted(3600.0 * b16["epochs_run"] / max(t, 1e-9)
                   for t in b16["times"])
    med_rate = 3600.0 * b16["epochs_run"] / max(t16, 1e-9)
    extras = {
        "unit": "epochs/hour",
        "dtype": "bf16",  # the lane measured; the f32 twin is below
        "n_epochs": b16["epochs_run"],
        "f32_epochs_per_hour": round(
            3600.0 * f32["epochs_run"] / max(t32, 1e-9), 1),
        "speedup_vs_f32": round(t32 / max(t16, 1e-9), 3),
        "bytes_reduction": round(reduction, 3),
        "params_bytes": f32["params_bytes"],          # equal by gate —
        "opt_state_bytes": f32["opt_bytes"],          # f32 masters
        "panel_bytes_f32": f32["panel_bytes"],
        "panel_bytes_bf16": b16["panel_bytes"],
        "ledger_arg_bytes_f32": f32["arg_bytes"],
        "ledger_arg_bytes_bf16": b16["arg_bytes"],
        "best_val_ic_f32": round(float(s32["best_val_ic"]), 5),
        "best_val_ic_bf16": round(float(s16["best_val_ic"]), 5),
        "ic_diff": round(ic_diff, 5),
        "ic_tol": ic_tol,
        "best_epoch": s16["best_epoch"],
        "early_stop_epochs_run": s16["epochs_run"],
        "warm_traces_bf16": b16["warm_traces"],
        "warm_panel_h2d_bf16": b16["warm_h2d"],
        "n_reps": len(b16["times"]),
    }
    if len(rates) >= 2:
        extras["spread_pct"] = round(
            100.0 * (rates[-1] - rates[0]) / max(med_rate, 1e-9), 1)
        extras["rep_values"] = [round(v, 1) for v in rates]
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("mixed_precision", med_rate, 0.0, **extras)


def _cpu_metric_fallback(flag: str, budget_s: float) -> bool:
    """Wedged-tunnel fallback for a backend-independent metric: the
    quantities walkforward_reuse (compiles/transfers per warm fold) and
    scoring_pipeline (fused-vs-host-loop months/sec ratio) price are
    meaningful on any backend, so when the axon tunnel is wedged the row
    is measured in a CPU SUBPROCESS (JAX_PLATFORMS=cpu; jax must not be
    imported in the wedged parent — see _tunnel_probe) instead of being
    lost with the throughput metrics. The child persists its own row
    (tagged backend=cpu by _backend_name) and its stdout is forwarded so
    the driver's tail parse sees it before the terminal tunnel_wedged
    status. Returns True when the child produced a row; failures never
    mask the outage path."""
    import subprocess

    if budget_s < 30:
        return False
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # The wedge is in the tunneled backend plugin; a forced-CPU child
    # must not inherit a half-claimed device.
    env.pop("LFM_BENCH_SKIP_PROBE", None)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), flag],
            env=env, capture_output=True, text=True,
            timeout=min(budget_s, 240))
    except Exception as e:  # noqa: BLE001 — a salvage attempt must never
        # replace the terminal tunnel_wedged record with bench_error
        # (test_bench_wedged_tunnel_emits_status_record pins this).
        print(f"[bench] CPU {flag} fallback failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
        return False
    sys.stdout.write(out.stdout)
    sys.stdout.flush()
    if out.returncode != 0:
        print(f"[bench] CPU {flag} fallback failed: "
              f"{out.stderr.strip()[-300:]}", file=sys.stderr, flush=True)
    return out.returncode == 0 and bool(out.stdout.strip())


def bench_scoring_pipeline() -> None:
    """scoring_pipeline — the device-resident scoring metric: months/sec
    through the WHOLE serving path (MC-dropout predict → multi-mode
    aggregate → backtest) on the fused engine vs the host-loop baseline,
    plus MC samples/sec for the sampling stage alone.

    The fused path (this PR's tentpole) runs K=16 MC samples as ONE
    vmapped dispatch with ONE D2H, aggregates every (mode, λ) from one
    stacked tensor in one dispatch, and backtests all modes × all months
    in one vmapped core dispatch (backtest/jax_engine.py). The baseline
    is the serial host loop it replaces: K separate forward dispatches,
    one numpy aggregate + one ``for t in range(T)`` numpy backtest per
    mode — the pre-PR serving path, with its per-sample scatter already
    vectorized so the comparison prices dispatch/loop structure, not the
    old scatter bug. Both paths produce identical reports (parity suite),
    so months/sec is an apples-to-apples rate: scored backtest months ×
    aggregation modes per second of end-to-end pipeline time. The toy
    model is small ON PURPOSE: the metric prices the scoring loop, not
    model FLOPs — c2/c5 own model throughput."""
    import time as _time

    from lfm_quant_tpu.backtest import aggregate_ensemble, run_backtest
    from lfm_quant_tpu.backtest.jax_engine import run_scoring_pipeline
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    # 660 months ≈ the reference lineage's 1970–2024 span; the universe
    # is toy-sized for the same reason the model is (the metric prices
    # the scoring loop, whose host cost is per-month Python overhead,
    # not cross-section width).
    n_months = int(os.environ.get("LFM_BENCH_SCORE_MONTHS", "660"))
    n_firms = int(os.environ.get("LFM_BENCH_SCORE_FIRMS", "64"))
    mc_k = int(os.environ.get("LFM_BENCH_MC_SAMPLES", "16"))
    reps = max(1, int(os.environ.get("LFM_BENCH_OUTER_REPS", "3")))
    cfg = RunConfig(
        name="scoring_bench",
        data=DataConfig(n_firms=n_firms, n_months=n_months, n_features=5,
                        window=6, dates_per_batch=8, firms_per_date=64),
        model=ModelConfig(kind="mlp",
                          kwargs={"hidden": (8,), "dropout": 0.1}),
        optim=OptimConfig(lr=1e-3, epochs=1, warmup_steps=1, loss="mse"),
        seed=0,
    )
    panel = synthetic_panel(n_firms=n_firms, n_months=n_months,
                            n_features=5, seed=7)
    # Test range = the scored OOS block (~60% of the panel — the
    # 171-month serving sweep's shape at toy scale).
    splits = PanelSplits.by_date(panel, int(panel.dates[n_months // 4]),
                                 int(panel.dates[n_months * 2 // 5]))
    trainer = Trainer(cfg, splits)
    trainer.state = trainer.init_state()  # prices the pipeline, not fit
    modes = [("mean", 1.0)] + [("mean_minus_std", lam)
                               for lam in (0.25, 0.5, 1.0, 2.0, 4.0)]
    bt_kw = dict(quantile=0.1, min_universe=20)

    rtt = dispatch_rtt_ms()  # covariate BEFORE measuring (contract)

    def fused_pass():
        t0 = _time.perf_counter()
        stacked, valid = trainer.predict("test", mc_samples=mc_k, mc_seed=0,
                                         mc_batched=True)
        t_mc = _time.perf_counter() - t0
        reports = run_scoring_pipeline(stacked, valid, panel, modes=modes,
                                       **bt_kw)
        dt = _time.perf_counter() - t0
        rep = next(iter(reports.values()))
        return rep.n_months * len(modes) / dt, mc_k / t_mc, rep

    def host_pass():
        t0 = _time.perf_counter()
        stacked, valid = trainer.predict("test", mc_samples=mc_k, mc_seed=0,
                                         mc_batched=False)
        t_mc = _time.perf_counter() - t0
        for mode, lam in modes:
            fc, v = aggregate_ensemble(stacked, valid, mode, lam)
            rep = run_backtest(fc, v, panel, **bt_kw)
        dt = _time.perf_counter() - t0
        return rep.n_months * len(modes) / dt, mc_k / t_mc, rep

    fused_pass()  # warmup: MC vmap + aggregate + core compiles
    host_pass()   # warmup: the per-sample forward trace
    by_rate = lambda r: r[0]  # noqa: E731 — reports aren't orderable
    # BEST-of-reps on BOTH paths (timeit's convention): the fused pass is
    # ~100 ms, so on a shared host a single scheduler hiccup halves its
    # median while barely denting the ~1 s host pass — min prices the
    # intrinsic cost symmetrically. The recorded per-rep rates keep the
    # spread honest.
    fused_reps = sorted((fused_pass() for _ in range(reps)), key=by_rate)
    host_reps = sorted((host_pass() for _ in range(reps)), key=by_rate)
    fused, host = fused_reps[-1], host_reps[-1]
    extras = {
        "unit": "months/sec",
        "host_months_per_sec": round(host[0], 1),
        "speedup": round(fused[0] / max(host[0], 1e-9), 2),
        "mc_samples_per_sec": round(fused[1], 1),
        "mc_samples_per_sec_host": round(host[1], 1),
        "mc_samples": mc_k,
        "mc_dispatches_fused": 1,
        "n_modes": len(modes),
        "n_months_scored": fused[2].n_months * len(modes),
        "n_firms": n_firms,
        "n_reps": reps,
        "rep_values": [round(r[0], 1) for r in fused_reps],
        "host_rep_values": [round(r[0], 1) for r in host_reps],
    }
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("scoring_pipeline", fused[0], 0.0, **extras)


def bench_serve() -> None:
    """serve — the always-on scoring-service metric: sustained scoring
    requests/sec through the zoo + micro-batcher + compiled-core path
    (lfm_quant_tpu/serve/), plus the latency (p50/p99 ms) and batch-
    occupancy distribution and the STEADY-STATE compile count (jit
    traces + panel H2D after warmup — the serving contract is both are
    ZERO; a non-zero value in this row is a regression, not noise).
    Mixed-shape traffic on purpose: universes with distinct
    cross-section sizes and lookbacks exercise the request-shape bucket
    ladder, which is what makes arbitrary queries compile-free. Toy
    models/universes on purpose: the metric prices the SERVING LOOP
    (queueing, coalescing, padding, dispatch, D2H, fan-out), not model
    FLOPs — c2/c5 own model throughput, scoring_pipeline owns the
    batch path. The p50/p99 in the row are cross-checked at measurement
    time against scripts/trace_report.py's rollup of the same run dir
    (same per-request latency_ms values — the agreement is a pinned
    contract, reported in the row as trace_p50_diff_pct: percent
    DISAGREEMENT, 0.0 = exact reproduction, the serve lane pins <=1)."""
    import shutil
    import tempfile

    import serve as serve_mod
    from lfm_quant_tpu.serve import ScoringService
    from lfm_quant_tpu.utils import telemetry
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    n_requests = int(os.environ.get("LFM_BENCH_SERVE_REQUESTS", "300"))
    n_threads = int(os.environ.get("LFM_BENCH_SERVE_THREADS", "4"))
    n_universes = int(os.environ.get("LFM_BENCH_SERVE_UNIVERSES", "3"))
    reps = max(1, int(os.environ.get("LFM_BENCH_OUTER_REPS", "3")))
    rtt = dispatch_rtt_ms()  # covariate BEFORE measuring (contract)
    run_dir = tempfile.mkdtemp(prefix="lfm_serve_bench_")
    try:
        svc = ScoringService()
        for name, (trainer, _) in serve_mod.build_universes(
                n_universes, train_epochs=0).items():
            svc.register(name, trainer)  # warm: compiles every bucket

        drive_errors: list = []

        def drive() -> float:
            # serve.py's closed-loop client driver IS the load pattern
            # (one implementation — the bench row and the demo cannot
            # drift apart on it); errors are tallied, not swallowed: a
            # dead client thread would otherwise leave its claimed
            # requests unserved while the row still reported
            # n_requests/elapsed as throughput.
            wall, errors, _ = serve_mod.drive_load(svc, n_requests,
                                                   n_threads)
            drive_errors.extend(errors)
            return n_requests / wall

        drive()  # warmup rep: first D2H/readback paths settle
        # Steady state begins HERE: counters snapshotted, the rolling
        # stats window zeroed (warmup errors dropped with it), and the
        # telemetry run attached — so the row's percentiles, errors,
        # spans in the run dir, and compile/H2D deltas all cover
        # exactly the timed reps (which is also what makes the
        # trace_report cross-check below exact).
        svc.batcher.reset_stats()
        drive_errors.clear()
        # Zero the live metrics plane at the steady-state line too, so
        # the saved /metrics scrape below covers exactly the timed
        # window the spans cover (what makes the trace_report metrics
        # cross-check exact).
        from lfm_quant_tpu.utils import metrics as metrics_mod
        from lfm_quant_tpu.utils.metrics import METRICS

        METRICS.reset()
        # The absorbed telemetry counters are process-LIFETIME — delta
        # them at the same line, or the scrape's shed/retry/breaker
        # totals would include warmup-era events the run's spans never
        # saw and the trace_report cross-check would cry mismatch on a
        # healthy run.
        counters_base = telemetry.COUNTERS.snapshot()
        snap = REUSE_COUNTERS.snapshot()
        with telemetry.run_scope(run_dir, extra={"entry": "bench_serve"}):
            rates = sorted(drive() for _ in range(reps))
            # Save the final scrape beside the spans: trace_report's
            # `metrics` section cross-checks it against the
            # span-derived request count / p99 (1% / one-bucket
            # contract).
            svc.monitor.collect()
            counters_delta = {
                k: v - counters_base.get(k, 0)
                for k, v in telemetry.COUNTERS.snapshot().items()
                if isinstance(v, (int, float))}
            with open(os.path.join(run_dir, "metrics.prom"), "w") as fh:
                fh.write(metrics_mod.render_prometheus(
                    METRICS, counters=counters_delta))
        steady = REUSE_COUNTERS.delta(snap)
        stats = svc.stats()
        n_request_errors = len(drive_errors)
        # Metrics-overhead A/B (the <2% contract, DESIGN.md §19):
        # median req/s with the live metrics plane OFF vs ON — the
        # recording path is O(1) per event behind one env read, and
        # this prices that claim on every row.
        prev_metrics = os.environ.get("LFM_METRICS")
        ratios, off_rates, on_rates = [], [], []
        try:
            # PAIRED off/on drives with alternating order, scored as
            # per-pair ratios: closed-loop rates on this box drift
            # several percent rep to rep (thread scheduling, allocator
            # state), so sequential phases — or even pooled medians —
            # price that drift as "overhead"; adjacent pairs see the
            # same machine state and the ratio cancels it, alternating
            # order cancels any first-of-pair bias.
            n_pairs = max(3, reps)
            for k in range(n_pairs):
                flags = ("0", "1") if k % 2 == 0 else ("1", "0")
                pair = {}
                for flag in flags:
                    os.environ["LFM_METRICS"] = flag
                    pair[flag] = drive()
                off_rates.append(pair["0"])
                on_rates.append(pair["1"])
                ratios.append(pair["1"] / pair["0"])
        finally:
            if prev_metrics is None:
                os.environ.pop("LFM_METRICS", None)
            else:
                os.environ["LFM_METRICS"] = prev_metrics
        off_rate = sorted(off_rates)[len(off_rates) // 2]
        on_rate = sorted(on_rates)[len(on_rates) // 2]
        ratios.sort()
        ratio = ratios[len(ratios) // 2]
        # Per-pair spread (half the inner quartile range, in %): the
        # closed-loop noise floor of THIS box, recorded beside the
        # point estimate per the BASELINE.md median±spread protocol —
        # a 1% overhead claim from a box whose pairs scatter ±15% would
        # otherwise read as precise.
        q1 = ratios[len(ratios) // 4]
        q3 = ratios[(3 * len(ratios)) // 4]
        overhead_spread_pct = round(100.0 * (q3 - q1) / 2.0, 2)
        metrics_overhead_pct = round(100.0 * (1.0 - ratio), 2)
        # Warn only on a CONFIDENT breach: the median must clear the
        # 2% contract by more than the box's own pair-to-pair spread
        # (a noisy box must not cry wolf; a real regression — e.g. a
        # numpy call sneaking back onto the batcher thread, which
        # measured ~16% before the lazy sketch fold — still clears).
        if metrics_overhead_pct - overhead_spread_pct >= 2.0:
            print(f"[bench] WARNING: metrics overhead "
                  f"{metrics_overhead_pct}% (±{overhead_spread_pct}%) "
                  f">= 2% ({off_rate:.1f} req/s off vs {on_rate:.1f} "
                  "on) — the live metrics plane is supposed to be "
                  "O(1) noise", file=sys.stderr, flush=True)
        svc.close()
        for e in drive_errors[:5]:
            print(f"[bench] serve request error: {e}", file=sys.stderr,
                  flush=True)
        # Cross-check against the offline rollup of the SAME run dir:
        # trace_report must reproduce the service's p50/p99 from the
        # serve_request spans alone (identical latency_ms values).
        trace_p50 = trace_p99 = diff_pct = None
        metrics_mismatches = None
        try:
            from lfm_quant_tpu.serve.stats import load_trace_report

            tr = load_trace_report(os.path.dirname(os.path.abspath(
                __file__)))
            rep_all = tr.build_report(tr.load_run(run_dir))
            srep = rep_all.get("serve") or {}
            # The live-metrics cross-check (scrape vs spans) runs as
            # part of the same rollup; surface its verdict in the row.
            metrics_mismatches = (rep_all.get("metrics") or {}).get(
                "mismatches")
            trace_p50 = srep.get("p50_ms")
            trace_p99 = srep.get("p99_ms")
            if trace_p50 and stats.get("p50_ms"):
                # Percent DISAGREEMENT (0.0 = the offline rollup
                # reproduced the service's p50 exactly; the serve lane
                # pins ≤ 1).
                diff_pct = round(100.0 * abs(trace_p50 - stats["p50_ms"])
                                 / stats["p50_ms"], 3)
        except Exception as e:  # noqa: BLE001 — cross-check is a covariate
            print(f"[bench] serve trace_report cross-check failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)
    med = rates[len(rates) // 2]
    extras = {
        "unit": "requests/sec",
        "p50_ms": stats.get("p50_ms"),
        "p99_ms": stats.get("p99_ms"),
        "mean_occupancy": stats.get("mean_occupancy"),
        "queue_peak": stats.get("queue_peak"),
        "compiles_steady_state": steady.get("jit_traces", 0),
        "panel_h2d_steady_state": steady.get("panel_transfers", 0),
        "request_errors": n_request_errors,
        "metrics_overhead_pct": metrics_overhead_pct,
        "metrics_overhead_spread_pct": overhead_spread_pct,
        # Provenance for the §21 re-pin: the overhead A/B above ran
        # with the flight recorder + request tracing + exemplars live
        # (they are always-on by default; the <2% contract now prices
        # them too — LFM_METRICS gates only the instruments).
        "flight_on": _flight_on(),
        "metrics_mismatches": (len(metrics_mismatches)
                               if metrics_mismatches is not None
                               else None),
        "n_universes": n_universes,
        "n_requests": n_requests,
        "n_threads": n_threads,
        "n_reps": reps,
        "rep_values": [round(r, 1) for r in rates],
        "trace_p50_ms": trace_p50,
        "trace_p99_ms": trace_p99,
        "trace_p50_diff_pct": diff_pct,
    }
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("serve", med, 0.0, **extras)


def bench_serve_degradation() -> None:
    """serve_degradation — the chaos-hardened serving metric: what the
    graceful-degradation layer (DESIGN.md §18) buys when the service is
    pushed past capacity and when the dispatch path faults.

    Two priced properties, one row:

    * OVERLOAD — drive ~2× the service's measured closed-loop capacity
      (open-loop, paced submits) for a fixed window with bounded
      admission ON (small LFM_SERVE_QUEUE_MAX) vs OFF (unbounded
      queue). Shedding on: the excess is refused in O(1) (the 429 path)
      and the p99 of ADMITTED requests stays bounded by queue_max ×
      service time; shedding off: everything is admitted and queue
      delay pushes p99 toward the whole window length. The row's
      primary value is goodput (completed requests/sec) with shedding
      on, median-of-reps; the shed-off p99 ratio is the comparison
      column.
    * RECOVERY — inject a deterministic burst of transient dispatch
      faults (utils/faults.py, the serve_dispatch site) under repeated
      scoring and measure wall time from the first fault to the next
      successful response — the bounded-retry path. Gated before
      recording: the recovered response is BIT-EQUAL to the fault-free
      score and the whole chaos episode pays zero steady-state jit
      traces and zero panel H2D (failures must not recompile anything).

    Toy universes on purpose (the metric prices the degradation
    machinery, not model FLOPs — c2/c5 own throughput, serve owns the
    healthy path). CPU fallback per the wedged-tunnel protocol;
    median-of-3 per BASELINE.md."""
    import time as _time

    import numpy as np

    import serve as serve_mod
    from lfm_quant_tpu.serve import ScoringService
    from lfm_quant_tpu.serve.errors import ServeError
    from lfm_quant_tpu.serve.stats import percentile
    from lfm_quant_tpu.utils import faults
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    reps = max(1, int(os.environ.get("LFM_BENCH_OUTER_REPS", "3")))
    window_s = float(os.environ.get("LFM_BENCH_DEGRADE_WINDOW_S", "2.0"))
    rtt = dispatch_rtt_ms()  # covariate BEFORE measuring (contract)
    universes = serve_mod.build_universes(2, train_epochs=0)

    def overload_pass(queue_max: int):
        """One 2×-overload window against a fresh service with the
        given admission bound (0 = unbounded). Returns the goodput/p99/
        shed rollup of the window. ``max_rows=1`` on purpose: with
        coalescing on, the closed-loop capacity probe under-reads the
        open-loop ceiling (batching absorbs the "overload") — one row
        per dispatch makes the probe the true service rate, so 2× it is
        a genuine overload."""
        svc = ScoringService(max_rows=1, max_wait_ms=0.0,
                             queue_max=queue_max, retries=0,
                             breaker_threshold=0, deadline_ms=0)
        try:
            for name, (trainer, _) in universes.items():
                svc.register(name, trainer)
            names = svc.zoo.universes()
            months = {u: svc.serveable_months(u) for u in names}
            # Capacity probe: short closed-loop drive (the serve row's
            # own load pattern) — the overload target is 2× this.
            wall, _, _ = serve_mod.drive_load(svc, 100, 4)
            capacity = 100 / max(wall, 1e-9)
            svc.batcher.reset_stats()
            target_rate = 2.0 * capacity
            n_target = max(20, int(target_rate * window_s))
            interval = 1.0 / target_rate
            futures = []
            t0 = _time.perf_counter()
            for k in range(n_target):
                due = t0 + k * interval
                lag = due - _time.perf_counter()
                if lag > 0:
                    _time.sleep(lag)
                u = names[k % len(names)]
                ms = months[u]
                futures.append(svc.submit(u, ms[k % len(ms)]))
            lat, completed, shed = [], 0, 0
            for f in futures:
                try:
                    r = f.result(timeout=120)
                    lat.append(r.latency_ms)
                    completed += 1
                except ServeError:
                    shed += 1
                except Exception:  # noqa: BLE001 — counted, not fatal
                    shed += 1
            wall2 = _time.perf_counter() - t0
            return {
                "offered": n_target,
                "offered_per_sec": round(n_target / wall2, 1),
                "capacity_probe_per_sec": round(capacity, 1),
                "goodput_per_sec": round(completed / wall2, 1),
                "completed": completed,
                "shed": shed,
                "shed_frac": round(shed / n_target, 4),
                "p50_ms": percentile(lat, 50.0),
                "p99_ms": percentile(lat, 99.0),
            }
        finally:
            svc.close()

    def recovery_pass():
        """One transient-fault episode: every dispatch fails (injected)
        until the fault budget drains; measure first-fault → first
        success and gate on bit-equal scores + zero recompiles."""
        # Knobs PINNED (not env defaults): the 4-fault budget's
        # "deterministic schedule" below assumes exactly 2 retries per
        # dispatch and no breaker — ambient LFM_SERVE_RETRIES /
        # LFM_SERVE_BREAKER must not silently change what this row
        # measures.
        svc = ScoringService(max_rows=4, max_wait_ms=0.5, queue_max=0,
                             retries=2, breaker_threshold=0)
        try:
            name, (trainer, _) = next(iter(universes.items()))
            svc.register(name, trainer)
            m = svc.serveable_months(name)[5]
            ref = svc.score(name, m).scores.copy()
            snap = REUSE_COUNTERS.snapshot()
            # retries default 2 → 3 attempts per dispatch; a 4-fault
            # budget fails the first score outright and recovers the
            # second via one retry — deterministic schedule.
            faults.configure("serve_dispatch:n=4,kind=transient")
            t0 = _time.perf_counter()
            recovered_ms = None
            incorrect = failures = 0
            deadline = t0 + 30.0
            while _time.perf_counter() < deadline:
                try:
                    r = svc.score(name, m, timeout=10)
                except Exception:  # noqa: BLE001 — the injected outage
                    failures += 1
                    continue
                if not np.array_equal(r.scores, ref):
                    incorrect += 1
                recovered_ms = (_time.perf_counter() - t0) * 1e3
                break
            faults.configure("")
            d = REUSE_COUNTERS.delta(snap)
            stats = svc.batcher.stats()
            return {
                "recovery_ms": (round(recovered_ms, 1)
                                if recovered_ms is not None else None),
                "failed_scores": failures,
                "incorrect_responses": incorrect,
                "retries": stats.get("retries", 0),
                "compiles_steady_state": d.get("jit_traces", 0),
                "panel_h2d_steady_state": d.get("panel_transfers", 0),
            }
        finally:
            faults.configure("")
            svc.close()

    on_reps = sorted((overload_pass(queue_max=32) for _ in range(reps)),
                     key=lambda r: r["goodput_per_sec"])
    shed_on = on_reps[len(on_reps) // 2]
    shed_off = overload_pass(queue_max=0)
    rec_reps = sorted((recovery_pass() for _ in range(reps)),
                      key=lambda r: r["recovery_ms"] or float("inf"))
    rec = rec_reps[len(rec_reps) // 2]
    extras = {
        "unit": "goodput requests/sec under 2x overload (shed on)",
        "queue_max_on": 32,
        "window_s": window_s,
        "n_reps": reps,
        "rep_values": [r["goodput_per_sec"] for r in on_reps],
        "shed_on": shed_on,
        "shed_off": shed_off,
        # The headline comparison: bounded admission keeps the admitted
        # tail bounded while the unbounded queue's p99 grows toward the
        # window length.
        "p99_ratio_off_over_on": (
            round(shed_off["p99_ms"] / shed_on["p99_ms"], 2)
            if shed_on.get("p99_ms") and shed_off.get("p99_ms") else None),
        "recovery": rec,
        "recovery_rep_ms": [r["recovery_ms"] for r in rec_reps],
    }
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("serve_degradation", shed_on["goodput_per_sec"], 0.0, **extras)


def bench_serve_restart() -> None:
    """serve_restart — what durable serving state (serve/persist.py,
    DESIGN.md §20) buys at restart: time-to-first-correct-response
    COLD (retrain every universe + compile the warmup trace ladder +
    first score) vs RESTORED (verified snapshot + drift references
    re-stamped from serialized sketches + warm ladder from serialized
    lowered executables + first score), same universes, same process
    machinery (program/panel caches cleared between phases to simulate
    the process boundary — the persistent artifacts are all that
    carries over, exactly the deploy-artifact contract).

    One HARD gate before the row records: the restored service's first
    response must be BIT-EQUAL to the pre-"crash" one and every
    universe must recover (a restore that serves different numbers is
    a failure, not a fast path — the row raises instead of recording).
    Two ADVISORY contracts surface in the row and warn loudly when
    breached, so a driver diffing rows sees the numbers move:
    ``restore_compiles`` (0 with the executable artifacts loading —
    the zero-cold-start claim) and the cold/restored TTFCR ratio
    (>= 5x). The value is the ratio."""
    import shutil
    import tempfile

    import numpy as np

    import serve as serve_mod
    from lfm_quant_tpu.data.windows import clear_panel_cache
    from lfm_quant_tpu.serve import ScoringService
    from lfm_quant_tpu.train import reuse
    from lfm_quant_tpu.utils import telemetry
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    n_universes = int(os.environ.get("LFM_BENCH_RESTART_UNIVERSES", "2"))
    train_epochs = int(os.environ.get("LFM_BENCH_RESTART_EPOCHS", "2"))
    rtt = dispatch_rtt_ms()
    store_dir = tempfile.mkdtemp(prefix="lfm_zoo_store_")
    run_dir = tempfile.mkdtemp(prefix="lfm_restart_bench_")
    try:
        def simulate_process_death():
            # The in-process stand-in for a real process boundary: drop
            # every compiled-program bundle and the resident panel, so
            # the next phase pays exactly what a cold process pays —
            # minus whatever the durable artifacts carry over.
            reuse.clear_program_cache()
            clear_panel_cache()

        def build_and_register(svc):
            refs = {}
            for name, (trainer, _) in serve_mod.build_universes(
                    n_universes, train_epochs=train_epochs).items():
                svc.register(name, trainer)
                m = svc.serveable_months(name)
                refs[name] = (m[len(m) // 3],)
            return refs

        # Phase A — publish: train + register with the durable store
        # attached; every generation commits (snapshot + probe + execs).
        svc = ScoringService(persist_dir=store_dir)
        months = build_and_register(svc)
        refs = {u: svc.score(u, m[0]).scores.copy()
                for u, m in months.items()}
        svc.close()

        # Phase B — RESTORED time-to-first-correct-response.
        simulate_process_death()
        snap = REUSE_COUNTERS.snapshot()
        with telemetry.run_scope(run_dir,
                                 extra={"entry": "bench_serve_restart"}):
            t0 = time.perf_counter()
            svc2 = ScoringService(persist_dir=store_dir)
            restored = svc2.restore()
            first_u = sorted(months)[0]
            r_first = svc2.score(first_u, months[first_u][0])
            t_restored = time.perf_counter() - t0
        d = REUSE_COUNTERS.delta(snap)
        restore_compiles = int(d.get("jit_traces", 0))
        restore_h2d = int(d.get("panel_transfers", 0))
        correct = bool(np.array_equal(r_first.scores, refs[first_u]))
        rest_all = {u: svc2.score(u, m[0]).scores for u, m in
                    months.items()}
        correct = correct and all(
            np.array_equal(rest_all[u], refs[u]) for u in refs)
        execs_loaded = sum(r.get("execs_loaded", 0) for r in restored)
        execs_recompiled = sum(r.get("execs_recompiled", 0)
                               for r in restored)
        svc2.close()
        if not correct:
            raise RuntimeError(
                "restored scores are NOT bit-equal to the published "
                "generation's — refusing to record a speed row for a "
                "restore that serves wrong numbers")
        if len(restored) != n_universes:
            raise RuntimeError(
                f"restore recovered {len(restored)}/{n_universes} "
                "universes — snapshot verification failed")

        # Phase C — COLD time-to-first-correct-response: the full
        # retrain + warmup ladder a crash without durable state pays.
        simulate_process_death()
        t0 = time.perf_counter()
        # persist_dir="" pins the store OFF for the cold phase: the
        # ctor must not fall back to an operator's LFM_ZOO_PERSIST and
        # journal bench universes into a real store (and pay publish
        # costs on only one side of the ratio).
        svc3 = ScoringService(persist_dir="")
        months3 = build_and_register(svc3)
        first_u3 = sorted(months3)[0]
        svc3.score(first_u3, months3[first_u3][0])
        t_cold = time.perf_counter() - t0
        svc3.close()

        # Offline cross-check: the restore section must be derivable
        # from the run dir alone (the trace_report satellite).
        trace_restore = None
        try:
            from lfm_quant_tpu.serve.stats import load_trace_report

            tr = load_trace_report(os.path.dirname(os.path.abspath(
                __file__)))
            trace_restore = tr.build_report(
                tr.load_run(run_dir)).get("restore")
        except Exception as e:  # noqa: BLE001 — cross-check is a covariate
            print(f"[bench] serve_restart trace_report cross-check "
                  f"failed: {type(e).__name__}: {e}", file=sys.stderr,
                  flush=True)
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)
        shutil.rmtree(run_dir, ignore_errors=True)
    ratio = t_cold / max(t_restored, 1e-9)
    if restore_compiles > 0:
        print(f"[bench] WARNING: restore path paid {restore_compiles} "
              "jit trace(s) — the serialized-executable artifact did "
              "not fully load (contract: 0)", file=sys.stderr, flush=True)
    if ratio < 5.0:
        print(f"[bench] WARNING: restored TTFCR only {ratio:.2f}x "
              "better than cold (contract: >= 5x)", file=sys.stderr,
              flush=True)
    extras = {
        "unit": "x_cold_vs_restored_ttfcr",
        "ttfcr_cold_s": round(t_cold, 3),
        "ttfcr_restored_s": round(t_restored, 3),
        "restore_compiles": restore_compiles,
        "restore_panel_h2d": restore_h2d,
        "execs_loaded": execs_loaded,
        "execs_recompiled": execs_recompiled,
        "restored_correct": correct,
        "n_universes": n_universes,
        "train_epochs": train_epochs,
        "trace_restore_wall_s": (trace_restore or {}).get(
            "restore_wall_s"),
        "trace_integrity": (trace_restore or {}).get("integrity"),
    }
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("serve_restart", ratio, 0.0, **extras)


def bench_fleet_failover() -> None:
    """fleet_failover — what the fleet layer (serve/fleet.py, DESIGN.md
    §22) buys through a member crash: goodput (correct responses/sec)
    and p99 latency while one of two subprocess members is SIGKILLed
    mid-traffic, vs the single-member baseline over the same store
    artifact. One HARD gate before the row records: ZERO incorrect
    responses (every response through the kill bit-equal to the
    published generation's reference — all members restored from one
    probe-verified store) and ZERO client errors (an open-circuit or
    dead member must be a reroute, not an error — the row raises
    otherwise). The value is kill-phase goodput as a fraction of the
    single-member baseline (1.0 = the crash was free); p99 through the
    kill bounds the failover latency. CPU fallback per the
    wedged-tunnel protocol — the metric prices the ROUTING layer, not
    chips."""
    import shutil
    import signal
    import tempfile
    import threading

    import numpy as np

    import serve as serve_mod
    from lfm_quant_tpu.data.windows import clear_panel_cache
    from lfm_quant_tpu.serve import (FleetCoordinator, FleetRouter,
                                     HttpMember, ScoringService, ZooStore)
    from lfm_quant_tpu.serve import fleet as fleet_mod
    from lfm_quant_tpu.train import reuse

    n_requests = int(os.environ.get("LFM_BENCH_FLEET_REQUESTS", "120"))
    n_threads = int(os.environ.get("LFM_BENCH_FLEET_THREADS", "4"))
    rtt = dispatch_rtt_ms()
    store_dir = tempfile.mkdtemp(prefix="lfm_fleet_store_")
    procs = []
    try:
        # Publish ONE universe to the store (the deploy artifact both
        # members bootstrap from), keep the reference scores.
        svc = ScoringService(persist_dir=store_dir)
        name, (trainer, _) = next(iter(serve_mod.build_universes(
            1, train_epochs=1).items()))
        svc.register(name, trainer)
        months = svc.serveable_months(name)[:16]
        refs = {m: svc.score(name, m).scores.copy() for m in months}
        svc.close()
        reuse.clear_program_cache()
        clear_panel_cache()

        specs = []
        for k in range(2):
            rf = os.path.join(store_dir, f"_ready{k}.json")
            specs.append((fleet_mod.spawn_member(
                store_dir, ready_file=rf,
                env={"LFM_ZOO_PERSIST": ""}), rf))
        infos = [fleet_mod.wait_member_ready(p, rf, 240)
                 for p, rf in specs]
        procs = [p for p, _ in specs]
        restore_compiles = sum(i["restore_compiles"] for i in infos)
        coord = FleetCoordinator(store=ZooStore(store_dir,
                                                readonly=True))
        members = []
        for k, info in enumerate(infos):
            hm = HttpMember(f"m{k}",
                            f"http://127.0.0.1:{info['port']}",
                            pid=info["pid"])
            coord.add_member(hm)
            members.append(hm)
        router = FleetRouter(coord, breaker=1, cooldown_ms=300,
                             retries=3)
        for m in months:  # settle: every bucket warm on both members
            router.score(name, m)

        def drive(phase_router, kill_at=None, kill_pid=None):
            lats, errors, incorrect = [], [], [0]
            done = [0]
            lock = threading.Lock()

            def client(cid):
                rng = np.random.default_rng(cid)
                while True:
                    with lock:
                        if done[0] >= n_requests:
                            return
                        done[0] += 1
                        k = done[0]
                    if kill_at is not None and k == kill_at:
                        os.kill(kill_pid, signal.SIGKILL)
                    m = months[int(rng.integers(len(months)))]
                    t0 = time.perf_counter()
                    try:
                        r = phase_router.score(name, m)
                        lats.append(
                            (time.perf_counter() - t0) * 1e3)
                        if not np.array_equal(r.scores, refs[m]):
                            with lock:
                                incorrect[0] += 1
                    except Exception as e:  # noqa: BLE001 — gated below
                        errors.append(f"{type(e).__name__}: {e}")

            threads = [threading.Thread(target=client, args=(c,),
                                        daemon=True)
                       for c in range(n_threads)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0, lats, errors, incorrect[0]

        # Baseline: one member behind the router (the degenerate
        # fleet), same store, same traffic.
        coord1 = FleetCoordinator(store=ZooStore(store_dir,
                                                 readonly=True))
        coord1.add_member(HttpMember(
            "solo", f"http://127.0.0.1:{infos[0]['port']}",
            pid=infos[0]["pid"]))
        router1 = FleetRouter(coord1, retries=1)
        base_wall, base_lats, base_errors, base_bad = drive(router1)
        if base_errors or base_bad:
            raise RuntimeError(
                f"fleet baseline phase failed: {base_bad} incorrect, "
                f"{len(base_errors)} errors ({base_errors[:3]})")

        # Kill phase: SIGKILL the universe's PRIMARY a third of the
        # way in, under concurrent traffic.
        victim = coord.route(name)[0]
        vk = int(victim[1:])
        kill_wall, kill_lats, kill_errors, kill_bad = drive(
            router, kill_at=max(2, n_requests // 3),
            kill_pid=procs[vk].pid)
        if kill_bad or kill_errors:
            raise RuntimeError(
                "refusing to record a fleet row with incorrect or "
                f"failed responses through the kill: {kill_bad} "
                f"incorrect, {len(kill_errors)} errors "
                f"({kill_errors[:3]})")
        stats = router.stats()
    finally:
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        shutil.rmtree(store_dir, ignore_errors=True)

    from lfm_quant_tpu.serve.stats import percentile

    goodput_base = len(base_lats) / max(base_wall, 1e-9)
    goodput_kill = len(kill_lats) / max(kill_wall, 1e-9)
    ratio = goodput_kill / max(goodput_base, 1e-9)
    if restore_compiles > 0:
        print(f"[bench] WARNING: fleet members paid {restore_compiles} "
              "restore compile(s) — store bootstrap should load "
              "serialized executables (contract: 0)", file=sys.stderr,
              flush=True)
    extras = {
        "unit": "x_goodput_through_kill_vs_single_member",
        "goodput_base_rps": round(goodput_base, 1),
        "goodput_kill_rps": round(goodput_kill, 1),
        "p99_base_ms": round(percentile(base_lats, 99.0) or 0.0, 2),
        "p99_kill_ms": round(percentile(kill_lats, 99.0) or 0.0, 2),
        "p50_kill_ms": round(percentile(kill_lats, 50.0) or 0.0, 2),
        "incorrect_responses": 0,
        "client_errors": 0,
        "reroutes": stats.get("rerouted"),
        "failovers": stats.get("failovers"),
        "restore_compiles": restore_compiles,
        "n_requests": n_requests,
        "n_threads": n_threads,
    }
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("fleet_failover", ratio, 0.0, **extras)


def bench_epoch_pipeline() -> None:
    """epoch_pipeline — the async training-loop metric: epochs/hour on a
    CHECKPOINT-ENABLED multi-epoch fit with the one-epoch-lookahead
    pipeline (train/pipeline.py, LFM_ASYNC=1 + LFM_ASYNC_CKPT=1) vs the
    lock-step reference loop (both knobs 0), plus the host-observed
    device-idle fraction of each. The two modes run identical programs
    on identical inputs (the parity suite's contract), so epochs/hour is
    apples-to-apples: the ratio prices exactly the per-epoch fixed costs
    the pipeline hides — next-epoch sampling + H2D staging, the metric
    sync, and the two Orbax checkpoint lines. Toy MLP geometry on
    purpose: the metric prices the LOOP STRUCTURE, not model throughput
    (c2/c5 own that) — which is also what makes the CPU fallback
    meaningful when the tunnel is wedged."""
    import shutil
    import tempfile

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer
    from lfm_quant_tpu.utils import telemetry
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    n_epochs = max(2, int(os.environ.get("LFM_BENCH_PIPE_EPOCHS", "8")))
    reps = max(1, int(os.environ.get("LFM_BENCH_OUTER_REPS", "3")))
    # Geometry picked so device compute and per-epoch host fixed costs
    # are COMPARABLE (sync idle fraction ~0.6): that is where hiding
    # the host window pays most — all-host (tiny model) caps the
    # speedup at 1/idle_frac with the host itself as the new critical
    # path, all-device buries the fixed costs the metric prices.
    cfg = RunConfig(
        name="pipe_bench",
        data=DataConfig(n_firms=200, n_months=200, n_features=8, window=24,
                        dates_per_batch=4, firms_per_date=128),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (128, 64)}),
        optim=OptimConfig(lr=1e-3, epochs=n_epochs, warmup_steps=5,
                          early_stop_patience=n_epochs + 1, loss="mse"),
        seed=0,
    )
    panel = synthetic_panel(n_firms=200, n_months=200, n_features=8, seed=11)
    splits = PanelSplits.by_date(panel, 198001, 198201)
    rtt = dispatch_rtt_ms()  # covariate BEFORE measuring (contract)

    knobs = ("LFM_ASYNC", "LFM_ASYNC_CKPT")

    def one(async_on: bool):
        old = {k: os.environ.get(k) for k in knobs}
        for k in knobs:
            os.environ[k] = "1" if async_on else "0"
        out = tempfile.mkdtemp(prefix="lfm_pipe_bench_")
        try:
            # Fresh run dir per pass (cold checkpoint lines both modes);
            # programs/panel come from the reuse caches, so reps price
            # the loop, not compilation.
            trainer = Trainer(cfg, splits, run_dir=os.path.join(out, "run"))
            snap = REUSE_COUNTERS.snapshot()
            t0 = time.perf_counter()
            s = trainer.fit()
            dt = time.perf_counter() - t0
            idle = REUSE_COUNTERS.delta(snap)["device_idle_s"]
            return 3600.0 * s["epochs_run"] / dt, idle / dt
        finally:
            shutil.rmtree(out, ignore_errors=True)
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # Telemetry-derived compile accounting: the program ledger
    # (utils/telemetry.py, fed by train/reuse.py ledger_jit) records
    # every program build's compile wall seconds. Snapshot around the
    # warmup pass so the row prices the one-time compile tax the
    # measured reps then amortize — the idle fractions below come from
    # the same telemetry counter registry (device_idle_s), so the row
    # is self-describing without a bench re-run (trace_report's rollup
    # uses identical formulas).
    ledger0 = telemetry.program_ledger_totals()
    one(True)  # warmup: traces + XLA compiles (shared by both modes)
    ledger1 = telemetry.program_ledger_totals()
    async_reps = sorted(one(True) for _ in range(reps))
    sync_reps = sorted(one(False) for _ in range(reps))
    ledger2 = telemetry.program_ledger_totals()
    a_med = async_reps[len(async_reps) // 2]
    s_med = sync_reps[len(sync_reps) // 2]
    extras = {
        "unit": "epochs/hour",
        "sync_epochs_per_hour": round(s_med[0], 1),
        "speedup": round(a_med[0] / max(s_med[0], 1e-9), 2),
        "idle_frac_async": round(a_med[1], 3),
        "idle_frac_sync": round(s_med[1], 3),
        # null (not a measured-looking 0.0) when LFM_TELEMETRY=0: the
        # ledger records nothing then, and a zero row would read as a
        # genuinely warm compile cache against the baselines.
        "compile_s_warmup": (round(
            ledger1["compile_s"] - ledger0["compile_s"], 3)
            if telemetry.enabled() else None),
        "compile_s_timed_reps": (round(
            ledger2["compile_s"] - ledger1["compile_s"], 3)
            if telemetry.enabled() else None),
        "program_builds": (int(ledger2["builds"] - ledger0["builds"])
                           if telemetry.enabled() else None),
        "n_epochs": n_epochs,
        "n_reps": reps,
        "rep_values": [round(r[0], 1) for r in async_reps],
        "sync_rep_values": [round(r[0], 1) for r in sync_reps],
    }
    if reps >= 2:
        extras["spread_pct"] = round(
            100.0 * (async_reps[-1][0] - async_reps[0][0])
            / max(a_med[0], 1e-9), 1)
    if rtt is not None:
        extras["rtt_ms"] = rtt
    _emit("epoch_pipeline", a_med[0], 0.0, **extras)


def _tunnel_probe(wait_s: float = 420.0) -> dict:
    """Fail FAST (and diagnosably) when the tunneled device is wedged.

    A wedged axon tunnel hangs every client at claim/init indefinitely
    (BASELINE.md 2026-07-30 note) — round 2's driver capture died that
    way with nothing in the log. Probe with a tiny matmul in a SUBPROCESS
    (the hang is in backend init; it cannot be interrupted in-process),
    retrying until LFM_BENCH_WAIT_S elapses so a tunnel that flaps back
    mid-window still yields a capture. The default window is 420 s: the
    driver timeboxes the whole bench run at ~600 s, and round 3's 600 s
    probe window raced it — the driver's faulthandler fired MID-probe and
    the run produced no parseable record at all. 420 s of probing leaves
    ~3 min for the measurements themselves, and a wedged tunnel now exits
    through the structured-status path instead of the driver's axe.
    Healthy tunnel cost: one ~20 s subprocess (compile included); set
    LFM_BENCH_SKIP_PROBE=1 when an outer harness (chip_campaign.sh) just
    probed. A timed-out probe gets SIGTERM + a 10 s grace before SIGKILL
    — a hard-killed client mid-claim is itself the documented wedge
    trigger. The first attempt gets 180 s (cold compile + tunnel RTT);
    an instant non-zero exit (< 5 s: ImportError, broken env — not a
    tunnel condition) fails immediately instead of burning the window.

    Returns {"ok": bool, "attempts": int, "detail": str} so the caller
    can fold the outcome into its final status record. ``wait_s`` comes
    from the caller (main() parses LFM_BENCH_WAIT_S exactly once) so the
    watchdog deadline and the probe window can never drift apart."""
    import subprocess

    if os.environ.get("LFM_BENCH_SKIP_PROBE") == "1":
        return {"ok": True, "attempts": 0, "detail": "probe skipped"}
    if os.environ.get("LFM_BENCH_FAKE_WEDGE") == "1":
        # Dry-run hook: exercise the whole wedged-tunnel capture path —
        # provisional record, structured give-up, re-arm logic — with zero
        # chip contact and zero waiting (tests/test_campaign_script.py
        # pins the end-to-end run at < 10 s).
        return {"ok": False, "attempts": 0, "kind": "tunnel_wedged",
                "detail": "fake wedge (LFM_BENCH_FAKE_WEDGE=1 dry run)"}
    deadline = time.monotonic() + wait_s
    code = ("import jax, jax.numpy as jnp;"
            "print('OK', float(jax.jit(lambda a: (a@a).sum())"
            "(jnp.ones((256,256), jnp.bfloat16))))")
    attempt = 0
    detail = ""
    while True:
        attempt += 1
        # Never let one attempt run past the window: the whole point is
        # to reach the structured give-up path inside the driver timebox.
        tmo = min(180 if attempt == 1 else 90,
                  max(20, deadline - time.monotonic()))
        t_start = time.monotonic()
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        try:
            stdout, stderr = proc.communicate(timeout=tmo)
            took = time.monotonic() - t_start
            if proc.returncode == 0 and "OK" in stdout:
                print(f"[bench] tunnel probe OK (attempt {attempt}, "
                      f"{took:.0f}s)", file=sys.stderr, flush=True)
                return {"ok": True, "attempts": attempt, "detail": "ok"}
            detail = (stderr or stdout).strip()[-300:]
            if took < 5:
                print(f"[bench] probe failed instantly (not a tunnel "
                      f"condition): {detail}", file=sys.stderr, flush=True)
                return {"ok": False, "attempts": attempt,
                        "kind": "probe_env_error",
                        "detail": f"instant failure: {detail}"}
        except subprocess.TimeoutExpired:
            proc.terminate()  # SIGTERM first: let the client leave its claim
            try:
                proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.communicate()
            detail = f"probe timed out at {tmo:.0f} s (wedged claim/init)"
        remaining = deadline - time.monotonic()
        print(f"[bench] tunnel probe attempt {attempt} failed: {detail}; "
              f"{max(0, int(remaining))}s left in wait window",
              file=sys.stderr, flush=True)
        if remaining <= 40:
            print("[bench] giving up: tunnel unhealthy for the whole wait "
                  "window (set LFM_BENCH_WAIT_S to wait longer)",
                  file=sys.stderr, flush=True)
            return {"ok": False, "attempts": attempt,
                    "kind": "tunnel_wedged", "detail": detail}
        time.sleep(min(30, max(1, deadline - time.monotonic() - 95)))


def _emit_status(status: str, persist: bool = True, **extras) -> None:
    """The guaranteed-parseable terminal record. Round 3's driver capture
    ended rc=1/parsed=null because the only output before the timeout was
    stderr probe chatter — this line is the fix: EVERY exit path now puts
    at least one schema-shaped JSON record on stdout, so an outage shows
    up in BENCH_r{N}.json as {"status": "tunnel_wedged", ...} instead of
    nothing. ``persist=False`` keeps a provisional record (see main()) off
    the durable ledger — it exists only for the driver's tail parser."""
    rec = {
        "metric": "bench_status",
        "value": 1.0 if status == "ok" else 0.0,
        "unit": "status",
        "vs_baseline": 1.0,
        "status": status,
        # dtype (but NOT backend): the precision tag is a pure env read,
        # while a backend query could hang on the wedged-tunnel path
        # this record exists for (see persist_row).
        "dtype": _precision_name(),
    }
    rec.update(extras)
    print(json.dumps(rec), flush=True)
    if persist:
        persist_row(rec)  # outages belong in the ledger too


_WATCHER_PATTERN = "scripts/campaign_on_recovery.sh"
_CAMPAIGN_PATTERNS = ("scripts/chip_campaign.sh",
                      _WATCHER_PATTERN,
                      "scripts/bench_ladder.py", "scripts/sweep_rnn_blocks.py",
                      "scripts/diag_c1.py", "scripts/hbm_probe.py")
# argv[0] must be an interpreter/launcher for a match — an editor or pager
# whose ARGUMENT mentions a campaign script (vim scripts/diag_c1.py) must
# never be signalled.
_PREEMPT_LAUNCHERS = {"bash", "sh", "dash", "python", "python3", "timeout",
                      "env", "nohup"}


def _list_procs() -> dict:
    """{pid: (ppid, argv)} snapshot of /proc — enough to anchor-match
    campaign processes and close over their descendants."""
    procs = {}
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as fh:
                argv = [a.decode("utf-8", "replace")
                        for a in fh.read().split(b"\0") if a]
            with open(f"/proc/{pid}/stat") as fh:
                stat = fh.read()
            ppid = int(stat.rsplit(")", 1)[1].split()[1])
        except (OSError, ValueError, IndexError):
            continue
        procs[int(pid)] = (ppid, argv)
    return procs


def _is_campaign_proc(argv) -> bool:
    if not argv or os.path.basename(argv[0]) not in _PREEMPT_LAUNCHERS:
        return False
    return any(tok.endswith(p) for tok in argv for p in _CAMPAIGN_PATTERNS)


def _preempt_campaign() -> dict:
    """Make way for the driver capture: SIGTERM any unattended measurement
    campaign still running (the recovery watcher fires it at whatever hour
    the tunnel heals, so it can straddle the driver's end-of-round bench).
    The single tunneled chip serializes clients — a campaign step holding
    it would eat the whole probe window and the capture would misreport
    `tunnel_wedged`. Campaign rows persist to the ledger per step
    (persist_row), so nothing measured is lost.

    Matched roots are killed together with their /proc DESCENDANTS — the
    chip claim is held by a grandchild (`timeout ... python ...`) whose
    own cmdline matches no pattern; killing only the shell would orphan
    the claim-holder and still eat the probe window. Skipped when
    bench.py IS the campaign's own step (LFM_BENCH_SKIP_PROBE=1) or
    under LFM_BENCH_NO_PREEMPT=1. Returns {"killed": n, "watcher": bool}
    so main() can re-arm a preempted recovery watcher on exit instead of
    leaving the staged campaign permanently disarmed."""
    import signal

    out = {"killed": 0, "watcher": False}
    if (os.environ.get("LFM_BENCH_SKIP_PROBE") == "1"
            or os.environ.get("LFM_BENCH_NO_PREEMPT") == "1"):
        return out
    me = os.getpid()
    try:
        procs = _list_procs()
    except OSError:
        return out
    roots = [pid for pid, (_, argv) in procs.items()
             if pid != me and _is_campaign_proc(argv)]
    if not roots:
        return out
    children = {}
    for pid, (ppid, _) in procs.items():
        children.setdefault(ppid, []).append(pid)
    doomed, stack = set(), list(roots)
    while stack:
        pid = stack.pop()
        if pid in doomed or pid == me:
            continue
        doomed.add(pid)
        stack.extend(children.get(pid, ()))
    for pid in doomed:
        argv = procs.get(pid, (0, []))[1]
        cmd = " ".join(argv)[:120]
        print(f"[bench] preempting campaign process {pid}: {cmd}",
              file=sys.stderr, flush=True)
        for i, tok in enumerate(argv):
            if tok.endswith(_WATCHER_PATTERN):
                out["watcher"] = True
                # Preserve the operator's arming choices across the
                # preempt/re-arm cycle: the positional args (probe
                # interval) and the CAMPAIGN_* env (log location) would
                # otherwise silently revert to defaults on re-arm.
                out["watcher_args"] = argv[i + 1:]
                try:
                    env_blob = open(f"/proc/{pid}/environ", "rb").read()
                    out["watcher_env"] = {
                        k.decode(): v.decode(errors="replace")
                        for k, _, v in (e.partition(b"=")
                                        for e in env_blob.split(b"\0") if e)
                        if k.startswith(b"CAMPAIGN_")}
                except OSError:
                    pass
                break
        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            pass
    time.sleep(10)  # let the chip client leave its claim gracefully
    for pid in doomed:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass  # already gone (the normal case)
    out["killed"] = len(doomed)
    return out


def _rearm_watcher(preempted: dict) -> None:
    """Re-launch the recovery watcher a preemption killed: the staged
    campaign must stay armed after the driver capture finishes — and if
    the capture just measured a healthy tunnel, the watcher's next probe
    fires the campaign immediately, which is exactly right. The victim's
    positional args and CAMPAIGN_* env (captured at preempt time) ride
    along so the operator's interval/log choices survive the cycle.

    Once-guarded: the watchdog's fire path and main()'s finally can race
    (cancel() is a no-op once fire() has started), and two re-arms would
    leave two watchers fighting over the serialized chip. The SPAWN stays
    inside the lock too: were the flag set before the Popen ran, the
    racing fire path would see it, skip, and os._exit the process with no
    watcher actually launched — fire must block until the spawn is done."""
    import subprocess

    with _REARM_LOCK:
        if preempted.get("rearmed"):
            return
        script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "scripts", "campaign_on_recovery.sh")
        if not os.path.exists(script):
            preempted["rearmed"] = True
            return
        env = dict(os.environ)
        env.update(preempted.get("watcher_env") or {})
        argv = ["bash", script] + list(preempted.get("watcher_args") or [])
        with open(os.devnull, "wb") as devnull:
            subprocess.Popen(argv, env=env, stdout=devnull, stderr=devnull,
                             start_new_session=True)
        preempted["rearmed"] = True
    print("[bench] recovery watcher re-armed", file=sys.stderr, flush=True)


def _arm_watchdog(deadline_s: float, preempted: dict):
    """A tunnel that wedges AFTER the probe passes hangs the measurement
    in uninterruptible backend-init C code — no in-process exception or
    signal handler ever runs, and the driver's axe would again leave
    rc=1/parsed=null. A daemon TIMER THREAD is immune to that: at the
    deadline it writes the status record from its own thread and
    os._exit()s the whole process. Returns the timer (cancel on success).

    `preempted` is the live dict main() shares with _preempt_campaign:
    os._exit skips main()'s finally, so a preempted recovery watcher
    must be re-armed HERE on the fire path or a post-probe wedge would
    leave the staged campaign permanently disarmed."""

    def fire():
        _emit_status("bench_timeout",
                     detail=f"measurement exceeded {deadline_s:.0f}s "
                            "deadline (tunnel wedged post-probe?)")
        sys.stdout.flush()
        if preempted.get("watcher"):
            try:
                _rearm_watcher(preempted)
            except Exception:  # noqa: BLE001 — nothing may block the exit
                pass
        os._exit(1)

    t = threading.Timer(deadline_s, fire)
    t.daemon = True
    t.start()
    return t


def main() -> int:
    # Hang forensics: the tunneled device has wedged before (a remote
    # compile that never returns leaves the client in a silent sleep
    # poll). Periodic all-thread stack dumps to stderr cost nothing and
    # turn a dead driver run into a diagnosable one.
    import faulthandler

    try:
        faulthandler.dump_traceback_later(240, repeat=True)
    except (io.UnsupportedOperation, ValueError, AttributeError):
        pass  # no real stderr fileno (pytest capture) — forensics only
    t_start = time.monotonic()
    watchdog = None
    preempted: dict = {}
    try:
        # FIRST output on stdout, before any probe/preempt/jax work: a
        # provisional schema-shaped record. The driver parses the LAST
        # JSON line of the tail (BENCH_r01/r04 captures), so every later
        # record supersedes this one — but if the driver's timebox ever
        # shrinks below the probe window again (round-4 verdict, Weak #5),
        # the capture still parses instead of ending parsed=null. Not
        # persisted: the ledger records outcomes, not placeholders.
        _emit_status(
            "no_capture", persist=False,
            detail="provisional startup record; superseded by any later "
                   "record on this stream")
        # Whole-run deadline, probe included: 540 s default keeps the
        # final record inside the driver's observed ~600 s timebox. An
        # operator who extends LFM_BENCH_WAIT_S gets a matching extension
        # (the watchdog must never fire mid-probe with its post-probe
        # diagnosis), and the float() parses sit INSIDE the try so a
        # malformed knob still exits through the bench_error record.
        wait_s = float(os.environ.get("LFM_BENCH_WAIT_S", "420"))
        deadline_s = max(float(os.environ.get("LFM_BENCH_DEADLINE_S", "540")),
                         wait_s + 120.0)
        watchdog = _arm_watchdog(deadline_s, preempted)
        if os.environ.get("LFM_BENCH_FAKE_WEDGE") != "1":
            # A fake-wedge dry run must never SIGTERM the real recovery
            # watcher holding the staged campaign.
            preempted.update(_preempt_campaign())
        probe = _tunnel_probe(wait_s)
        if not probe["ok"]:
            # Salvage the backend-independent metric on CPU before the
            # terminal outage record (skipped for dry runs — a fake
            # wedge must stay a <10 s no-chip path for the campaign
            # tests). Leaves 30 s of watchdog headroom so a slow child
            # can never turn the structured give-up into an os._exit.
            if (os.environ.get("LFM_BENCH_FAKE_WEDGE") != "1"
                    and probe.get("kind") == "tunnel_wedged"):
                for flag in ("--walkforward-reuse", "--walkforward-foldstack",
                             "--config-sweep", "--bucketed-train",
                             "--mixed-precision", "--scoring-pipeline",
                             "--epoch-pipeline", "--serve",
                             "--serve-degradation", "--serve-restart",
                             "--fleet-failover"):
                    _cpu_metric_fallback(
                        flag,
                        deadline_s - (time.monotonic() - t_start) - 30.0)
            # A FAKE_WEDGE dry run must not bank a bogus outage record in
            # the durable ledger — regen_baseline reports the latest
            # status row, and a fake one would misreport a healthy tunnel.
            _emit_status(probe.get("kind", "tunnel_wedged"),
                         persist=os.environ.get("LFM_BENCH_FAKE_WEDGE")
                         != "1",
                         probe_attempts=probe["attempts"],
                         detail=probe["detail"],
                         waited_s=round(time.monotonic() - t_start, 1))
            return 1
        try:
            bench_c2()
        except Exception as e:  # noqa: BLE001 — the driver must get a record
            _emit_status("bench_error", stage="c2",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_c5_ensemble()
        except Exception as e:  # noqa: BLE001 — c2 result must still reach the driver
            print(f"bench_c5_ensemble failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            _emit_status("bench_error", stage="c5_ensemble",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_walkforward_reuse()
        except Exception as e:  # noqa: BLE001 — throughput rows must still reach the driver
            print(f"bench_walkforward_reuse failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            _emit_status("bench_error", stage="walkforward_reuse",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_walkforward_foldstack()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_walkforward_foldstack failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            _emit_status("bench_error", stage="walkforward_foldstack",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_config_sweep()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_config_sweep failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            _emit_status("bench_error", stage="config_sweep",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_bucketed_train()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_bucketed_train failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            _emit_status("bench_error", stage="bucketed_train",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_mixed_precision()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_mixed_precision failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            _emit_status("bench_error", stage="mixed_precision",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_scoring_pipeline()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_scoring_pipeline failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            _emit_status("bench_error", stage="scoring_pipeline",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_epoch_pipeline()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_epoch_pipeline failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            _emit_status("bench_error", stage="epoch_pipeline",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_serve()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_serve failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            _emit_status("bench_error", stage="serve",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_serve_degradation()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_serve_degradation failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            _emit_status("bench_error", stage="serve_degradation",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_serve_restart()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_serve_restart failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            _emit_status("bench_error", stage="serve_restart",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        try:
            bench_fleet_failover()
        except Exception as e:  # noqa: BLE001 — earlier rows must still reach the driver
            print(f"bench_fleet_failover failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            _emit_status("bench_error", stage="fleet_failover",
                         detail=f"{type(e).__name__}: {e}"[:300])
            return 1
        return 0
    except Exception as e:  # noqa: BLE001 — NO exit path may skip the record
        _emit_status("bench_error", stage="harness",
                     detail=f"{type(e).__name__}: {e}"[:300])
        return 1
    finally:
        if watchdog is not None:
            watchdog.cancel()
        faulthandler.cancel_dump_traceback_later()
        if preempted.get("watcher"):
            _rearm_watcher(preempted)


def _single_metric_main(fn, stage: str) -> int:
    """``bench.py --walkforward-reuse`` / ``--scoring-pipeline``: the
    single-metric entry points — no probe, no watchdog, no campaign
    preemption. The caller owns the backend choice (the CPU fallback
    sets JAX_PLATFORMS=cpu) and the timebox (subprocess timeout)."""
    try:
        fn()
        return 0
    except Exception as e:  # noqa: BLE001 — the parent expects a record or rc!=0
        _emit_status("bench_error", stage=stage,
                     detail=f"{type(e).__name__}: {e}"[:300])
        return 1


if __name__ == "__main__":
    if "--walkforward-reuse" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_walkforward_reuse,
                                     "walkforward_reuse"))
    if "--walkforward-foldstack" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_walkforward_foldstack,
                                     "walkforward_foldstack"))
    if "--config-sweep" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_config_sweep, "config_sweep"))
    if "--bucketed-train" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_bucketed_train,
                                     "bucketed_train"))
    if "--mixed-precision" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_mixed_precision,
                                     "mixed_precision"))
    if "--scoring-pipeline" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_scoring_pipeline,
                                     "scoring_pipeline"))
    if "--epoch-pipeline" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_epoch_pipeline,
                                     "epoch_pipeline"))
    if "--serve-degradation" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_serve_degradation,
                                     "serve_degradation"))
    if "--serve-restart" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_serve_restart,
                                     "serve_restart"))
    if "--fleet-failover" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_fleet_failover,
                                     "fleet_failover"))
    if "--serve" in sys.argv[1:]:
        sys.exit(_single_metric_main(bench_serve, "serve"))
    sys.exit(main())
