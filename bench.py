#!/usr/bin/env python
"""Benchmark harness: ladder config 2 (single-seed LSTM, 20 features,
60-month lookback — BASELINE.json:8) training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: firm-months/sec/chip (BASELINE.json:2) — firm-month observations
consumed by training per second (real windows × window length; padded
slots excluded). No reference number exists (BASELINE.json:13
"published": {} — see BASELINE.md), so vs_baseline is reported against the
round-1 recorded value in BENCH_BASELINE.json when present, else 1.0.
"""

import json
import os
import sys
import time


def main() -> int:
    import jax
    import jax.numpy as jnp

    from lfm_quant_tpu.config import get_preset
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    cfg = get_preset("c2")
    # Bench panel: full config-2 feature/window geometry, trimmed months so
    # panel generation isn't the bench bottleneck.
    d = cfg.data
    panel = synthetic_panel(
        n_firms=d.n_firms, n_months=240, n_features=d.n_features,
        horizon=d.horizon, seed=0,
    )
    splits = PanelSplits.by_date(panel, 198601, 198801)
    trainer = Trainer(cfg, splits)
    state = trainer.init_state()

    # One epoch of index batches, scanned inside a single jit dispatch
    # (lax.scan over steps) — per-dispatch latency is excluded by design,
    # and the final float() readback forces a true device sync (on the
    # tunneled axon device, block_until_ready alone does not wait).
    b = trainer.train_sampler.stacked_epoch(0)
    k = min(30, b.firm_idx.shape[0])
    import dataclasses as _dc
    b = _dc.replace(b, firm_idx=b.firm_idx[:k], time_idx=b.time_idx[:k],
                    weight=b.weight[:k])
    fi, ti, w = trainer._batch_args(b, train=True, steps=True)
    fm = float(b.weight.sum()) * trainer.window

    # Warmup: compile + one full pass.
    _, ms = trainer._jit_multi_step(state, trainer.dev, fi, ti, w)
    _ = float(ms["loss"][-1])

    reps = 3
    t0 = time.perf_counter()
    st = state
    for _ in range(reps):
        st, ms = trainer._jit_multi_step(st, trainer.dev, fi, ti, w)
    _ = float(ms["loss"][-1])
    dt = (time.perf_counter() - t0) / reps

    value = fm / dt
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    vs = 1.0
    if os.path.exists(base_path):
        try:
            with open(base_path) as fh:
                base = json.load(fh).get("value", 0.0)
            if base > 0:
                vs = value / base
        except Exception:
            pass
    print(json.dumps({
        "metric": "train_throughput_c2_lstm",
        "value": round(value, 1),
        "unit": "firm-months/sec/chip",
        "vs_baseline": round(vs, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
