#!/usr/bin/env python
"""Backtest entry point — parity with the reference's ``backtest.py``
(SURVEY.md §4.3; BASELINE.json:5): trained checkpoint(s) → forecasts for
every eligible firm×month → monthly cross-sectional ranks → top-quantile
portfolio → CAGR/Sharpe/IC report.

Usage:
    python backtest.py --run-dir runs/c1_mlp_toy/seed0
    python backtest.py --run-dir runs/c5_lstm_ensemble64/ensemble \\
        --mode mean_minus_std --quantile 0.2 --long-short
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--run-dir",
                     help="run directory written by train.py")
    src.add_argument("--forecast-npz",
                     help="stitched forecast file written by walk-forward "
                          "mode (train.py --walk-forward): walkforward.npz "
                          "or its directory; the sibling config.json "
                          "resolves the panel")
    ap.add_argument("--split", default=None, choices=["test", "val", "train"],
                    help="which date split to simulate on (default: test; "
                         "not applicable with --forecast-npz, whose months "
                         "are fixed by the stitched file)")
    ap.add_argument("--quantile", type=float, default=0.1)
    ap.add_argument("--long-short", action="store_true")
    ap.add_argument("--costs-bps", type=float, default=0.0)
    ap.add_argument("--mode", default="mean",
                    choices=["mean", "mean_minus_std",
                             "mean_minus_total_std"],
                    help="aggregation over seeds (ensemble run dirs) or "
                         "MC-dropout samples (--mc-samples); "
                         "mean_minus_total_std adds the heteroscedastic "
                         "head's aleatoric variance to the seed spread "
                         "(nll-trained run dirs, or --forecast-npz files "
                         "stitched from an nll walk-forward)")
    ap.add_argument("--risk-lambda", type=float, default=1.0)
    ap.add_argument("--mc-samples", type=int, default=0,
                    help="single-model run dirs: draw this many MC-dropout "
                         "forecast samples (model must have dropout > 0) "
                         "and aggregate them with --mode, the "
                         "uncertainty-aware-LFM alternative to a seed "
                         "ensemble")
    ap.add_argument("--json-out", default=None,
                    help="write the full report JSON here")
    ap.add_argument("--yearly", action="store_true",
                    help="also print the calendar-year breakdown")
    args = ap.parse_args(argv)

    from lfm_quant_tpu.backtest import aggregate_ensemble, resolve_backtest
    from lfm_quant_tpu.utils import telemetry

    # Engine dispatch: the fused device-resident backtest
    # (backtest/jax_engine.py — all months in one jitted dispatch) by
    # default, the numpy reference under LFM_JAX_BACKTEST=0 or when jax
    # is unavailable. Same report either way (parity-suite contract).
    run_backtest = resolve_backtest()

    # Telemetry scope over the scoring run: manifest + spans land in the
    # run dir being graded (the stitched file's directory for
    # --forecast-npz), so `scripts/trace_report.py <dir>` covers the
    # backtest pass too. LFM_TELEMETRY=0 makes this a no-op.
    tele_dir = args.run_dir
    if args.forecast_npz:
        tele_dir = (args.forecast_npz if os.path.isdir(args.forecast_npz)
                    else os.path.dirname(args.forecast_npz) or ".")
    with telemetry.run_scope(tele_dir, extra={
            "entry": "backtest",
            "cli": {"mode": args.mode, "quantile": args.quantile,
                    "long_short": args.long_short,
                    "costs_bps": args.costs_bps,
                    "mc_samples": args.mc_samples}}):
        if args.forecast_npz:
            import numpy as np

            from lfm_quant_tpu.config import RunConfig
            from lfm_quant_tpu.train.loop import resolve_panel

            if args.mc_samples > 0:
                ap.error("--mc-samples needs a live model; a forecast file "
                         "is already sampled/stitched")
            if args.split is not None:
                ap.error("--split does not apply to --forecast-npz: the "
                         "simulated months are fixed by the stitched file")
            path = args.forecast_npz
            if os.path.isdir(path):
                path = os.path.join(path, "walkforward.npz")
            with open(os.path.join(os.path.dirname(path),
                                   "config.json")) as fh:
                cfg = RunConfig.from_json(fh.read())
            data = np.load(path)
            forecast, fc_valid = data["forecast"], data["valid"]
            panel = resolve_panel(cfg.data)
            if args.mode == "mean_minus_total_std":
                if "variance" not in data:
                    ap.error("--mode mean_minus_total_std needs stitched "
                             "aleatoric variances; this file has none "
                             "(train the walk-forward with a "
                             "heteroscedastic config — loss='nll')")
                avar = data["variance"]
                if forecast.ndim == 2:  # single heteroscedastic model
                    forecast, avar = forecast[None], avar[None]
                forecast, fc_valid = aggregate_ensemble(
                    forecast, fc_valid, args.mode, args.risk_lambda,
                    aleatoric_var=avar)
            elif forecast.ndim == 3:  # stacked walk-forward ensemble
                forecast, fc_valid = aggregate_ensemble(
                    forecast, fc_valid, args.mode, args.risk_lambda)
            elif args.mode != "mean":
                ap.error(f"--mode {args.mode} needs stacked forecasts; "
                         "this file holds a single model's (already-"
                         "aggregated) walk-forward forecasts")
        else:
            from lfm_quant_tpu.train.forecast import (is_ensemble_run_dir,
                                                      load_forecaster,
                                                      run_forecast)

            if is_ensemble_run_dir(args.run_dir) and args.mc_samples > 0:
                # Validate BEFORE load_forecaster restores every seed
                # checkpoint (minutes on a real ensemble run dir).
                ap.error("--mc-samples applies to single-model run dirs "
                         "only; this is a seed ensemble — its uncertainty "
                         "comes from the seeds (use --mode mean_minus_std "
                         "directly)")
            model, splits, is_ensemble = load_forecaster(args.run_dir)
            with telemetry.span("predict", cat="predict"):
                forecast, fc_valid = run_forecast(
                    model, is_ensemble, mode=args.mode,
                    risk_lambda=args.risk_lambda, mc_samples=args.mc_samples,
                    error=ap.error, split=args.split or "test")
            panel = splits.panel

        with telemetry.span("score", cat="score"):
            report = run_backtest(
                forecast, fc_valid, panel,
                quantile=args.quantile, long_short=args.long_short,
                costs_bps=args.costs_bps,
            )
        print(report.summary())
        if args.yearly:
            for y, rec in sorted(report.yearly().items()):
                print(f"  {y}: ret {rec['ret']:+8.2%}  bench "
                      f"{rec['bench']:+8.2%}  IC {rec['mean_ic']:+.3f}  "
                      f"({rec['n_months']} mo)")
        if args.json_out:
            with open(args.json_out, "w") as fh:
                fh.write(report.to_json())
    return 0


if __name__ == "__main__":
    sys.exit(main())
