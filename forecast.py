#!/usr/bin/env python
"""Live-forecast entry point: trained checkpoint(s) → rankings for months
whose realized outcome is NOT yet observable.

The production half the backtest cannot serve: ``backtest.py`` scores
anchors against realized targets, so eligibility requires
``target_valid`` and the last ``horizon`` months of the panel — exactly
the cross-sections a user trades on — are unreachable by construction.
This CLI predicts with ``require_target=False`` (window-validity only;
see ``data/windows.py anchor_index``), the deployment step of the
reference's research→production workflow (SURVEY.md §4.3's forecast
stage, decoupled from the simulation stage).

Usage:
    python forecast.py --run-dir runs/c2_lstm_single/seed0
    python forecast.py --run-dir runs/c5_lstm_ensemble64/ensemble \\
        --mode mean_minus_std --csv live_ranks.csv
    python forecast.py --run-dir ... --from-date 202401 --to-date 202406

Defaults to the panel's live block (the trailing ``horizon`` months).
Writes an npz (forecast [N, T], valid [N, T], dates, firm_ids) and/or a
long-format CSV of per-month rankings; prints the latest month's top
names.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def _month_index(dates: np.ndarray, yyyymm: int, name: str) -> int:
    ix = np.nonzero(dates == yyyymm)[0]
    if ix.size == 0:
        raise SystemExit(
            f"{name} {yyyymm} not in the panel (spans "
            f"{int(dates[0])}..{int(dates[-1])})")
    return int(ix[0])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--run-dir", required=True,
                    help="run directory written by train.py (single seed "
                         "or ensemble — auto-detected)")
    ap.add_argument("--from-date", type=int, default=None,
                    help="first anchor month, YYYYMM inclusive (default: "
                         "start of the live block — the panel's last "
                         "`horizon` months)")
    ap.add_argument("--to-date", type=int, default=None,
                    help="last anchor month, YYYYMM inclusive (default: "
                         "panel end)")
    ap.add_argument("--mode", default="mean",
                    choices=("mean", "mean_minus_std",
                             "mean_minus_total_std"),
                    help="ensemble aggregation (as in backtest.py)")
    ap.add_argument("--risk-lambda", type=float, default=1.0)
    ap.add_argument("--mc-samples", type=int, default=0,
                    help="MC-dropout samples (single-model run dirs with "
                         "dropout > 0)")
    ap.add_argument("--out", help="write forecasts npz here")
    ap.add_argument("--csv", help="write long-format rankings CSV here "
                                  "(firm_id,yyyymm,forecast,rank)")
    ap.add_argument("--top", type=int, default=10,
                    help="names to print for the latest month")
    args = ap.parse_args(argv)

    import glob
    import json
    import os

    from lfm_quant_tpu.data import anchor_index
    from lfm_quant_tpu.train.forecast import (is_ensemble_run_dir,
                                              load_forecaster, run_forecast)

    # A walk-forward directory resolves to its LAST COMPLETED fold — the
    # model trained on the most recent data, which is the one to trade
    # live. (Detection must precede load_forecaster: the wf root carries
    # a config.json of its own but no checkpoint.)
    for progress in ("summary.json", "partial.json"):
        path = os.path.join(args.run_dir, progress)
        if not os.path.exists(path) or not glob.glob(
                os.path.join(args.run_dir, "fold_*")):
            continue
        with open(path) as fh:
            doc = json.load(fh)
        records = doc["folds"] if isinstance(doc, dict) else doc
        if not records:
            raise SystemExit(f"{args.run_dir} is a walk-forward dir with "
                             "no completed folds yet")
        rec = records[-1]  # appended in fold order (resume validates it)
        fold_dir = os.path.join(args.run_dir, f"fold_{rec['fold']}")
        # Older runs predate per-fold config.json: the fold DIR exists
        # (checkpoints were always written there) but is not loadable.
        if not os.path.exists(os.path.join(fold_dir, "config.json")):
            raise SystemExit(
                f"walk-forward progress names fold {rec['fold']} but "
                f"{fold_dir} has no config.json (older run predating "
                "loadable fold dirs? re-run the walk-forward, or point "
                "--run-dir at a single-model run dir directly)")
        print(f"walk-forward dir: using fold {rec['fold']}'s model "
              f"(trained through {rec['train_end']})")
        args.run_dir = fold_dir
        break

    if is_ensemble_run_dir(args.run_dir) and args.mc_samples > 0:
        # Validate BEFORE load_forecaster restores every seed checkpoint.
        ap.error("--mc-samples applies to single-model run dirs only")
    model, splits, is_ensemble = load_forecaster(args.run_dir)
    panel = splits.panel

    # Default range: the live block — anchors past the last observable
    # target. End-exclusive month-index range for predict().
    lo = (_month_index(panel.dates, args.from_date, "--from-date")
          if args.from_date else max(0, panel.n_months - panel.horizon))
    hi = (_month_index(panel.dates, args.to_date, "--to-date") + 1
          if args.to_date else panel.n_months)
    if lo >= hi:
        ap.error(
            f"empty forecast range: it runs {int(panel.dates[min(lo, panel.n_months - 1)])}"
            f"..{int(panel.dates[hi - 1])} after resolution"
            + ("" if args.from_date else
               " (--from-date defaults to the live block, the panel's "
               f"last {panel.horizon} months — pass an explicit "
               "--from-date at or before --to-date for historical "
               "forecasts)"))

    # Pre-check: predict()'s sampler raises a raw ValueError on an empty
    # range; answer the common operator mistake with its actual cause.
    d = model.cfg.data
    elig = anchor_index(panel, d.window, d.min_valid_months,
                        require_target=False)
    if not elig[:, lo:hi].any():
        raise SystemExit(
            "no eligible anchors in the requested range (firms need "
            "enough lookback history even without a target)")

    forecast, valid = run_forecast(
        model, is_ensemble, mode=args.mode, risk_lambda=args.risk_lambda,
        mc_samples=args.mc_samples, error=ap.error,
        date_range=(lo, hi), require_target=False)

    months = [t for t in range(lo, hi) if valid[:, t].any()]

    if args.out:
        np.savez_compressed(args.out, forecast=forecast, valid=valid,
                            dates=panel.dates, firm_ids=panel.firm_ids)
        print(f"wrote {args.out}")
    if args.csv:
        with open(args.csv, "w") as fh:
            fh.write("firm_id,yyyymm,forecast,rank\n")
            for t in months:
                ix = np.nonzero(valid[:, t])[0]
                order = ix[np.argsort(-forecast[ix, t])]
                for rank, i in enumerate(order, 1):
                    fh.write(f"{int(panel.firm_ids[i])},"
                             f"{int(panel.dates[t])},"
                             f"{forecast[i, t]:.6f},{rank}\n")
        print(f"wrote {args.csv}")

    t = months[-1]
    ix = np.nonzero(valid[:, t])[0]
    order = ix[np.argsort(-forecast[ix, t])][:args.top]
    n_live = sum(1 for m in months if not panel.target_valid[:, m].any())
    print(f"{len(months)} forecast month(s) {int(panel.dates[months[0]])}"
          f"..{int(panel.dates[t])} ({n_live} live); latest month "
          f"{int(panel.dates[t])}: {ix.size} names")
    for rank, i in enumerate(order, 1):
        print(f"  #{rank:<3d} firm {int(panel.firm_ids[i]):>8d}  "
              f"forecast {forecast[i, t]:+.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
