#!/usr/bin/env python
"""Reproducers for the CPU-measured design-decision evidence rows.

Every framework decision taken on measured evidence (ledger rows in
BENCH_ROWS.jsonl, narrative in docs/DESIGN.md) must be re-runnable from
the tree — these are the exact protocols behind the 2026-07-31 rows:

  recurrence   → `recurrence_accuracy` rows: LSTM vs LRU at the c2
                 window geometry (scripts/compare_recurrence.py — kept
                 as its own script; listed here for discoverability).
  lamb         → `large_batch_optimizer` rows: reference-batch AdamW vs
                 8× batch AdamW (linearly scaled lr) vs 8× batch LAMB.
  warmstart    → `walkforward_warm_start` rows: per-fold epochs-to-stop
                 and fold val IC, warm vs cold carry.
  uncertainty  → `uncertainty_aggregation` rows: mean / mean−λ·std /
                 mean−λ·total_std backtest Sharpe on the heteroscedastic
                 testbed (synthetic_panel het_noise=1.0).
  derived      → `derived_features` rows: anchor-only MLP vs windowed
                 MLP/LSTM vs anchor MLP + chg_12 — the generator
                 separation calibration.
  mcdropout    → `noise_profile_recovery` rows: NLL head vs MC-dropout
                 std at recovering the planted noise profile — the
                 estimator division-of-labor measurement.

Run: python scripts/evidence_probes.py <probe> [seeds]
Rows append to the ledger (LFM_BENCH_ROWS overrides the path); point it
at a scratch file to re-measure without touching the banked evidence.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# These probes ARE the backend=cpu evidence class — force the CPU platform
# before any backend use. The axon PJRT plugin ignores JAX_PLATFORMS env,
# and on a wedged tunnel the default backend claim HANGS the whole probe
# (observed 2026-07-31: a probe slept at claim for minutes with 2 s of CPU
# time). LFM_PROBE_BACKEND=tpu deliberately opts back into the chip.
if os.environ.get("LFM_PROBE_BACKEND", "cpu") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

from bench import persist_row  # noqa: E402


def _mean_std(vals):
    import numpy as np

    return round(float(np.mean(vals)), 4), round(float(np.std(vals)), 4)


def probe_lamb(seeds=(0, 1)):
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    panel = synthetic_panel(n_firms=2000, n_months=240, n_features=16, seed=0)
    splits = PanelSplits.by_date(panel, 198601, 198801)

    def run(dates, opt, lr, seed):
        cfg = RunConfig(
            name="lamb_probe",
            data=DataConfig(n_firms=2000, n_months=240, n_features=16,
                            window=12, dates_per_batch=dates,
                            firms_per_date=0),
            model=ModelConfig(kind="mlp", kwargs={"hidden": (64, 32)}),
            optim=OptimConfig(lr=lr, epochs=6, warmup_steps=20,
                              early_stop_patience=6, loss="mse",
                              optimizer=opt),
            seed=seed)
        return Trainer(cfg, splits).fit()["best_val_ic"]

    arms = (("ref_adamw_b4", 4, "adamw", 3e-3),
            ("big_adamw_b32", 32, "adamw", 2.4e-2),
            ("big_lamb_b32", 32, "lamb", 2.4e-2))
    for tag, dates, opt, lr in arms:
        mean, std = _mean_std([run(dates, opt, lr, s) for s in seeds])
        rec = {"metric": "large_batch_optimizer", "config": tag,
               "value": mean, "std": std, "unit": "best_val_ic",
               "n_seeds": len(seeds), "optimizer": opt, "backend": "cpu"}
        persist_row(rec)
        print(rec, flush=True)


def probe_warmstart(seeds=(0, 1)):
    import shutil
    import tempfile

    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.walkforward import run_walkforward

    panel = synthetic_panel(n_firms=300, n_months=220, n_features=8, seed=3)

    def cfg(seed):
        return RunConfig(
            name="warm_probe",
            data=DataConfig(n_firms=300, n_months=220, n_features=8,
                            window=12, dates_per_batch=4, firms_per_date=64,
                            panel_seed=3),
            model=ModelConfig(kind="mlp", kwargs={"hidden": (32,)}),
            optim=OptimConfig(lr=3e-3, epochs=12, warmup_steps=10,
                              early_stop_patience=2, loss="mse"),
            seed=seed)

    scratch = tempfile.mkdtemp(prefix="warm_probe_")
    try:
        for warm in (False, True):
            epochs, ics = [], []
            for seed in seeds:
                out = os.path.join(scratch, f"{warm}_{seed}")
                _, _, summary = run_walkforward(
                    cfg(seed), panel, start=198101, step_months=12,
                    val_months=24, n_folds=4, out_dir=out,
                    warm_start=warm)
                later = summary["folds"][1:]  # fold 0 identical either way
                epochs += [r["epochs_run"] for r in later]
                ics += [r["best_val_ic"] for r in later]
            rec = {"metric": "walkforward_warm_start",
                   "config": "warm" if warm else "cold",
                   "value": round(float(np.mean(epochs)), 2),
                   "unit": "epochs_to_stop_per_fold",
                   "mean_best_val_ic": round(float(np.mean(ics)), 4),
                   "n_folds": len(epochs), "backend": "cpu"}
            persist_row(rec)
            print(rec, flush=True)
    finally:
        # Orbax writes per-epoch checkpoints under every fold dir —
        # unbounded /tmp growth across re-measurements otherwise.
        shutil.rmtree(scratch, ignore_errors=True)


def probe_uncertainty(seeds=(0,)):
    """``seeds`` = base seeds; each trains its OWN 4-member ensemble and
    the per-mode Sharpes average across them (the ensemble's internal
    member count stays 4 — the aggregation comparison, not the ensemble
    width, is what this probe measures)."""
    import numpy as np

    from lfm_quant_tpu.backtest import aggregate_ensemble, run_backtest
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    panel = synthetic_panel(n_firms=800, n_months=400, n_features=6, seed=11,
                            het_noise=1.0, signal_strength=1.0)
    splits = PanelSplits.by_date(panel, 198601, 198801)
    modes = ("mean", "mean_minus_std", "mean_minus_total_std")
    sharpes = {m: [] for m in modes}
    extras = {}
    for seed in seeds:
        cfg = RunConfig(
            name="unc_probe", n_seeds=4,
            data=DataConfig(n_firms=800, n_months=400, n_features=6,
                            window=12, dates_per_batch=4, firms_per_date=128,
                            panel_seed=11, het_noise=1.0),
            model=ModelConfig(kind="mlp", kwargs={"hidden": (48,)}),
            optim=OptimConfig(lr=3e-3, epochs=8, warmup_steps=15,
                              early_stop_patience=3, loss="nll"),
            seed=seed)
        tr = EnsembleTrainer(cfg, splits)
        tr.fit()
        stacked, avar, valid = tr.predict("test", return_variance=True)
        for mode in modes:
            kw = ({"aleatoric_var": avar}
                  if mode == "mean_minus_total_std" else {})
            fc, fcv = aggregate_ensemble(stacked, valid, mode, 1.0, **kw)
            rep = run_backtest(fc, fcv, panel, quantile=0.1)
            sharpes[mode].append(float(rep.sharpe_ann))
            extras[mode] = {"cagr": round(float(rep.cagr), 4),
                            "mean_ic": round(float(rep.mean_ic), 4),
                            "oos_months": int(rep.n_months)}
    for mode in modes:
        mean, std = _mean_std(sharpes[mode])
        rec = {"metric": "uncertainty_aggregation", "config": mode,
               "value": mean, "std": std, "unit": "sharpe_ann",
               **extras[mode], "het_noise": 1.0, "n_seeds": 4,
               "n_runs": len(seeds), "backend": "cpu"}
        persist_row(rec)
        print(rec, flush=True)


def probe_derived(seeds=(0, 1)):
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.data.features import add_derived_features
    from lfm_quant_tpu.train import Trainer

    base_panel = synthetic_panel(n_firms=600, n_months=220, n_features=5,
                                 seed=17)

    def run(kind, kwargs, panel, n_feat, window, seed):
        cfg = RunConfig(
            name="derived_probe",
            data=DataConfig(n_firms=600, n_months=220, n_features=n_feat,
                            window=window, dates_per_batch=4,
                            firms_per_date=96, panel_seed=17),
            model=ModelConfig(kind=kind, kwargs=kwargs),
            optim=OptimConfig(lr=3e-3, epochs=8, warmup_steps=15,
                              early_stop_patience=3, loss="mse"),
            seed=seed)
        splits = PanelSplits.by_date(panel, 198401, 198601)
        return Trainer(cfg, splits).fit()["best_val_ic"]

    arms = (
        ("mlp_w1_plain", "mlp", {"hidden": (48,)}, base_panel, 5, 1),
        ("mlp_w1_derived", "mlp", {"hidden": (48,)},
         add_derived_features(base_panel, ("chg_ebit_ev_12",)), 6, 1),
        ("mlp_w12_plain", "mlp", {"hidden": (48,)}, base_panel, 5, 12),
        ("lstm_w12_plain", "lstm", {"hidden": 32}, base_panel, 5, 12),
    )
    for tag, kind, kwargs, panel, nf, w in arms:
        mean, std = _mean_std(
            [run(kind, kwargs, panel, nf, w, s) for s in seeds])
        rec = {"metric": "derived_features", "config": tag, "value": mean,
               "std": std, "unit": "best_val_ic", "n_seeds": len(seeds),
               "backend": "cpu"}
        persist_row(rec)
        print(rec, flush=True)


def probe_mcdropout(seeds=(0,)):
    """NLL head vs MC-dropout std at recovering the planted noise
    profile on the het testbed — per-firm Spearman ρ of predicted
    uncertainty vs realized residual spread (seeds average the ρs)."""
    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.ops.metrics import noise_recovery_rho
    from lfm_quant_tpu.train import Trainer

    panel = synthetic_panel(n_firms=300, n_months=160, n_features=5, seed=9,
                            het_noise=1.0)
    splits = PanelSplits.by_date(panel, 198001, 198201)
    data = DataConfig(n_firms=300, n_months=160, n_features=5, window=12,
                      dates_per_batch=4, firms_per_date=64, panel_seed=9,
                      het_noise=1.0)

    def firm_corr(unc_std, fc, valid):
        # ONE protocol with the CI gate: lfm_quant_tpu.ops.metrics.
        return noise_recovery_rho(panel.targets, fc, unc_std, valid)

    rhos = {"nll_head": [], "mc_dropout": []}
    for seed in seeds:
        cfg = RunConfig(
            name="mcd_nll", data=data,
            model=ModelConfig(kind="mlp", kwargs={"hidden": (32,)}),
            optim=OptimConfig(lr=3e-3, epochs=8, warmup_steps=10,
                              early_stop_patience=8, loss="nll"), seed=seed)
        tr = Trainer(cfg, splits)
        tr.fit()
        fc, avar, valid = tr.predict("val", return_variance=True)
        rhos["nll_head"].append(firm_corr(np.sqrt(avar), fc, valid))

        cfg = RunConfig(
            name="mcd_drop", data=data,
            model=ModelConfig(kind="mlp",
                              kwargs={"hidden": (32,), "dropout": 0.2}),
            optim=OptimConfig(lr=3e-3, epochs=8, warmup_steps=10,
                              early_stop_patience=8, loss="mse"), seed=seed)
        tr = Trainer(cfg, splits)
        tr.fit()
        stacked, valid = tr.predict("val", mc_samples=16)
        rhos["mc_dropout"].append(
            firm_corr(stacked.std(axis=0), stacked.mean(axis=0), valid))
    for tag, vals in rhos.items():
        mean, std = _mean_std(vals)
        rec = {"metric": "noise_profile_recovery", "config": tag,
               "value": mean, "std": std,
               "unit": "spearman_rho_vs_realized",
               "het_noise": 1.0, "n_seeds": len(seeds), "backend": "cpu"}
        persist_row(rec)
        print(rec, flush=True)


def probe_native(seeds=(0, 1, 2)):
    """The two C++ host-runtime claims (README "native" row): CSV parse
    vs pandas' C parser and epoch index sampling vs the numpy sampler.
    ``seeds`` doubles as the rep count — each value is the median of
    len(seeds) interleaved reps so one host-scheduler hiccup can't mint
    a speedup claim."""
    import shutil
    import tempfile
    import time

    import numpy as np

    from lfm_quant_tpu import native
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.data.compustat import (_parse_native, _parse_pandas,
                                              to_long_frame)
    from lfm_quant_tpu.data.windows import DateBatchSampler

    if not native.available():
        print("native library unavailable — nothing to measure",
              file=sys.stderr)
        return

    reps = max(3, len(seeds))
    panel = synthetic_panel(n_firms=2000, n_months=240, n_features=16,
                            seed=0)
    work = tempfile.mkdtemp(prefix="native_probe_")
    csv_path = os.path.join(work, "panel.csv")
    to_long_frame(panel).to_csv(csv_path, index=False)

    def _ratio_rec(config, unit, slow, fast, extras):
        # Per-rep ratios (interleaved, so drift hits both engines within
        # a rep): median + spread_pct in the exact shape
        # regen_baseline's error-bar renderer consumes.
        ratios = sorted(s / f for s, f in zip(slow, fast))
        med = float(np.median(ratios))
        rec = {"metric": "native_host_runtime", "config": config,
               "value": round(med, 2), "unit": unit, "n_reps": len(ratios),
               "spread_pct": round(
                   100.0 * (ratios[-1] - ratios[0]) / med, 1),
               "rep_values": [round(r, 2) for r in ratios],
               **extras, "backend": "cpu"}
        persist_row(rec)
        print(rec, flush=True)

    try:
        times = {"native": [], "pandas": []}
        for _ in range(reps):  # interleaved: drift hits both engines
            t0 = time.perf_counter()
            _parse_native(csv_path, None)
            times["native"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            _parse_pandas(csv_path, None)
            times["pandas"].append(time.perf_counter() - t0)
        _ratio_rec("csv_parse", "speedup_vs_pandas",
                   times["pandas"], times["native"],
                   {"native_s": round(float(np.median(times["native"])), 3),
                    "pandas_s": round(float(np.median(times["pandas"])), 3)})
    finally:
        shutil.rmtree(work, ignore_errors=True)

    times = {"native": [], "python": []}
    n_epochs = 8  # amortize the one-time eligibility/CSR setup

    def one_rep(engine):
        sampler = DateBatchSampler(panel, 12, 8, 256, seed=1, engine=engine)
        sampler.stacked_epoch(0)  # warm: build + first-epoch caches
        t0 = time.perf_counter()
        for ep in range(1, n_epochs + 1):
            sampler.stacked_epoch(ep)
        return (time.perf_counter() - t0) / n_epochs

    for engine in ("native", "python"):
        one_rep(engine)  # DISCARDED process-level warmup: the .so
        # load/bind and allocator/cache warm costs land here, not on rep
        # 1's ledgered timing (the first measured rep read 0.48-0.75x —
        # native "slower" than numpy — before this existed)
    for _ in range(reps):
        for engine in ("native", "python"):
            times[engine].append(one_rep(engine))
    _ratio_rec("epoch_sampling", "speedup_vs_numpy",
               times["python"], times["native"],
               {"native_ms": round(float(np.median(times["native"])) * 1e3,
                                   2),
                "python_ms": round(float(np.median(times["python"])) * 1e3,
                                   2)})


PROBES = {"lamb": probe_lamb, "warmstart": probe_warmstart,
          "uncertainty": probe_uncertainty, "derived": probe_derived,
          "mcdropout": probe_mcdropout, "native": probe_native}


def main(argv) -> int:
    if not argv or argv[0] not in PROBES:
        print(f"usage: evidence_probes.py {{{'|'.join(sorted(PROBES))}}} "
              "[n_seeds]", file=sys.stderr)
        return 2
    kw = {}
    if len(argv) > 1:
        n = int(argv[1])
        if n < 1:
            print(f"n_seeds must be >= 1, got {n} (a zero-seed run would "
                  "append NaN rows to the evidence ledger)", file=sys.stderr)
            return 2
        kw["seeds"] = tuple(range(n))
    PROBES[argv[0]](**kw)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
