"""Staged c1 bench diagnostic: per-stage prints + periodic stack dumps.

The first on-chip `bench_ladder.py c1` run (2026-07-30) hung with no
output and left the axon tunnel wedged for every subsequent client (see
BASELINE.md's outage note). This script re-runs the same measurement
stage by stage — panel build, trainer build (device_put), state init,
batch staging, one multi-step dispatch, readback, full measure — with
per-stage timing prints and all-thread stack dumps to stderr every 60 s,
so a recurrence pinpoints the exact blocking frame.

Run:  python scripts/diag_c1.py [gather_impl|-] [k]
  gather_impl: xla | pallas | - (config default; NOTE: "auto" now
    resolves f32 panels to the XLA gather — resolve_gather_impl's
    safety gate added after this suspect was identified — so "-" is a
    safe-default run, and the suspect probe must say "pallas"
    EXPLICITLY).
    Diagnose with "xla" FIRST (rules out the MLP program), then
    "pallas" (the f32 Pallas DMA gather — the prime suspect: c1 is the
    only f32 ladder config, and only bf16 gathers have ever run on
    chip).
  k: steps per dispatch (default 5).
DIAG_CPU=1 forces the CPU backend (sanity check of the script itself).
"""
import dataclasses
import faulthandler
import os as _os
import sys
import time

faulthandler.dump_traceback_later(60, repeat=True)

_repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
sys.path.insert(0, _repo)
sys.path.insert(0, _os.path.join(_repo, "scripts"))

t0 = time.time()


def stage(msg):
    print(f"[{time.time()-t0:7.1f}s] {msg}", flush=True)


stage("importing jax")
import os  # noqa: E402

import jax  # noqa: E402

if os.environ.get("DIAG_CPU"):
    jax.config.update("jax_platforms", "cpu")

stage(f"backend={jax.default_backend()} devices={jax.devices()}")

from bench import measure_trainer  # noqa: E402
from bench_ladder import _bench_panel  # noqa: E402
from lfm_quant_tpu.config import get_preset  # noqa: E402
from lfm_quant_tpu.train import Trainer  # noqa: E402

cfg = get_preset("c1")
if len(sys.argv) > 1 and sys.argv[1] != "-":
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(cfg.data, gather_impl=sys.argv[1]))
k = int(sys.argv[2]) if len(sys.argv) > 2 else 5
stage("building panel")
splits = _bench_panel(cfg)
stage("building trainer (device_put panel)")
tr = Trainer(cfg, splits)
stage(f"trainer built; gather_impl={tr._gather_impl}")
state = tr.init_state()
stage("state init done")
b = tr.train_sampler.stacked_epoch(0)
b = dataclasses.replace(b, firm_idx=b.firm_idx[:k], time_idx=b.time_idx[:k],
                        weight=b.weight[:k])
fi, ti, w = tr._batch_args(b, train=True, steps=True)
stage(f"batch staged k={k}; dispatching multi-step (compile)")
_, ms = tr._jit_multi_step(state, tr.dev, fi, ti, w)
stage("dispatched; forcing readback")
loss = float(ms["loss"][-1])
stage(f"readback done loss={loss:.5f}")
v = measure_trainer(tr, k=k, reps=1)
stage(f"measured {v:.0f} fm/s")
# Bank the outcome: the campaign's resume guard (ledger_has) skips this
# diagnostic on later heal-cycles once a measured row exists — without
# it the pallas suspect probe would re-trip the wedge on EVERY cycle.
from bench import _backend_name, persist_row  # noqa: E402

persist_row({"metric": "diag_c1", "impl": tr._gather_impl,
             "value": round(v, 1), "unit": "firm-months/sec/chip",
             "backend": _backend_name()})
faulthandler.cancel_dump_traceback_later()
