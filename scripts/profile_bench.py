#!/usr/bin/env python
"""Ad-hoc profiling of the c2 bench step: where does the time go?

Compares wall-clock of variants on the real chip:
  full      — train step (fwd+bwd+optax) as bench.py runs it
  fwd       — forward+loss only
  fwd_model — forward without gather (pre-gathered windows)
  gather    — window gather only
Also sweeps batch geometry to test latency- vs throughput-bound.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lfm_quant_tpu.config import get_preset
from lfm_quant_tpu.data import PanelSplits, synthetic_panel
from lfm_quant_tpu.train import Trainer
import dataclasses as dc


def timeit(fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)
    # force real sync via readback
    _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / reps


def main():
    cfg = get_preset("c2")
    d = cfg.data
    panel = synthetic_panel(n_firms=d.n_firms, n_months=240,
                            n_features=d.n_features, horizon=d.horizon, seed=0)
    splits = PanelSplits.by_date(panel, 198601, 198801)
    trainer = Trainer(cfg, splits)
    state = trainer.init_state()

    # Trainer builds its panel with raw=False (xm only); the gather
    # isolation below needs the unpacked features/valid arrays too.
    from lfm_quant_tpu.data.windows import device_panel
    # lane_pad must match what Trainer.__init__ chose, or a pallas-resolved
    # gather re-pads the whole panel inside every profiled step.
    # compute_dtype must match what Trainer.__init__ resolved (the
    # per-model bf16 flag folded with the LFM_PRECISION lane) for the
    # same reason as lane_pad: a dtype-mismatched panel gives every
    # profiled step fresh avals and the profile measures compiles.
    trainer.dev = device_panel(
        splits.panel, None,
        compute_dtype=trainer._compute_dtype, raw=True,
        lane_pad=trainer._gather_impl == "pallas")

    b = trainer.train_sampler.stacked_epoch(0)
    k = min(30, b.firm_idx.shape[0])
    b = dc.replace(b, firm_idx=b.firm_idx[:k], time_idx=b.time_idx[:k],
                   weight=b.weight[:k])
    fi, ti, w = trainer._batch_args(b, train=True, steps=True)
    fm = float(b.weight.sum()) * trainer.window

    # The multi-step wrapper DONATES its state (train/reuse.py): thread
    # the returned state through a holder so each rep consumes the
    # previous rep's output instead of a deleted buffer.
    state_box = [state]

    def full_step():
        st, ms = trainer._jit_multi_step(state_box[0], trainer.dev,
                                         fi, ti, w)
        state_box[0] = st
        return ms

    t_full = timeit(full_step)
    print(f"full multi-step ({k} steps): {t_full*1e3:.1f} ms  "
          f"-> {fm/t_full/1e6:.1f} M fm/s")

    # forward only, scanned over the same steps
    from lfm_quant_tpu.data.windows import gather_windows, gather_targets

    @jax.jit
    def fwd_scan(params, dev, fi, ti, w):
        def body(c, batch):
            bfi, bti, bw = batch
            x, m = gather_windows(dev["features"], dev["valid"], bfi, bti,
                                  trainer.window)
            y = gather_targets(dev["targets"], bfi, bti)
            out = trainer._apply(params, x, m)
            return c, trainer.loss_fn(out, y, bw)
        return jax.lax.scan(body, 0, (fi, ti, w))

    t_fwd = timeit(lambda: fwd_scan(state_box[0].params, trainer.dev, fi, ti, w))
    print(f"fwd+loss scan: {t_fwd*1e3:.1f} ms ({t_fwd/t_full*100:.0f}% of full)")

    @jax.jit
    def gather_scan(dev, fi, ti, w):
        def body(c, batch):
            bfi, bti, bw = batch
            x, m = gather_windows(dev["features"], dev["valid"], bfi, bti,
                                  trainer.window)
            return c, (x.sum(), m.sum())
        return jax.lax.scan(body, 0, (fi, ti, w))

    t_g = timeit(lambda: gather_scan(trainer.dev, fi, ti, w))
    print(f"gather-only scan: {t_g*1e3:.1f} ms ({t_g/t_full*100:.0f}% of full)")

    # pre-gathered model forward (no gather, no loss): isolates the RNN
    x, m = jax.jit(gather_windows, static_argnums=4)(
        trainer.dev["features"], trainer.dev["valid"],
        jnp.asarray(b.firm_idx[0]), jnp.asarray(b.time_idx[0]), trainer.window)

    @jax.jit
    def model_only(params, x, m):
        return trainer._apply(params, x, m)

    t_m = timeit(lambda: model_only(state_box[0].params, x, m), reps=10)
    per_batch_full = t_full / k
    print(f"model fwd single batch [{x.shape[0]}x{x.shape[1]}]: {t_m*1e3:.2f} ms "
          f"(full step avg {per_batch_full*1e3:.2f} ms)")

    # batch-size sweep on the raw model forward
    for mult in (2, 4, 8):
        xx = jnp.tile(x, (mult, 1, 1, 1)).reshape((-1,) + x.shape[1:])[
            : x.shape[0] * mult]
        mm = jnp.tile(m, (mult, 1, 1)).reshape((-1,) + m.shape[1:])[
            : m.shape[0] * mult]
        t = timeit(lambda: model_only(state_box[0].params, xx, mm), reps=5)
        print(f"model fwd batch x{mult} [{xx.shape[0]}]: {t*1e3:.2f} ms "
              f"({t/t_m:.2f}x time for {mult}x work)")


if __name__ == "__main__":
    main()
