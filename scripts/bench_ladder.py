#!/usr/bin/env python
"""Measure training throughput for EVERY capability-ladder config (c1–c5)
at its own geometry on the current backend (the single real chip under
axon; CPU when forced) — the evidence stream for BASELINE.md's measured
table (SURVEY.md §7: "every config on the ladder gets a recorded number").

Each line: {"metric": "train_throughput_<cfg>", "value": fm/s, "unit":
"firm-months/sec/chip", "mfu_pct": ...} — same schema as bench.py (which
stays the driver-facing 2-metric harness; this script is the full sweep).

Multi-shard configs (c3: 8-way date sharding, c4: 16-way) degrade to the
single visible device — the measured number exercises the full batch
geometry, the rank-IC loss (c3) and bf16 transformer (c4) paths; the mesh
variants of the same step are equality-tested on the virtual 8-device CPU
mesh (tests/test_parallel.py), so per-shard throughput transfers.

Run: python scripts/bench_ladder.py [c1 c2 ...]   (default: all)
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import (  # noqa: E402
    V5E_BF16_PEAK,
    _backend_name,
    eval_path,
    measure_ensemble_trainer,
    measure_eval,
    measure_trainer,
    measure_with_spread,
    persist_row,
)


def _mlp_train_flops_per_fm(hidden, window: int, features: int) -> float:
    """MLP consumes the flattened [W·F] window per anchor; amortize the
    per-window FLOPs over its W firm-months to keep the metric comparable
    across model families."""
    dims = (window * features,) + tuple(hidden) + (1,)
    per_window = sum(2 * a * b for a, b in zip(dims, dims[1:]))
    return 3.0 * per_window / window


def _rnn_train_flops_per_fm(cell: str, hidden: int, features: int) -> float:
    gates = {"lstm": 4, "gru": 3}[cell]
    fwd = 2 * features * hidden + 2 * hidden * gates * hidden * 2
    return 3.0 * fwd


def _transformer_train_flops_per_fm(dim: int, depth: int, window: int,
                                    features: int) -> float:
    """Per token (= firm-month): embed + depth × (qkvo projections,
    attention scores/values over the W-token window, 4× MLP)."""
    per_layer = 8 * dim * dim + 4 * window * dim + 16 * dim * dim
    fwd = 2 * features * dim + depth * per_layer
    return 3.0 * fwd


def _lru_train_flops_per_fm(hidden: int, state: int, layers: int,
                            features: int) -> float:
    """Per firm-month: embed (F→H) + per layer the complex B (2× H→N) and
    C (2× N→H) GEMMs; the associative scan is elementwise (excluded, like
    the RNN gate math)."""
    fwd = 2 * features * hidden + layers * 4 * 2 * hidden * state
    return 3.0 * fwd


def _flops_per_fm(cfg) -> float:
    kind, kw, d = cfg.model.kind, cfg.model.kwargs, cfg.data
    if kind == "mlp":
        return _mlp_train_flops_per_fm(kw.get("hidden", (64, 32)), d.window,
                                       d.n_features)
    if kind in ("lstm", "gru"):
        return _rnn_train_flops_per_fm(kind, kw.get("hidden", 128),
                                       d.n_features)
    if kind == "lru":
        return _lru_train_flops_per_fm(kw.get("hidden", 128),
                                       kw.get("state_dim", 128),
                                       kw.get("layers", 2), d.n_features)
    return _transformer_train_flops_per_fm(kw.get("dim", 64),
                                           kw.get("depth", 2), d.window,
                                           d.n_features)


def _bench_panel(cfg):
    """Full firm/feature/window geometry; months trimmed to 4× the window
    so panel generation isn't the bottleneck (throughput is O(batch), not
    O(panel), once the panel is HBM-resident)."""
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel

    d = cfg.data
    n_months = min(d.n_months, max(4 * d.window, 240))
    panel = synthetic_panel(n_firms=d.n_firms, n_months=n_months,
                            n_features=d.n_features, horizon=d.horizon,
                            seed=0)
    dates = panel.dates
    train_end = int(dates[int(len(dates) * 0.80)])
    val_end = int(dates[int(len(dates) * 0.90)])
    return PanelSplits.by_date(panel, train_end, val_end)


def _log(msg: str) -> None:
    """Stage progress on stderr: a hung run (remote compile, tunnel) then
    shows exactly which config/stage it died in instead of going silent."""
    print(f"[bench_ladder] {msg}", file=sys.stderr, flush=True)


def _overrides(cfg):
    """Env overrides mirroring bench.py's LFM_BENCH_SCAN_IMPL:
    LFM_BENCH_GATHER_IMPL=auto|xla|pallas reroutes the window gather —
    the bisection hook for on-chip gather issues."""
    import bench as _bench

    if cfg.model.kind in ("lstm", "gru"):  # scan_impl is an RNN-only knob
        cfg = _bench._scan_impl_override(cfg)
    gi = os.environ.get("LFM_BENCH_GATHER_IMPL")
    if gi:
        cfg = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data, gather_impl=gi))
    # LFM_BENCH_DATES: dates per batch on THIS device. The sharded
    # configs (c3: 8-way, c4: 16-way) degrade to the one visible chip;
    # their real per-shard batch is dates_per_batch / n_shards, and at
    # c3's full-universe width (Bf ≈ 8192) the full-D batch may not fit
    # one chip's HBM even though the per-shard batch does.
    dates = os.environ.get("LFM_BENCH_DATES")
    if dates:
        cfg = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data,
                                          dates_per_batch=int(dates)),
            n_data_shards=1)
    return cfg


def bench_config(name: str):
    """Yield train then eval throughput records for one ladder config.

    A GENERATOR so the train record reaches the caller (and stdout)
    before the eval sweep runs — a tunnel death or OOM mid-eval must not
    discard an already-measured train number from a scarce chip session.
    Eval is the inference/backtest half of the workflow (SURVEY.md §4.3):
    the stacked full-cross-section sweep; its analytic MFU uses
    forward-only FLOPs (1/3 of the 3× fwd+bwd training count)."""
    from lfm_quant_tpu.config import get_preset
    from lfm_quant_tpu.train import Trainer
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    preset = get_preset(name)
    cfg = _overrides(preset)
    _log(f"{name}: building panel")
    splits = _bench_panel(cfg)
    extras = {}
    if cfg.data.dates_per_batch != preset.data.dates_per_batch:
        # LFM_BENCH_DATES was applied: the record must say which batch
        # geometry it measured (per-shard vs full-D are different rows).
        extras["dates_per_batch"] = cfg.data.dates_per_batch
    if cfg.n_seeds > 1:
        n_seeds = int(os.environ.get("LFM_BENCH_SEEDS", "16"))
        seed_block = int(os.environ.get("LFM_BENCH_SEED_BLOCK", "0"))
        cfg = dataclasses.replace(cfg, n_seeds=n_seeds,
                                  seed_block=seed_block)
        extras["n_seeds"] = n_seeds
        if seed_block:  # record the memory/throughput trade-off knob
            extras["seed_block"] = seed_block
        _log(f"{name}: building EnsembleTrainer ({cfg.n_seeds} seeds)")
        trainer = EnsembleTrainer(cfg, splits)
        _log(f"{name}: measuring train (compile on first dispatch)")
        value, spread = measure_with_spread(lambda: measure_ensemble_trainer(
            trainer, k=int(os.environ.get("LFM_BENCH_STEPS", "10"))))
    else:
        _log(f"{name}: building Trainer")
        trainer = Trainer(cfg, splits)
        _log(f"{name}: gather={trainer._gather_impl}; measuring train "
             "(compile on first dispatch)")
        value, spread = measure_with_spread(lambda: measure_trainer(
            trainer, k=int(os.environ.get("LFM_BENCH_STEPS", "30"))))
    # The RESOLVED impls (auto → xla|pallas|pallas_fused happened at
    # build time) and the backend, per row: a ledger row must say which
    # program ran where — A/B rows differ only by these fields, and a CPU
    # smoke run must never collapse onto a chip row under regen's
    # latest-per-key rule.
    inner = getattr(trainer, "inner", trainer)
    extras["backend"] = _backend_name()
    extras["gather_impl"] = inner._gather_impl
    if cfg.model.kind in ("lstm", "gru"):
        extras["scan_impl"] = inner.model.scan_impl
    flops = _flops_per_fm(cfg)
    yield {
        "metric": f"train_throughput_{name}",
        "value": round(value, 1),
        "unit": "firm-months/sec/chip",
        "mfu_pct": round(100.0 * value * flops / V5E_BF16_PEAK, 2),
        "config": cfg.name,
        "loss": cfg.optim.loss,
        **extras,
        **spread,
    }
    _log(f"{name}: measuring eval sweep")
    eval_value, eval_spread = measure_with_spread(
        lambda: measure_eval(trainer))
    _log(f"{name}: done")
    # The EVAL dispatch's own gather (promotion flag included) — not the
    # train gather: the A/B rows the promotion flag exists for must get
    # distinct regen keys. lane_pad records the PANEL LAYOUT the eval
    # gathered from: since auto-config eval always rides the XLA gather,
    # a train-gather A/B pair's eval rows share gather_impl=xla but
    # measure different layouts (the pallas-train leg lane-pads the
    # device panel) — without the tag, regen's latest-per-key rule would
    # silently overwrite one with the other.
    eval_extras = dict(extras)
    eval_extras["gather_impl"] = (
        inner._eval_gather_sharded if eval_path(trainer) == "month_sharded"
        else inner._eval_gather_impl)
    eval_extras["lane_pad"] = inner._gather_impl == "pallas"
    yield {
        "metric": f"eval_throughput_{name}",
        "value": round(eval_value, 1),
        "unit": "firm-months/sec/chip",
        "mfu_pct": round(100.0 * eval_value * (flops / 3.0)
                         / V5E_BF16_PEAK, 2),
        "config": cfg.name,
        "eval_path": eval_path(trainer),
        **eval_extras,
        **eval_spread,
    }


def main(argv) -> int:
    names = argv or ["c1", "c2", "c3", "c4", "c5", "lru", "lru64", "lc"]
    for name in names:
        for rec in bench_config(name):
            # Print AND persist per record, not per config: a tunnel death
            # mid-eval must not lose the train row already measured (the
            # generator yields train first for the same reason).
            print(json.dumps(rec), flush=True)
            persist_row(rec)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
