#!/usr/bin/env python
"""Per-run telemetry rollup from a run directory alone — no re-running
bench, no jax import (pure file reading, safe on any host).

Reads the artifacts the unified telemetry layer
(lfm_quant_tpu/utils/telemetry.py) writes when a run is active:

* ``manifest.json``  — provenance (config, knobs, devices, git sha)
* ``spans.jsonl``    — one line per closed span, with per-span counter
                       deltas (``d``) and result args (``args``)
* ``ledger.jsonl``   — program ledger: per-compiled-program compile
                       wall seconds + XLA cost/memory analysis
* ``trace.json``     — the Chrome-trace/Perfetto event stream (only
                       its presence is reported here; load it at
                       ui.perfetto.dev for the timeline)

Prints epochs/hour, device-idle fraction, host-sync counts, the top
spans by total wall time, and the HBM/compile-cost ledger by program.
The epochs/hour and idle-fraction formulas match ``bench.py
epoch_pipeline`` (epochs per fit-wall-hour; idle seconds over fit
wall), so the rollup is directly cross-checkable against the bench
ledger on comparable geometry.

Usage:
    python scripts/trace_report.py runs/c1_mlp_toy/wf
    python scripts/trace_report.py runs/c1_mlp_toy/seed0 --json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional


def _pctl(values: List[float], q: float) -> Optional[float]:
    """Linear-interpolation percentile — VERBATIM twin of
    ``lfm_quant_tpu/serve/stats.py percentile`` (this script must stay
    importable with no package/jax dependency, so the formula is
    duplicated; the serve test lane cross-checks the two on the same
    run dir, and ``bench.py serve`` re-checks at measurement time)."""
    if not values:
        return None
    v = sorted(values)
    k = (len(v) - 1) * q / 100.0
    f, c = math.floor(k), math.ceil(k)
    if f == c:
        return float(v[int(k)])
    return float(v[f] * (c - k) + v[c] * (k - f))


def _parse_prom(text: str) -> Dict[str, List[Any]]:
    """Parse a Prometheus text scrape into name → [(labels, value)] —
    VERBATIM twin of ``lfm_quant_tpu/utils/metrics.py
    parse_prometheus`` (this script must stay importable with no
    package dependency; the metrics test lane cross-checks the two on
    the same scrape, the percentile-twin discipline applied to
    parsing)."""
    out: Dict[str, List[Any]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, _, val = line.rpartition(" ")
            if "{" in head:
                name, _, rest = head.partition("{")
                body = rest.rsplit("}", 1)[0]
                labels: Dict[str, str] = {}
                for part in body.split(","):
                    if not part:
                        continue
                    k, _, v = part.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
            else:
                name, labels = head, {}
            v = float("inf") if val == "+Inf" else float(val)
            out.setdefault(name.strip(), []).append((labels, v))
        except ValueError:
            continue  # never die on a foreign exposition line
    return out


def _prom_hist_quantile(pairs: List[Any], q: float) -> Optional[float]:
    """Estimated quantile from CUMULATIVE ``(le, count)`` pairs —
    VERBATIM twin of ``utils/metrics.py hist_quantile_from_buckets``
    (same rank rule and in-bucket interpolation as the in-process
    ``LogHistogram.quantile``, so scrape-side estimates can never
    silently drift from the live ones)."""
    if not pairs:
        return None
    pairs = sorted(pairs, key=lambda p: p[0])
    total = pairs[-1][1]
    if total <= 0:
        return None
    rank = (total - 1) * q / 100.0
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in pairs:
        if cum > rank and cum > prev_cum:
            if not math.isfinite(le):
                return float(prev_le)  # overflow bucket: clamp
            c = cum - prev_cum
            frac = (rank - prev_cum + 0.5) / c
            return float(prev_le + (le - prev_le)
                         * min(max(frac, 0.0), 1.0))
        if math.isfinite(le):
            prev_le, prev_cum = le, max(prev_cum, cum)
    return float(prev_le)


def _merged_hist_pairs(entries: List[Any]) -> List[Any]:
    """Merge per-label-set cumulative bucket series into one cumulative
    ladder. Series truncate at their own last non-empty bucket (the
    exposition elides trailing zeros), so a plain per-``le`` sum would
    go NON-MONOTONE where a short series stops; instead each series
    contributes its cumulative value at the largest emitted bound <=
    the target ``le`` (== its total once past its last bucket)."""
    series: Dict[Any, List[Any]] = {}
    les: set = set()
    for labels, v in entries:
        le_s = labels.get("le", "")
        le = float("inf") if le_s in ("+Inf", "inf") else float(le_s)
        key = tuple(sorted((k, s) for k, s in labels.items()
                           if k != "le"))
        series.setdefault(key, []).append((le, v))
        if math.isfinite(le):
            les.add(le)
    for pairs in series.values():
        pairs.sort(key=lambda p: p[0])

    def cum_at(pairs: List[Any], le: float) -> float:
        best = 0.0
        for b, v in pairs:
            if b <= le or not math.isfinite(b) and le == math.inf:
                best = max(best, v)
        return best

    out = [(le, sum(cum_at(p, le) for p in series.values()))
           for le in sorted(les)]
    total = sum(max((v for _, v in p), default=0.0)
                for p in series.values())
    out.append((math.inf, total))
    return out


def _read_json(path: str):
    """One JSON document, or None (missing/corrupt — report, don't die)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # a line truncated by a crash — skip, don't die
    return out


def load_run(run_dir: str) -> Dict[str, Any]:
    """All telemetry artifacts of a run dir (missing ones → empty)."""
    manifest: Optional[Dict[str, Any]] = None
    mpath = os.path.join(run_dir, "manifest.json")
    if os.path.exists(mpath):
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
        except (OSError, json.JSONDecodeError):
            manifest = None
    import glob

    # A saved /metrics scrape (serve.py --run-dir and bench.py serve
    # write one as metrics.prom) — the live metrics plane's text
    # document, cross-checked against the span-derived numbers below.
    metrics_text = None
    for p in sorted(glob.glob(os.path.join(run_dir, "metrics*.prom"))):
        try:
            with open(p) as fh:
                metrics_text = fh.read()
            break
        except OSError:
            continue

    # Incident bundles (serve/incident.py, DESIGN.md §21): complete
    # iff incident.json exists (written last, fsync'd) — half-written
    # bundles from a crashed capture are skipped, not half-parsed.
    incidents = []
    inc_base = os.path.join(run_dir, "incidents")
    if os.path.isdir(inc_base):
        for name in sorted(os.listdir(inc_base)):
            bdir = os.path.join(inc_base, name)
            ipath = os.path.join(bdir, "incident.json")
            if not os.path.isfile(ipath):
                continue
            meta = _read_json(ipath)
            if meta is None:
                continue
            prom = None
            ppath = os.path.join(bdir, "metrics.prom")
            if os.path.isfile(ppath):
                try:
                    with open(ppath) as fh:
                        prom = fh.read()
                except OSError:
                    prom = None
            incidents.append({
                "dir": bdir,
                "name": name,
                "meta": meta,
                "flight": _read_jsonl(os.path.join(bdir, "flight.jsonl")),
                "slow": _read_json(os.path.join(bdir,
                                                "slow_requests.json")),
                "metrics_text": prom,
            })

    # The aggregated fleet scrape (serve.py --fleet writes fleet.prom:
    # router counters + member-labeled member series) — deliberately
    # NOT matching the metrics*.prom glob above, because its summed
    # member series answer fleet questions, not the single-process
    # serve cross-check.
    fleet_text = None
    fpath = os.path.join(run_dir, "fleet.prom")
    if os.path.isfile(fpath):
        try:
            with open(fpath) as fh:
                fleet_text = fh.read()
        except OSError:
            fleet_text = None

    return {
        "run_dir": run_dir,
        "manifest": manifest,
        "spans": _read_jsonl(os.path.join(run_dir, "spans.jsonl")),
        "ledger": _read_jsonl(os.path.join(run_dir, "ledger.jsonl")),
        "metrics_text": metrics_text,
        "fleet_text": fleet_text,
        "incidents": incidents,
        # First process owns trace.json; later ones (backtest over a
        # train dir) land as trace.<pid>.json — count them all.
        "trace_files": sorted(
            os.path.basename(p)
            for p in glob.glob(os.path.join(run_dir, "trace*.json"))),
    }


def build_report(run: Dict[str, Any], top: int = 12) -> Dict[str, Any]:
    """Roll the raw artifacts up into the printed/JSON report dict."""
    spans = run["spans"]
    fits = [s for s in spans
            if s.get("name") in ("fit", "foldstack_fit", "stack_fit")]
    epochs = [s for s in spans if s.get("name") == "epoch"]
    runs = [s for s in spans if s.get("name") == "run"]

    fit_wall = sum(s.get("dur_s", 0.0) for s in fits)

    def _fit_epochs(s):
        # A fold-stacked fit's epochs_run is a per-fold list; the
        # stacked loop runs max(folds) epochs of wall time.
        v = s.get("args", {}).get("epochs_run", 0)
        return max((int(x) for x in v), default=0) if isinstance(v, list) \
            else int(v or 0)

    n_epochs = sum(_fit_epochs(s) for s in fits)
    if n_epochs == 0:  # fit spans absent/foreign — fall back to counting
        n_epochs = sum(1 for s in epochs
                       if not s.get("args", {}).get("discarded"))
    idle_s = sum(s.get("d", {}).get("device_idle_s", 0.0) for s in fits)
    syncs = sum(s.get("d", {}).get("host_syncs", 0) for s in fits)
    sync_s = sum(s.get("d", {}).get("host_sync_s", 0.0) for s in fits)

    # Run-level counters: sum over run records (one per process that
    # attached this run dir — train, then backtest, then resume, ...).
    counters: Dict[str, Any] = defaultdict(float)
    for r in runs:
        for k, v in r.get("d", {}).items():
            counters[k] += v
    counters = {k: (int(v) if float(v).is_integer() else v)
                for k, v in counters.items()}
    run_wall = sum(r.get("dur_s", 0.0) for r in runs)

    by_name: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        name = s.get("name", "?")
        agg = by_name.setdefault(name, {"name": name, "count": 0,
                                        "total_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += s.get("dur_s", 0.0)
    for agg in by_name.values():
        agg["total_s"] = round(agg["total_s"], 4)
        agg["mean_s"] = round(agg["total_s"] / max(agg["count"], 1), 5)
        if run_wall > 0:
            agg["pct_wall"] = round(100.0 * agg["total_s"] / run_wall, 1)
    top_spans = sorted((a for a in by_name.values() if a["name"] != "run"),
                       key=lambda a: -a["total_s"])[:top]

    programs: Dict[str, Dict[str, Any]] = {}
    for e in run["ledger"]:
        name = e.get("program", "?")
        agg = programs.setdefault(name, {"program": name, "builds": 0,
                                         "compile_s": 0.0, "flops": 0.0,
                                         "bytes_accessed": 0.0,
                                         "hbm_bytes": 0, "arg_bytes": 0})
        agg["builds"] += 1
        agg["compile_s"] += e.get("compile_s", 0.0)
        agg["flops"] += e.get("flops", 0.0)
        agg["bytes_accessed"] += e.get("bytes_accessed", 0.0)
        # hbm_bytes needs the opt-in deep analysis
        # (LFM_TELEMETRY_ANALYSIS=1); arg_bytes is always recorded and
        # serves as the resident-footprint proxy otherwise.
        agg["hbm_bytes"] = max(agg["hbm_bytes"], e.get("hbm_bytes", 0))
        agg["arg_bytes"] = max(agg["arg_bytes"], e.get("arg_bytes", 0))
    for agg in programs.values():
        agg["compile_s"] = round(agg["compile_s"], 3)
    ledger_rows = sorted(
        programs.values(),
        key=lambda a: -(a["hbm_bytes"] or a["arg_bytes"] or 0))

    report = {
        "run_dir": run["run_dir"],
        "has_trace_json": bool(run["trace_files"]),
        "trace_files": run["trace_files"],
        "n_processes": len(runs),
        "wall_s": round(run_wall, 3),
        "n_fits": len(fits),
        "n_epochs": n_epochs,
        "fit_wall_s": round(fit_wall, 3),
        "epochs_per_hour": (round(3600.0 * n_epochs / fit_wall, 1)
                            if fit_wall > 0 else None),
        "idle_frac": (round(idle_s / fit_wall, 4) if fit_wall > 0
                      else None),
        "host_syncs": int(syncs),
        "host_sync_s": round(sync_s, 4),
        "syncs_per_epoch": (round(syncs / n_epochs, 3) if n_epochs
                            else None),
        "counters": {k: (round(v, 4) if isinstance(v, float) else v)
                     for k, v in sorted(counters.items())},
        "compile_s_total": round(sum(e.get("compile_s", 0.0)
                                     for e in run["ledger"]), 3),
        "top_spans": top_spans,
        "programs": ledger_rows,
    }
    # Fold-stack attribution: per-fold epoch counts / best epochs from
    # the foldstack_fit span args plus the per-fold stop marks, so a
    # stacked run's report shows where each fold's share of the stacked
    # wall went without re-deriving it from metrics files.
    stacks = [s for s in fits if s.get("name") == "foldstack_fit"]
    if stacks:
        stops = [s.get("args", {}) for s in spans
                 if s.get("name") == "fold_stopped"]
        last = stacks[-1].get("args", {})
        # Per-fit fields all scope to the LAST stacked fit (a bench-style
        # run dir holds a warmup stack plus a timed one — mixing an
        # aggregate fold count with last-fit stats would misattribute);
        # n_stacked_fits says how many this run dir holds, and the
        # early-stop marks span all of them.
        report["foldstack"] = {
            "n_stacked_fits": len(stacks),
            "fold_count": int(last.get("fold_count", 0)),
            "fold_mesh": last.get("fold_mesh"),
            "epochs_per_fold": last.get("epochs_run"),
            "best_epochs": last.get("best_epochs"),
            "early_stops": [{"fold": a.get("fold"), "epoch": a.get("epoch")}
                            for a in stops],
        }
    # Stacked-sweep rollup (the generic stacked-run engine,
    # train/stacked.py): per-run epoch counts / best epochs from the
    # stack_fit span args plus the per-run stop marks — and, critically,
    # every degrade-to-sequential event (the ``stack_degraded`` instants
    # + ``stack_degrades`` counter the fold/config drivers emit), so a
    # sweep that silently fell back to serial execution is visible from
    # the run dir alone. The fold-stack section above stays as-is — this
    # section covers the generic engine and the degrade accounting.
    sweeps = [s for s in fits if s.get("name") == "stack_fit"]
    degrades = [s.get("args", {}) for s in spans
                if s.get("name") == "stack_degraded"]
    if sweeps or degrades or counters.get("stack_degrades"):
        section: Dict[str, Any] = {
            "n_stacked_fits": len(sweeps),
            "degrades": len(degrades) or int(
                counters.get("stack_degrades", 0) or 0),
            "degrade_reasons": [
                {"kind": a.get("kind"), "reason": a.get("reason")}
                for a in degrades],
        }
        if sweeps:
            stops2 = [s.get("args", {}) for s in spans
                      if s.get("name") == "run_stopped"]
            last2 = sweeps[-1].get("args", {})
            # Per-fit fields scope to the LAST stacked fit (a bench-style
            # run dir holds a warmup stack plus a timed one).
            section.update(
                kind=last2.get("kind"),
                run_count=int(last2.get("run_count", 0)),
                stack_mesh=last2.get("stack_mesh"),
                stack_block=last2.get("stack_block"),
                hyper=last2.get("hyper"),
                epochs_per_run=last2.get("epochs_run"),
                best_epochs=last2.get("best_epochs"),
                early_stops=[{"run": a.get("run"), "epoch": a.get("epoch")}
                             for a in stops2],
            )
        report["stacked_sweep"] = section
    # Bucketed-geometry rollup (LFM_BUCKETS, DESIGN.md §16): the ladder
    # and static per-epoch cell budgets from the fits' bucket_geometry
    # instants, plus the MEASURED padded-FLOP accounting from the
    # run-level bucket_* counters (bumped per built epoch) — the
    # occupancy/padding numbers ``bench.py bucketed_train`` prices.
    geos = [s.get("args", {}) for s in spans
            if s.get("name") == "bucket_geometry"]
    if geos or counters.get("bucket_dispatches"):
        disp = float(counters.get("bucket_cells_dispatched", 0) or 0)
        real_c = float(counters.get("bucket_cells_real", 0) or 0)
        mx = float(counters.get("bucket_cells_max_shape", 0) or 0)
        last_geo = geos[-1] if geos else {}
        report["buckets"] = {
            "n_fits": len(geos),
            "ladder": last_geo.get("ladder"),
            "n_train_buckets": last_geo.get("n_train_buckets"),
            "n_eval_buckets": last_geo.get("n_eval_buckets"),
            "dispatches": int(counters.get("bucket_dispatches", 0) or 0),
            "cells_dispatched": int(disp),
            "cells_real": int(real_c),
            "cells_max_shape": int(mx),
            # Of the cells actually dispatched, how many were padding —
            # and how many the ladder saved vs max-shape padding.
            "padded_flop_fraction": (round(1.0 - real_c / disp, 4)
                                     if disp else None),
            "padded_flop_fraction_max_shape": (
                round(1.0 - real_c / mx, 4) if mx else None),
            "cells_saved_vs_max_shape": (round(1.0 - disp / mx, 4)
                                         if mx else None),
        }
    # Serving rollup (scoring service, lfm_quant_tpu/serve/): latency
    # percentiles from the per-request ``latency_ms`` the serve_request
    # spans carry — the SAME numbers ScoringService.stats() and
    # ``bench.py serve`` report, so the three agree by construction —
    # plus batch occupancy and queue depth from the serve_batch spans.
    reqs = [s for s in spans if s.get("name") == "serve_request"]
    batches = [s for s in spans if s.get("name") == "serve_batch"]
    if reqs or batches:
        lat = [s["args"]["latency_ms"] for s in reqs
               if "latency_ms" in s.get("args", {})]
        rows = sum(int(s.get("args", {}).get("rows", 0)) for s in batches)
        real = sum(int(s.get("args", {}).get("rows_real", 0))
                   for s in batches)
        depths = [int(s["args"]["queue_depth"]) for s in batches
                  if "queue_depth" in s.get("args", {})]
        report["serve"] = {
            "requests": len(reqs),
            "completed": len(lat),
            "p50_ms": _pctl(lat, 50.0),
            "p99_ms": _pctl(lat, 99.0),
            "max_ms": max(lat) if lat else None,
            "batches": len(batches),
            "rows": rows,
            "rows_real": real,
            "mean_occupancy": round(real / rows, 4) if rows else None,
            "queue_depth_max": max(depths) if depths else None,
            "zoo_swaps": sum(1 for s in spans
                             if s.get("name") == "zoo_swap"),
            "refreshes": sum(1 for s in spans
                             if s.get("name") == "serve_refresh"),
            # Steady-state compile accounting: with warmup inside the
            # run, non-zero means warmup compiles — the serve bench
            # snapshots counters AFTER warmup to pin zero.
            "jit_traces_run": counters.get("jit_traces", 0),
            "panel_transfers_run": counters.get("panel_transfers", 0),
            # Degradation accounting (DESIGN.md §18): shed/dropped/
            # retried request counts from the run-record counter deltas,
            # breaker transitions from the circuit_* instants (the
            # circuit_state gauge is a snapshot, not a delta — the
            # instants are the durable record of each open/close).
            "shed": int(counters.get("serve_shed", 0) or 0),
            "deadline_drops": int(
                counters.get("serve_deadline_drops", 0) or 0),
            "retries": int(counters.get("serve_retries", 0) or 0),
            "breaker_opens": (
                sum(1 for s in spans if s.get("name") == "circuit_open")
                or int(counters.get("serve_breaker_opens", 0) or 0)),
            "breaker_closes": sum(1 for s in spans
                                  if s.get("name") == "circuit_closed"),
            "faults_injected": int(
                counters.get("faults_injected", 0) or 0),
        }
        # Slowest-request waterfall (DESIGN.md §21): every completed
        # serve_request span carries its request_id and the
        # queue/batch/retry/dispatch phase breakdown the batcher
        # stamped O(1) — the table answers "where did the p99 request
        # spend its time" from the run dir alone.
        phased = [s.get("args", {}) for s in reqs
                  if "latency_ms" in s.get("args", {})
                  and "queue_ms" in s.get("args", {})]
        report["serve"]["slowest"] = [
            {k: a.get(k) for k in ("request_id", "universe", "month",
                                   "latency_ms", "queue_ms", "batch_ms",
                                   "retry_ms", "dispatch_ms", "retries",
                                   "width")}
            for a in sorted(phased,
                            key=lambda a: -a["latency_ms"])[:8]]
    # Durable-restore rollup (serve/persist.py, DESIGN.md §20): restore
    # wall time and per-universe outcomes from the zoo_restore span +
    # restore_generation instants, executables loaded vs recompiled and
    # journal/sweep/quarantine accounting from the run-level counters —
    # so "did the restart actually skip the compile ladder, and did
    # every snapshot verify?" is answerable from the run dir alone.
    restores = [s for s in spans if s.get("name") == "zoo_restore"]
    commits = [s for s in spans if s.get("name") == "zoo_persist_commit"]
    if restores or commits or counters.get("persist_commits"):
        gens = [s.get("args", {}) for s in spans
                if s.get("name") == "restore_generation"]
        quarantines = [s.get("args", {}) for s in spans
                       if s.get("name") == "restore_quarantine"]
        # The verdict keys on BOTH the quarantine instants and the
        # failure counter: some rungs (e.g. a missing panel file) fail
        # with nothing left to rename, so no instant is emitted.
        n_fails = int(counters.get("restore_integrity_failures", 0) or 0)
        last = restores[-1].get("args", {}) if restores else {}
        report["restore"] = {
            "restores": len(restores),
            "restore_wall_s": round(sum(s.get("dur_s", 0.0)
                                        for s in restores), 3),
            "universes_restored": last.get("universes"),
            "execs_loaded": int(
                counters.get("restore_execs_loaded", 0) or 0),
            "execs_recompiled": int(
                counters.get("restore_execs_recompiled", 0) or 0),
            "probes_ok": int(counters.get("restore_probe_ok", 0) or 0),
            # ANY failed rung of the verification ladder (panel hash,
            # config rebuild, params checksum, parity probe) — each
            # such generation was quarantined.
            "integrity_failures": n_fails,
            # Every restored generation passed the bit-equality gate by
            # construction; quarantines/failures are the record of the
            # snapshots that did NOT.
            "integrity": ("quarantined" if (quarantines or n_fails)
                          else ("bit_equal" if gens else None)),
            "generations": [{"universe": a.get("universe"),
                             "generation": a.get("generation"),
                             "execs_loaded": a.get("execs_loaded"),
                             "probe": a.get("probe")} for a in gens],
            "quarantines": [{"path": a.get("path"),
                             "reason": a.get("reason")}
                            for a in quarantines],
            "journal_replays": int(
                counters.get("persist_journal_replays", 0) or 0),
            "sweep_orphans": int(
                counters.get("persist_sweep_orphans", 0) or 0),
            "commits": int(counters.get("persist_commits", 0) or 0),
            "execs_exported": int(
                counters.get("persist_execs_exported", 0) or 0),
            "gc_pruned": int(counters.get("persist_gc_pruned", 0) or 0),
        }
    # Incident-bundle rollup (serve/incident.py, DESIGN.md §21): which
    # triggers fired, when, and what each bundle captured — plus two
    # cross-checks per bundle, both the 1% discipline:
    #   1. SCRAPE INTEGRITY — the scrape's lfm_*_total lines must
    #      equal the manifest's counters_at_capture (both rendered
    #      from ONE snapshot at capture; a torn/forged scrape breaks
    #      the agreement);
    #   2. RUN DISCIPLINE — counters_since_run (capture totals minus
    #      the run's starting snapshot; the registry is process-
    #      lifetime, so raw totals would false-positive on long-lived
    #      services) can only be AT MOST the run's final span-derived
    #      counts — a mid-run capture cannot have seen more events
    #      than the whole run recorded.
    if run.get("incidents"):
        sv = report.get("serve") or {}
        bundles = []
        inc_mismatches: List[str] = []
        for b in run["incidents"]:
            meta = b["meta"] or {}
            ring = b["flight"]
            rec: Dict[str, Any] = {
                "name": b["name"],
                "trigger": meta.get("trigger"),
                "ts": meta.get("ts"),
                "context": meta.get("context"),
                "flight_events": len(ring),
                "slow_traces": (len(b["slow"])
                                if isinstance(b["slow"], list) else 0),
                "has_scrape": b["metrics_text"] is not None,
                "host": (meta.get("host") or {}).get("host"),
                "git_sha": ((meta.get("host") or {}).get("git_sha")
                            or "")[:12] or None,
            }
            # Timeline: the ring's last events BEFORE the trigger —
            # the "seconds before the degradation" evidence.
            rec["timeline"] = [
                {k: e.get(k) for k in ("ts", "kind", "universe",
                                       "error", "streak")
                 if e.get(k) is not None}
                for e in ring[-6:]]
            cap = meta.get("counters_at_capture") or {}
            since = meta.get("counters_since_run")
            checked = ("serve_shed", "serve_deadline_drops",
                       "serve_retries", "serve_breaker_opens",
                       "serve_batches")
            if b["metrics_text"] and cap:
                prom = _parse_prom(b["metrics_text"])
                for cname in checked:
                    vals = prom.get(f"lfm_{cname}_total")
                    manifest_v = cap.get(cname)
                    if vals is None and not manifest_v:
                        continue  # absent both sides: never bumped
                    scraped = (int(sum(v for _, v in vals))
                               if vals else 0)
                    manifest_v = int(manifest_v or 0)
                    tol = max(1.0, 0.01 * abs(manifest_v))
                    if abs(scraped - manifest_v) > tol:
                        inc_mismatches.append(
                            f"{b['name']}: {cname}: scrape total "
                            f"{scraped} vs the bundle manifest's "
                            f"counters_at_capture {manifest_v} (>1% — "
                            "both came from ONE snapshot; the scrape "
                            "is torn or forged)")
            if since and sv:
                for key, cname in (("shed", "serve_shed"),
                                   ("deadline_drops",
                                    "serve_deadline_drops"),
                                   ("retries", "serve_retries"),
                                   ("breaker_opens",
                                    "serve_breaker_opens")):
                    run_v = since.get(cname)
                    spans_v = sv.get(key)
                    if run_v is None or spans_v is None:
                        continue
                    tol = max(1.0, 0.01 * abs(spans_v))
                    if run_v - spans_v > tol:
                        inc_mismatches.append(
                            f"{b['name']}: {key}: bundle "
                            f"counters_since_run {run_v} exceeds the "
                            f"run's span-derived total {spans_v} (>1% "
                            "— a mid-run capture cannot have seen "
                            "more than the full run)")
            bundles.append(rec)
        report["incidents"] = {
            "bundles": bundles,
            "count": len(bundles),
            "triggered": int(counters.get("incidents_triggered", 0)
                             or 0),
            "captured": int(counters.get("incidents_captured", 0) or 0),
            "suppressed": int(counters.get("incidents_suppressed", 0)
                              or 0),
            "mismatches": inc_mismatches,
        }
    # Fleet rollup (serve/fleet.py, DESIGN.md §22): the router's
    # request/reroute/failover accounting, the join events with their
    # restore verdicts, and a per-member HEALTH TIMELINE from the
    # fleet_* instants (joined → out → probe → readmitted) — so "which
    # member failed, when did the router notice, how long until
    # readmission" is answerable from the run dir alone. Cross-checked
    # against the aggregated fleet scrape (fleet.prom) with the same
    # 1% discipline as the serve/metrics sections: the scrape's
    # lfm_fleet_*_total lines and the run-record counter deltas come
    # from ONE process registry, so disagreement means a torn/forged
    # scrape or a counter bumped outside the run.
    fleet_events = [s for s in spans
                    if str(s.get("name", "")).startswith("fleet_")]
    if fleet_events or counters.get("fleet_requests"):
        joins = [s.get("args", {}) for s in fleet_events
                 if s.get("name") == "fleet_member_joined"]
        refusals = [s.get("args", {}) for s in fleet_events
                    if s.get("name") == "fleet_member_refused"]
        timeline: Dict[str, List[Dict[str, Any]]] = {}
        for s in fleet_events:
            a = s.get("args", {})
            member = a.get("member")
            if not member:
                continue
            ev = {"ts": s.get("ts"),
                  "event": str(s.get("name"))[len("fleet_"):]}
            for k in ("reason", "error", "universe", "generations"):
                if a.get(k) is not None:
                    ev[k] = a[k]
            timeline.setdefault(member, []).append(ev)
        for evs in timeline.values():
            evs.sort(key=lambda e: e.get("ts") or 0.0)
        fleet_sec: Dict[str, Any] = {
            "requests": int(counters.get("fleet_requests", 0) or 0),
            "reroutes": int(counters.get("fleet_reroutes", 0) or 0),
            "failovers": int(counters.get("fleet_failovers", 0) or 0),
            "member_outs": int(counters.get("fleet_member_out", 0) or 0),
            "probes": int(counters.get("fleet_probes", 0) or 0),
            "readmissions": int(
                counters.get("fleet_readmissions", 0) or 0),
            "joins": [{"member": a.get("member"),
                       "universes": a.get("universes"),
                       "restore_compiles": a.get("restore_compiles"),
                       "host": a.get("host"), "pid": a.get("pid")}
                      for a in joins],
            "refusals": [{"member": a.get("member"),
                          "reason": a.get("reason")} for a in refusals],
            "unroutable": int(counters.get("fleet_unroutable", 0) or 0),
            "timeline": timeline,
        }
        fleet_mismatches: List[str] = []
        if run.get("fleet_text"):
            fprom = _parse_prom(run["fleet_text"])

            def _ftotal(name: str) -> Optional[int]:
                # Router-side counters only: member-labeled series are
                # the members' OWN registries, not the router's tally.
                vals = fprom.get(name)
                if vals is None:
                    return None
                return int(sum(v for lab, v in vals
                               if "member" not in lab))

            # Direction-aware, the §21 lesson: scrape counters are
            # PROCESS-LIFETIME while the run record holds this run's
            # deltas, so on a long-lived router the scrape may
            # legitimately exceed the run — but it can NEVER show
            # fewer events than the run recorded (same 1% discipline).
            for key, cname in (("requests", "lfm_fleet_requests_total"),
                               ("reroutes", "lfm_fleet_reroutes_total"),
                               ("failovers",
                                "lfm_fleet_failovers_total"),
                               ("member_outs",
                                "lfm_fleet_member_out_total"),
                               ("readmissions",
                                "lfm_fleet_readmissions_total")):
                scraped = _ftotal(cname)
                spans_v = fleet_sec.get(key)
                if scraped is None and not spans_v:
                    continue
                scraped = scraped or 0
                tol = max(1.0, 0.01 * abs(spans_v))  # the 1% contract
                if scraped + tol < spans_v:
                    fleet_mismatches.append(
                        f"{key}: fleet scrape total {scraped} is BELOW "
                        f"the run-record counters {spans_v} (>1% — a "
                        "lifetime total can never show fewer events "
                        "than the run recorded; the scrape is torn or "
                        "forged)")
            fleet_sec["scrape_members"] = sorted(
                {lab["member"] for entries in fprom.values()
                 for lab, _ in entries if "member" in lab})
        fleet_sec["mismatches"] = fleet_mismatches
        report["fleet"] = fleet_sec
    # Live-metrics cross-check (the /metrics scrape vs the spans — the
    # pull-side plane and the post-hoc plane must tell the same story):
    # served-request count and degradation totals within 1%, the
    # histogram-estimated p99 within one bucket's relative resolution
    # of the exact span-derived percentile (the log-spaced sketch's
    # documented error bound — utils/metrics.py LogHistogram).
    if run.get("metrics_text"):
        prom = _parse_prom(run["metrics_text"])
        hist_counts = prom.get("lfm_serve_latency_ms_count", [])
        bucket_entries = prom.get("lfm_serve_latency_ms_bucket", [])
        pairs = _merged_hist_pairs(bucket_entries) if bucket_entries \
            else []
        fin = [le for le, _ in pairs if math.isfinite(le)]
        # The ladder's growth factor, recovered from the scrape itself:
        # one bucket's relative width is the quantile error bound.
        rel_res = (fin[1] / fin[0] - 1.0) if len(fin) >= 2 else 0.5

        def _total(name: str) -> Optional[int]:
            vals = prom.get(name)
            return int(sum(v for _, v in vals)) if vals else None

        msec: Dict[str, Any] = {
            "requests": int(sum(v for _, v in hist_counts)),
            "p50_ms": _prom_hist_quantile(pairs, 50.0),
            "p99_ms": _prom_hist_quantile(pairs, 99.0),
            "rel_resolution": round(rel_res, 4),
            "shed": _total("lfm_serve_shed_total"),
            "deadline_drops": _total("lfm_serve_deadline_drops_total"),
            "retries": _total("lfm_serve_retries_total"),
            "breaker_opens": _total("lfm_serve_breaker_opens_total"),
            "drift_psi": {
                tuple(sorted(lab.items())): v
                for lab, v in prom.get("lfm_score_drift_psi", [])} or None,
            "slo_burn": next((v for _, v in prom.get("lfm_slo_burn", [])),
                             None),
        }
        msec["drift_psi"] = (
            {"/".join(f"{k}={v}" for k, v in key): val
             for key, val in msec["drift_psi"].items()}
            if msec["drift_psi"] else None)
        mismatches: List[str] = []
        sv = report.get("serve")
        if sv:
            def _count_mismatch(name: str, scraped, spans_v) -> None:
                if scraped is None or spans_v is None:
                    return
                tol = max(1.0, 0.01 * abs(spans_v))  # the 1% contract
                if abs(scraped - spans_v) > tol:
                    mismatches.append(
                        f"{name}: scrape {scraped} vs spans {spans_v} "
                        "(>1% apart — the live plane and the span "
                        "record disagree)")

            _count_mismatch("requests", msec["requests"],
                            sv.get("completed"))
            for k in ("shed", "deadline_drops", "retries",
                      "breaker_opens"):
                _count_mismatch(k, msec[k], sv.get(k))
            # p99: the scrape-side estimate interpolates WITHIN the
            # bucket covering the rank, while the span percentile
            # interpolates BETWEEN order statistics — on small/outlier
            # streams those differ legitimately. The RIGOROUS invariant
            # (holds for any distribution when the two cover the same
            # stream): the estimate lies within one bucket factor of
            # the rank's order statistic in the span latencies.
            span_lat = sorted(
                s["args"]["latency_ms"] for s in spans
                if s.get("name") == "serve_request"
                and "latency_ms" in s.get("args", {}))
            mp99 = msec["p99_ms"]
            if span_lat and mp99:
                anchor = span_lat[int((len(span_lat) - 1) * 0.99)]
                g = 1.0 + rel_res
                if not (anchor / g - 0.01 <= mp99 <= anchor * g + 0.01):
                    mismatches.append(
                        f"p99_ms: scrape estimate {mp99:.3f} outside "
                        f"one bucket of the span stream's p99-rank "
                        f"order statistic {anchor:.3f} (×{g:.3f})")
        msec["mismatches"] = mismatches
        report["metrics"] = msec

    m = run["manifest"]
    if m:
        jx = m.get("jax") if isinstance(m.get("jax"), dict) else {}
        report["manifest"] = {
            "ts": m.get("ts"),
            "entry": m.get("entry"),
            "git_sha": (m.get("git_sha") or "")[:12] or None,
            "backend": jx.get("backend"),
            "devices": jx.get("device_count"),
            "jax": jx.get("jax_version"),
            "config_name": (m.get("config") or {}).get("name")
            if isinstance(m.get("config"), dict) else None,
            "knobs": m.get("knobs"),
        }
    return report


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:,.1f} GiB"


def print_report(rep: Dict[str, Any]) -> None:
    print(f"run dir     : {rep['run_dir']}")
    m = rep.get("manifest")
    if m:
        print(f"manifest    : {m.get('config_name') or '?'}  "
              f"entry={m.get('entry')}  backend={m.get('backend')}"
              f"×{m.get('devices')}  jax={m.get('jax')}  "
              f"git={m.get('git_sha')}  at {m.get('ts')}")
        knobs = m.get("knobs") or {}
        # Bool knobs render as on/off; VALUED knobs (e.g. the precision
        # lane "f32"/"bf16") render as name=value — "on=precision" for
        # an f32 run would be nonsense.
        on = [k for k, v in knobs.items() if v is True]
        off = [k for k, v in knobs.items() if v is False]
        valued = [f"{k}={v}" for k, v in knobs.items()
                  if v is not None and not isinstance(v, bool)]
        print(f"knobs       : on={','.join(on) or '-'}  "
              f"off={','.join(off) or '-'}"
              + (f"  {' '.join(valued)}" if valued else ""))
    tf = rep.get("trace_files") or []
    print(f"trace files : "
          f"{', '.join(tf) + ' (load at ui.perfetto.dev)' if tf else 'MISSING (run still in flight or crashed?)'}")
    print(f"wall        : {rep['wall_s']:.1f}s over "
          f"{rep['n_processes']} process(es); "
          f"{rep['n_fits']} fit(s), {rep['n_epochs']} epochs")
    eph = rep["epochs_per_hour"]
    print(f"throughput  : "
          f"{eph:,.1f} epochs/hour" if eph is not None else
          "throughput  : n/a (no fit spans)")
    if rep["idle_frac"] is not None:
        print(f"device idle : {100.0 * rep['idle_frac']:.1f}% of fit wall")
    fs = rep.get("foldstack")
    if fs:
        extra = (f" (last of {fs['n_stacked_fits']} stacked fits)"
                 if fs.get("n_stacked_fits", 1) > 1 else "")
        print(f"fold stack  : {fs['fold_count']} folds{extra} "
              f"mesh={fs.get('fold_mesh')}  "
              f"epochs/fold={fs.get('epochs_per_fold')}  "
              f"best={fs.get('best_epochs')}  "
              f"early_stops={len(fs.get('early_stops') or [])}")
    sw = rep.get("stacked_sweep")
    if sw:
        if sw.get("n_stacked_fits"):
            extra = (f" (last of {sw['n_stacked_fits']} stacked fits)"
                     if sw["n_stacked_fits"] > 1 else "")
            print(f"stacked sweep: {sw.get('kind')} ×{sw.get('run_count')}"
                  f"{extra}  mesh={sw.get('stack_mesh')}  "
                  f"block={sw.get('stack_block')}  "
                  f"operands={sw.get('hyper')}  "
                  f"epochs/run={sw.get('epochs_per_run')}  "
                  f"best={sw.get('best_epochs')}  "
                  f"early_stops={len(sw.get('early_stops') or [])}  "
                  f"degrades={sw.get('degrades')}")
        else:
            reasons = "; ".join(
                f"{d.get('kind')}: {d.get('reason')}"
                for d in sw.get("degrade_reasons") or []) or "?"
            print(f"stacked sweep: DEGRADED to sequential "
                  f"×{sw.get('degrades')} ({reasons})")
    bk = rep.get("buckets")
    if bk:
        print(f"buckets     : ladder={bk.get('ladder')}  "
              f"padded_flop={bk.get('padded_flop_fraction')}"
              f" (max-shape {bk.get('padded_flop_fraction_max_shape')})  "
              f"cells_saved={bk.get('cells_saved_vs_max_shape')}  "
              f"dispatches={bk.get('dispatches')}")
    sv = rep.get("serve")
    if sv:
        p50 = sv.get("p50_ms")
        p99 = sv.get("p99_ms")
        print(f"serve       : {sv['requests']} requests in "
              f"{sv['batches']} batches  "
              f"p50 {p50 if p50 is None else f'{p50:.2f}'}ms  "
              f"p99 {p99 if p99 is None else f'{p99:.2f}'}ms  "
              f"occupancy {sv.get('mean_occupancy')}  "
              f"queue<= {sv.get('queue_depth_max')}  "
              f"swaps {sv.get('zoo_swaps')}")
        if any(sv.get(k) for k in ("shed", "deadline_drops", "retries",
                                   "breaker_opens", "faults_injected")):
            print(f"  degraded  : shed {sv.get('shed', 0)}  "
                  f"deadline_drops {sv.get('deadline_drops', 0)}  "
                  f"retries {sv.get('retries', 0)}  "
                  f"breaker_opens {sv.get('breaker_opens', 0)}  "
                  f"faults_injected {sv.get('faults_injected', 0)}")
        slowest = sv.get("slowest") or []
        if slowest:
            print("  slowest requests (phase waterfall, ms):")
            print(f"    {'request_id':<18} {'total':>8} {'queue':>7} "
                  f"{'batch':>7} {'retry':>7} {'disp':>7} {'rt':>3}  "
                  f"universe/month")
            for a in slowest[:5]:
                rid = str(a.get("request_id") or "?")[:16]
                print(f"    {rid:<18} {a.get('latency_ms', 0):>8.2f} "
                      f"{a.get('queue_ms', 0):>7.2f} "
                      f"{a.get('batch_ms', 0):>7.2f} "
                      f"{a.get('retry_ms', 0):>7.2f} "
                      f"{a.get('dispatch_ms', 0):>7.2f} "
                      f"{a.get('retries', 0):>3}  "
                      f"{a.get('universe')}/{a.get('month')}")
    rs = rep.get("restore")
    if rs:
        if rs.get("restores"):
            print(f"restore     : {rs.get('universes_restored')} "
                  f"universe(s) in {rs['restore_wall_s']:.2f}s  "
                  f"execs loaded {rs['execs_loaded']} / recompiled "
                  f"{rs['execs_recompiled']}  integrity "
                  f"{rs.get('integrity')}  journal_replays "
                  f"{rs['journal_replays']}  swept {rs['sweep_orphans']}")
            for q in rs.get("quarantines") or []:
                print(f"  QUARANTINED: {q.get('path')} — {q.get('reason')}")
        if rs.get("commits"):
            print(f"persist     : {rs['commits']} commit(s)  "
                  f"execs exported {rs['execs_exported']}  "
                  f"gc pruned {rs['gc_pruned']}")
    inc = rep.get("incidents")
    if inc:
        print(f"incidents   : {inc['count']} bundle(s)  "
              f"triggered {inc['triggered']}  captured {inc['captured']}"
              f"  suppressed {inc['suppressed']}")
        for b in inc["bundles"]:
            print(f"  {b['name']}: trigger={b['trigger']} at {b['ts']}  "
                  f"flight_events={b['flight_events']}  "
                  f"slow_traces={b['slow_traces']}  "
                  f"scrape={'yes' if b['has_scrape'] else 'MISSING'}  "
                  f"host={b.get('host')}")
            tl = b.get("timeline") or []
            if tl:
                tail = "; ".join(
                    str(e.get("kind")) + (f"({e['error']})"
                                          if e.get("error") else "")
                    for e in tl)
                print(f"    timeline … {tail}")
        for msg in inc.get("mismatches") or []:
            print(f"  INCIDENT MISMATCH: {msg}")
    fl = rep.get("fleet")
    if fl:
        print(f"fleet       : {fl['requests']} routed  "
              f"reroutes {fl['reroutes']}  failovers {fl['failovers']}  "
              f"member_outs {fl['member_outs']}  probes {fl['probes']}  "
              f"readmissions {fl['readmissions']}  "
              f"unroutable {fl['unroutable']}")
        for j in fl.get("joins") or []:
            print(f"  joined    : {j.get('member')} "
                  f"(host={j.get('host')} pid={j.get('pid')})  "
                  f"universes={j.get('universes')}  "
                  f"restore_compiles={j.get('restore_compiles')}")
        for r in fl.get("refusals") or []:
            print(f"  REFUSED   : {r.get('member')} — {r.get('reason')}")
        for member, evs in sorted((fl.get("timeline") or {}).items()):
            tail = "; ".join(
                str(e.get("event"))
                + (f"({e.get('reason') or e.get('error')})"
                   if e.get("reason") or e.get("error") else "")
                for e in evs[-6:])
            print(f"  {member:<10}: {tail}")
        for msg in fl.get("mismatches") or []:
            print(f"  FLEET MISMATCH: {msg}")
    mx = rep.get("metrics")
    if mx:
        p99 = mx.get("p99_ms")
        print(f"metrics     : scrape requests={mx.get('requests')}  "
              f"p99~{p99 if p99 is None else f'{p99:.2f}'}ms "
              f"(±{100 * mx.get('rel_resolution', 0):.0f}% bucket "
              f"resolution)  slo_burn={mx.get('slo_burn')}  "
              f"drift={mx.get('drift_psi') or '-'}")
        for msg in mx.get("mismatches") or []:
            print(f"  METRICS MISMATCH: {msg}")
    print(f"host syncs  : {rep['host_syncs']} "
          f"({rep['syncs_per_epoch']}/epoch, {rep['host_sync_s']:.3f}s "
          f"blocked)" if rep["syncs_per_epoch"] is not None else
          f"host syncs  : {rep['host_syncs']}")
    c = rep["counters"]
    print(f"counters    : jit_traces={c.get('jit_traces', 0)}  "
          f"panel_transfers={c.get('panel_transfers', 0)}  "
          f"program_builds={c.get('program_builds', 0)}  "
          f"compile_s={rep['compile_s_total']}")
    if rep["top_spans"]:
        print("\ntop spans (by total wall):")
        for a in rep["top_spans"]:
            pct = f"{a.get('pct_wall', 0):5.1f}%" if "pct_wall" in a else ""
            print(f"  {a['name']:<14} ×{a['count']:<5} "
                  f"{a['total_s']:>9.3f}s  mean {a['mean_s']:.4f}s  {pct}")
    if rep["programs"]:
        print("\nprogram ledger (compile cost + HBM by program; "
              "'args' = input-footprint proxy, set "
              "LFM_TELEMETRY_ANALYSIS=1 for the full HBM analysis):")
        for a in rep["programs"]:
            flops = f"{a['flops']:,.0f} flops" if a["flops"] else ""
            mem = (f"hbm {_fmt_bytes(a['hbm_bytes']):>12}"
                   if a["hbm_bytes"] else
                   f"args {_fmt_bytes(a['arg_bytes']):>11}")
            print(f"  {a['program']:<18} builds={a['builds']:<3} "
                  f"compile {a['compile_s']:>7.3f}s  {mem}  {flops}")
    else:
        print("\nprogram ledger: empty (telemetry run was not active "
              "during compilation, or analysis disabled)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("run_dir", help="run directory written by train.py / "
                                    "backtest.py with telemetry on")
    ap.add_argument("--top", type=int, default=12,
                    help="how many span rows to print (default 12)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report JSON instead")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        ap.error(f"not a directory: {args.run_dir}")
    run = load_run(args.run_dir)
    if not run["spans"] and not run["ledger"] and run["manifest"] is None:
        ap.error(f"no telemetry artifacts under {args.run_dir} "
                 "(was the run made with LFM_TELEMETRY on?)")
    rep = build_report(run, top=args.top)
    try:
        if args.json:
            print(json.dumps(rep, indent=2, default=str))
        else:
            print_report(rep)
    except BrokenPipeError:  # `trace_report ... | head` is fine
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
