#!/bin/bash
# Unattended tunnel watcher: probe the axon TPU tunnel on a timer and fire
# the full measurement campaign (scripts/chip_campaign.sh) the moment a
# probe succeeds. Exists because the tunnel has now been wedged for three
# working sessions (BASELINE.md outage notes) and recovery can happen at
# any hour — rows persist to BENCH_ROWS.jsonl per step, so even a
# mid-campaign re-wedge keeps everything captured up to that point.
#
# The campaign is RESUMABLE (ledger_has guards skip banked rows), so a
# mid-campaign re-wedge does not end the watch: the watcher goes back to
# probing and re-fires on the next heal, and only the still-missing rows
# spend chip time. CAMPAIGN_MAX_FIRES bounds the thrash if the tunnel
# heals and re-wedges repeatedly (CAMPAIGN_ prefix: bench.py's
# preempt/re-arm cycle preserves exactly the CAMPAIGN_* env, so an
# operator's cap must live under it to survive a driver-bench eviction).
#
# Usage: nohup bash scripts/campaign_on_recovery.sh [probe_interval_s] &
cd "$(dirname "$0")/.."
INTERVAL=${1:-180}
LOG=${CAMPAIGN_WATCH_LOG:-/tmp/campaign_watch.log}
MAX_FIRES=${CAMPAIGN_MAX_FIRES:-8}
FIRES=0
echo "=== watcher start $(date) (interval ${INTERVAL}s) ===" >> "$LOG"
while true; do
  # -k 10: a SIGTERM-immune wedged probe gets SIGKILLed (the probe itself
  # TERMs first via timeout; a killed client is the documented wedge
  # trigger, but the tunnel is already wedged on this path).
  if timeout -k 10 150 python -c "
import jax, jax.numpy as jnp
print('TUNNEL_OK', float(jax.jit(lambda a: a@a)(jnp.ones((256,256), jnp.bfloat16)).sum()))" >> "$LOG" 2>&1; then
    echo "=== tunnel recovered $(date) — firing campaign ===" >> "$LOG"
    touch /tmp/TUNNEL_OK
    bash scripts/chip_campaign.sh /tmp/campaign.log >> "$LOG" 2>&1
    rc=$?
    FIRES=$((FIRES+1))
    echo "=== campaign pass $FIRES finished rc=$rc $(date) ===" >> "$LOG"
    if [ $rc -eq 0 ]; then
      touch /tmp/CAMPAIGN_DONE
      exit 0
    fi
    if [ $FIRES -ge "$MAX_FIRES" ]; then
      echo "=== giving up after $FIRES aborted passes ===" >> "$LOG"
      exit $rc
    fi
    echo "=== campaign aborted (re-wedge?) — resuming watch ===" >> "$LOG"
  fi
  echo "[watch $(date +%H:%M:%S)] tunnel still wedged" >> "$LOG"
  sleep "$INTERVAL"
done
