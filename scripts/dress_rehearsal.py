#!/usr/bin/env python
"""Real-data dress rehearsal: the DOCUMENTED CSV path end-to-end at c5
scale (SURVEY.md §4.4; README "Real data").

Stages, each wall-clocked and printed as one JSON line at the end:

  1. synthesize  — c5-sized panel (~8000 firms × 660 months × 20 features)
  2. export      — to_long_frame → CSV (the documented long schema)
  3. parse_native / parse_pandas — load_compustat_csv with each engine on
     the SAME file, equality-checked; the measured pair substantiates the
     "~2× faster than pandas" claim in data/compustat.py
  4. walkforward — train.py --config (panel_path=CSV, target_col, derived
     features) --walk-forward: the real CLI, stitching OOS forecasts
  5. backtest    — backtest.py --forecast-npz ... --yearly

Default geometry is the full c5 panel; training cost is controlled by
--epochs/--wf-folds so the rehearsal is feasible on CPU (full-depth
training is a chip job — pass --epochs/--wf-folds higher there). Use
--scale to shrink the panel itself for smoke runs.

Run: python scripts/dress_rehearsal.py [--scale 1.0] [--epochs 2]
     [--wf-folds 2] [--keep]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-feasible rehearsal: force the CPU platform before any backend use
# (the axon plugin ignores JAX_PLATFORMS env; a wedged tunnel hangs the
# claim). LFM_PROBE_BACKEND=tpu opts back into the chip.
if os.environ.get("LFM_PROBE_BACKEND", "cpu") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")


def _log(msg):
    print(f"[dress] {msg}", file=sys.stderr, flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0,
                    help="shrink firms/months by this factor (smoke runs)")
    ap.add_argument("--epochs", type=int, default=2,
                    help="epochs per walk-forward fold")
    ap.add_argument("--wf-folds", type=int, default=2,
                    help="number of walk-forward folds")
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir (default: delete)")
    args = ap.parse_args(argv)

    import numpy as np

    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.data.compustat import load_compustat_csv, to_long_frame

    n_firms = max(200, int(8000 * args.scale))
    n_months = max(120, int(660 * args.scale))
    work = tempfile.mkdtemp(prefix="dress_")
    stages = {}

    t0 = time.perf_counter()
    panel = synthetic_panel(n_firms=n_firms, n_months=n_months,
                            n_features=20, start_yyyymm=197001, horizon=12,
                            seed=0)
    stages["synthesize_s"] = round(time.perf_counter() - t0, 2)
    _log(f"panel {n_firms}×{n_months}×20 in {stages['synthesize_s']}s "
         f"({panel.valid.sum():,} firm-months)")

    csv_path = os.path.join(work, "panel.csv")
    t0 = time.perf_counter()
    to_long_frame(panel).to_csv(csv_path, index=False)
    stages["export_s"] = round(time.perf_counter() - t0, 2)
    stages["csv_mb"] = round(os.path.getsize(csv_path) / 1e6, 1)
    _log(f"CSV {stages['csv_mb']} MB in {stages['export_s']}s")

    # Parser-only comparison (the "~2×" claim in data/compustat.py is
    # about the parse itself; load_compustat_csv also grids + winsorizes,
    # identical work for both engines, which dilutes the ratio).
    from lfm_quant_tpu.data.compustat import _parse_native, _parse_pandas

    t0 = time.perf_counter()
    raw_native = _parse_native(csv_path, None)
    stages["parse_only_native_s"] = (round(time.perf_counter() - t0, 2)
                                     if raw_native is not None else None)
    t0 = time.perf_counter()
    _parse_pandas(csv_path, None)
    stages["parse_only_pandas_s"] = round(time.perf_counter() - t0, 2)
    if raw_native is not None:
        stages["parse_only_speedup"] = round(
            stages["parse_only_pandas_s"] / stages["parse_only_native_s"],
            2)
        _log(f"parse-only: native {stages['parse_only_native_s']}s vs "
             f"pandas {stages['parse_only_pandas_s']}s "
             f"({stages['parse_only_speedup']}×)")

    loaded = {}
    for engine in ("native", "pandas"):
        t0 = time.perf_counter()
        try:
            loaded[engine] = load_compustat_csv(csv_path, horizon=12,
                                                engine=engine)
            stages[f"load_{engine}_s"] = round(time.perf_counter() - t0, 2)
            _log(f"load[{engine}] {stages[f'load_{engine}_s']}s")
        except RuntimeError as e:  # no native toolchain — record and go on
            stages[f"load_{engine}_s"] = None
            _log(f"load[{engine}] unavailable: {e}")
    if len(loaded) == 2:
        a, b = loaded["native"], loaded["pandas"]
        np.testing.assert_array_equal(a.valid, b.valid)
        np.testing.assert_allclose(a.features, b.features, atol=2e-6)
        stages["load_speedup"] = round(
            stages["load_pandas_s"] / stages["load_native_s"], 2)
        _log(f"engines identical; end-to-end load speedup "
             f"{stages['load_speedup']}×")

    # Walk-forward through the REAL CLI on the CSV path with derived
    # features — the documented real-data recipe.
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    cfg = RunConfig(
        name="dress",
        data=DataConfig(
            panel_path=csv_path, horizon=12, window=60,
            dates_per_batch=8, firms_per_date=256,
            derived_features=("mom_12_1", "vol_12"),
        ),
        model=ModelConfig(kind="lstm", kwargs={"hidden": 128}, bf16=True),
        optim=OptimConfig(lr=1e-3, epochs=args.epochs, warmup_steps=20,
                          loss="mse"),
        out_dir=os.path.join(work, "runs"),
    )
    cfg_path = os.path.join(work, "cfg.json")
    with open(cfg_path, "w") as fh:
        fh.write(cfg.to_json())

    import backtest as backtest_cli
    import train as train_cli

    t0 = time.perf_counter()
    rc = train_cli.main(["--config", cfg_path, "--walk-forward", "60",
                         "--wf-folds", str(args.wf_folds), "--echo"])
    stages["walkforward_s"] = round(time.perf_counter() - t0, 2)
    if rc not in (0, None):
        _log(f"walk-forward FAILED rc={rc}; work dir kept for debugging: "
             f"{work}")
        return 1
    _log(f"walk-forward ({args.wf_folds} folds × {args.epochs} epochs) "
         f"in {stages['walkforward_s']}s")

    npz = os.path.join(cfg.out_dir, "dress", "wf", "walkforward.npz")
    t0 = time.perf_counter()
    rc = backtest_cli.main(["--forecast-npz", npz, "--yearly"])
    stages["backtest_s"] = round(time.perf_counter() - t0, 2)
    if rc not in (0, None):
        _log(f"backtest FAILED rc={rc}; work dir kept for debugging: "
             f"{work}")
        return 1

    import jax
    stages.update(n_firms=n_firms, n_months=n_months,
                  backend=jax.default_backend())
    print(json.dumps({"metric": "dress_rehearsal", **stages}), flush=True)
    if not args.keep:
        shutil.rmtree(work, ignore_errors=True)
    else:
        _log(f"kept {work}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
