#!/usr/bin/env python
"""Sweep the Pallas RNN kernel's batch block size at the config-2 train
geometry on the real chip, printing one JSON line per point — the tuning
evidence behind rnn_scan's block_b default. Set LFM_BENCH_SCAN_IMPL=
pallas_fused to sweep the fused-projection variant instead.

The trade: bigger blocks mean larger `[bb, H] @ [H, G·H]` MXU matmuls and
fewer grid steps, but more VMEM per pipeline stage (xw block = bb·G·H
bytes, double-buffered) and less DMA/compute overlap across blocks.

Run: python scripts/sweep_rnn_blocks.py [bb ...]   (default sweep below)
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _scan_impl_override, measure_trainer  # noqa: E402


def sweep(block_sizes) -> None:
    from lfm_quant_tpu.config import get_preset
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    base = get_preset("c2")
    d = base.data
    panel = synthetic_panel(n_firms=d.n_firms, n_months=240,
                            n_features=d.n_features, horizon=d.horizon,
                            seed=0)
    splits = PanelSplits.by_date(panel, 198601, 198801)
    best = (None, 0.0)
    for bb in block_sizes:
        kw = dict(base.model.kwargs)
        if bb:
            kw["scan_block_b"] = bb
        cfg = _scan_impl_override(dataclasses.replace(
            base, model=dataclasses.replace(base.model, kwargs=kw)))
        try:
            value = measure_trainer(Trainer(cfg, splits))
        except Exception as e:  # noqa: BLE001 — report the point, keep going
            print(json.dumps({"block_b": bb, "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
            continue
        print(json.dumps({"block_b": bb or "default",
                          "value": round(value, 1),
                          "unit": "firm-months/sec/chip"}), flush=True)
        if value > best[1]:
            best = (bb, value)
    print(json.dumps({"best_block_b": best[0] or "default",
                      "value": round(best[1], 1)}), flush=True)


if __name__ == "__main__":
    sizes = [int(a) for a in sys.argv[1:]] or [None, 256, 512, 1024, 2048]
    sweep(sizes)
