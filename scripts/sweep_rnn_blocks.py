#!/usr/bin/env python
"""Sweep the Pallas RNN kernel's batch block size at the config-2
geometry on the real chip, printing one JSON line per point — the tuning
evidence behind rnn_scan's block_b default (DESIGN.md §8's falsifiable
"wider bb lifts MFU" prediction). Set LFM_BENCH_SCAN_IMPL=pallas_fused
to sweep the fused-projection variant instead.

Each point banks BOTH halves of the workflow:
  sweep_c2_block_b      — train step at scan_block_b=bb
  sweep_c2_eval_block_b — the stacked eval sweep at eval_scan_block_b=bb
    (round-4 verdict ask 7: eval runs at ~train/3 MFU — the same
    per-step-overhead floor at 1/3 the FLOPs — and, being fwd-only, can
    afford wider blocks than the backward's VMEM budget allows; the eval
    list extends to 4096 for exactly that reason).

The trade: bigger blocks mean larger `[bb, H] @ [H, G·H]` MXU matmuls and
fewer grid steps, but more VMEM per pipeline stage (xw block = bb·G·H
bytes, double-buffered) and less DMA/compute overlap across blocks.

Run: python scripts/sweep_rnn_blocks.py [bb ...]   (default sweep below)
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import (_backend_name, _scan_impl_override,  # noqa: E402
                   measure_eval, measure_trainer, measure_with_spread,
                   persist_row)


def _banked_rows(metric="sweep_c2_block_b"):
    """TPU sweep rows already in the ledger — a resumed sweep (the
    campaign re-fires after each tunnel heal) must spend chip time only
    on the points a prior pass did not bank."""
    from regen_baseline import ledger_path, load_rows

    return [r for r in load_rows(ledger_path())
            if r.get("metric") == metric
            and r.get("backend") == "tpu"]


def sweep(block_sizes, eval_sizes=None) -> None:
    from lfm_quant_tpu.config import get_preset
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    eval_sizes = block_sizes if eval_sizes is None else eval_sizes
    base = get_preset("c2")
    d = base.data
    panel = synthetic_panel(n_firms=d.n_firms, n_months=240,
                            n_features=d.n_features, horizon=d.horizon,
                            seed=0)
    splits = PanelSplits.by_date(panel, 198601, 198801)
    # Pre-build skip matches on the impl THIS run would sweep: the env
    # override when set (resolved == requested then), else the auto
    # resolution for this backend (config.py: pallas_fused on TPU, xla
    # elsewhere) — a curve banked under a different variant must not
    # suppress the default variant's points, and a point must not cost a
    # Trainer build just to discover it was already measured.
    import jax

    want = (os.environ.get("LFM_BENCH_SCAN_IMPL")
            or base.model.kwargs.get("scan_impl")
            or ("pallas_fused" if jax.default_backend() == "tpu" else "xla"))
    banked = {r.get("block_b"): float(r.get("value", 0.0))
              for r in _banked_rows() if r.get("scan_impl") == want}
    banked_eval = {r.get("block_b"): float(r.get("value", 0.0))
                   for r in _banked_rows("sweep_c2_eval_block_b")
                   if r.get("scan_impl") == want}
    # Banked points compete in the best-point summary too — a resumed
    # sweep measuring only the residual points must not crown a "best"
    # that the already-banked curve beats (or report 0.0 on a fully
    # banked resume).
    best = (None, 0.0)
    for b, v in banked.items():
        if v > best[1]:
            best = (None if b == "default" else b, v)
    # One ordered pass over the union: a size in both lists costs ONE
    # Trainer build (and its compile) for both halves.
    seen, ordered = set(), []
    for bb in list(block_sizes) + list(eval_sizes):
        if (bb or "default") not in seen:
            seen.add(bb or "default")
            ordered.append(bb)
    for bb in ordered:
        key_bb = bb or "default"
        do_train = bb in block_sizes and key_bb not in banked
        do_eval = bb in eval_sizes and key_bb not in banked_eval
        if not (do_train or do_eval):
            print(json.dumps({"block_b": key_bb, "skipped": "already banked",
                              "value": banked.get(key_bb,
                                                  banked_eval.get(key_bb))}),
                  flush=True)
            continue
        kw = dict(base.model.kwargs)
        if bb:
            # Eval-only points (e.g. the 4096 tail of the eval list) must
            # set ONLY eval_scan_block_b: scan_block_b reaches the TRAIN
            # step, and an eager train-step compile would lower a
            # backward at a width the backward's VMEM budget never
            # validated. Train points still mirror into the eval override
            # so the eval half measures the same width.
            if bb in block_sizes:
                kw["scan_block_b"] = bb
            if bb in eval_sizes:
                kw["eval_scan_block_b"] = bb
        cfg = _scan_impl_override(dataclasses.replace(
            base, model=dataclasses.replace(base.model, kwargs=kw)))
        # The finally releases this point's device panel + compiled
        # executables on BOTH paths before the next Trainer constructs —
        # the overlap would double HBM residency on exactly the points
        # that probe the memory limit (an OOM'd point then poisoning the
        # next one). Impls are captured eagerly (they are RESOLVED at
        # build time — recording the 'auto' request would fork ledger
        # keys from bench.py's resolved rows).
        try:
            trainer = Trainer(cfg, splits)
            scan_impl, gather_impl = (trainer.model.scan_impl,
                                      trainer._gather_impl)
            if do_train:
                value, vspread = measure_with_spread(
                    lambda: measure_trainer(trainer, k=int(
                        os.environ.get("LFM_BENCH_STEPS", "30"))))
                rec = {"metric": "sweep_c2_block_b",
                       "block_b": key_bb,
                       "value": round(value, 1),
                       "unit": "firm-months/sec/chip",
                       "scan_impl": scan_impl,
                       "gather_impl": gather_impl,
                       "backend": _backend_name(),
                       **vspread}
                # Each point is durable the moment it exists (round-3
                # weak #7: a mid-campaign re-wedge must not lose the
                # already-measured curve), and block_b is a ledger key
                # field so points coexist in the table.
                persist_row(rec)
                print(json.dumps(rec), flush=True)
                if value > best[1]:
                    best = (bb, value)
            if do_eval:
                evalue, espread = measure_with_spread(
                    lambda: measure_eval(trainer))
                rec = {"metric": "sweep_c2_eval_block_b",
                       "block_b": key_bb,
                       "value": round(evalue, 1),
                       "unit": "firm-months/sec/chip",
                       "scan_impl": scan_impl,
                       "gather_impl": gather_impl,
                       "backend": _backend_name(),
                       **espread}
                persist_row(rec)
                print(json.dumps(rec), flush=True)
        except Exception as e:  # noqa: BLE001 — report the point, keep going
            print(json.dumps({"block_b": bb, "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
            continue
        finally:
            trainer = None
    print(json.dumps({"best_block_b": best[0] or "default",
                      "value": round(best[1], 1)}), flush=True)


if __name__ == "__main__":
    sizes = [int(a) for a in sys.argv[1:]] or [None, 256, 512, 1024, 2048]
    # Fwd-only eval affords blocks the backward's VMEM budget cannot.
    evals = sizes if sys.argv[1:] else sizes + [4096]
    sweep(sizes, evals)
