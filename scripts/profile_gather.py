#!/usr/bin/env python
"""Micro-profile of gather_windows sub-parts on the real chip."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from lfm_quant_tpu.config import get_preset
from lfm_quant_tpu.data import PanelSplits, synthetic_panel
from lfm_quant_tpu.data.windows import DateBatchSampler, device_panel


def timeit(fn, *args, reps=5):
    out = fn(*args)
    _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _ = np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / reps


def main():
    cfg = get_preset("c2")
    d = cfg.data
    panel = synthetic_panel(n_firms=d.n_firms, n_months=240,
                            n_features=d.n_features, horizon=d.horizon, seed=0)
    splits = PanelSplits.by_date(panel, 198601, 198801)
    sampler = DateBatchSampler(splits.panel, d.window, d.dates_per_batch,
                               d.firms_per_date, seed=0,
                               date_range=splits.train_range)
    dev = device_panel(splits.panel)
    W = d.window
    b = sampler.stacked_epoch(0)
    k = min(18, b.firm_idx.shape[0])
    fi = jnp.asarray(b.firm_idx[:k])  # [K, D, Bf]
    ti = jnp.asarray(b.time_idx[:k])  # [K, D]

    T = dev["features"].shape[1]

    def scan(body):
        @jax.jit
        def run(dev, fi, ti):
            def step(c, batch):
                return c, body(dev, *batch)
            return jax.lax.scan(step, 0, (fi, ti))
        return run

    frow = scan(lambda dev, f, t: dev["features"][f].sum())
    print(f"feature row gather: {timeit(frow, dev, fi, ti)*1e3:.1f} ms")

    vrow = scan(lambda dev, f, t: dev["valid"][f].sum())
    print(f"valid row gather:   {timeit(vrow, dev, fi, ti)*1e3:.1f} ms")

    def slc(dev, f, t):
        rows = dev["features"][f]
        start = jnp.clip(t - (W - 1), 0, T - W)
        out = jax.vmap(
            lambda r, s: jax.lax.dynamic_slice_in_dim(r, s, W, axis=1)
        )(rows, start)
        return out.sum()
    print(f"row gather + slice: {timeit(scan(slc), dev, fi, ti)*1e3:.1f} ms")

    # variant: valid as int8 gathered together with features? pack valid as
    # an extra feature column instead of a separate bool gather
    feats_aug = jnp.concatenate(
        [dev["features"], dev["valid"][..., None].astype(jnp.float32)], axis=-1)

    def aug(dev_aug, f, t):
        rows = dev_aug[f]
        start = jnp.clip(t - (W - 1), 0, T - W)
        out = jax.vmap(
            lambda r, s: jax.lax.dynamic_slice_in_dim(r, s, W, axis=1)
        )(rows, start)
        return out.sum()
    r = scan(lambda dv, f, t: aug(dv["aug"], f, t))
    print(f"augmented (valid-as-col) gather+slice: "
          f"{timeit(r, {'aug': feats_aug}, fi, ti)*1e3:.1f} ms")

    # bf16 variant
    dev_bf = {"aug": feats_aug.astype(jnp.bfloat16)}
    print(f"bf16 augmented gather+slice: "
          f"{timeit(r, dev_bf, fi, ti)*1e3:.1f} ms")

    # date-first: slice panel on T per date, then gather firms
    def datefirst(dev, f, t):
        start = jnp.clip(t - (W - 1), 0, T - W)
        def per_date(fd, s):
            win = jax.lax.dynamic_slice_in_dim(dev["features"], s, W, axis=1)
            return win[fd]
        out = jax.vmap(per_date)(f, start)
        return out.sum()
    print(f"date-first slice+gather: {timeit(scan(datefirst), dev, fi, ti)*1e3:.1f} ms")


if __name__ == "__main__":
    main()
