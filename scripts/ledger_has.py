#!/usr/bin/env python
"""Exit 0 iff BENCH_ROWS.jsonl already holds a TPU-backed row matching the
given key=value filters — the campaign's resume guard. After a mid-campaign
re-wedge (c3-fullD's timeout-kill wedged the tunnel on 2026-07-31, aborting
the first pass with 8 of ~20 rows banked) the watcher re-fires the whole
campaign on recovery; these guards turn that re-fire into a resume, so each
heal-cycle only spends chip time on rows the ledger does not yet hold.

Usage: python scripts/ledger_has.py metric=eval_throughput_c3 \
           dates_per_batch=1 [--min-count N] [--distinct KEY] [--has KEY]

Values compare as strings against str(row[key]); a key absent from the row
compares as the string "None" (mirrors regen_baseline's key normalization,
so `dates_per_batch=None` matches rows that never recorded the field).
--has KEY requires the field to be PRESENT with any value — the guard
shape for "a spread-carrying row exists" (n_reps varies with
LFM_BENCH_OUTER_REPS, so an equality filter would re-burn chip time
whenever the operator picked a different rep count).
--distinct KEY counts DISTINCT values of KEY among matching rows instead of
raw rows — a resumed sweep re-banks earlier points, so a raw count would
satisfy the guard with duplicates of an incomplete curve. Rows with
unit == "status" (outage records) and non-TPU backends never count: a CPU
smoke run must not suppress a chip measurement.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from regen_baseline import ledger_path, load_rows, measurement_rows  # noqa: E402


def main(argv) -> int:
    min_count, distinct_key = 1, None
    filters, has_keys = {}, []
    args = list(argv)
    while "--min-count" in args:
        i = args.index("--min-count")
        min_count = int(args[i + 1])
        del args[i:i + 2]
    while "--distinct" in args:
        i = args.index("--distinct")
        distinct_key = args[i + 1]
        del args[i:i + 2]
    while "--has" in args:
        i = args.index("--has")
        has_keys.append(args[i + 1])
        del args[i:i + 2]
    for a in args:
        k, _, v = a.partition("=")
        filters[k] = v
    hits = [row for row in measurement_rows(load_rows(ledger_path()))
            if all(str(row.get(k, None)) == v for k, v in filters.items())
            and all(k in row for k in has_keys)]
    n = (len({str(r.get(distinct_key, None)) for r in hits}) if distinct_key
         else len(hits))
    return 0 if n >= min_count else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
