#!/usr/bin/env python
"""Accuracy leg of the flagship-recurrence decision (round-3 verdict
item 5): LSTM vs the time-parallel LRU at the c2 window geometry, same
data, same optimizer budget, multiple seeds. The throughput leg comes
from the chip campaign's lru/lru64 rows; this script supplies the
planted-signal accuracy comparison those rows must be weighed against,
and persists each result to the measurement ledger (backend-tagged, so
CPU rows never displace chip rows).

Run: python scripts/compare_recurrence.py [--seeds 3] [--firms 500]
     [--epochs 10]

CPU-feasible by scaling the firm axis only — the window stays the full
60 months (the axis the recurrences actually differ on).
"""

import argparse
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU-evidence script: force the CPU platform before any backend use (the
# axon plugin ignores JAX_PLATFORMS env; a wedged tunnel hangs the claim).
# LFM_PROBE_BACKEND=tpu opts back into the chip.
if os.environ.get("LFM_PROBE_BACKEND", "cpu") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

from bench import _backend_name, persist_row  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--firms", type=int, default=500)
    ap.add_argument("--epochs", type=int, default=10)
    args = ap.parse_args(argv)

    import numpy as np

    from lfm_quant_tpu.config import get_preset
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    # ONE panel for both models, built from c2's geometry — the invariant
    # is structural, not a coincidence of preset configs staying equal.
    ref = dataclasses.replace(get_preset("c2").data, n_firms=args.firms,
                              n_months=240)
    panel = synthetic_panel(n_firms=ref.n_firms, n_months=ref.n_months,
                            n_features=ref.n_features, horizon=ref.horizon,
                            seed=0)
    splits = PanelSplits.by_date(panel, 198601, 198801)

    results = {}
    for preset in ("c2", "lru"):
        base = get_preset(preset)
        d = dataclasses.replace(base.data, n_firms=args.firms, n_months=240)
        if (d.n_features, d.horizon, d.window) != (
                ref.n_features, ref.horizon, ref.window):
            raise SystemExit(
                f"preset {preset} drifted from c2's data geometry — the "
                "same-panel comparison no longer holds; re-align the "
                "presets or generalize this script")
        cfg = dataclasses.replace(
            base, data=d,
            optim=dataclasses.replace(base.optim, epochs=args.epochs))
        ics = []
        for s in range(args.seeds):
            tr = Trainer(dataclasses.replace(cfg, seed=s), splits)
            fit = tr.fit()
            ics.append(fit["best_val_ic"])
            print(f"[{preset} seed {s}] best_val_ic={ics[-1]:.4f} "
                  f"({fit['epochs_run']} epochs)", flush=True)
        results[preset] = ics
        rec = {"metric": "recurrence_accuracy",
               "config": cfg.name,  # full preset name: one config
               # namespace with the throughput rows in the ledger
               "value": round(float(np.mean(ics)), 4),
               "std": round(float(np.std(ics)), 4),
               "unit": "best_val_ic",
               "n_seeds": args.seeds,
               "firms": args.firms,
               "epochs": args.epochs,
               "backend": _backend_name()}
        persist_row(rec)
        print(rec, flush=True)

    lstm, lru = np.mean(results["c2"]), np.mean(results["lru"])
    print(f"SUMMARY: LSTM val IC {lstm:.4f} vs LRU {lru:.4f} "
          f"(delta {lru - lstm:+.4f}) at firms={args.firms}, "
          f"window=60, epochs={args.epochs}, seeds={args.seeds}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
