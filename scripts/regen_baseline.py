#!/usr/bin/env python
"""Rebuild BASELINE.md's auto-generated measured table from BENCH_ROWS.jsonl.

BENCH_ROWS.jsonl is the append-only measurement ledger (`bench.persist_row`):
every bench.py / bench_ladder.py record lands there the moment it is
measured, so rows survive a mid-campaign tunnel re-wedge. This script
derives the human-readable table — the ledger is the source of truth, the
table is a view. For each distinct measurement key (metric + geometry
knobs) the LATEST row wins; older rows stay in the ledger as history.

Run: python scripts/regen_baseline.py          # rewrites BASELINE.md in place
     python scripts/regen_baseline.py --print  # table to stdout only
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
START = "<!-- AUTOGEN:BENCH_ROWS (scripts/regen_baseline.py) START -->"
END = "<!-- AUTOGEN:BENCH_ROWS END -->"

# Extras that define a DIFFERENT measurement (not just metadata): two rows
# sharing metric but differing here are separate table lines. backend is a
# key field so a CPU smoke run can never displace a chip capture; dtype
# (the resolved LFM_PRECISION lane, stamped on every row since PR 9) is
# one so a bf16 capture can never displace — or be displaced by — the
# f32 trajectory it is compared against.
KEY_FIELDS = ("backend", "dtype", "config", "n_seeds", "seed_block",
              "dates_per_batch", "scan_impl", "gather_impl", "lane_pad",
              "block_b", "impl", "firms", "epochs")


def ledger_path():
    """The one place the scripts resolve the ledger location (bench.py's
    persist_row keeps its own copy — it must stay importable without the
    scripts/ dir on sys.path)."""
    return os.environ.get("LFM_BENCH_ROWS") or os.path.join(
        REPO, "BENCH_ROWS.jsonl")


def measurement_rows(rows, backend="tpu"):
    """The canonical 'which ledger rows count as real measurements'
    filter, shared by ledger_has (resume guards), drift_report, and any
    future consumer: status/outage records never count, and (by default)
    neither do non-TPU smoke rows — a CPU run must not satisfy a chip
    guard or enter a chip drift analysis. ``backend=None`` disables the
    backend filter."""
    return [r for r in rows
            if r.get("unit") != "status"
            and (backend is None or r.get("backend") == backend)]


def row_key(row):
    """The canonical measurement identity: metric + every KEY_FIELD
    (absent == None, so a row missing a field never forks a near-
    duplicate key from one carrying it as None). render_table and
    drift_report must agree on this — two rows that the table shows as
    one measurement line are repeat captures, not different programs.
    Exception: an absent ``dtype`` normalizes to ``"f32"`` — every row
    captured before the precision stamp (PR 9) ran the f32 lane, and a
    fresh f32 capture must continue that trajectory, not fork it."""
    return (row.get("metric"),) + tuple(
        (k, row.get(k) if not (k == "dtype" and row.get(k) is None)
         else "f32")
        for k in KEY_FIELDS)


def load_rows(path):
    rows = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    print(f"[regen] skipping unparseable ledger line: "
                          f"{line[:80]}", file=sys.stderr)
    except FileNotFoundError:
        pass
    return rows


def render_table(rows):
    """Collapse the ledger to latest-per-key and render markdown."""
    measured, history, status = {}, {}, []
    for row in rows:  # file order == chronological; later rows override
        if row.get("unit") == "status":
            status.append(row)
            continue
        key = row_key(row)
        measured[key] = row
        if isinstance(row.get("value"), (int, float)):
            history.setdefault(key, []).append(float(row["value"]))

    lines = [
        "| Metric | Value | Unit | MFU % | Geometry | Backend | When |",
        "|---|---|---|---|---|---|---|",
    ]
    for key in sorted(measured, key=lambda k: str(k)):
        r = measured[key]
        geom = ", ".join(f"{k}={v}" for k, v in key[1:]
                         if v is not None and k != "backend") or "—"
        val = r.get("value")
        if isinstance(val, (int, float)):
            # Throughputs read best with thousands separators; small
            # magnitudes (ICs, ratios) need their decimals kept.
            val = (f"**{val:,.1f}**" if abs(val) >= 10
                   else f"**{val:.4g}**")
            # Error bars (round-4 verdict, Weak #1): the row's own
            # median-of-reps spread if it has one, else (for an std-
            # carrying evidence row) its cross-seed std, else — when the
            # ledger holds repeat captures of the same key — the
            # cross-session spread of the historical values.
            if r.get("spread_pct") is not None:
                val += f" ± {r['spread_pct']}% (n={r.get('n_reps', '?')})"
            elif r.get("std"):
                val += f" ± {r['std']:.4g}"
            else:
                hist = history.get(key, [])
                if len(hist) >= 2 and min(hist) > 0:
                    drift = 100.0 * (max(hist) - min(hist)) / min(hist)
                    if drift >= 1.0:
                        val += (f" (history n={len(hist)}: "
                                f"{drift:.0f}% session drift)")
        else:
            val = "—"
        lines.append(
            f"| `{r.get('metric')}` | {val} | {r.get('unit', '—')} "
            f"| {r.get('mfu_pct', '—')} | {geom} "
            f"| {r.get('backend', '—')} | {r.get('ts', '—')} |")
    if len(lines) == 2:
        lines = ["*(no measured rows in the ledger yet)*"]
    if status:
        last = status[-1]
        lines.append("")
        lines.append(
            f"Ledger also holds {len(status)} harness status record(s); "
            f"latest: `{last.get('status')}` at {last.get('ts', '?')} "
            f"({str(last.get('detail', ''))[:120]}).")
    return "\n".join(lines)


def main(argv) -> int:
    table = render_table(load_rows(ledger_path()))
    if "--print" in argv:
        print(table)
        return 0
    path = os.path.join(REPO, "BASELINE.md")
    with open(path) as fh:
        text = fh.read()
    block = f"{START}\n{table}\n{END}"
    if START in text and END in text:
        head, rest = text.split(START, 1)
        _, tail = rest.split(END, 1)
        text = head + block + tail
    else:  # first run: append the auto section
        text = text.rstrip() + (
            "\n\n## Measured rows (auto-generated)\n\n"
            "Regenerated by `scripts/regen_baseline.py` from the "
            "append-only `BENCH_ROWS.jsonl` measurement ledger — edit the "
            "ledger, not this table.\n\n") + block + "\n"
    with open(path, "w") as fh:
        fh.write(text)
    print(f"[regen] BASELINE.md updated from {ledger_path()}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
