#!/bin/bash
# On-chip measurement campaign — fills BASELINE.md's pending ladder rows
# after a tunnel outage (see BASELINE.md's 2026-07-30 note). Ordered so a
# re-wedge loses the least: driver metrics first, the c1 suspect LAST.
# Every step is timeboxed and logged; a timeout on a non-c1 step means
# the tunnel wedged again and the campaign aborts.
#
# Usage: bash scripts/chip_campaign.sh [logfile]
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/campaign.log}
echo "=== campaign start $(date) ===" | tee -a "$LOG"

step() {
  name=$1; shift
  echo "--- $name: $* ($(date +%H:%M:%S))" | tee -a "$LOG"
  timeout "$TMO" "$@" >> "$LOG" 2>&1
  rc=$?
  echo "--- $name rc=$rc" | tee -a "$LOG"
  case "$name" in
    c1*) ;;  # expected-risky steps don't abort the campaign
    *) if [ $rc -ne 0 ]; then
         echo "!!! $name failed — aborting (tunnel may be wedged)" | tee -a "$LOG"
         exit $rc
       fi ;;
  esac
}

TMO=120 step probe python -c "
import jax, jax.numpy as jnp
print('TUNNEL_OK', float(jax.jit(lambda a: a@a)(jnp.ones((256,256), jnp.bfloat16)).sum()))"

TMO=600 step bench python bench.py
TMO=600 step ladder-c3 python scripts/bench_ladder.py c3
TMO=600 step ladder-c4 python scripts/bench_ladder.py c4
TMO=600 step ladder-lru python scripts/bench_ladder.py lru
TMO=900 step ladder-c5 python scripts/bench_ladder.py c5

# The c1 suspect, isolated and LAST (see scripts/diag_c1.py): first the
# XLA gather (rules out the MLP program), then the Pallas DMA gather.
TMO=420 step c1diag-xla python scripts/diag_c1.py xla 5
TMO=420 step c1diag-pallas python scripts/diag_c1.py - 5
TMO=600 step c1 python scripts/bench_ladder.py c1

echo "=== campaign done $(date) ===" | tee -a "$LOG"
