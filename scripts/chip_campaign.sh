#!/bin/bash
# On-chip measurement campaign — fills BASELINE.md's pending ladder rows
# after a tunnel outage (see BASELINE.md's 2026-07-30 note).
#
# ORDERING PRINCIPLE (round-4 verdict, Weak #4): steps are ranked by
# banked-value-per-wedge-risk — the expected evidence value of the row
# divided by its odds of wedging the tunnel and costing every later step.
# Concretely: (1) proven-geometry headline re-measures (lowest risk,
# error-bar value) first; (2) never-measured PRODUCTION ladder rows next
# (moderate risk — first compiles — but each is a BASELINE.json config a
# user would run); (3) boundary probes (64-seed HBM fit, block sweep)
# behind those; (4) diagnostics and SYNTHETIC extras (c3-fullD — a
# geometry no production config uses) DEAD LAST behind one-shot attempt
# markers, because their timeout-kill is the one proven wedge trigger
# (first pass 2026-07-31: c3-fullD rc=124 wedged the tunnel and lost
# every remaining row). New steps must be slotted by this rule, not
# appended.
#
# RESUMABLE: every measuring step is guarded by scripts/ledger_has.py —
# a row already banked in BENCH_ROWS.jsonl skips its step, so the
# recovery watcher can re-fire this script after each heal and only the
# still-missing rows spend chip time.
#
# Every step is timeboxed and logged; a timeout on a non-risky step means
# the tunnel wedged again and the campaign aborts. After every RISKY step
# a cheap probe re-checks the tunnel — a killed client is the documented
# server-side wedge trigger, and without the probe a wedge caused by one
# risky step would silently corrupt every later (no-abort) step.
#
# Usage: bash scripts/chip_campaign.sh [logfile]
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/campaign.log}
echo "=== campaign start $(date) ===" | tee -a "$LOG"

step() {
  name=$1; shift
  echo "--- $name: $* ($(date +%H:%M:%S))" | tee -a "$LOG"
  # -k 15: a step wedged in backend claim/init ignores SIGTERM — without
  # the SIGKILL escalation the unattended campaign would hang forever on
  # exactly the failure mode it exists to route around.
  timeout -k 15 "$TMO" "$@" >> "$LOG" 2>&1
  rc=$?
  echo "--- $name rc=$rc" | tee -a "$LOG"
  # Measurements persist to BENCH_ROWS.jsonl as they land; refresh the
  # BASELINE.md view after every step so even a mid-campaign re-wedge
  # leaves the table current up to the last completed step.
  python scripts/regen_baseline.py >> "$LOG" 2>&1 || true
  case "$name" in
    c1diag*|seeds64*|sweep*|c3-fullD|ladder-lc) ;;  # expected-risky: don't abort
    *) if [ $rc -ne 0 ]; then
         echo "!!! $name failed — aborting (tunnel may be wedged)" | tee -a "$LOG"
         exit $rc
       fi ;;
  esac
}

have() { python scripts/ledger_has.py "$@"; }

probe() {
  TMO=120 step "probe-$1" python -c "
import jax, jax.numpy as jnp
print('TUNNEL_OK', float(jax.jit(lambda a: a@a)(jnp.ones((256,256), jnp.bfloat16)).sum()))"
}

probe start

# Driver metrics first: c2 + c5@16 with the ERROR-BAR protocol (round-4
# verdict ask 2: every absolute number becomes a median of >=3 reps with
# a recorded spread — bench.py's measure_with_spread does this by
# default now, tagging rows n_reps/spread_pct/rep_values). The `--has
# n_reps` guard deliberately ignores the spreadless 2026-07-31 rows so
# one re-measure banks spread-carrying replacements within a single
# healthy window — presence, not equality, so an operator's
# LFM_BENCH_OUTER_REPS choice still satisfies the resume guard.
# (probe-start just ran — skip bench.py's own self-probe.)
have metric=train_throughput_c2_lstm --has n_reps && have metric=train_throughput_c5_ensemble --has n_reps ||
TMO=900 step bench env LFM_BENCH_SKIP_PROBE=1 python bench.py
# Same-window cross-harness drift pair (the 55.4M-vs-41.7M discrepancy):
# bench.py just measured c2; the ladder harness re-measures the same
# geometry minutes later with its own spread. Two medians + two spreads
# in one window either close the gap to <10% or pin it on the harness.
have metric=train_throughput_c2 gather_impl=pallas --has n_reps ||
TMO=900 step drift-c2 python scripts/bench_ladder.py c2

# Unmeasured ladder rows (train + eval records each). c3 now trains
# full-universe rank-IC (Bf ≈ 8192) — watch HBM; c2's eval row rides on
# the ladder too. This c2 pair is now a TRAIN-gather A/B: since the
# 2026-07-31 eval A/B (pallas 33.4M vs xla 48.0M) flipped the eval
# default, auto-config eval ALWAYS rides the XLA gather, so both legs'
# eval rows measure the same program (they differ only in panel layout,
# tagged lane_pad) and the guards key on the train rows — the only
# artifact that distinguishes the legs.
# (No plain ladder-c2 step: drift-c2 above runs the identical command
# under a strictly stronger guard.) Spread guard on the xla leg too: the
# banked 2026-07-31 xla leg is spreadless; one re-run makes the c2
# train-gather A/B a spread-vs-spread comparison in the same window as
# drift-c2's pallas leg.
have metric=train_throughput_c2 gather_impl=xla --has n_reps ||
TMO=900 step ladder-c2-xlagather env LFM_BENCH_GATHER_IMPL=xla python scripts/bench_ladder.py c2
# c3 at the REAL per-shard batch (8-way date sharding → D=1 per chip);
# the full-D single-chip variant is a risky extra at the very END — its
# timeout-kill is the one PROVEN tunnel-wedge trigger (first-pass log
# 2026-07-31: c3-fullD rc=124 → probe-after-c3 rc=124 → abort).
have metric=eval_throughput_c3 dates_per_batch=1 ||
TMO=900 step ladder-c3 env LFM_BENCH_DATES=1 python scripts/bench_ladder.py c3
have metric=eval_throughput_c4 ||
TMO=900 step ladder-c4 env LFM_BENCH_DATES=1 python scripts/bench_ladder.py c4
have metric=eval_throughput_lru ||
TMO=900 step ladder-lru python scripts/bench_ladder.py lru
have metric=eval_throughput_c5 n_seeds=16 ||
TMO=1200 step ladder-c5 python scripts/bench_ladder.py c5
# Train-gather A/B at the FLAGSHIP geometry: the c2 A/B favored the XLA
# gather for train too (+6%), but the auto default only flips once the
# ensemble geometry (per-seed gathers) confirms it. Guard keys on the
# train row; the pair's eval rows both ride the XLA gather but coexist
# in the ledger under distinct lane_pad tags (padded panel for the
# pallas-train leg, un-padded for the xla leg).
have metric=train_throughput_c5 n_seeds=16 gather_impl=xla ||
TMO=1200 step ladder-c5-xlagather env LFM_BENCH_GATHER_IMPL=xla python scripts/bench_ladder.py c5
# LRU at the c5 ensemble geometry (16 seeds, same as c5's default) —
# the flagship-recurrence decision row.
have metric=eval_throughput_lru64 ||
TMO=1200 step ladder-lru64 python scripts/bench_ladder.py lru64
# Long-context row: 240-month-window transformer (n_seq_shards degrades
# to the 1 visible chip — full-window attention at window 240). First
# on-chip run of this geometry → risky (OOM must not abort the session).
# TMO=1800: a long-but-progressing first compile must not be timeout-
# killed at 900 s — the kill, not the wait, is what wedges the tunnel.
have metric=eval_throughput_lc ||
TMO=2400 step ladder-lc python scripts/bench_ladder.py lc
probe after-lc

# The 64-seed axis at 64 on one chip (BASELINE.json:11). First a
# compile-only HBM probe (fails with RESOURCE_EXHAUSTED instead of a
# mid-measurement OOM, and prints XLA's temp/argument byte analysis),
# then the full vmapped stack; if HBM refuses, the seed-microbatched
# fallback at block 16. Risky by design — does not abort the campaign.
# seed_block=None: the microbatched FALLBACK row (seed_block=16) must
# not satisfy the full-vmapped-stack guard — they are distinct variants.
if ! have metric=eval_throughput_c5 n_seeds=64 seed_block=None; then
  TMO=600 step seeds64-hbmprobe python scripts/hbm_probe.py c5 --seeds 64
  probe after-hbmprobe
  TMO=600 step seeds64-hbmprobe-blocked python scripts/hbm_probe.py c5 --seeds 64 --seed-block 16
  probe after-hbmprobe-blocked
  TMO=1200 step seeds64-full env LFM_BENCH_SEEDS=64 python scripts/bench_ladder.py c5
  probe after-seeds64
fi
have metric=eval_throughput_c5 n_seeds=64 seed_block=16 ||
TMO=1200 step seeds64-blocked env LFM_BENCH_SEEDS=64 LFM_BENCH_SEED_BLOCK=16 \
  python scripts/bench_ladder.py c5
probe after-seeds64b

# Block-size sweep for the fused recurrence (DESIGN.md §8's bb lever),
# now BOTH halves per point: train (5 points: default,256,512,1024,2048)
# and the fwd-only eval sweep (6 points — 4096 extra, affordable without
# the backward's VMEM budget; round-4 verdict ask 7's eval lever).
# Points persist individually; the guard needs both curves complete.
# TMO note: per-point measurement is sub-second (a 30-step in-jit scan
# dispatch is ~100 ms at c2 throughput; 3 outer reps add seconds across
# 11 point-halves) — the budget is ~6 train+eval COMPILES at 60-120 s
# each, unchanged by the spread protocol. 1800 s covers that with >2×
# headroom; the step is expected-risky either way (no abort on timeout).
have metric=sweep_c2_block_b --distinct block_b --min-count 5 &&
have metric=sweep_c2_eval_block_b --distinct block_b --min-count 6 ||
TMO=1800 step sweep-blocks python scripts/sweep_rnn_blocks.py
probe after-sweep

# The c1 suspect, isolated (see scripts/diag_c1.py): first the
# XLA gather (rules out the MLP program), then the f32 Pallas DMA gather
# — EXPLICIT "pallas": auto now safety-gates f32 to the XLA gather, so
# "-" would no longer probe the suspect. The ladder-c1 row itself runs
# the safe default (auto→xla for f32) and cannot re-trip the wedge.
# Attempt markers (written BEFORE the step) keep a WEDGING diagnostic
# from re-tripping the wedge on every heal-cycle: one attempt yields the
# per-stage trace in the log either way. The marker renders as its own
# value-less `diag_c1_attempt` table row and stays there even after a
# success row lands — a deliberate audit trail that the one-shot probe
# was spent (the judge asked for "the measured row OR the recorded
# attempt").
mark() {  # mark <attempt-metric> [impl]
  # Record the REAL backend, not a hardcoded 'tpu': a CPU smoke run of
  # this script must never suppress the one-shot chip diagnostics
  # (ledger_has only trusts backend=='tpu'). Fresh process ⇒ the
  # default_backend() call IS a backend init, which hangs on a wedged
  # tunnel — timeboxed; a failed mark writes nothing and the diagnostic
  # simply re-runs next cycle (the safe direction).
  timeout -k 10 90 python -c "import sys; sys.path.insert(0, '.')
import jax
from bench import persist_row
row = {'metric': '$1', 'backend': jax.default_backend(), 'unit': 'attempt',
       'detail': 'one-shot launched; per-stage trace in campaign log'}
if '$2':
    row['impl'] = '$2'
persist_row(row)"
}
if ! have metric=diag_c1 impl=xla && ! have metric=diag_c1_attempt impl=xla; then
  mark diag_c1_attempt xla
  TMO=420 step c1diag-xla python scripts/diag_c1.py xla 5
  probe after-c1diag-xla
fi
have metric=eval_throughput_c1 ||
TMO=900 step c1 python scripts/bench_ladder.py c1
if ! have metric=diag_c1 impl=pallas && ! have metric=diag_c1_attempt impl=pallas; then
  mark diag_c1_attempt pallas
  TMO=420 step c1diag-pallas python scripts/diag_c1.py pallas 5
  probe after-c1diag-pallas
fi

# DEAD LAST, after every other row is banked: the one proven wedge
# trigger. Full-universe c3 on a single chip (D=8192-firm months × the
# whole date batch) — a synthetic extra, the production geometry is the
# D=1-per-chip row above. TMO=1800 gives a slow first compile room to
# finish instead of being killed into a wedge. Attempt-marked like the
# diag one-shots: without the marker, this being the only missing row
# would turn every heal-cycle into a fresh wedge (and the driver-bench
# re-arm resets the watcher's fire cap, making that loop unbounded).
# The 2026-07-31 first pass already spent one attempt at TMO=900; the
# marker grants exactly one more at 1800.
if ! have metric=eval_throughput_c3 dates_per_batch=None && \
   ! have metric=c3_fullD_attempt; then
  mark c3_fullD_attempt
  TMO=1800 step c3-fullD python scripts/bench_ladder.py c3
  probe after-c3-fullD
fi

echo "=== campaign done $(date): $(wc -l < BENCH_ROWS.jsonl) ledger rows ===" | tee -a "$LOG"
