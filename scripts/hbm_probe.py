#!/usr/bin/env python
"""Compile-only HBM footprint probe: does a ladder config's train step
FIT the chip? (BASELINE.json:11 — the 64-seed HBM-fit question.)

Compiles the real jitted step (no execution beyond state init) and
prints XLA's memory analysis — argument/output/temp/generated-code
bytes — as one JSON line. Much cheaper than a bench run and fails with
a RESOURCE_EXHAUSTED compile error instead of a mid-measurement OOM, so
the campaign learns the fit boundary without losing a timebox.

Run: python scripts/hbm_probe.py c5 [--seeds 64] [--seed-block 16]
     python scripts/hbm_probe.py c3 [--dates 1]
"""

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("preset")
    ap.add_argument("--seeds", type=int, default=0,
                    help="override n_seeds (ensemble presets)")
    ap.add_argument("--seed-block", type=int, default=0)
    ap.add_argument("--dates", type=int, default=0,
                    help="override dates_per_batch (per-shard batch)")
    args = ap.parse_args(argv)

    from bench_ladder import _bench_panel, _overrides
    from lfm_quant_tpu.config import get_preset
    from lfm_quant_tpu.train import Trainer
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    # Same override stack as the bench step this probe predicts
    # (scan_impl guarded to RNN kinds, gather reroute, LFM_BENCH_DATES) —
    # a fit verdict for a different program would be worthless. CLI flags
    # layer on top for manual use; the campaign drives everything via the
    # same env vars as the bench steps.
    cfg = _overrides(get_preset(args.preset))
    seeds = args.seeds or int(os.environ.get("LFM_BENCH_SEEDS", "0"))
    if seeds and cfg.n_seeds > 1:
        cfg = dataclasses.replace(cfg, n_seeds=seeds)
    seed_block = (args.seed_block
                  or int(os.environ.get("LFM_BENCH_SEED_BLOCK", "0")))
    if seed_block:
        cfg = dataclasses.replace(cfg, seed_block=seed_block)
    if args.dates:
        cfg = dataclasses.replace(
            cfg, data=dataclasses.replace(cfg.data,
                                          dates_per_batch=args.dates),
            n_data_shards=1)

    splits = _bench_panel(cfg)
    if cfg.n_seeds > 1:
        trainer = EnsembleTrainer(cfg, splits)
        state = trainer.init_state()
        arrays = trainer._stacked_batch(
            [s.epoch(0) for s in trainer.samplers])
    else:
        trainer = Trainer(cfg, splits)
        state = trainer.init_state()
        b = next(iter(trainer.train_sampler.epoch(0)))
        arrays = trainer._batch_args(b, train=True)

    rec = {"metric": f"hbm_probe_{args.preset}",
           "n_seeds": cfg.n_seeds, "seed_block": cfg.seed_block,
           "dates_per_batch": cfg.data.dates_per_batch}
    lowered = trainer._jit_step.lower(state, trainer.dev, *arrays)
    try:
        compiled = lowered.compile()
    except Exception as e:  # RESOURCE_EXHAUSTED = the probe's answer, not a crash
        msg = str(e)
        rec.update(fits=False, error=msg[:300])
        print(json.dumps(rec), flush=True)
        # Only an OOM-style compile failure is a clean "doesn't fit";
        # anything else should still fail the step loudly.
        return 0 if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg \
            else 1
    rec["fits"] = True
    ma = None
    try:
        ma = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 — backend-dependent API
        rec["memory_analysis"] = f"unavailable: {type(e).__name__}"
    if ma is not None:
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "alias_size_in_bytes",
                     "generated_code_size_in_bytes"):
            v = getattr(ma, attr, None)
            if v is not None:
                rec[attr.replace("_in_bytes", "_mb")] = round(v / 1e6, 1)
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
