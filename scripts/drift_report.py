#!/usr/bin/env python
"""Cross-capture drift report for one geometry (default: c2 train).

Round-4 verdict ask 2: the 55.4M-vs-41.7M same-geometry spread must be
"resolved to <10% or explained by a recorded tunnel-health covariate".
The rows now carry both instruments — per-row median-of-reps spreads
(spread_pct) and the rtt_ms tunnel-latency covariate — and this script
is the one-command analysis over them: every capture of the geometry in
chronological order, the cross-capture spread of the medians, and the
rtt correlation when there is enough data to say anything.

Run: python scripts/drift_report.py [metric_prefix ...]
     (default prefixes: train_throughput_c2 eval_throughput_c2)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from regen_baseline import (ledger_path, load_rows,  # noqa: E402
                            measurement_rows, row_key)


def _fmt_value(v) -> str:
    """Magnitude-aware value format: sub-10 metrics (speedup ratios like
    1.76×) keep significant digits via ``.4g`` — the old ``,.0f`` rendered
    1.76 as "2" — while big throughput numbers stay comma-grouped."""
    v = float(v)
    return f"{v:.4g}" if abs(v) < 10 else f"{v:,.0f}"


def report(prefixes) -> int:
    rows = [r for r in measurement_rows(load_rows(ledger_path()))
            if isinstance(r.get("value"), (int, float))
            and any(str(r.get("metric", "")).startswith(p)
                    for p in prefixes)]
    if not rows:
        print(f"no tpu rows match prefixes {prefixes}")
        return 1
    print(f"{'ts':20} {'metric':28} {'impl':14} {'value':>14} "
          f"{'±%':>6} {'rtt_ms':>7}")
    for r in rows:  # ledger order == chronological
        impl = r.get("gather_impl") or r.get("scan_impl") or "-"
        spread = r.get("spread_pct")
        print(f"{r.get('ts', '?'):20} {r.get('metric', '?'):28} "
              f"{impl:14} {_fmt_value(r.get('value', 0)):>14} "
              f"{spread if spread is not None else '—':>6} "
              f"{r.get('rtt_ms') if r.get('rtt_ms') is not None else '—':>7}")
    # Group by the CANONICAL measurement identity (regen_baseline's
    # row_key — metric + every KEY_FIELD) so deliberate A/B variants
    # (gather legs, lane_pad panel layouts, dates_per_batch geometries)
    # are never conflated into fake "drift": only repeat captures of the
    # SAME program + geometry form a group.
    groups = {}
    for r in rows:
        groups.setdefault(row_key(r), []).append(r)
    print()
    for key, grp in groups.items():
        if len(grp) < 2:
            continue
        vals = [float(r["value"]) for r in grp]
        drift = 100.0 * (max(vals) - min(vals)) / min(vals)
        within = [r.get("spread_pct") for r in grp
                  if r.get("spread_pct") is not None]
        verdict = ("RESOLVED (<10%)" if drift < 10.0 else
                   "within per-capture spread" if within
                   and drift <= max(within) else "environmental drift")
        rtts = [(r.get("rtt_ms"), float(r["value"])) for r in grp
                if r.get("rtt_ms") is not None]
        rtt_note = ""
        if len(rtts) >= 2:
            # Extremes by the rtt covariate ALONE: plain tuple max/min
            # would tie-break equal rtts on throughput, silently picking
            # the pairing that confirms the covariate story.
            hi = max(rtts, key=lambda t: t[0])
            lo = min(rtts, key=lambda t: t[0])
            hi_rtt, lo_rtt = hi[0], lo[0]
            if hi_rtt and lo_rtt and hi_rtt > 1.5 * lo_rtt:
                slower_at_hi = hi[1] < lo[1]
                rtt_note = (" — rtt covariate moves with it"
                            if slower_at_hi else
                            " — rtt covariate does NOT explain it")
        tags = ", ".join(f"{k}={v}" for k, v in key[1:] if v is not None)
        print(f"{key[0]} ({tags or '-'}): "
              f"{len(grp)} captures, cross-capture drift {drift:.1f}% "
              f"→ {verdict}{rtt_note}")
    # The original mystery spans two HARNESSES (bench.py's
    # train_throughput_c2_lstm vs bench_ladder's train_throughput_c2,
    # pallas leg) — compare their latest captures explicitly.
    bench_rows = [r for r in rows
                  if r.get("metric") == "train_throughput_c2_lstm"
                  and r.get("gather_impl") == "pallas"]
    ladder_rows = [r for r in rows
                   if r.get("metric") == "train_throughput_c2"
                   and r.get("gather_impl") == "pallas"]
    if bench_rows and ladder_rows:
        b, l = bench_rows[-1], ladder_rows[-1]
        pair = sorted([float(b["value"]), float(l["value"])])
        gap = 100.0 * (pair[1] - pair[0]) / pair[0]
        spreads = [r.get("spread_pct") for r in (b, l)
                   if r.get("spread_pct") is not None]
        print(f"cross-harness c2 pair (bench {b.get('ts')} vs ladder "
              f"{l.get('ts')}): gap {gap:.1f}%"
              + (f", per-capture spreads {spreads}" if spreads else
                 " (pre-protocol captures: no per-row spreads)"))
    return 0


if __name__ == "__main__":
    sys.exit(report(sys.argv[1:] or
                    ["train_throughput_c2", "eval_throughput_c2"]))
