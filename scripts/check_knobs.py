#!/usr/bin/env python
"""Static LFM_* knob-documentation cross-check (CI tooling).

Every ``LFM_*`` environment variable the codebase READS must be
documented in README.md, and every knob the telemetry run manifest
PROBES (``utils/telemetry.py _KNOB_PROBES``) must resolve to a real
function in a real module — otherwise a new knob (this repo grows one
most PRs: LFM_BUCKETS, LFM_STACK_BLOCK, LFM_PRECISION, ...) can land
invisible to operators and to the manifest's provenance record.

Wholly static: sources are scanned with regex/ast, nothing is imported
(no jax, no backend init — the check runs in milliseconds anywhere,
including the wedged-tunnel box). Wired as a fast test in
tests/test_amp.py, so an undocumented knob fails tier-1 before it
lands.

Scope rules:
  * reads under ``tests/`` are exempt (test-local knobs like LFM_OTHER
    are fixtures, not operator surface);
  * a knob read ONLY under ``scripts/`` must be documented in the
    script's own module docstring OR README (operator tooling documents
    itself);
  * everything else (lfm_quant_tpu/, top-level entry points) must
    appear in README.md.

Exit 0 = clean; exit 1 prints the offending knobs.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: os.environ reads: .get("LFM_X"), ["LFM_X"] — pops/sets/dels are
#: writes or cleanup, not operator-facing reads, and stay out on
#: purpose (a knob that is only ever written is not a knob).
_READ_RE = re.compile(
    r"""os\.environ(?:\.get\(\s*|\[\s*)['"](LFM_[A-Z0-9_]+)['"]""")
_TOKEN_RE = re.compile(r"LFM_[A-Z0-9_]+")


def _py_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git", ".claude")]
        out.extend(os.path.join(dirpath, f) for f in filenames
                   if f.endswith(".py"))
    return sorted(out)


def env_reads(repo: str = REPO) -> Dict[str, Set[str]]:
    """knob name → set of repo-relative files that READ it."""
    reads: Dict[str, Set[str]] = {}
    for path in _py_files(repo):
        rel = os.path.relpath(path, repo)
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        for name in _READ_RE.findall(src):
            reads.setdefault(name, set()).add(rel)
    return reads


def documented_knobs(repo: str = REPO) -> Set[str]:
    try:
        with open(os.path.join(repo, "README.md"), encoding="utf-8") as fh:
            return set(_TOKEN_RE.findall(fh.read()))
    except OSError:
        return set()


def _module_docstring(path: str) -> str:
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        return ast.get_docstring(tree) or ""
    except (OSError, SyntaxError):
        return ""


def manifest_probes(repo: str = REPO) -> List[Tuple[str, str, str]]:
    """The (name, module, fn) triples of ``_KNOB_PROBES``, read
    statically (ast.literal_eval of the assignment) — no import."""
    path = os.path.join(repo, "lfm_quant_tpu", "utils", "telemetry.py")
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "_KNOB_PROBES"
                        for t in node.targets)):
            return [tuple(x) for x in ast.literal_eval(node.value)]
    raise AssertionError("_KNOB_PROBES not found in utils/telemetry.py")


def check(repo: str = REPO) -> List[str]:
    """All problems found (empty list = clean)."""
    problems: List[str] = []
    reads = env_reads(repo)
    docs = documented_knobs(repo)

    for name, files in sorted(reads.items()):
        non_test = {f for f in files if not f.startswith("tests" + os.sep)}
        if not non_test:
            continue  # test-fixture knob (e.g. LFM_OTHER)
        if name in docs:
            continue
        script_only = all(f.startswith("scripts" + os.sep)
                          for f in non_test)
        if script_only and all(
                name in _module_docstring(os.path.join(repo, f))
                for f in non_test):
            continue  # operator tooling documenting its own knob
        problems.append(
            f"undocumented knob {name} (read in "
            f"{', '.join(sorted(non_test))}) — add it to README.md")

    # Manifest probes must resolve: module file exists and defines fn.
    for name, mod, fn in manifest_probes(repo):
        mpath = os.path.join(repo, *mod.split(".")) + ".py"
        if not os.path.exists(mpath):
            mpath = os.path.join(repo, *mod.split("."), "__init__.py")
        if not os.path.exists(mpath):
            problems.append(
                f"manifest knob probe {name!r}: module {mod} has no file")
            continue
        with open(mpath, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
        defs = {n.name for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # Re-exports (e.g. lfm_quant_tpu.backtest.jax_backtest_enabled)
        # surface as imported names, not defs.
        imports = {a.asname or a.name for n in ast.walk(tree)
                   if isinstance(n, ast.ImportFrom) for a in n.names}
        if fn not in defs | imports:
            problems.append(
                f"manifest knob probe {name!r}: {mod}.{fn} not found")
    return problems


def main() -> int:
    problems = check()
    reads = env_reads()
    print(f"[check_knobs] {len(reads)} LFM_* knobs read, "
          f"{len(documented_knobs())} documented in README.md, "
          f"{len(manifest_probes())} manifest probes")
    for p in problems:
        print(f"[check_knobs] FAIL: {p}")
    if not problems:
        print("[check_knobs] OK — every knob documented, every probe "
              "resolves")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
