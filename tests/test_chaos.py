"""Chaos lane (``-m chaos``): failure paths pinned, not hoped for.

The fault-injection harness (utils/faults.py) makes failures happen on
demand — deterministic, seeded, site-addressed — and this module uses
it to pin the graceful-degradation contracts (DESIGN.md §18):

* the harness itself: spec parsing, schedule determinism, loud errors,
  and the MEASURED non-interference contract (LFM_FAULTS unset ⇒ a
  warm fit pays zero jit traces, zero panel H2D, one host sync/epoch —
  the same counters as before the fault layer existed);
* serving: transient dispatch faults recover via bounded retry with
  BIT-EQUAL responses and zero recompiles; retry exhaustion fails
  loudly; consecutive failures open the circuit breaker (fast-fail +
  retry-after, real /healthz readiness) and a half-open probe recovers
  it; a full queue SHEDS instead of growing without bound; expired
  deadlines are dropped BEFORE dispatch; a dead batcher thread fails
  pending and future requests fast instead of hanging clients;
* checkpointing: the ``ckpt_write`` fault site fires; a wedged async
  save can no longer hang shutdown (bounded wait + loud warning);
* preemption: a SIGTERM mid-epoch — delivered at an exact fault-site
  call, in-process and in a real subprocess — grace-stops with the
  recorded epochs durable, and a resume reproduces the uninterrupted
  fit's history and best params EXACTLY.

Module named early in the alphabet on purpose: it must sort before the
tier-1 timebox cut (ROADMAP tier-1 notes).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import clear_panel_cache
from lfm_quant_tpu.serve import ScoringService
from lfm_quant_tpu.serve import errors as serrors
from lfm_quant_tpu.train import preempt, reuse
from lfm_quant_tpu.train.checkpoint import CheckpointManager
from lfm_quant_tpu.train.loop import Trainer, restore_state_dict
from lfm_quant_tpu.utils import faults, telemetry
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(n_firms=60, window=8, seed=0, epochs=1, name="chaos_t"):
    return RunConfig(
        name=name,
        data=DataConfig(n_firms=n_firms, n_months=160, n_features=5,
                        window=window, dates_per_batch=4,
                        firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=2,
                          loss="mse"),
        seed=seed,
    )


def _universe(n_firms=60, window=8, seed=0, panel_seed=3, fit=False):
    panel = synthetic_panel(n_firms=n_firms, n_months=160, n_features=5,
                            seed=panel_seed)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(n_firms=n_firms, window=window, seed=seed), splits)
    if fit:
        tr.fit()
    else:
        tr.state = tr.init_state()
    return tr, panel, splits


@pytest.fixture(autouse=True)
def _chaos_hygiene(monkeypatch):
    """No fault schedule, no stale preemption flag, fresh caches — in
    AND out, so a failing chaos test can never poison its neighbors."""
    monkeypatch.delenv("LFM_FAULTS", raising=False)
    faults.configure("")
    preempt.clear()
    reuse.clear_program_cache()
    clear_panel_cache()
    yield
    faults.configure("")
    preempt.clear()
    reuse.clear_program_cache()
    clear_panel_cache()


# ---- the harness itself --------------------------------------------------


def test_fault_spec_parsing_and_determinism():
    plans = faults.parse_spec(
        "serve_dispatch:p=0.5,seed=7,n=3;ckpt_write:at=1+3,kind=permanent")
    assert set(plans) == {"serve_dispatch", "ckpt_write"}
    assert plans["serve_dispatch"].limit == 3
    assert plans["ckpt_write"].at == frozenset({1, 3})
    assert plans["ckpt_write"].kind == "permanent"
    # Seeded p-mode schedules are a pure function of (seed, call index).
    a = faults.parse_spec("device_get:p=0.3,seed=11")["device_get"]
    b = faults.parse_spec("device_get:p=0.3,seed=11")["device_get"]
    fires_a = [a.fire() is not None for _ in range(64)]
    fires_b = [b.fire() is not None for _ in range(64)]
    assert fires_a == fires_b
    assert any(fires_a) and not all(fires_a)
    # A different seed is a different schedule.
    c = faults.parse_spec("device_get:p=0.3,seed=12")["device_get"]
    assert [c.fire() is not None for _ in range(64)] != fires_a


def test_fault_spec_loud_on_garbage():
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.parse_spec("nope:p=1")
    with pytest.raises(ValueError, match="kind"):
        faults.parse_spec("ckpt_write:kind=weird")
    with pytest.raises(ValueError, match="unknown key"):
        faults.parse_spec("ckpt_write:frequency=2")
    with pytest.raises(ValueError, match="duplicate"):
        faults.parse_spec("ckpt_write:at=0;ckpt_write:at=1")


def test_fault_kinds_and_counters():
    faults.configure("device_get:at=0")
    snap = telemetry.COUNTERS.snapshot()
    with pytest.raises(faults.TransientFault) as ei:
        faults.check("device_get")
    assert serrors.is_transient(ei.value)
    faults.check("device_get")  # call 1: not scheduled — no raise
    d = telemetry.COUNTERS.delta(snap)
    assert d.get("faults_injected") == 1 and d.get("fault_device_get") == 1
    faults.configure("device_get:kind=permanent,n=1")
    with pytest.raises(faults.PermanentFault) as ei:
        faults.check("device_get")
    assert not serrors.is_transient(ei.value)
    faults.check("device_get")  # budget n=1 spent — site is quiet now


def test_faults_unset_is_exact_noop_and_fit_non_interference(monkeypatch):
    """The measured non-interference contract: with LFM_FAULTS unset
    the fault layer — wired into serve_dispatch, panel_h2d, zoo_lease,
    ckpt_write AND device_get — adds zero jit traces, zero panel H2D
    and zero extra host syncs to a warm fit (the reuse/pipeline lane
    numbers, unchanged)."""
    monkeypatch.delenv("LFM_FAULTS", raising=False)
    faults.configure()
    assert not faults.active()
    faults.check("serve_dispatch")  # no spec → returns, raises nothing
    panel = synthetic_panel(n_firms=60, n_months=160, n_features=5, seed=3)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(epochs=2), splits)
    tr.fit()  # cold: compiles + panel transfer
    snap = REUSE_COUNTERS.snapshot()
    tr.rebind()
    out = tr.fit()  # warm
    d = REUSE_COUNTERS.delta(snap)
    assert d.get("jit_traces", 0) == 0, d
    assert d.get("panel_transfers", 0) == 0, d
    assert d.get("host_syncs", 0) == out["epochs_run"], d


# ---- serving: retry / breaker / shed / deadline / death ------------------


def test_transient_dispatch_fault_retries_bit_equal_zero_recompiles(
        tmp_path):
    """The acceptance pin: under injected transient dispatch faults the
    service recovers via bounded retry with ZERO incorrect responses
    and ZERO steady-state recompiles — and the degradation counters
    surface in trace_report's serve section from the run dir alone."""
    run_dir = str(tmp_path / "chaos_serve")
    assert telemetry._ACTIVE is None
    svc = ScoringService(max_rows=4, max_wait_ms=1.0)
    try:
        tr, _, _ = _universe(fit=True)
        svc.register("us", tr)
        m = svc.serveable_months("us")[5]
        ref = svc.score("us", m).scores.copy()
        with telemetry.run_scope(run_dir, extra={"entry": "test_chaos"}):
            snap = REUSE_COUNTERS.snapshot()
            # Two transient faults, default LFM_SERVE_RETRIES=2 → the
            # third attempt of the SAME batch succeeds.
            faults.configure("serve_dispatch:n=2,kind=transient")
            r = svc.score("us", m)
            np.testing.assert_array_equal(r.scores, ref)
            d = REUSE_COUNTERS.delta(snap)
            assert d.get("jit_traces", 0) == 0, d
            assert d.get("panel_transfers", 0) == 0, d
        stats = svc.batcher.stats()
        assert stats["retries"] == 2
        assert stats["circuit"] == "closed"
        assert svc.health()["ok"]
    finally:
        svc.close()
    from lfm_quant_tpu.serve.stats import load_trace_report

    tr_mod = load_trace_report(REPO)
    sv = tr_mod.build_report(tr_mod.load_run(run_dir)).get("serve")
    assert sv is not None
    assert sv["retries"] == 2
    assert sv["faults_injected"] == 2
    assert sv["jit_traces_run"] == 0


def test_retry_exhaustion_fails_loudly_then_recovers():
    svc = ScoringService(max_rows=4, max_wait_ms=0.5, retries=1,
                         breaker_threshold=0)
    try:
        tr, _, _ = _universe()
        svc.register("us", tr)
        m = svc.serveable_months("us")[5]
        svc.score("us", m)  # settle
        faults.configure("serve_dispatch:n=10,kind=transient")
        with pytest.raises(faults.TransientFault):
            svc.score("us", m, timeout=30)
        faults.configure("")
        r = svc.score("us", m)  # healed backend → next request serves
        assert r.scores.size > 0
    finally:
        svc.close()


def test_circuit_breaker_opens_fast_fails_half_open_recovers():
    svc = ScoringService(max_rows=2, max_wait_ms=0.0, retries=0,
                         breaker_threshold=2, breaker_cooldown_ms=80)
    try:
        tr, _, _ = _universe()
        svc.register("us", tr)
        m = svc.serveable_months("us")[5]
        svc.score("us", m)  # settle the healthy path
        faults.configure("serve_dispatch:kind=permanent")  # every call
        for _ in range(2):  # streak reaches the threshold
            with pytest.raises(faults.PermanentFault):
                svc.score("us", m, timeout=30)
        # OPEN: real readiness + fast-fail with retry-after.
        h = svc.health()
        assert not h["ok"] and h["circuit"] == "open"
        assert "circuit open" in h["reason"]
        assert h["retry_after_s"] >= 0
        with pytest.raises(serrors.CircuitOpenError) as ei:
            svc.score("us", m, timeout=30)
        assert ei.value.http_status == 503
        assert svc.batcher.stats()["breaker_opens"] == 1
        assert telemetry.COUNTERS.get("circuit_state") == 2
        # Cooldown elapses with the backend HEALED → the half-open
        # probe succeeds and the circuit closes.
        faults.configure("")
        time.sleep(0.1)
        r = svc.score("us", m)
        assert r.scores.size > 0
        h = svc.health()
        assert h["ok"] and h["circuit"] == "closed"
        assert telemetry.COUNTERS.get("circuit_state") == 0
    finally:
        svc.close()


def test_half_open_probe_failure_reopens():
    svc = ScoringService(max_rows=2, max_wait_ms=0.0, retries=0,
                         breaker_threshold=1, breaker_cooldown_ms=40)
    try:
        tr, _, _ = _universe()
        svc.register("us", tr)
        m = svc.serveable_months("us")[5]
        svc.score("us", m)
        faults.configure("serve_dispatch:kind=permanent")
        with pytest.raises(faults.PermanentFault):
            svc.score("us", m, timeout=30)  # opens (threshold 1)
        assert not svc.health()["ok"]
        time.sleep(0.06)  # cooldown elapsed, backend STILL broken
        with pytest.raises(faults.PermanentFault):
            svc.score("us", m, timeout=30)  # the probe fails
        h = svc.health()  # ... which re-opened the circuit instantly
        assert not h["ok"] and h["circuit"] == "open"
        assert svc.batcher.stats()["breaker_opens"] == 2
    finally:
        faults.configure("")
        svc.close()


def test_overload_sheds_instead_of_unbounded_queue():
    """2×-overload semantics at unit scale: a burst beyond the queue
    bound sheds in O(1) (429-path), the queue never exceeds the bound,
    and every ADMITTED request completes."""
    svc = ScoringService(max_rows=1, max_wait_ms=0.0, queue_max=8,
                         retries=0, breaker_threshold=0)
    try:
        tr, _, _ = _universe()
        svc.register("us", tr)
        months = svc.serveable_months("us")
        svc.score("us", months[0])  # settle
        snap = telemetry.COUNTERS.snapshot()
        futures = [svc.submit("us", months[k % len(months)])
                   for k in range(120)]
        shed = completed = 0
        for f in futures:
            try:
                f.result(timeout=60)
                completed += 1
            except serrors.ShedError as e:
                assert e.http_status == 429
                shed += 1
        assert shed > 0, "burst never overflowed the bounded queue"
        assert completed == 120 - shed
        assert svc.batcher.stats()["shed"] == shed
        assert svc.batcher.stats()["queue_peak"] <= 8
        assert telemetry.COUNTERS.delta(snap).get("serve_shed") == shed
    finally:
        svc.close()


def test_expired_deadline_dropped_before_dispatch():
    svc = ScoringService(max_rows=2, max_wait_ms=0.0, retries=0)
    try:
        tr, _, _ = _universe()
        svc.register("us", tr)
        m = svc.serveable_months("us")[5]
        f = svc.submit("us", m, deadline_ms=0.001)  # expired by dispatch
        with pytest.raises(serrors.DeadlineError) as ei:
            f.result(timeout=30)
        assert ei.value.http_status == 504
        stats = svc.batcher.stats()
        assert stats["deadline_drops"] == 1
        # Dropped BEFORE dispatch: no batch was ever dispatched for it
        # (registration warmup bypasses the batcher, so batches==0).
        assert stats["batches"] == 0
        # A sane deadline (score's client timeout propagates as one)
        # serves normally.
        r = svc.score("us", m, timeout=30)
        assert r.scores.size > 0
        assert svc.batcher.stats()["deadline_drops"] == 1
    finally:
        svc.close()


def test_batcher_thread_death_fails_pending_and_fast_fails(recwarn):
    """Satellite pin: if the batcher loop dies OUTSIDE the per-batch
    failure path, pending futures fail LOUDLY, the service reports
    unready, and subsequent submits fail fast — no client ever hangs
    to its timeout."""
    svc = ScoringService(max_rows=1, max_wait_ms=0.0, retries=5,
                         breaker_threshold=0)
    try:
        tr, _, _ = _universe()
        svc.register("us", tr)
        m = svc.serveable_months("us")[5]
        svc.score("us", m)  # settle
        # Keep the batcher busy (injected transient faults × 5 retries
        # of backoff ≥ ~30 ms) while the death is staged behind it.
        faults.configure("serve_dispatch:n=50,kind=transient")
        f1 = svc.submit("us", m)
        deadline = time.perf_counter() + 5.0
        while svc.batcher._queue and time.perf_counter() < deadline:
            time.sleep(0.001)  # until the batcher popped f1
        boom = RuntimeError("boom in _next_batch")

        def dead_next_batch():
            raise boom

        svc.batcher._next_batch = dead_next_batch
        f2 = svc.submit("us", m)
        f3 = svc.submit("us", m)
        with pytest.raises(faults.TransientFault):
            f1.result(timeout=30)  # retries exhausted on the fault
        with pytest.raises(serrors.BatcherDeadError):
            f2.result(timeout=30)  # pending at death → failed loudly
        with pytest.raises(serrors.BatcherDeadError):
            f3.result(timeout=30)
        h = svc.health()
        assert not h["ok"] and h["circuit"] == "dead"
        assert "batcher thread dead" in h["reason"]
        with pytest.raises(serrors.BatcherDeadError):
            svc.submit("us", m).result(timeout=30)  # fail-fast submit
        assert telemetry.COUNTERS.get("serve_batcher_dead") == 1
        assert any("batcher thread died" in str(w.message)
                   for w in recwarn.list)
    finally:
        faults.configure("")
        telemetry.COUNTERS.set("serve_batcher_dead", 0)
        svc.close()


def test_http_status_mapping():
    """The serve.py failure-semantics table (one mapping, errors.py)."""
    assert serrors.http_status(serrors.ShedError(8)) == 429
    assert serrors.http_status(serrors.CircuitOpenError(0.2)) == 503
    assert serrors.http_status(
        serrors.DeadlineError("us", 199001, 0.1)) == 504
    assert serrors.http_status(
        serrors.BatcherDeadError(RuntimeError("x"))) == 503
    assert serrors.http_status(KeyError("us")) == 404
    assert serrors.http_status(RuntimeError("?")) == 500
    assert serrors.CircuitOpenError(0.2).retry_after_s == pytest.approx(0.2)
    assert serrors.ShedError(8).retry_after_s > 0


def test_zoo_lease_and_panel_h2d_sites_fire():
    """The other serving-side fault sites are really wired: an injected
    zoo_lease fault surfaces through the dispatch retry layer exactly
    like a dispatch fault (it is inside the retried region)."""
    svc = ScoringService(max_rows=2, max_wait_ms=0.0, retries=1,
                         breaker_threshold=0)
    try:
        tr, _, _ = _universe()
        svc.register("us", tr)
        m = svc.serveable_months("us")[5]
        ref = svc.score("us", m).scores.copy()
        faults.configure("zoo_lease:n=1,kind=transient")
        r = svc.score("us", m)  # one lease fault → one retry → served
        np.testing.assert_array_equal(r.scores, ref)
        assert svc.batcher.stats()["retries"] >= 1
    finally:
        svc.close()
    clear_panel_cache()
    faults.configure("panel_h2d:n=1,kind=permanent")
    with pytest.raises(faults.PermanentFault):
        _universe(panel_seed=17)  # trainer construction transfers panel
    faults.configure("")
    _universe(panel_seed=17)  # healed: the cold transfer proceeds


# ---- checkpointing: ckpt_write site + bounded waits ----------------------


def test_ckpt_write_fault_site_fires_and_heals(tmp_path):
    faults.configure("ckpt_write:at=0")
    mgr = CheckpointManager(str(tmp_path / "latest"))
    state = {"x": np.zeros(3, np.float32)}
    with pytest.raises(faults.TransientFault):
        mgr.save(1, state)
    faults.configure("")
    mgr.save(1, state, wait=True)
    assert mgr.latest_step() == 1
    mgr.close()


def test_ckpt_wait_bounded_never_hangs(tmp_path, monkeypatch):
    """Satellite pin: a wedged async Orbax writer can no longer hang
    shutdown — the wait is bounded (LFM_CKPT_WAIT_S), warns loudly,
    and close() abandons instead of blocking forever."""
    mgr = CheckpointManager(str(tmp_path / "latest"))
    release = threading.Event()
    monkeypatch.setattr(mgr._mgr, "wait_until_finished",
                        lambda: release.wait(30))
    t0 = time.perf_counter()
    with pytest.warns(RuntimeWarning, match="still\\s+unfinished"):
        ok = mgr.wait(timeout_s=0.1)
    assert ok is False
    assert time.perf_counter() - t0 < 5.0
    assert telemetry.COUNTERS.get("ckpt_wait_timeouts") >= 1
    with pytest.warns(RuntimeWarning, match="abandoned"):
        mgr.close(timeout_s=0.1)
    release.set()  # let the daemon waiter drain


# ---- preemption: SIGTERM grace + identical resume ------------------------


def _read_history(run_dir):
    """metrics.jsonl → {epoch: (val_ic, train_loss)}, last line wins
    (a resumed run appends to the same stream)."""
    out = {}
    with open(os.path.join(run_dir, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if "epoch" in rec:
                out[rec["epoch"]] = (rec["val_ic"], rec["train_loss"])
    return out


def _best_params(run_dir, trainer):
    mgr = CheckpointManager(os.path.join(run_dir, "ckpt", "best"))
    restored = restore_state_dict(mgr, trainer.init_state()._asdict())
    mgr.close()
    return restored["params"]


def test_sigterm_grace_stop_and_identical_resume_in_process(tmp_path):
    """SIGTERM at a fault-injected ckpt_write: the fit grace-stops with
    recorded epochs durable (Preempted), and a resume reproduces the
    uninterrupted fit's history and best params EXACTLY."""
    import jax

    cfg = _cfg(epochs=4, name="chaos_pre")
    panel = synthetic_panel(n_firms=60, n_months=160, n_features=5, seed=3)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    run_a = str(tmp_path / "a")
    run_b = str(tmp_path / "b")
    # Reference: uninterrupted fit.
    ref = Trainer(cfg, splits, run_dir=run_b)
    out_ref = ref.fit()
    # Interrupted fit: SIGTERM delivered at the 3rd checkpoint write
    # (mid-fit, epoch 1's end_epoch) — the grace handler settles the
    # in-flight epoch, flushes both lines, and raises.
    faults.configure("ckpt_write:at=2,kind=sigterm")
    tr = Trainer(cfg, splits, run_dir=run_a)
    with pytest.raises(preempt.Preempted):
        tr.fit()
    faults.configure("")
    preempt.clear()
    part = _read_history(run_a)
    assert 0 < len(part) < out_ref["epochs_run"], part
    # Resume: continues from the last recorded epoch.
    tr2 = Trainer(cfg, splits, run_dir=run_a)
    out2 = tr2.fit(resume=True)
    assert out2["best_epoch"] == out_ref["best_epoch"]
    hist_a, hist_b = _read_history(run_a), _read_history(run_b)
    assert hist_a == hist_b  # bit-identical epoch history, end to end
    pa, pb = _best_params(run_a, tr2), _best_params(run_b, ref)
    for la, lb in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


_CHILD = """\
import json, sys
sys.path.insert(0, sys.argv[2])
from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, \\
    RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.train.preempt import Preempted

cfg = RunConfig(
    name="chaos_child",
    data=DataConfig(n_firms=60, n_months=160, n_features=5, window=8,
                    dates_per_batch=4, firms_per_date=32),
    model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
    optim=OptimConfig(lr=1e-3, epochs=4, warmup_steps=2, loss="mse"),
    seed=0)
panel = synthetic_panel(n_firms=60, n_months=160, n_features=5, seed=3)
splits = PanelSplits.by_date(panel, 197801, 198001)
tr = Trainer(cfg, splits, run_dir=sys.argv[1])
try:
    out = tr.fit(resume="--resume" in sys.argv)
except Preempted:
    sys.exit(75)
print(json.dumps({"best_epoch": out["best_epoch"],
                  "epochs_run": out["epochs_run"]}))
"""


def test_kill_mid_epoch_subprocess_resumes_identically(tmp_path):
    """The acceptance pin, as a REAL subprocess: a fit SIGTERM'd at a
    fault-injected ckpt_write exits 75 (EX_TEMPFAIL) with its recorded
    epochs durable; rerunning with resume completes, and the combined
    history + best params equal an uninterrupted fit bit for bit."""
    script = tmp_path / "child_fit.py"
    script.write_text(_CHILD)
    run_dir = str(tmp_path / "run")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("LFM_FAULTS", None)

    def child(*extra, fault=None):
        e = dict(env)
        if fault:
            e["LFM_FAULTS"] = fault
        return subprocess.run(
            [sys.executable, str(script), run_dir, REPO, *extra],
            env=e, capture_output=True, text=True, timeout=240)

    # Kill mid-epoch: the sigterm fault kind delivers the signal at the
    # 3rd checkpoint write; the grace path exits 75.
    out1 = child(fault="ckpt_write:at=2,kind=sigterm")
    assert out1.returncode == 75, (out1.returncode, out1.stderr[-800:])
    part = _read_history(run_dir)
    assert len(part) > 0
    # Resume: exits 0 and completes the remaining epochs.
    out2 = child("--resume")
    assert out2.returncode == 0, (out2.returncode, out2.stderr[-800:])
    summary = json.loads(out2.stdout.strip().splitlines()[-1])
    # Reference: the same fit, uninterrupted, in this process (same
    # backend, deterministic samplers ⇒ bit-identical).
    cfg = RunConfig(
        name="chaos_child",
        data=DataConfig(n_firms=60, n_months=160, n_features=5, window=8,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=4, warmup_steps=2, loss="mse"),
        seed=0)
    panel = synthetic_panel(n_firms=60, n_months=160, n_features=5, seed=3)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    ref_dir = str(tmp_path / "ref")
    ref = Trainer(cfg, splits, run_dir=ref_dir)
    out_ref = ref.fit()
    assert summary["best_epoch"] == out_ref["best_epoch"]
    assert _read_history(run_dir) == _read_history(ref_dir)
    import jax

    pa = _best_params(run_dir, ref)
    pb = _best_params(ref_dir, ref)
    for la, lb in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_grace_scope_installs_and_restores_handler():
    import signal

    prev = signal.getsignal(signal.SIGTERM)
    with preempt.grace_scope():
        assert signal.getsignal(signal.SIGTERM) is preempt._handler
        with preempt.grace_scope():  # nested: ref-counted, same handler
            assert signal.getsignal(signal.SIGTERM) is preempt._handler
        assert signal.getsignal(signal.SIGTERM) is preempt._handler
        assert not preempt.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        # CPython delivers at the next bytecode boundary; poll briefly.
        deadline = time.perf_counter() + 2.0
        while not preempt.requested() and time.perf_counter() < deadline:
            time.sleep(0.001)
        assert preempt.requested()
    assert signal.getsignal(signal.SIGTERM) == prev
    preempt.clear()
