"""Cross-fold reuse layer (train/reuse.py): compile-once walk-forward.

The reuse layer's contract is measured, not asserted — every test here
reads the ``utils/profiling.py`` ReuseCounters deltas that walk-forward
surfaces per fold:

* a same-shape sweep pays jit tracing and panel H2D exactly once (folds
  after the first report ZERO for both);
* a changed program key (model config, n_seeds) is a cache MISS — fresh
  compile, never stale-executable reuse;
* the reuse path is numerically IDENTICAL to the serial pre-reuse path
  (``LFM_PROGRAM_REUSE=0``) for the same seeds.

All tests carry the ``reuse`` marker: they are the fast CI regression
guard (``pytest -m reuse``) against refactors that quietly re-instantiate
jit wrappers per fold and bring the ~15× compile tax back.
"""

import dataclasses
import os

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import (
    cached_device_panel,
    clear_panel_cache,
    invalidate_panel,
)
from lfm_quant_tpu.train import reuse
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.train.walkforward import run_walkforward
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.reuse


def _cfg(tmp, n_seeds=1, **model_kwargs):
    return RunConfig(
        name="wf",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp",
                          kwargs={"hidden": (16,), **model_kwargs}),
        optim=OptimConfig(lr=1e-3, epochs=2, warmup_steps=5, loss="mse"),
        seed=0,
        n_seeds=n_seeds,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)


@pytest.fixture(autouse=True)
def _fresh_caches():
    """Deterministic counter arithmetic: every test starts from empty
    program/panel caches (other modules' trainers otherwise donate hits)."""
    reuse.clear_program_cache()
    clear_panel_cache()
    yield
    reuse.clear_program_cache()
    clear_panel_cache()


def _run_wf(cfg, panel, tmp, n_folds=2, **kw):
    return run_walkforward(
        cfg, panel, start=198001, step_months=12, val_months=24,
        n_folds=n_folds, out_dir=str(tmp / "wf"), **kw)


def test_second_fold_zero_traces_zero_transfers(panel, tmp_path):
    """The tentpole contract: on a same-shape (rolling-window) schedule,
    fold 2 binds fold 1's executables and resident panel — zero new jit
    traces, zero panel H2D re-transfers, measured by the per-fold
    counters in the fold records."""
    _, _, summary = _run_wf(_cfg(tmp_path), panel, tmp_path,
                            train_months=72)
    r0, r1 = [r["reuse"] for r in summary["folds"]]
    # Fold 1 pays the fixed costs exactly once.
    assert r0["jit_traces"] > 0
    assert r0["panel_transfers"] == 1
    assert r0["program_cache_misses"] >= 1
    # Fold 2 pays nothing.
    assert r1["jit_traces"] == 0, r1
    assert r1["panel_transfers"] == 0, r1
    assert r1["program_cache_hits"] >= 1
    assert r1["program_cache_misses"] == 0
    assert r1["panel_cache_hits"] >= 1


def test_ensemble_second_fold_zero_traces_zero_transfers(panel, tmp_path):
    """Same contract through the seed-vmapped EnsemblePrograms bundle."""
    _, _, summary = _run_wf(_cfg(tmp_path, n_seeds=2), panel, tmp_path,
                            train_months=72)
    r0, r1 = [r["reuse"] for r in summary["folds"]]
    assert r0["jit_traces"] > 0 and r0["panel_transfers"] == 1
    assert r1["jit_traces"] == 0, r1
    assert r1["panel_transfers"] == 0, r1


def test_async_pipeline_zero_traces_on_warm_folds(panel, tmp_path,
                                                  monkeypatch):
    """Pipeline × reuse guard: the prefetch/double-buffer machinery
    (train/pipeline.py — background H2D staging, chained eval dispatch,
    device-side checkpoint snapshots) must add ZERO jit traces and ZERO
    panel H2D transfers on warm folds, with the knobs pinned ON
    explicitly so a flipped default can never silently shrink this
    lane's coverage. One blocking host fetch per epoch is part of the
    same contract (host_syncs == epochs trained in the fold)."""
    monkeypatch.setenv("LFM_ASYNC", "1")
    monkeypatch.setenv("LFM_ASYNC_CKPT", "1")
    _, _, summary = _run_wf(_cfg(tmp_path), panel, tmp_path,
                            train_months=72)
    r0, r1 = [r["reuse"] for r in summary["folds"]]
    assert r0["jit_traces"] > 0 and r0["panel_transfers"] == 1
    assert r1["jit_traces"] == 0, r1
    assert r1["panel_transfers"] == 0, r1
    # Sync-point observability rides the same per-fold delta: each
    # fold's epochs paid exactly one counted device→host fetch each.
    for rec, r in zip(summary["folds"], (r0, r1)):
        assert r["host_syncs"] == rec["epochs_run"], r


def test_changed_model_config_misses_cache(panel, tmp_path):
    """Invalidation: a changed model config is a different program key —
    fresh compile (cache miss + new traces), never a stale executable."""
    splits = PanelSplits.by_date(panel, 198001, 198201)
    t1 = Trainer(_cfg(tmp_path / "a"), splits)
    t1.fit()
    snap = REUSE_COUNTERS.snapshot()
    wide = _cfg(tmp_path / "b")
    wide = dataclasses.replace(
        wide, model=dataclasses.replace(wide.model, kwargs={"hidden": (32,)}))
    t2 = Trainer(wide, splits)
    t2.fit()
    d = REUSE_COUNTERS.delta(snap)
    assert t2.program_key != t1.program_key
    assert d["program_cache_misses"] >= 1
    assert d["jit_traces"] > 0  # really recompiled, not reused stale
    assert t2.programs is not t1.programs


def test_changed_n_seeds_misses_ensemble_cache(panel, tmp_path):
    """Invalidation: n_seeds changes the vmapped program geometry — the
    ensemble bundle must rebuild (fresh traces), while the shared panel
    stays resident (no re-transfer)."""
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    splits = PanelSplits.by_date(panel, 198001, 198201)
    e2 = EnsembleTrainer(_cfg(tmp_path / "a", n_seeds=2), splits)
    e2.fit()
    snap = REUSE_COUNTERS.snapshot()
    e4 = EnsembleTrainer(_cfg(tmp_path / "b", n_seeds=4), splits)
    e4.fit()
    d = REUSE_COUNTERS.delta(snap)
    assert e4.program_key != e2.program_key
    assert d["program_cache_misses"] >= 1
    assert d["jit_traces"] > 0
    # A changed seed-mesh geometry is a changed panel PLACEMENT — the
    # residency cache must re-transfer rather than alias the old layout
    # (on a 1-device platform both meshes collapse and the panel stays
    # resident).
    from lfm_quant_tpu.parallel.mesh import mesh_fingerprint

    expected = 1 if mesh_fingerprint(e4.mesh) != mesh_fingerprint(e2.mesh) else 0
    assert d["panel_transfers"] == expected, d


def test_rebind_same_key_keeps_programs(panel, tmp_path):
    """Trainer.rebind with unchanged trace-relevant config keeps the
    exact program bundle (identity, not just equality) while resetting
    per-fold state."""
    splits1 = PanelSplits.by_date(panel, 198001, 198201,
                                  train_start=197401)
    t = Trainer(_cfg(tmp_path), splits1)
    programs = t.programs
    t.fit()
    snap = REUSE_COUNTERS.snapshot()
    splits2 = PanelSplits.by_date(panel, 198101, 198301,
                                  train_start=197501)
    t.rebind(splits=splits2, run_dir=None)
    assert t.programs is programs
    assert t.splits is splits2
    t.fit()
    d = REUSE_COUNTERS.delta(snap)
    assert d["jit_traces"] == 0, d
    assert d["panel_transfers"] == 0, d


def test_reuse_path_matches_serial_path(panel, tmp_path, monkeypatch):
    """Numerical identity: the compile-once sweep produces bit-identical
    stitched forecasts to the pre-reuse serial path (fresh wrappers per
    fold, LFM_PROGRAM_REUSE=0) for the same seeds."""
    fc_r, v_r, _ = _run_wf(_cfg(tmp_path / "r"), panel, tmp_path / "r",
                           train_months=72)
    reuse.clear_program_cache()
    clear_panel_cache()
    monkeypatch.setenv("LFM_PROGRAM_REUSE", "0")
    fc_s, v_s, summary_s = _run_wf(_cfg(tmp_path / "s"), panel,
                                   tmp_path / "s", train_months=72)
    # The kill switch really disabled reuse: fold 2 recompiled.
    assert summary_s["folds"][1]["reuse"]["jit_traces"] > 0
    np.testing.assert_array_equal(v_r, v_s)
    np.testing.assert_array_equal(fc_r, fc_s)


def test_multi_step_donates_state(panel, tmp_path):
    """Donation guard for the fast ``-m reuse`` lane: the multi-step
    wrapper must CONSUME its input TrainState (XLA aliases the donated
    params/opt_state buffers into the outputs — the HBM double-buffer
    this PR removed), and donation must not break the zero-retrace
    contract: a second same-shape dispatch pays no new traces. An
    un-donated fallback (donation quietly dropped by a refactor) fails
    the is_deleted assertion; a donation-induced retrace fails the
    counter one."""
    import jax

    splits = PanelSplits.by_date(panel, 198001, 198201)
    t = Trainer(_cfg(tmp_path), splits)
    state = t.init_state()
    b = t.train_sampler.stacked_epoch(0)
    fi, ti, w = t._batch_args(b, train=True, steps=True)
    st, _ = t._jit_multi_step(state, t.dev, fi, ti, w)
    jax.block_until_ready(st)
    donated = [leaf.is_deleted()
               for leaf in jax.tree.leaves((state.params, state.opt_state))]
    assert all(donated), "multi-step input state was NOT donated"
    snap = REUSE_COUNTERS.snapshot()
    st2, _ = t._jit_multi_step(st, t.dev, fi, ti, w)
    jax.block_until_ready(st2)
    assert REUSE_COUNTERS.delta(snap)["jit_traces"] == 0


def test_donation_kill_switch(panel, tmp_path, monkeypatch):
    """LFM_DONATE=0 restores the double-buffered path (input state stays
    alive), and the donation flag is part of the program key — a bundle
    built with donation on is never served to a donation-off trainer."""
    import jax

    splits = PanelSplits.by_date(panel, 198001, 198201)
    t_on = Trainer(_cfg(tmp_path / "on"), splits)
    monkeypatch.setenv("LFM_DONATE", "0")
    t_off = Trainer(_cfg(tmp_path / "off"), splits)
    assert t_off.program_key != t_on.program_key
    state = t_off.init_state()
    b = t_off.train_sampler.stacked_epoch(0)
    fi, ti, w = t_off._batch_args(b, train=True, steps=True)
    st, _ = t_off._jit_multi_step(state, t_off.dev, fi, ti, w)
    jax.block_until_ready(st)
    assert not any(leaf.is_deleted()
                   for leaf in jax.tree.leaves(state.params))


def test_ensemble_multi_step_donates_state(panel, tmp_path):
    """Same donation guard through the seed-vmapped ensemble wrapper —
    the stacked state is where the double-buffer actually hurt (64 seeds
    × params + both Adam moments)."""
    import jax

    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    splits = PanelSplits.by_date(panel, 198001, 198201)
    e = EnsembleTrainer(_cfg(tmp_path, n_seeds=2), splits)
    state = e.init_state()
    fi, ti, w = e._stacked_epoch(0)
    st, _ = e._jit_multi_step(state, e.dev, fi, ti, w)
    jax.block_until_ready(st)
    assert all(leaf.is_deleted()
               for leaf in jax.tree.leaves((state.params, state.opt_state)))


def test_program_cache_lru_bound(monkeypatch):
    """The program cache is LRU-bounded (LFM_PROGRAM_CACHE_SIZE): a
    long-lived process sweeping many geometries must not pin every
    bundle it ever built; recently-used keys survive eviction."""
    monkeypatch.setattr(reuse, "_PROGRAM_CACHE_SIZE", 2)
    built = []
    for k in ("a", "b", "c"):
        reuse.get_programs(("k", k), lambda k=k: built.append(k) or k)
    assert reuse.program_cache_size() == 2
    reuse.get_programs(("k", "c"), lambda: built.append("c!") or "c!")
    assert built == ["a", "b", "c"]  # "c" still resident — no rebuild
    reuse.get_programs(("k", "a"), lambda: built.append("a2") or "a2")
    assert built == ["a", "b", "c", "a2"]  # "a" was the evicted oldest


def test_panel_residency_and_invalidation(panel):
    """cached_device_panel: one transfer per (panel, mesh, dtype,
    padding); invalidate_panel forces the next bind to re-transfer."""
    snap = REUSE_COUNTERS.snapshot()
    dev1 = cached_device_panel(panel, None)
    dev2 = cached_device_panel(panel, None)
    d = REUSE_COUNTERS.delta(snap)
    assert d["panel_transfers"] == 1
    assert d["panel_cache_hits"] == 1
    assert d["panel_bytes"] > 0
    assert dev1 is dev2  # the SAME resident arrays, zero H2D
    # A different dtype is a different residency entry (no aliasing).
    import jax.numpy as jnp

    cached_device_panel(panel, None, compute_dtype=jnp.bfloat16)
    assert REUSE_COUNTERS.delta(snap)["panel_transfers"] == 2
    # Explicit invalidation drops every placement of THIS panel.
    assert invalidate_panel(panel) == 2
    cached_device_panel(panel, None)
    assert REUSE_COUNTERS.delta(snap)["panel_transfers"] == 3


@pytest.mark.slow
def test_persistent_cache_knob_populates_dir_cold(tmp_path):
    """``RunConfig.compilation_cache_dir`` end to end, in a COLD
    subprocess: on jax 0.4.x the persistent cache only attaches if it is
    configured before the process's first XLA compile (documented in
    enable_persistent_cache), so the in-process suite can never exercise
    it — a child process trains one toy epoch and must leave XLA
    executables in the directory."""
    import subprocess
    import sys
    import textwrap

    cache_dir = tmp_path / "xla_cache"
    script = textwrap.dedent(f"""
        import dataclasses, os
        os.environ["JAX_PLATFORMS"] = "cpu"
        from tests.test_reuse import _cfg
        from lfm_quant_tpu.data import synthetic_panel
        from lfm_quant_tpu.data.panel import PanelSplits
        from lfm_quant_tpu.train.loop import Trainer
        cfg = dataclasses.replace(_cfg({str(tmp_path)!r}),
                                  compilation_cache_dir={str(cache_dir)!r})
        panel = synthetic_panel(n_firms=100, n_months=200, n_features=5,
                                seed=5)
        splits = PanelSplits.by_date(panel, 198001, 198201)
        Trainer(cfg, splits).fit()
        print("ENTRIES", len(os.listdir({str(cache_dir)!r})))
    """)
    env = dict(os.environ, PYTHONPATH=os.getcwd())
    env.pop("LFM_COMPILATION_CACHE", None)
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    n = int(out.stdout.split("ENTRIES")[-1])
    assert n > 0, "cold process wrote no persistent cache entries"
    # The env fallback resolves the same directory (pure knob logic —
    # safe to check in-process; attaching is the subprocess's job).
    os.environ["LFM_COMPILATION_CACHE"] = str(cache_dir)
    try:
        reuse._PERSISTENT_CACHE_DIR = None
        assert reuse.enable_persistent_cache(None) == str(cache_dir)
    finally:
        os.environ.pop("LFM_COMPILATION_CACHE", None)


def test_program_cache_readmission_builds_exactly_once(monkeypatch):
    """After LRU eviction, the NEXT fetch of the evicted key rebuilds
    exactly once and re-enters the LRU; a fetch whose builder returns an
    ALREADY-BUILT bundle (the serving zoo's re-seed path) re-admits it
    without constructing anything new."""
    monkeypatch.setattr(reuse, "_PROGRAM_CACHE_SIZE", 2)
    builds = []

    def builder(tag):
        return lambda: builds.append(tag) or f"bundle-{tag}"

    a = reuse.get_programs(("k", "a"), builder("a"))
    reuse.get_programs(("k", "b"), builder("b"))
    reuse.get_programs(("k", "c"), builder("c"))  # evicts "a"
    assert reuse.program_cache_keys() == (("k", "b"), ("k", "c"))
    # Re-admission of the evicted key: exactly one rebuild...
    a2 = reuse.get_programs(("k", "a"), builder("a2"))
    assert builds == ["a", "b", "c", "a2"]
    assert a2 == "bundle-a2" and a2 != a
    # ...and a holder of the OLD bundle can re-seed it instead (builder
    # returns the existing object — admitted, nothing rebuilt).
    reuse.get_programs(("k", "held"), lambda: a)
    assert builds == ["a", "b", "c", "a2"]
    assert reuse.get_programs(("k", "held"), builder("never")) is a
    assert builds == ["a", "b", "c", "a2"]


def test_serve_keys_distinct_from_every_other_program_family():
    """Serve program keys live in the same cache as trainer/ensemble/
    foldstack bundles; the leading family tag plus tagged bucket tuples
    make cross-family collisions impossible by construction."""
    inner = ("trainer", "cpu", "geom")
    sk = reuse.serve_program_key(inner, (4, 64))
    assert sk == ("serve", inner, ("bucket", 4, 64))
    assert sk != ("ensemble", inner, "cpu", 4, 64)
    assert sk != ("foldstack", inner, "cpu", 4, 64)
    # rows/width are positionally tagged — transposed buckets differ.
    assert sk != reuse.serve_program_key(inner, (64, 4))
