"""Derived feature engineering (data/features.py): hand-computed math,
no-lookahead guarantee, standardization, and trainer integration."""

import dataclasses

import numpy as np
import pytest

from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.features import (
    _raw_column,
    add_derived_features,
    standardize_column,
)

pytestmark = pytest.mark.fast  # whole module is smoke-lane cheap


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=60, n_months=120, n_features=5, seed=9)


def _manual_mom(panel, i, t, L, S):
    """Sum of log1p over returns earned in months (t-L, t-S]."""
    rv = panel.ret_valid if panel.ret_valid is not None else panel.valid
    total = 0.0
    for u in range(t - L + 1, t - S + 1):
        if u - 1 < 0 or not rv[i, u - 1]:
            return np.nan
        total += np.log1p(panel.returns[i, u - 1])
    return total


def test_momentum_matches_manual(panel):
    raw = _raw_column(panel, "mom_12_1")
    rng = np.random.default_rng(0)
    for _ in range(50):
        i = int(rng.integers(0, panel.n_firms))
        t = int(rng.integers(12, panel.n_months))
        want = _manual_mom(panel, i, t, 12, 1)
        got = raw[i, t]
        if np.isnan(want):
            assert np.isnan(got), (i, t)
        else:
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_vol_and_rev_match_manual(panel):
    vol = _raw_column(panel, "vol_6")
    rev = _raw_column(panel, "rev_2")
    rv = panel.ret_valid if panel.ret_valid is not None else panel.valid
    i, t = 3, 40
    win = [np.log1p(panel.returns[i, u - 1]) for u in range(t - 5, t + 1)]
    if all(rv[i, u - 1] for u in range(t - 5, t + 1)):
        np.testing.assert_allclose(vol[i, t], np.std(win), rtol=1e-8)
    win2 = [np.log1p(panel.returns[i, u - 1]) for u in range(t - 1, t + 1)]
    if all(rv[i, u - 1] for u in range(t - 1, t + 1)):
        np.testing.assert_allclose(rev[i, t], -sum(win2), rtol=1e-8)


def test_chg_matches_manual(panel):
    name = panel.feature_names[0]
    raw = _raw_column(panel, f"chg_{name}_3")
    i, t = 7, 50
    if panel.valid[i, t] and panel.valid[i, t - 3]:
        want = panel.features[i, t, 0] - panel.features[i, t - 3, 0]
        np.testing.assert_allclose(raw[i, t], want, rtol=1e-6)


def test_no_lookahead(panel):
    """Derived values at anchors <= t must not move when the future
    (returns earned after month t) changes."""
    t_cut = 60
    raw_before = {s: _raw_column(panel, s)
                  for s in ("mom_12_1", "vol_6", "rev_1")}
    mutated = dataclasses.replace(
        panel, returns=panel.returns.copy())
    # returns[:, u] is the forward return earned over (u, u+1] — indexes
    # info revealed AFTER month u. Mutating u >= t_cut must leave anchors
    # <= t_cut untouched.
    mutated.returns[:, t_cut:] = 9.9
    for s, before in raw_before.items():
        after = _raw_column(mutated, s)
        np.testing.assert_array_equal(before[:, :t_cut + 1],
                                      after[:, :t_cut + 1])


def test_standardize_column(panel):
    raw = _raw_column(panel, "mom_12_1")
    col = standardize_column(raw, panel.valid, min_cross_section=8)
    avail = np.isfinite(raw) & panel.valid
    for j in (30, 60, 100):
        sel = avail[:, j]
        if sel.sum() >= 8:
            assert abs(col[sel, j].mean()) < 1e-5
            assert 0.5 < col[sel, j].std() < 1.5  # winsorized → not exactly 1
    assert (col[~avail] == 0).all()


def test_add_derived_features(panel):
    specs = ["mom_12_1", "vol_6", f"chg_{panel.feature_names[0]}_3"]
    out = add_derived_features(panel, specs)
    assert out.n_features == panel.n_features + 3
    assert list(out.feature_names)[-3:] == specs
    np.testing.assert_array_equal(out.features[..., :panel.n_features],
                                  panel.features)
    # Original untouched; other arrays shared semantics intact.
    assert panel.n_features == 5
    np.testing.assert_array_equal(out.valid, panel.valid)


def test_bad_specs_raise(panel):
    with pytest.raises(ValueError, match="unknown feature spec"):
        _raw_column(panel, "bogus_3")
    with pytest.raises(ValueError, match="lookback > skip"):
        _raw_column(panel, "mom_1_1")
    with pytest.raises(ValueError, match="no feature column"):
        _raw_column(panel, "chg_nope_3")


def test_trainer_integration(tmp_path):
    from lfm_quant_tpu.config import (
        DataConfig,
        ModelConfig,
        OptimConfig,
        RunConfig,
    )
    from lfm_quant_tpu.data import PanelSplits
    from lfm_quant_tpu.train import Trainer
    from lfm_quant_tpu.train.loop import resolve_panel

    cfg = RunConfig(
        name="feat",
        data=DataConfig(n_firms=80, n_months=150, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=24,
                        derived_features=("mom_12_1", "rev_1")),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=1, warmup_steps=2, loss="mse"),
        out_dir=str(tmp_path),
    )
    panel = resolve_panel(cfg.data)
    assert panel.n_features == 7  # 5 base + 2 derived
    splits = PanelSplits.by_date(panel, 197910, 198101)
    trainer = Trainer(cfg, splits)
    summary = trainer.fit()
    assert np.isfinite(summary["best_val_ic"])
