"""Always-on scoring service (lfm_quant_tpu/serve/): the serve lane.

The serving contract, measured not asserted:

* served scores are BIT-IDENTICAL to the batch scoring path
  (``run_scoring_pipeline``'s aggregation stage) for the same
  panel/month — the service is a routing/batching layer over the same
  compiled forward, never a numerical fork;
* a mixed-shape request stream (distinct universe sizes AND lookbacks)
  reaches steady state with ZERO new jit traces and ZERO panel H2D
  after warmup — the request-shape buckets (serve/buckets.py) folded
  into the program-cache key make arbitrary queries compile-free;
* an incremental refresh (warm retrain + atomic zoo swap) serves the
  new generation with no recompile and no dropped/torn request under
  concurrent traffic;
* p50/p99 latency and batch occupancy agree between
  ``ScoringService.stats()``, ``scripts/trace_report.py`` and the bench
  formulas (same per-request ``latency_ms`` values end to end).

All tests carry the ``serve`` marker (fast lane: ``pytest -m serve``).
"""

import json
import os
import threading

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import clear_panel_cache
from lfm_quant_tpu.serve import ScoringService
from lfm_quant_tpu.serve.buckets import bucket_width
from lfm_quant_tpu.train import reuse
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.serve


def _cfg(n_firms=80, window=8, seed=0, epochs=1, name="serve_t"):
    return RunConfig(
        name=name,
        data=DataConfig(n_firms=n_firms, n_months=160, n_features=5,
                        window=window, dates_per_batch=4,
                        firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=2,
                          loss="mse"),
        seed=seed,
    )


def _universe(n_firms=80, window=8, seed=0, panel_seed=3, fit=False,
              train_months=None):
    """(trainer, panel, splits) for one toy universe; init-state params
    unless ``fit`` (serving prices routing, not training quality)."""
    panel = synthetic_panel(n_firms=n_firms, n_months=160, n_features=5,
                            seed=panel_seed)
    train_start = None
    if train_months is not None:
        y, m = divmod(197801, 100)
        mm = (y * 12 + (m - 1)) - train_months
        train_start = (mm // 12) * 100 + (mm % 12) + 1
    splits = PanelSplits.by_date(panel, 197801, 198001,
                                 train_start=train_start)
    tr = Trainer(_cfg(n_firms=n_firms, window=window, seed=seed), splits)
    if fit:
        tr.fit()
    else:
        tr.state = tr.init_state()
    return tr, panel, splits


@pytest.fixture(autouse=True)
def _fresh_caches():
    """Deterministic counter arithmetic, same as the reuse lane."""
    reuse.clear_program_cache()
    clear_panel_cache()
    yield
    reuse.clear_program_cache()
    clear_panel_cache()


@pytest.fixture()
def service():
    # max_rows=4 keeps the warmup ladder (rows × widths) small — the
    # lane prices correctness, not warmup breadth.
    svc = ScoringService(max_rows=4, max_wait_ms=1.0)
    yield svc
    svc.close()


# ---- keys ----------------------------------------------------------------
# (Device-free bucket/key/percentile unit tests live in
# tests/test_buckets.py — this module is the integration half.)


def test_zoo_routing_key_no_collisions():
    """(universe, generation) zoo keys cannot collide across adversarial
    name/generation splits ("u1", 2) vs ("u", 12)."""
    tr, _, _ = _universe()
    from lfm_quant_tpu.serve.zoo import ZooEntry

    e_a = ZooEntry("u1", 2, tr)
    e_b = ZooEntry("u", 12, tr)
    assert e_a.key != e_b.key
    assert e_a.key == ("zoo", ("universe", "u1"), ("generation", 2))


# ---- parity: served == batch scoring path --------------------------------


def test_served_scores_bit_identical_to_batch_path(service):
    """The acceptance pin: for every test-range month, the served
    cross-section scores equal the batch path's
    (predict → aggregate_scores_device, the scoring stage of
    ``run_scoring_pipeline``) BIT FOR BIT — and the backtest report
    built from serve-backed scores equals the batch report exactly."""
    from lfm_quant_tpu.backtest.jax_engine import (aggregate_scores_device,
                                                   run_scoring_pipeline)

    tr, panel, splits = _universe(fit=True)
    service.register("us", tr)
    fc, valid = tr.predict("test")
    scores = np.asarray(aggregate_scores_device(fc[None], valid,
                                                ["mean"])[0])[0]
    lo, hi = splits.test_range
    serve_fc = np.zeros_like(fc)
    checked = 0
    for t in range(lo, hi):
        month = int(panel.dates[t])
        try:
            r = service.score("us", month)
        except KeyError:
            continue  # month has no serveable cross-section
        assert r.generation == 0 and r.month == month
        assert r.firm_idx.size == r.scores.size > 0
        mask = valid[r.firm_idx, t]
        np.testing.assert_array_equal(r.scores[mask],
                                      scores[r.firm_idx[mask], t])
        serve_fc[r.firm_idx[mask], t] = r.scores[mask]
        checked += int(mask.sum())
    assert checked > 100  # the comparison really covered the range
    # End to end: serve-backed forecasts through the fused backtest
    # reproduce the batch report exactly (same masked values in, same
    # compiled core).
    rep_batch = run_scoring_pipeline(fc, valid, panel)["mean"]
    rep_serve = run_scoring_pipeline(np.where(valid, serve_fc, 0.0),
                                     valid, panel)["mean"]
    assert rep_batch.n_months == rep_serve.n_months
    np.testing.assert_array_equal(rep_batch.monthly_ic,
                                  rep_serve.monthly_ic)


# ---- steady state: zero compiles, zero H2D -------------------------------


def test_mixed_shape_stream_zero_traces_zero_h2d(service):
    """Three universes with distinct cross-section sizes AND lookbacks,
    warmed at registration; a concurrent mixed request stream must then
    pay ZERO new jit traces and ZERO panel H2D — the bucket ladder +
    residency caches make steady state compile-free and transfer-free."""
    geos = [(60, 6, 11), (110, 9, 12), (160, 12, 13)]
    for k, (n_firms, window, pseed) in enumerate(geos):
        tr, _, _ = _universe(n_firms=n_firms, window=window, seed=k,
                             panel_seed=pseed)
        service.register(f"u{k}", tr)
    months = {u: service.serveable_months(u)
              for u in service.zoo.universes()}
    # One sequential pass first: the batcher's coalescing pattern is
    # load-dependent, but every (rows, width) bucket it can produce was
    # warmed, so no pattern may trace.
    snap = REUSE_COUNTERS.snapshot()
    for u in months:
        service.score(u, months[u][5])
    errors = []

    def client(cid):
        rng = np.random.default_rng(cid)
        for _ in range(25):
            u = f"u{int(rng.integers(3))}"
            m = months[u][int(rng.integers(len(months[u])))]
            try:
                r = service.score(u, m)
                if r.scores.size == 0:
                    errors.append(f"{u}/{m}: empty")
            except Exception as e:  # noqa: BLE001 — tallied for assert
                errors.append(f"{u}/{m}: {e}")

    threads = [threading.Thread(target=client, args=(c,)) for c in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    d = REUSE_COUNTERS.delta(snap)
    assert d.get("jit_traces", 0) == 0, d
    assert d.get("panel_transfers", 0) == 0, d
    assert service.stats()["completed"] >= 103


# ---- incremental refresh -------------------------------------------------


def test_refresh_swap_no_recompile_no_dropped_request(service):
    """Monthly data arrival: a warm single-fold retrain + atomic zoo
    swap under CONCURRENT traffic — zero new jit traces end to end
    (same-shape rolling fold = program-cache hit; adopted bucket
    programs), every request answered (none dropped), every response
    entirely from one generation (none torn), and the new generation
    serves afterwards."""
    tr, panel, _ = _universe(fit=True, train_months=72)
    service.register("us", tr)
    months = service.serveable_months("us")
    for m in months[:4]:
        service.score("us", m)  # settle the serving path
    stop = threading.Event()
    seen = []
    errors = []

    def hammer():
        rng = np.random.default_rng(0)
        while not stop.is_set():
            m = months[int(rng.integers(len(months)))]
            try:
                r = service.score("us", m)
                seen.append(r.generation)
            except Exception as e:  # noqa: BLE001 — tallied for assert
                errors.append(str(e))

    t = threading.Thread(target=hammer)
    t.start()
    snap = REUSE_COUNTERS.snapshot()
    # The advanced rolling fold: same train_months window, boundaries
    # stepped one year — identical shapes, so everything is warm.
    splits2 = PanelSplits.by_date(panel, 197901, 198101,
                                  train_start=197301)
    entry = service.refresh("us", splits2)
    stop.set()
    t.join()
    d = REUSE_COUNTERS.delta(snap)
    assert entry.generation == 1
    assert service.zoo.generation("us") == 1
    assert d.get("jit_traces", 0) == 0, d
    assert not errors, errors[:3]
    assert seen, "hammer thread never completed a request"
    # No torn request: generations observed are only {0, 1}, and once
    # the swap lands the stream moves to 1 (monotone non-decreasing).
    assert set(seen) <= {0, 1}
    assert sorted(seen) == seen
    r = service.score("us", months[10])
    assert r.generation == 1
    # The refreshed params actually changed the served model (it
    # trained on a year of newer data).
    import jax

    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(entry.params),
                        jax.tree.leaves(tr.state.params)))
    assert changed, "refresh published byte-identical params"


def test_refresh_copy_protects_served_params_from_donation(service):
    """The refresh warm start feeds the donating fit a COPY of the
    served params: after a refresh, the OLD generation's params must
    still be alive (an in-flight dispatch may still read them) — a
    refactor that hands the live buffers to the donated TrainState
    fails here with deleted arrays."""
    import jax

    tr, panel, _ = _universe(fit=True, train_months=72)
    service.register("us", tr)
    old_params = service.zoo.current("us").params
    splits2 = PanelSplits.by_date(panel, 197901, 198101,
                                  train_start=197301)
    service.refresh("us", splits2)
    assert not any(leaf.is_deleted()
                   for leaf in jax.tree.leaves(old_params))


# ---- zoo LRU / refcount --------------------------------------------------


def test_zoo_lru_eviction_is_refcount_safe():
    """Over-capacity registration evicts the least-recently-leased
    universe; an entry evicted WHILE LEASED stays fully servable until
    the lease drains, then decommissions exactly once."""
    svc = ScoringService(zoo_capacity=2, max_rows=2, max_wait_ms=0.0)
    try:
        trainers = [
            _universe(n_firms=60 + 10 * k, seed=k, panel_seed=20 + k)[0]
            for k in range(3)]
        svc.register("a", trainers[0])
        svc.register("b", trainers[1])
        months_a = svc.serveable_months("a")
        with svc.zoo.lease("b") as doomed_entry:
            # Leasing bumps recency, so refresh 'a' AFTER taking the
            # lease: 'b' (still leased) becomes the LRU victim.
            svc.score("a", months_a[5])
            svc.register("c", trainers[2])  # evicts 'b' while leased
            assert set(svc.zoo.universes()) == {"a", "c"}
            # The leased entry still serves: its programs/panel are
            # pinned (decommission deferred to release).
            t = int(doomed_entry._sampler.months_with_anchors()[0])
            pool = doomed_entry.pool(t)
            assert pool.size > 0
            with doomed_entry.lease_panel() as dev:
                out = np.asarray(doomed_entry.programs_for(
                    (1, bucket_width(pool.size)))(
                        doomed_entry.params, dev,
                        np.zeros((1, bucket_width(pool.size)), np.int32),
                        np.asarray([t], np.int32),
                        np.zeros((1, bucket_width(pool.size)),
                                 np.float32)))
            assert out.shape == (1, bucket_width(pool.size))
            assert doomed_entry.doomed
        assert telemetry.COUNTERS.get("serve_zoo_evictions") >= 1
        with pytest.raises(KeyError):
            svc.score("b", months_a[5], timeout=5)
    finally:
        svc.close()


# ---- latency observability: stats == trace_report == bench formulas ------


def _load_trace_report():
    from lfm_quant_tpu.serve.stats import load_trace_report

    return load_trace_report(os.path.join(os.path.dirname(__file__), ".."))


def test_stats_agree_with_trace_report_within_1pct(tmp_path, monkeypatch):
    """The acceptance pin: p50/p99 and occupancy from a served run's
    stats() equal scripts/trace_report.py's rollup of the same run dir
    within 1% (they consume the same per-request latency_ms values, so
    the agreement is exact up to float repr), and queue-depth counters
    surface in the serve section."""
    monkeypatch.setenv("LFM_TELEMETRY", "1")
    assert telemetry._ACTIVE is None
    run_dir = str(tmp_path / "serve_run")
    with telemetry.run_scope(run_dir, extra={"entry": "test_serve"}):
        svc = ScoringService(max_rows=4, max_wait_ms=1.0)
        try:
            tr_a, _, _ = _universe(seed=0, panel_seed=31)
            tr_b, _, _ = _universe(n_firms=120, window=10, seed=1,
                                   panel_seed=32)
            svc.register("a", tr_a)
            svc.register("b", tr_b)
            months = {u: svc.serveable_months(u) for u in ("a", "b")}

            def client(cid):
                rng = np.random.default_rng(cid)
                for _ in range(20):
                    u = ("a", "b")[int(rng.integers(2))]
                    svc.score(u, months[u][int(rng.integers(
                        len(months[u])))])

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            stats = svc.stats()
        finally:
            svc.close()
    tr_mod = _load_trace_report()
    rep = tr_mod.build_report(tr_mod.load_run(run_dir))
    sv = rep.get("serve")
    assert sv is not None, "trace_report produced no serve section"
    assert sv["requests"] == sv["completed"] == stats["completed"] == 60
    assert sv["batches"] == stats["batches"]
    for key in ("p50_ms", "p99_ms"):
        assert sv[key] == pytest.approx(stats[key], rel=0.01), (
            key, sv[key], stats[key])
    assert sv["mean_occupancy"] == pytest.approx(
        stats["mean_occupancy"], rel=0.01)
    assert sv["queue_depth_max"] is not None
    assert stats["queue_peak"] >= 1
    # And the spans really are per-request with valid JSON lines.
    with open(os.path.join(run_dir, "spans.jsonl")) as fh:
        names = [json.loads(line)["name"] for line in fh]
    assert names.count("serve_request") == 60
    assert names.count("serve_batch") == sv["batches"]


# ---- misc routing --------------------------------------------------------


def test_unknown_universe_and_month_fail_fast(service):
    tr, panel, _ = _universe()
    service.register("us", tr)
    with pytest.raises(KeyError):
        service.score("nope", 199001, timeout=5)
    with pytest.raises(KeyError):
        service.score("us", 999912, timeout=5)  # not a panel month
    # Live months (no realized target) ARE serveable — the production
    # query: the last horizon months of the panel.
    months = service.serveable_months("us")
    live = int(panel.dates[-2])
    assert live in months
    r = service.score("us", live)
    assert r.scores.size > 0
