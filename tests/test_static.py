"""Minimal static-analysis pass (no mypy/pyright in this environment).

``from __future__ import annotations`` keeps a module importable even when
an annotation references an un-imported name (the string is never
evaluated) — until someone calls ``typing.get_type_hints`` and gets a
``NameError``. This walks every module in the package and force-resolves
every class's annotations, so missing-typing-import bugs fail CI instead
of lurking (a real one shipped in data/panel.py in round 1).
"""

import importlib
import inspect
import pkgutil
import typing

import lfm_quant_tpu

import pytest

pytestmark = pytest.mark.fast  # whole module is smoke-lane cheap


def _walk_modules():
    yield lfm_quant_tpu
    for info in pkgutil.walk_packages(lfm_quant_tpu.__path__,
                                      prefix="lfm_quant_tpu."):
        if info.name.rsplit(".", 1)[-1] == "_panel_native":
            continue  # ctypes .so, not a Python extension module
        yield importlib.import_module(info.name)


def test_all_annotations_resolve():
    failures = []
    for mod in _walk_modules():
        for name, obj in vars(mod).items():
            if not inspect.isclass(obj) or obj.__module__ != mod.__name__:
                continue
            try:
                typing.get_type_hints(obj)
            except Exception as e:  # noqa: BLE001 - report all resolution bugs
                failures.append(f"{mod.__name__}.{name}: {type(e).__name__}: {e}")
        # Module-level annotations too (rare but same failure class).
        try:
            typing.get_type_hints(mod)
        except Exception as e:  # noqa: BLE001
            failures.append(f"{mod.__name__} (module): {e}")
    assert not failures, "unresolvable annotations:\n" + "\n".join(failures)


def test_public_functions_annotations_resolve():
    failures = []
    for mod in _walk_modules():
        for name, obj in vars(mod).items():
            if not inspect.isfunction(obj) or obj.__module__ != mod.__name__:
                continue
            try:
                typing.get_type_hints(obj)
            except Exception as e:  # noqa: BLE001
                failures.append(f"{mod.__name__}.{name}: {type(e).__name__}: {e}")
    assert not failures, "unresolvable annotations:\n" + "\n".join(failures)
