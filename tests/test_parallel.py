"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §5:
distributed code paths run in CI via xla_force_host_platform_device_count).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import PanelSplits, synthetic_panel
from lfm_quant_tpu.parallel import (
    make_mesh,
    seed_sharding,
    shard_batch,
)
from lfm_quant_tpu.train import Trainer


def test_devices_available():
    assert jax.device_count() == 8, "conftest must provide 8 CPU devices"


def test_make_mesh_shapes():
    m = make_mesh(4, 2)
    assert m.shape == {"seed": 4, "data": 2}
    m2 = make_mesh(2)  # data defaults to 8//2
    assert m2.shape == {"seed": 2, "data": 4}
    with pytest.raises(ValueError, match="needs"):
        make_mesh(8, 2)
    with pytest.raises(ValueError, match="divisible"):
        make_mesh(3)


def test_shard_batch_placement():
    mesh = make_mesh(1, 8)
    fi = jnp.zeros((8, 16), jnp.int32)
    ti = jnp.zeros((8,), jnp.int32)
    w = jnp.ones((8, 16), jnp.float32)
    fi_s, ti_s, w_s = shard_batch(mesh, (fi, ti, w))
    assert len(fi_s.sharding.device_set) == 8
    # Date axis sharded: each device holds one date row.
    assert fi_s.addressable_shards[0].data.shape == (1, 16)
    assert ti_s.addressable_shards[0].data.shape == (1,)


def test_seed_axis_sharding():
    mesh = make_mesh(8, 1)
    x = jnp.zeros((8, 3, 5))
    xs = jax.device_put(x, seed_sharding(mesh))
    assert xs.addressable_shards[0].data.shape == (1, 3, 5)


def _fit_cfg(panel, n_shards, tmp, seed=0):
    return RunConfig(
        name=f"dp{n_shards}",
        data=DataConfig(n_firms=150, n_months=150, n_features=5, window=12,
                        dates_per_batch=8, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=2, warmup_steps=5,
                          early_stop_patience=5, loss="mse"),
        seed=seed,
        n_data_shards=n_shards,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=150, n_months=150, n_features=5, seed=13)


def test_dp_training_matches_single_device(panel, tmp_path):
    """Date-sharded DP must be numerically equivalent to single-device
    training — same batches, same model, same loss math (the SURVEY.md §8
    step-8 correctness requirement)."""
    splits = PanelSplits.by_date(panel, 197910, 198101)

    t1 = Trainer(_fit_cfg(panel, 1, tmp_path / "a"), splits)
    t8 = Trainer(_fit_cfg(panel, 8, tmp_path / "b"), splits)
    assert t8.mesh is not None and t8.mesh.shape["data"] == 8

    s1, s8 = t1.init_state(), t8.init_state()
    for b in t1.train_sampler.epoch(0):
        a1 = t1._batch_args(b, train=True)
        a8 = t8._batch_args(b, train=True)
        s1, m1 = t1._jit_step(s1, t1.dev, *a1)
        s8, m8 = t8._jit_step(s8, t8.dev, *a8)
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), rel=1e-4)
    for l1, l8 in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l8),
                                   rtol=1e-4, atol=1e-5)


def test_dp_training_grouped_lstm_matches_single_device(panel, tmp_path):
    """The G-LSTM's einsum-based block-diagonal projections must be
    GSPMD-clean: date-sharded training numerically equals single-device
    (replicated params, batch-sharded einsum operand)."""
    import dataclasses

    splits = PanelSplits.by_date(panel, 197910, 198101)

    def cfg(n_shards, sub):
        c = _fit_cfg(panel, n_shards, tmp_path / sub)
        return dataclasses.replace(
            c, model=ModelConfig(kind="lstm",
                                 kwargs={"hidden": 16, "n_groups": 4}))

    t1 = Trainer(cfg(1, "a"), splits)
    t8 = Trainer(cfg(8, "b"), splits)
    assert t8.mesh is not None and t8.mesh.shape["data"] == 8
    assert t8.model.n_groups == 4

    s1, s8 = t1.init_state(), t8.init_state()
    for b in t1.train_sampler.epoch(0):
        s1, m1 = t1._jit_step(s1, t1.dev, *t1._batch_args(b, train=True))
        s8, m8 = t8._jit_step(s8, t8.dev, *t8._batch_args(b, train=True))
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), rel=1e-4)
    for l1, l8 in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l8),
                                   rtol=1e-4, atol=1e-5)


def test_dp_rank_ic_loss_shard_local(panel, tmp_path):
    """rank_ic ranks within months; sharding dates across devices must not
    change the loss value."""
    splits = PanelSplits.by_date(panel, 197910, 198101)
    cfg1 = _fit_cfg(panel, 1, tmp_path / "a")
    cfg8 = _fit_cfg(panel, 8, tmp_path / "b")
    import dataclasses
    cfg1 = dataclasses.replace(cfg1, optim=dataclasses.replace(cfg1.optim, loss="rank_ic"))
    cfg8 = dataclasses.replace(cfg8, optim=dataclasses.replace(cfg8.optim, loss="rank_ic"))
    t1, t8 = Trainer(cfg1, splits), Trainer(cfg8, splits)
    s1, s8 = t1.init_state(), t8.init_state()
    b = next(iter(t1.train_sampler.epoch(0)))
    _, m1 = t1._jit_step(s1, t1.dev, *t1._batch_args(b, train=True))
    _, m8 = t8._jit_step(s8, t8.dev, *t8._batch_args(b, train=True))
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), rel=1e-4)


def test_indivisible_batch_raises(panel, tmp_path):
    splits = PanelSplits.by_date(panel, 197910, 198101)
    cfg = _fit_cfg(panel, 8, tmp_path)
    import dataclasses
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(cfg.data, dates_per_batch=6))
    with pytest.raises(ValueError, match="divisible"):
        Trainer(cfg, splits)


def _pallas_cfg(n_shards, tmp, impls=("pallas", "pallas"), seed=0):
    """LSTM config with explicit scan/gather impls ("pallas" runs the real
    kernels in interpret mode on the CPU test platform)."""
    scan_impl, gather_impl = impls
    return RunConfig(
        name=f"pl{n_shards}",
        data=DataConfig(n_firms=120, n_months=160, n_features=5, window=12,
                        dates_per_batch=8, firms_per_date=32,
                        gather_impl=gather_impl),
        model=ModelConfig(kind="lstm", kwargs={"hidden": 16},
                          scan_impl=scan_impl),
        optim=OptimConfig(lr=1e-3, epochs=2, warmup_steps=5,
                          early_stop_patience=5, loss="mse"),
        seed=seed,
        n_data_shards=n_shards,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def lstm_panel():
    return synthetic_panel(n_firms=120, n_months=160, n_features=5, seed=29)


def test_shard_map_pallas_matches_single_device_xla(lstm_panel, tmp_path):
    """THE mesh-survival property (round-1 verdict item 1): the fused
    Pallas RNN + DMA gather running per-shard inside shard_map over an
    8-way date mesh must reproduce single-device XLA training numerics."""
    splits = PanelSplits.by_date(lstm_panel, 198001, 198201)

    t_xla = Trainer(_pallas_cfg(1, tmp_path / "a", ("xla", "xla")), splits)
    t_pal = Trainer(_pallas_cfg(8, tmp_path / "b", ("pallas", "pallas")),
                    splits)
    assert t_pal.mesh is not None and t_pal.mesh.shape["data"] == 8
    assert t_pal._gather_impl == "pallas"
    assert t_pal.model.scan_impl == "pallas"
    # Eval stays GSPMD-safe under the mesh.
    assert t_pal._eval_gather_impl == "xla"
    assert t_pal.eval_model.scan_impl == "xla"

    s_x, s_p = t_xla.init_state(), t_pal.init_state()
    # Identical param trees between scan impls (checkpoint interchange).
    for a, b in zip(jax.tree.leaves(s_x.params), jax.tree.leaves(s_p.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)

    for b in t_xla.train_sampler.epoch(0):
        s_x, m_x = t_xla._jit_step(s_x, t_xla.dev, *t_xla._batch_args(b, train=True))
        s_p, m_p = t_pal._jit_step(s_p, t_pal.dev, *t_pal._batch_args(b, train=True))
    assert float(m_x["loss"]) == pytest.approx(float(m_p["loss"]), rel=1e-3)
    # atol covers two epochs of accumulated interpret-mode-vs-XLA float
    # drift; jax 0.4.x's shard_map (check_rep) reorders reductions
    # slightly differently than newer releases, so the bound is 5e-5
    # rather than 1e-5 on params of scale ~1e-2.
    for a, b in zip(jax.tree.leaves(s_x.params), jax.tree.leaves(s_p.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=5e-5)
    # The eval forward (GSPMD path, XLA twin model reading the lane-padded
    # panel through fp) agrees across the two trainers.
    v_x = t_xla.evaluate(s_x.params)
    v_p = t_pal.evaluate(s_p.params)
    assert v_x["ic"] == pytest.approx(v_p["ic"], abs=1e-3)


def test_shard_map_multi_step_pallas(lstm_panel, tmp_path):
    """The whole-epoch in-jit scan composes with shard_map + Pallas."""
    splits = PanelSplits.by_date(lstm_panel, 198001, 198201)
    t_xla = Trainer(_pallas_cfg(1, tmp_path / "a", ("xla", "xla")), splits)
    t_pal = Trainer(_pallas_cfg(4, tmp_path / "b", ("pallas", "pallas")),
                    splits)
    s_x, s_p = t_xla.init_state(), t_pal.init_state()
    b = t_xla.train_sampler.stacked_epoch(0)
    s_x, m_x = t_xla._jit_multi_step(
        s_x, t_xla.dev, *t_xla._batch_args(b, train=True, steps=True))
    s_p, m_p = t_pal._jit_multi_step(
        s_p, t_pal.dev, *t_pal._batch_args(b, train=True, steps=True))
    np.testing.assert_allclose(np.asarray(m_x["loss"]),
                               np.asarray(m_p["loss"]), rtol=1e-3, atol=1e-5)
    # Same accumulated-drift bound as test_shard_map_pallas_matches_....
    for a, c in zip(jax.tree.leaves(s_x.params), jax.tree.leaves(s_p.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-3, atol=5e-5)


def test_sharded_eval_pallas_gather_promotion(lstm_panel, tmp_path,
                                              monkeypatch):
    """LFM_EVAL_SHARDED_GATHER=pallas routes ONLY the month-sharded eval
    dispatches (inside shard_map, where the DMA gather is legal) through
    the Pallas gather; the promoted sweep must reproduce the default
    XLA-gather sharded eval, and the GSPMD eval paths must stay on XLA.
    The flag exists so the on-chip campaign can measure the promotion
    (round-3 verdict: an unmeasured optimization) without code edits."""
    import dataclasses

    splits = PanelSplits.by_date(lstm_panel, 198001, 198201)

    def het(sub):  # heteroscedastic twin: the sharded VARIANCE dispatch
        c = _pallas_cfg(4, tmp_path / sub, ("pallas", "pallas"))
        return dataclasses.replace(
            c, model=dataclasses.replace(c.model, heteroscedastic=True))

    monkeypatch.delenv("LFM_EVAL_SHARDED_GATHER", raising=False)  # hermetic
    t_def = Trainer(_pallas_cfg(4, tmp_path / "a", ("pallas", "pallas")),
                    splits)
    t_hdef = Trainer(het("ha"), splits)
    monkeypatch.setenv("LFM_EVAL_SHARDED_GATHER", "pallas")
    t_pro = Trainer(_pallas_cfg(4, tmp_path / "b", ("pallas", "pallas")),
                    splits)
    t_hpro = Trainer(het("hb"), splits)
    assert t_def._eval_gather_sharded == "xla"
    assert t_pro._eval_gather_sharded == "pallas"
    assert t_pro._eval_gather_impl == "xla"  # GSPMD paths untouched

    # A trainer whose panel is NOT lane-padded must refuse the promotion.
    t_xla = Trainer(_pallas_cfg(4, tmp_path / "c", ("xla", "xla")), splits)
    assert t_xla._eval_gather_sharded == "xla"

    s = t_def.init_state()
    v_def = t_def.evaluate(s.params)
    v_pro = t_pro.evaluate(s.params)
    assert v_pro["ic"] == pytest.approx(v_def["ic"], abs=1e-5)
    assert v_pro["mse"] == pytest.approx(v_def["mse"], rel=1e-5)
    # The predict/backtest forecasts ride the same dispatch: full parity.
    b = t_def.val_sampler.stacked_cross_sections()
    p_def, _, _ = t_def._forward_eval(s.params, b)
    p_pro, _, _ = t_pro._forward_eval(s.params, b)
    np.testing.assert_allclose(np.asarray(p_def), np.asarray(p_pro),
                               rtol=1e-5, atol=1e-6)

    # The sharded VARIANCE dispatch (fwd_var) promotes too — it marks
    # itself with the mesh axis exactly like the deterministic one.
    hs = t_hdef.init_state()
    m_def, v_def_, _ = t_hdef._forward_eval(hs.params, b, variance=True)
    m_pro, v_pro_, _ = t_hpro._forward_eval(hs.params, b, variance=True)
    np.testing.assert_allclose(np.asarray(m_def), np.asarray(m_pro),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v_def_), np.asarray(v_pro_),
                               rtol=1e-5, atol=1e-7)


def test_ensemble_shard_map_pallas_matches_xla(lstm_panel, tmp_path):
    """vmap(seeds) ∘ shard_map(seed × data) ∘ Pallas kernels: the stacked
    ensemble step with per-shard Pallas must match the same ensemble on
    XLA impls (same mesh), proving the fast path survives the full
    target-topology composition."""
    import dataclasses

    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    splits = PanelSplits.by_date(lstm_panel, 198001, 198201)
    mk = lambda impls, sub: dataclasses.replace(  # noqa: E731
        _pallas_cfg(2, tmp_path / sub, impls), n_seeds=4)
    e_xla = EnsembleTrainer(mk(("xla", "xla"), "a"), splits)
    e_pal = EnsembleTrainer(mk(("pallas", "pallas"), "b"), splits)
    assert e_pal.mesh is not None
    assert e_pal.mesh.shape == {"seed": 4, "data": 2}

    s_x, s_p = e_xla.init_state(), e_pal.init_state()
    fi, ti, w = e_pal._stacked_epoch(0)
    s_x, m_x = e_xla._jit_multi_step(s_x, e_xla.dev, fi, ti, w)
    s_p, m_p = e_pal._jit_multi_step(s_p, e_pal.dev, fi, ti, w)
    np.testing.assert_allclose(np.asarray(m_x["loss"]),
                               np.asarray(m_p["loss"]), rtol=1e-3, atol=1e-5)
    for a, c in zip(jax.tree.leaves(s_x.params), jax.tree.leaves(s_p.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-3, atol=1e-5)


@pytest.mark.nightly
def test_dp_training_lru_matches_single_device(panel, tmp_path):
    """The LRU's associative scan must survive the trainer's shard_map
    (its AD only composes with shard_map under jit — which the trainer
    guarantees): 8-way date-sharded steps == single-device steps."""
    import dataclasses

    splits = PanelSplits.by_date(panel, 197910, 198101)
    mdl = ModelConfig(kind="lru", kwargs={"hidden": 16, "state_dim": 16})
    cfg1 = dataclasses.replace(_fit_cfg(panel, 1, tmp_path / "a"), model=mdl)
    cfg8 = dataclasses.replace(_fit_cfg(panel, 8, tmp_path / "b"), model=mdl)
    t1, t8 = Trainer(cfg1, splits), Trainer(cfg8, splits)
    assert t8.mesh is not None and t8.mesh.shape["data"] == 8

    s1, s8 = t1.init_state(), t8.init_state()
    for b in t1.train_sampler.epoch(0):
        s1, m1 = t1._jit_step(s1, t1.dev, *t1._batch_args(b, train=True))
        s8, m8 = t8._jit_step(s8, t8.dev, *t8._batch_args(b, train=True))
    assert float(m1["loss"]) == pytest.approx(float(m8["loss"]), rel=1e-4)
    for l1, l8 in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l8),
                                   rtol=1e-4, atol=1e-5)


def test_make_mesh_topology_path_spans_all_devices():
    """The mesh_utils-built grid (full-device meshes) must contain every
    device exactly once and keep the (seed, data) axis names."""
    m = make_mesh(2, 4)
    assert m.shape == {"seed": 2, "data": 4}
    assert sorted(d.id for row in m.devices for d in row) == sorted(
        d.id for d in jax.devices())


def test_month_sharded_eval_matches_unsharded(tmp_path):
    """Under a data mesh the eval sweep shards the stacked month axis
    (with weight-0 padding to the axis size) instead of replicating the
    whole computation per device — evaluate() and predict() must match
    the meshless trainer exactly on identical params."""
    import dataclasses

    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    panel = synthetic_panel(n_firms=120, n_months=151, n_features=5,
                            seed=23)
    splits = PanelSplits.by_date(panel, 197901, 198101)
    cfg = RunConfig(
        name="ev_shard",
        data=DataConfig(n_firms=120, n_months=151, n_features=5,
                        window=12, dates_per_batch=4, firms_per_date=24),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=3e-3, epochs=1, warmup_steps=2, loss="mse"),
        n_data_shards=4,
        out_dir=str(tmp_path),
    )
    meshed = Trainer(cfg, splits)
    assert meshed._eval_sharded
    plain = Trainer(dataclasses.replace(cfg, n_data_shards=1), splits,
                    mesh=None)
    state = plain.init_state()  # same seed → same params for both
    meshed.state = plain.state = state

    ev_m = meshed.evaluate(state.params)
    ev_p = plain.evaluate(state.params)
    assert ev_m["n_months"] == ev_p["n_months"]
    np.testing.assert_allclose(ev_m["ic"], ev_p["ic"], rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(ev_m["mse"], ev_p["mse"], rtol=1e-5)

    fm, vm = meshed.predict("test")
    fp, vp = plain.predict("test")
    np.testing.assert_array_equal(vm, vp)
    np.testing.assert_allclose(fm[vm], fp[vp], rtol=1e-5, atol=1e-6)


def test_month_sharded_eval_variance_path(tmp_path):
    """The sharded heteroscedastic eval (predict(return_variance=True)
    under a data mesh) must match the meshless trainer exactly."""
    import dataclasses

    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer

    panel = synthetic_panel(n_firms=100, n_months=151, n_features=5,
                            seed=24)
    splits = PanelSplits.by_date(panel, 197901, 198101)
    cfg = RunConfig(
        name="ev_var_shard",
        data=DataConfig(n_firms=100, n_months=151, n_features=5,
                        window=12, dates_per_batch=4, firms_per_date=24),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)},
                          heteroscedastic=True),
        optim=OptimConfig(lr=3e-3, epochs=1, warmup_steps=2, loss="nll"),
        n_data_shards=4,
        out_dir=str(tmp_path),
    )
    meshed = Trainer(cfg, splits)
    assert meshed._eval_sharded
    plain = Trainer(dataclasses.replace(cfg, n_data_shards=1), splits,
                    mesh=None)
    state = plain.init_state()
    meshed.state = plain.state = state

    fm, vm_var, vm = meshed.predict("test", return_variance=True)
    fp, vp_var, vp = plain.predict("test", return_variance=True)
    np.testing.assert_array_equal(vm, vp)
    np.testing.assert_allclose(fm[vm], fp[vp], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(vm_var[vm], vp_var[vp], rtol=1e-5,
                               atol=1e-7)
    assert (vm_var[vm] > 0).all()
