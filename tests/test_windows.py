"""Windowing pipeline (L2) tests: eligibility, sampling, on-device gather."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.data import (
    DateBatchSampler,
    anchor_index,
    device_panel,
    gather_targets,
    gather_windows,
    synthetic_panel,
)
from lfm_quant_tpu.data.windows import rolling_valid_count

pytestmark = pytest.mark.fast  # whole module is smoke-lane cheap

WINDOW = 24


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=120, n_months=140, n_features=5, seed=11)


def test_anchor_index_matches_bruteforce(panel):
    elig = anchor_index(panel, WINDOW, min_valid_months=12)
    n, t = panel.valid.shape
    rng = np.random.default_rng(0)
    for _ in range(200):
        i = int(rng.integers(0, n))
        j = int(rng.integers(0, t))
        lo = max(0, j - WINDOW + 1)
        n_valid = int(panel.valid[i, lo : j + 1].sum())
        expect = bool(
            panel.target_valid[i, j] and panel.valid[i, j] and n_valid >= 12
        )
        assert bool(elig[i, j]) == expect, (i, j)


def test_anchor_index_live_mode_drops_only_target_conjunct(panel):
    """require_target=False (the forecast.py live path) must equal the
    default eligibility with exactly the target_valid conjunct removed —
    reaching the last-`horizon`-month live block and nothing else new."""
    strict = anchor_index(panel, WINDOW, min_valid_months=12)
    live = anchor_index(panel, WINDOW, min_valid_months=12,
                        require_target=False)
    np.testing.assert_array_equal(live & panel.target_valid, strict)
    extra = live & ~strict
    assert extra.any()
    assert not panel.target_valid[extra].any()
    # The panel's final month — never target-eligible — is forecastable.
    assert live[:, -1].any() and not strict[:, -1].any()


def test_sampler_layout_and_eligibility(panel):
    s = DateBatchSampler(panel, WINDOW, dates_per_batch=4, firms_per_date=16, seed=5)
    elig = anchor_index(panel, WINDOW)
    batches = list(s.epoch(0))
    assert len(batches) == s.batches_per_epoch()
    for b in batches:
        assert b.firm_idx.shape == (4, 16)
        assert b.time_idx.shape == (4,)
        assert b.weight.shape == (4, 16)
        for j in range(4):
            t = int(b.time_idx[j])
            for k in range(16):
                if b.weight[j, k] > 0:
                    assert elig[b.firm_idx[j, k], t]
            # Real (weighted) samples within a date are distinct firms.
            real = b.firm_idx[j][b.weight[j] > 0]
            assert len(np.unique(real)) == len(real)


def test_sampler_determinism_and_seed_independence(panel):
    mk = lambda seed: [
        (b.firm_idx.copy(), b.time_idx.copy())
        for b in DateBatchSampler(
            panel, WINDOW, dates_per_batch=2, firms_per_date=8, seed=seed
        ).epoch(0)
    ]
    a, b, c = mk(1), mk(1), mk(2)
    for (fa, ta), (fb, tb) in zip(a, b):
        np.testing.assert_array_equal(fa, fb)
        np.testing.assert_array_equal(ta, tb)
    assert any(
        not np.array_equal(ta, tc) or not np.array_equal(fa, fc)
        for (fa, ta), (fc, tc) in zip(a, c)
    )


def test_epochs_differ(panel):
    s = DateBatchSampler(panel, WINDOW, dates_per_batch=2, firms_per_date=8, seed=1)
    e0 = [b.time_idx.copy() for b in s.epoch(0)]
    e1 = [b.time_idx.copy() for b in s.epoch(1)]
    assert any(not np.array_equal(x, y) for x, y in zip(e0, e1))


def test_gather_windows_matches_numpy(panel):
    dev = device_panel(panel)
    s = DateBatchSampler(panel, WINDOW, dates_per_batch=3, firms_per_date=8, seed=2)
    b = next(iter(s.epoch(0)))
    x, m = jax.jit(gather_windows, static_argnames="window")(
        dev["features"], dev["valid"], jnp.asarray(b.firm_idx),
        jnp.asarray(b.time_idx), window=WINDOW,
    )
    assert x.shape == (3, 8, WINDOW, panel.n_features)
    assert m.shape == (3, 8, WINDOW)
    x, m = np.asarray(x), np.asarray(m)
    for j in range(3):
        t = int(b.time_idx[j])
        lo = t - WINDOW + 1
        for k in range(8):
            f = int(b.firm_idx[j, k])
            for w in range(WINDOW):
                tt = lo + w
                if tt < 0:
                    assert not m[j, k, w]
                    assert np.all(x[j, k, w] == 0)
                else:
                    assert m[j, k, w] == panel.valid[f, tt]
                    np.testing.assert_allclose(
                        x[j, k, w],
                        panel.features[f, tt] if panel.valid[f, tt] else 0.0,
                    )


def test_gather_targets(panel):
    dev = device_panel(panel)
    s = DateBatchSampler(panel, WINDOW, dates_per_batch=3, firms_per_date=8, seed=2)
    b = next(iter(s.epoch(0)))
    y = np.asarray(
        gather_targets(dev["targets"], jnp.asarray(b.firm_idx), jnp.asarray(b.time_idx))
    )
    for j in range(3):
        for k in range(8):
            assert y[j, k] == panel.targets[b.firm_idx[j, k], b.time_idx[j]]


def test_full_cross_sections_cover_everything(panel):
    s = DateBatchSampler(panel, WINDOW, dates_per_batch=2, firms_per_date=8, seed=0)
    elig = anchor_index(panel, WINDOW)
    seen = np.zeros_like(elig, dtype=bool)
    for b in s.full_cross_sections():
        t = int(b.time_idx[0])
        for k in range(b.firm_idx.shape[1]):
            if b.weight[0, k] > 0:
                seen[b.firm_idx[0, k], t] = True
    # Every eligible anchor appears, including thin-cross-section dates
    # below the training min_cross_section filter.
    np.testing.assert_array_equal(seen, elig)


def test_short_history_padding_masked(panel):
    # An anchor early in a firm's life must produce left-padded masked steps.
    dev = device_panel(panel)
    elig = anchor_index(panel, WINDOW, min_valid_months=12)
    # Find an anchor with < WINDOW valid months in window.
    tot = rolling_valid_count(panel.valid, WINDOW)
    cands = np.argwhere(elig & (tot < WINDOW))
    assert cands.size, "fixture should contain short-history anchors"
    f, t = map(int, cands[0])
    x, m = gather_windows(
        dev["features"], dev["valid"], jnp.asarray([[f]]), jnp.asarray([t]), WINDOW
    )
    m = np.asarray(m)[0, 0]
    assert m.sum() < WINDOW
    assert np.all(np.asarray(x)[0, 0][~m] == 0.0)


def test_gather_young_anchor_aligns_to_last_position(panel):
    """Anchors younger than the window (t < W-1) must still place the
    anchor month at the LAST window position, with leading padding masked
    (the fast path clamps its slice start and rolls)."""
    dev = device_panel(panel)
    t = WINDOW // 2  # young anchor
    firms = np.nonzero(panel.valid[:, t])[0][:4].astype(np.int32)
    x, m = jax.jit(gather_windows, static_argnames="window")(
        dev["features"], dev["valid"], jnp.asarray(firms[None, :]),
        jnp.asarray([t], np.int32), window=WINDOW,
    )
    x, m = np.asarray(x)[0], np.asarray(m)[0]
    pad = WINDOW - 1 - t
    assert not m[:, :pad].any(), "pre-history positions must be masked"
    assert np.all(x[:, :pad] == 0)
    for k, f in enumerate(firms):
        for w in range(pad, WINDOW):
            tt = t - (WINDOW - 1) + w
            assert m[k, w] == panel.valid[f, tt]
            if m[k, w]:
                np.testing.assert_allclose(x[k, w], panel.features[f, tt])


def test_gather_windows_packed_matches_general(panel):
    from lfm_quant_tpu.data import gather_windows_packed

    dev = device_panel(panel)
    s = DateBatchSampler(panel, WINDOW, dates_per_batch=3, firms_per_date=8,
                         seed=2)
    b = next(iter(s.epoch(0)))
    # include a young anchor row to exercise the clamp+roll path
    fi = np.concatenate([b.firm_idx,
                         b.firm_idx[:1]], axis=0)
    young = WINDOW // 3
    pool = np.nonzero(panel.valid[:, young])[0]
    fi[-1] = pool[np.arange(8) % pool.size]
    ti = np.concatenate([b.time_idx, [young]]).astype(np.int32)

    xg, mg = jax.jit(gather_windows, static_argnames="window")(
        dev["features"], dev["valid"], jnp.asarray(fi), jnp.asarray(ti),
        window=WINDOW)
    xp, mp = jax.jit(gather_windows_packed, static_argnames="window")(
        dev["xm"], jnp.asarray(fi), jnp.asarray(ti), window=WINDOW)
    np.testing.assert_array_equal(np.asarray(mg), np.asarray(mp))
    np.testing.assert_allclose(np.asarray(xg), np.asarray(xp), rtol=0, atol=0)

    # bf16 packed panel: same mask, features quantized to bf16
    dev_bf = device_panel(panel, compute_dtype=jnp.bfloat16)
    xb, mb = jax.jit(gather_windows_packed, static_argnames="window")(
        dev_bf["xm"], jnp.asarray(fi), jnp.asarray(ti), window=WINDOW)
    np.testing.assert_array_equal(np.asarray(mg), np.asarray(mb))
    np.testing.assert_allclose(np.asarray(xb).astype(np.float32),
                               np.asarray(xg), rtol=1e-2, atol=1e-2)


def test_full_universe_sampler(panel):
    """firms_per_date=0: every batch row carries the date's ENTIRE eligible
    pool (set equality with the anchor index), padded to a static rounded
    Bf with weight 0."""
    s = DateBatchSampler(panel, WINDOW, dates_per_batch=4, firms_per_date=0,
                         seed=5)
    elig = anchor_index(panel, WINDOW)
    mx = max(int(elig[:, t].sum()) for t in s._dates)
    assert s.firms_per_date == -(-mx // 8) * 8  # rounded max (small panel)
    for b in s.epoch(0):
        for j in range(4):
            t = int(b.time_idx[j])
            pool = set(np.nonzero(elig[:, t])[0].tolist())
            real = b.firm_idx[j][b.weight[j] > 0]
            assert set(real.tolist()) == pool  # full universe, exactly once
            assert len(np.unique(real)) == len(real)
            # padding (if any) is weight-0 repetition of pool members
            pad = b.firm_idx[j][b.weight[j] == 0]
            assert set(pad.tolist()) <= pool


def test_full_universe_rounds_to_chunk_multiple():
    """Above 2×FIRM_CHUNK eligible firms, full-universe Bf rounds to a
    FIRM_CHUNK multiple so the chunked gather divides evenly."""
    from lfm_quant_tpu.data.windows import FIRM_CHUNK

    big = synthetic_panel(n_firms=2600, n_months=100, n_features=3, seed=3,
                          min_history=24)
    s = DateBatchSampler(big, 12, dates_per_batch=2, firms_per_date=0,
                         seed=0)
    assert s.firms_per_date % FIRM_CHUNK == 0
    assert s.firms_per_date >= max(
        int(anchor_index(big, 12)[:, t].sum()) for t in s._dates)


def test_gather_firm_chunked_matches_unchunked(panel):
    """firm_chunk must be a pure memory-shape knob: identical output."""
    from lfm_quant_tpu.data import gather_windows_packed

    dev = device_panel(panel)
    rng = np.random.default_rng(8)
    fi = rng.integers(0, panel.n_firms, size=(3, 64)).astype(np.int32)
    ti = rng.integers(WINDOW, panel.n_months, size=(3,)).astype(np.int32)
    x0, m0 = jax.jit(gather_windows_packed, static_argnames="window")(
        dev["xm"], jnp.asarray(fi), jnp.asarray(ti), window=WINDOW)
    xc, mc = jax.jit(gather_windows_packed,
                     static_argnames=("window", "firm_chunk"))(
        dev["xm"], jnp.asarray(fi), jnp.asarray(ti), window=WINDOW,
        firm_chunk=16)
    np.testing.assert_array_equal(np.asarray(m0), np.asarray(mc))
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(xc))
    # Non-multiple width (eval sweeps pad Bf to the raw max pool): the
    # chunked path pads internally and slices back — still identical.
    xn, mn = jax.jit(gather_windows_packed,
                     static_argnames=("window", "firm_chunk"))(
        dev["xm"], jnp.asarray(fi[:, :50]), jnp.asarray(ti), window=WINDOW,
        firm_chunk=16)
    np.testing.assert_array_equal(np.asarray(m0[:, :50]), np.asarray(mn))
    np.testing.assert_array_equal(np.asarray(x0[:, :50]), np.asarray(xn))


def test_sub_window_gather_equals_slice_of_full(panel):
    """The sequence-parallel step gathers per-shard SUB-windows (length
    W/n ending at anchor − (W − (s+1)·wl)); each must equal the matching
    slice of the full-window gather — including young anchors whose early
    shards fall entirely before the firm's history."""
    from lfm_quant_tpu.data import gather_windows_packed

    dev = device_panel(panel)
    n = 4
    wl = WINDOW // n
    rng = np.random.default_rng(12)
    fi = rng.integers(0, panel.n_firms, size=(3, 8)).astype(np.int32)
    # anchors: normal + young (t < W-1, so shard 0's sub-window is fully
    # pre-history) + very young
    ti = np.asarray([panel.n_months - 2, WINDOW // 2, 3], np.int32)
    xf, mf = jax.jit(gather_windows_packed, static_argnames="window")(
        dev["xm"], jnp.asarray(fi), jnp.asarray(ti), window=WINDOW)
    for s in range(n):
        shift = WINDOW - (s + 1) * wl
        xs, ms = jax.jit(gather_windows_packed, static_argnames="window")(
            dev["xm"], jnp.asarray(fi), jnp.asarray(ti - shift), window=wl)
        np.testing.assert_array_equal(
            np.asarray(mf)[:, :, s * wl:(s + 1) * wl], np.asarray(ms),
            err_msg=f"shard {s} mask")
        np.testing.assert_array_equal(
            np.asarray(xf)[:, :, s * wl:(s + 1) * wl], np.asarray(xs),
            err_msg=f"shard {s} features")


# ---- device-panel residency: concurrency + refcount-safe eviction --------
#
# The scoring service dispatches from a micro-batcher thread while
# refresh/eviction runs elsewhere, so the residency cache is
# lock-guarded and lease-refcounted (serve satellite work). These
# regressions pin the three properties that make that safe.


def test_panel_cache_cold_race_pays_one_transfer(monkeypatch):
    """Two threads racing a COLD panel key must pay exactly ONE H2D
    (pre-lock, both missed and both transferred). The transfer is
    artificially slowed so the race window is real."""
    import threading
    import time

    from lfm_quant_tpu.data import windows
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    panel = synthetic_panel(n_firms=40, n_months=120, n_features=3, seed=77)
    real = windows.device_panel

    def slow_device_panel(*a, **kw):
        time.sleep(0.1)  # hold the miss window open
        return real(*a, **kw)

    monkeypatch.setattr(windows, "device_panel", slow_device_panel)
    snap = REUSE_COUNTERS.snapshot()
    devs = [None, None]

    def reader(i):
        devs[i] = windows.cached_device_panel(panel, None)

    threads = [threading.Thread(target=reader, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    d = REUSE_COUNTERS.delta(snap)
    assert d["panel_transfers"] == 1, d
    assert devs[0] is devs[1]  # the SAME resident entry
    windows.invalidate_panel(panel)


def test_invalidate_during_inflight_lease_defers_drop():
    """The forged-slow-dispatch regression: a reader holds a lease (an
    in-flight scoring dispatch) while another thread invalidates the
    panel. The leased arrays must stay live and usable through the
    whole dispatch; NEW readers must immediately re-transfer fresh
    bytes; and the doomed entry finalizes exactly once, at the last
    release (counted by panel_deferred_drops)."""
    import threading

    from lfm_quant_tpu.data import windows
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS
    from lfm_quant_tpu.utils.telemetry import COUNTERS

    panel = synthetic_panel(n_firms=40, n_months=120, n_features=3, seed=78)
    snap = REUSE_COUNTERS.snapshot()
    entered = threading.Event()
    release = threading.Event()
    result = {}

    def slow_dispatch():
        with windows.lease_device_panel(panel, None) as dev:
            entered.set()
            release.wait(timeout=30)
            # The forged "dispatch" consumes the leased arrays AFTER the
            # invalidation landed — a premature free would break here.
            result["sum"] = float(jnp.asarray(dev["xm"]).sum())

    t = threading.Thread(target=slow_dispatch)
    t.start()
    assert entered.wait(timeout=30)
    drops0 = COUNTERS.get("panel_deferred_drops")
    assert windows.invalidate_panel(panel) == 1  # the leased entry
    # New readers re-transfer immediately (no stale aliasing).
    dev2 = windows.cached_device_panel(panel, None)
    assert REUSE_COUNTERS.delta(snap)["panel_transfers"] == 2
    # The in-flight lease has NOT been finalized yet.
    assert COUNTERS.get("panel_deferred_drops") == drops0
    release.set()
    t.join(timeout=30)
    assert np.isfinite(result["sum"])  # dispatch completed on live arrays
    assert COUNTERS.get("panel_deferred_drops") == drops0 + 1
    # The fresh entry is untouched by the deferred drop.
    assert windows.cached_device_panel(panel, None) is dev2
    windows.invalidate_panel(panel)


def test_lease_without_invalidation_is_plain_hit():
    """Leases on a healthy entry are free: same arrays as the unleased
    path, no transfers, no deferred drops."""
    from lfm_quant_tpu.data import windows
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS
    from lfm_quant_tpu.utils.telemetry import COUNTERS

    panel = synthetic_panel(n_firms=40, n_months=120, n_features=3, seed=79)
    dev = windows.cached_device_panel(panel, None)
    drops0 = COUNTERS.get("panel_deferred_drops")
    snap = REUSE_COUNTERS.snapshot()
    with windows.lease_device_panel(panel, None) as leased:
        assert leased is dev
        with windows.lease_device_panel(panel, None) as nested:
            assert nested is dev  # reentrant leases stack fine
    d = REUSE_COUNTERS.delta(snap)
    assert d["panel_transfers"] == 0
    assert COUNTERS.get("panel_deferred_drops") == drops0
    windows.invalidate_panel(panel)
