"""Ensemble trainer (L5) tests: vmapped multi-seed training, seed
diversity, stacked checkpoints, seed-sharded mesh, ensemble backtest path.
"""

import dataclasses

import jax
import numpy as np
import pytest

from lfm_quant_tpu.backtest import aggregate_ensemble, run_backtest
from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.train.ensemble import (
    EnsembleTrainer,
    load_ensemble,
    run_ensemble_experiment,
)


def ens_cfg(tmp, n_seeds=4, **over):
    base = dict(
        name="t_ens",
        data=DataConfig(n_firms=150, n_months=150, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=48, panel_seed=31),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=3e-3, epochs=3, warmup_steps=5,
                          early_stop_patience=3, loss="mse"),
        seed=0,
        n_seeds=n_seeds,
        out_dir=str(tmp),
    )
    base.update(over)
    return RunConfig(**base)


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=150, n_months=150, n_features=5, seed=31)


@pytest.fixture(scope="module")
def fitted(panel, tmp_path_factory):
    cfg = ens_cfg(tmp_path_factory.mktemp("ens"), n_seeds=4)
    summary, trainer, splits = run_ensemble_experiment(cfg, panel=panel)
    return cfg, summary, trainer, splits


def test_ensemble_trains_and_recovers_signal(fitted):
    _, summary, _, _ = fitted
    assert summary["n_seeds"] == 4
    assert summary["best_val_ic"] > 0.1
    hist = summary["history"]
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]


def test_ensemble_predicts_live_anchors(fitted):
    """The seed-stacked predict reaches the live block too (forecast.py's
    ensemble path): target-free anchors get forecasts from every seed."""
    import numpy as np

    _, _, trainer, splits = fitted
    panel = splits.panel
    live_lo = panel.n_months - panel.horizon
    stacked, valid = trainer.predict(
        date_range=(live_lo, panel.n_months), require_target=False)
    assert valid.any() and not panel.target_valid[:, live_lo:].any()
    assert stacked.shape[0] == trainer.n_seeds
    assert np.isfinite(stacked[:, valid]).all()
    # Seeds genuinely differ on live anchors (independent members).
    assert np.std(stacked[:, valid], axis=0).mean() > 0


def test_members_differ(fitted):
    """Different seeds ⇒ different params and different forecasts —
    the diversity requirement from SURVEY.md §8 ('hard parts')."""
    _, _, trainer, splits = fitted
    p = trainer.state.params
    leaves = jax.tree.leaves(p)
    for leaf in leaves:
        arr = np.asarray(leaf)
        assert arr.shape[0] == 4
        if arr.ndim > 1 and arr.size >= 8:
            assert not np.allclose(arr[0], arr[1]), "seeds collapsed"
    stacked, valid = trainer.predict("test")
    assert not np.allclose(stacked[0][valid], stacked[1][valid])


def test_seed_mesh_sharding(fitted):
    """State leaves must be sharded over the seed axis of the mesh
    (4 seeds over the 8-device CPU mesh → seed axis 4)."""
    _, _, trainer, _ = fitted
    assert trainer.mesh is not None
    assert trainer.mesh.shape["seed"] == 4
    leaf = jax.tree.leaves(trainer.state.params)[0]
    assert len(leaf.sharding.device_set) >= 4


def test_per_seed_data_orders_differ(fitted):
    _, _, trainer, _ = fitted
    b0 = next(iter(trainer.samplers[0].epoch(0)))
    b1 = next(iter(trainer.samplers[1].epoch(0)))
    assert (not np.array_equal(b0.time_idx, b1.time_idx)
            or not np.array_equal(b0.firm_idx, b1.firm_idx))


def test_ensemble_checkpoint_roundtrip_and_backtest(fitted, panel):
    cfg, _, trainer, splits = fitted
    reloaded, rsplits = load_ensemble(
        trainer.run_dir, panel=panel)
    for a, b in zip(jax.tree.leaves(trainer.state.params),
                    jax.tree.leaves(reloaded.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    stacked, valid = reloaded.predict("test")
    assert stacked.shape[0] == cfg.n_seeds
    for mode in ("mean", "mean_minus_std"):
        fc, fcv = aggregate_ensemble(stacked, valid, mode)
        rep = run_backtest(fc, fcv, rsplits.panel, min_universe=10)
        assert rep.n_months > 0
        assert np.isfinite(rep.sharpe_ann)


def test_ensemble_beats_or_matches_worst_member(fitted):
    """The ensemble mean forecast should not be worse than the worst
    individual member on test IC (basic variance-reduction sanity)."""
    _, _, trainer, splits = fitted
    stacked, valid = trainer.predict("test")
    t = splits.panel
    member_ics = []
    mask = valid & t.target_valid
    for s in range(stacked.shape[0]):
        member_ics.append(np.corrcoef(stacked[s][mask], t.targets[mask])[0, 1])
    ens = stacked.mean(axis=0)
    ens_ic = np.corrcoef(ens[mask], t.targets[mask])[0, 1]
    assert ens_ic >= min(member_ics) - 1e-6


def test_ensemble_warm_start_fit(panel, tmp_path):
    """EnsembleTrainer.fit(init_params=...) — the stacked warm start the
    walk-forward carry uses: training proceeds from the given seed-stacked
    weights, and a seed-count mismatch fails loudly (the opt-state tree
    must keep init_state's vmapped structure, so this path has its own
    branch)."""
    from lfm_quant_tpu.data.panel import PanelSplits

    splits = PanelSplits.by_date(panel, 198001, 198201)
    donor = EnsembleTrainer(ens_cfg(tmp_path / "a", n_seeds=2), splits)
    donor_params = donor.init_state().params
    tr = EnsembleTrainer(ens_cfg(tmp_path / "b", n_seeds=2), splits)
    fit = tr.fit(init_params=donor_params)
    assert np.isfinite(fit["best_val_ic"])
    # Mismatched seed count: loud error, not a jit structure failure.
    tr3 = EnsembleTrainer(ens_cfg(tmp_path / "c", n_seeds=3), splits)
    with pytest.raises(ValueError, match="does not match"):
        tr3.fit(init_params=donor_params)


def test_requires_two_seeds(panel, tmp_path):
    from lfm_quant_tpu.data import PanelSplits
    splits = PanelSplits.by_date(panel, 197910, 198101)
    with pytest.raises(ValueError, match="n_seeds"):
        EnsembleTrainer(ens_cfg(tmp_path, n_seeds=1), splits)


def test_heteroscedastic_ensemble_variance_and_total_std(panel, tmp_path):
    """NLL-trained members expose per-seed aleatoric variance, and the
    mean_minus_total_std aggregation penalizes at least as hard as the
    epistemic-only mode."""
    from lfm_quant_tpu.data import PanelSplits

    cfg = ens_cfg(tmp_path, n_seeds=2,
                  optim=OptimConfig(lr=3e-3, epochs=2, warmup_steps=5,
                                    early_stop_patience=3, loss="nll"))
    dates = panel.dates
    splits = PanelSplits.by_date(panel, int(dates[100]), int(dates[120]))
    tr = EnsembleTrainer(cfg, splits)
    tr.state = tr.init_state()
    tr.fit()
    stacked, avar, valid = tr.predict("test", return_variance=True)
    assert stacked.shape == avar.shape == (2, panel.n_firms, panel.n_months)
    assert (avar[:, valid] > 0).all(), "aleatoric variance must be positive"
    total, _ = aggregate_ensemble(stacked, valid, "mean_minus_total_std",
                                  aleatoric_var=avar)
    epist, _ = aggregate_ensemble(stacked, valid, "mean_minus_std")
    assert (total[valid] <= epist[valid] + 1e-6).all()
    # hand-check one cell
    s, e = stacked[:, valid], avar[:, valid]
    expect = s.mean(0) - np.sqrt(s.var(0) + e.mean(0))
    np.testing.assert_allclose(total[valid], expect, rtol=1e-5, atol=1e-6)


def test_total_std_mode_requires_variance():
    fc = np.zeros((3, 4, 5), np.float32)
    valid = np.ones((4, 5), bool)
    with pytest.raises(ValueError, match="aleatoric_var"):
        aggregate_ensemble(fc, valid, "mean_minus_total_std")


@pytest.mark.nightly
def test_lru_ensemble_trains(panel, tmp_path):
    """The associative-scan LRU composes with the seed-vmapped ensemble
    (generic batching over the scan) — guard the kind=lru + n_seeds>1
    path end to end."""
    cfg = ens_cfg(tmp_path, n_seeds=2,
                  model=ModelConfig(kind="lru",
                                    kwargs={"hidden": 16, "state_dim": 16}))
    summary, tr, _ = run_ensemble_experiment(cfg, panel=panel)
    assert summary["n_seeds"] == 2
    stacked, valid = tr.predict("test")
    assert stacked.shape[0] == 2
    assert not np.allclose(stacked[0][valid], stacked[1][valid])


@pytest.mark.nightly
def test_seed_block_matches_unblocked(panel, tmp_path):
    """seed_block is a pure memory-shape knob: scanning the seed stack in
    blocks must reproduce the all-at-once vmapped step (seeds are
    independent)."""
    base = ens_cfg(tmp_path, n_seeds=16,
                   optim=OptimConfig(lr=3e-3, epochs=1, warmup_steps=5,
                                     early_stop_patience=3, loss="mse"))
    blocked = dataclasses.replace(base, seed_block=1, name="t_ens_blk")
    out = {}
    for cfg in (base, blocked):
        summary, trainer, _ = run_ensemble_experiment(cfg, panel=panel)
        out[cfg.seed_block] = trainer.state
    for a, b in zip(jax.tree.leaves(out[0].params),
                    jax.tree.leaves(out[1].params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_seed_block_must_divide_local_seeds(panel, tmp_path):
    from lfm_quant_tpu.data import PanelSplits

    # 48 seeds over the 8-device mesh → 6 per shard; 4 does not divide 6.
    cfg = ens_cfg(tmp_path, n_seeds=48, seed_block=4)
    splits = PanelSplits.by_date(panel, 197901, 198101)
    with pytest.raises(ValueError, match="seed_block"):
        EnsembleTrainer(cfg, splits)


def test_seed_block_oversized_is_noop_and_negative_rejected(panel, tmp_path):
    """A block >= the per-shard seed count degrades to the unblocked step
    (pod-portability of single-chip configs); negative blocks fail loudly."""
    from lfm_quant_tpu.data import PanelSplits

    splits = PanelSplits.by_date(panel, 197901, 198101)
    big = ens_cfg(tmp_path, n_seeds=4, seed_block=64)
    EnsembleTrainer(big, splits)  # must construct, not raise
    with pytest.raises(ValueError, match="seed_block"):
        EnsembleTrainer(ens_cfg(tmp_path, n_seeds=4, seed_block=-4), splits)
