"""Dropout is live in training (round-1 verdict item 5: a configured
dropout>0 used to be silently ignored — Trainer never passed
deterministic=False or an rng). Pins: dropout changes training losses,
is deterministic per (state, step) for resume replay, stays OFF in eval,
and composes with the shard_map mesh path and the vmapped ensemble."""

import dataclasses

import jax
import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import PanelSplits, synthetic_panel
from lfm_quant_tpu.train import Trainer
from lfm_quant_tpu.train.ensemble import EnsembleTrainer


def _cfg(tmp, dropout, n_shards=1, n_seeds=1):
    return RunConfig(
        name=f"drop{dropout}",
        data=DataConfig(n_firms=120, n_months=150, n_features=5, window=12,
                        dates_per_batch=8, firms_per_date=32),
        model=ModelConfig(kind="mlp",
                          kwargs={"hidden": (16,), "dropout": dropout}),
        optim=OptimConfig(lr=1e-3, epochs=2, warmup_steps=5, loss="mse"),
        seed=0,
        n_seeds=n_seeds,
        n_data_shards=n_shards,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=120, n_months=150, n_features=5, seed=31)


@pytest.fixture(scope="module")
def splits(panel):
    return PanelSplits.by_date(panel, 197910, 198101)


def test_dropout_changes_training_loss(splits, tmp_path):
    t0 = Trainer(_cfg(tmp_path / "a", 0.0), splits)
    t5 = Trainer(_cfg(tmp_path / "b", 0.5), splits)
    assert not t0._needs_rng and t5._needs_rng
    s0, s5 = t0.init_state(), t5.init_state()
    # Same seed, no dropout params → identical initial params.
    for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s5.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    b = next(iter(t0.train_sampler.epoch(0)))
    args = t0._batch_args(b)
    _, m0 = t0._jit_step(s0, t0.dev, *args)
    _, m5 = t5._jit_step(s5, t5.dev, *args)
    assert float(m0["loss"]) != pytest.approx(float(m5["loss"]), rel=1e-6)


def test_dropout_deterministic_per_step(splits, tmp_path):
    """fold_in(rng, step) keys: replaying the same state+batch gives the
    same loss (crash resume replays the identical dropout stream)."""
    t = Trainer(_cfg(tmp_path, 0.5), splits)
    s = t.init_state()
    b = next(iter(t.train_sampler.epoch(0)))
    args = t._batch_args(b)
    _, m1 = t._jit_step(s, t.dev, *args)
    _, m2 = t._jit_step(s, t.dev, *args)
    assert float(m1["loss"]) == float(m2["loss"])
    # ...but the NEXT step (step+1) draws a different mask.
    s_next, _ = t._jit_step(s, t.dev, *args)
    _, m3 = t._jit_step(s_next, t.dev, *args)
    assert float(m3["loss"]) != float(m1["loss"])


def test_eval_is_deterministic(splits, tmp_path):
    """Dropout must be OFF in the eval forward: same params → same IC as
    the no-dropout twin (identical eval graphs)."""
    t0 = Trainer(_cfg(tmp_path / "a", 0.0), splits)
    t5 = Trainer(_cfg(tmp_path / "b", 0.5), splits)
    s = t5.init_state()
    v0 = t0.evaluate(s.params)
    v5 = t5.evaluate(s.params)
    assert v0["ic"] == pytest.approx(v5["ic"], abs=1e-9)
    assert v0["mse"] == pytest.approx(v5["mse"], rel=1e-9)


def test_dropout_under_shard_map(splits, tmp_path):
    """The rng plumb composes with the mesh path (axis_index fold)."""
    t = Trainer(_cfg(tmp_path, 0.5, n_shards=8), splits)
    assert t.mesh is not None
    s = t.init_state()
    b = next(iter(t.train_sampler.epoch(0)))
    s, m = t._jit_step(s, t.dev, *t._batch_args(b, train=True))
    assert np.isfinite(float(m["loss"]))
    # Still deterministic given the same state.
    _, m2 = t._jit_step(
        t.init_state(), t.dev, *t._batch_args(b, train=True))
    assert float(m2["loss"]) == pytest.approx(float(m["loss"]), rel=1e-6)


def test_dropout_in_ensemble(splits, tmp_path):
    """Vmapped members train with dropout (per-member rng from the
    vmapped init) without error; losses stay finite."""
    cfg = _cfg(tmp_path, 0.3, n_shards=2, n_seeds=4)
    e = EnsembleTrainer(cfg, splits)
    s = e.init_state()
    # Per-member dropout streams are independent: the stacked state rng
    # rows differ.
    rngs = np.asarray(s.rng)
    assert rngs.shape[0] == 4 and len({tuple(r) for r in rngs}) == 4
    arrays = e._stacked_batch([smp.epoch(0) for smp in e.samplers])
    s, m = e._jit_step(s, e.dev, *arrays)
    assert np.isfinite(np.asarray(m["loss"])).all()


def test_transformer_dropout_trains(splits, tmp_path):
    cfg = _cfg(tmp_path, 0.2)
    cfg = dataclasses.replace(cfg, model=ModelConfig(
        kind="transformer",
        kwargs={"dim": 16, "depth": 1, "heads": 2, "dropout": 0.2}))
    t = Trainer(cfg, splits)
    assert t._needs_rng
    s = t.init_state()
    b = next(iter(t.train_sampler.epoch(0)))
    _, m = t._jit_step(s, t.dev, *t._batch_args(b))
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# MC-dropout inference (Trainer.predict(mc_samples=K)) — the uncertainty-
# aware-LFM single-model alternative to a seed ensemble.
# ---------------------------------------------------------------------------


def _fitted(splits, tmp, dropout):
    t = Trainer(_cfg(tmp, dropout), splits)
    t.state = t.init_state()
    return t


def test_mc_predict_shapes_and_diversity(splits, tmp_path):
    t = _fitted(splits, tmp_path / "mc", 0.5)
    stacked, valid = t.predict("test", mc_samples=4, mc_seed=7)
    n, tm = splits.panel.n_firms, splits.panel.n_months
    assert stacked.shape == (4, n, tm) and valid.shape == (n, tm)
    assert valid.any()
    # Dropout live → samples differ where predictions exist.
    sd = stacked.std(axis=0)[valid]
    assert float(sd.max()) > 0.0
    # Same seed → bit-identical replay; different seed → different draws.
    again, _ = t.predict("test", mc_samples=4, mc_seed=7)
    np.testing.assert_array_equal(stacked, again)
    other, _ = t.predict("test", mc_samples=4, mc_seed=8)
    assert not np.array_equal(stacked, other)


def test_mc_predict_aggregates_like_ensemble(splits, tmp_path):
    from lfm_quant_tpu.backtest import aggregate_ensemble, run_backtest

    t = _fitted(splits, tmp_path / "mcagg", 0.5)
    stacked, valid = t.predict("test", mc_samples=3)
    fc, v = aggregate_ensemble(stacked, valid, "mean_minus_std", 1.0)
    assert fc.shape == v.shape == valid.shape
    report = run_backtest(fc, v, splits.panel, quantile=0.3, min_universe=5)
    assert report.n_months > 0


def test_mc_predict_requires_dropout(splits, tmp_path):
    t = _fitted(splits, tmp_path / "mcno", 0.0)
    with pytest.raises(ValueError, match="dropout"):
        t.predict("test", mc_samples=4)


def test_mc_predict_validity_matches_plain(splits, tmp_path):
    t = _fitted(splits, tmp_path / "mceq", 0.5)
    _, v_mc = t.predict("test", mc_samples=2)
    _, v = t.predict("test")
    np.testing.assert_array_equal(v_mc, v)


def test_mc_predict_batched_is_one_dispatch_and_matches_loop(splits,
                                                            tmp_path):
    """The batched MC path (default) draws bit-identical samples to the
    per-sample loop fallback (shared key derivation: per-sample fold_in →
    per-chunk split), and K samples cost ONE trace on first use and ZERO
    on repeat — the 1-compile/1-dispatch contract of the fused scoring
    pipeline."""
    from lfm_quant_tpu.data.windows import clear_panel_cache
    from lfm_quant_tpu.train import reuse
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    # Fresh program bundle: sibling tests share the cached wrappers (and
    # their already-traced executables), which would zero the counter.
    reuse.clear_program_cache()
    clear_panel_cache()
    t = _fitted(splits, tmp_path / "mc1d", 0.5)
    loop, v_loop = t.predict("test", mc_samples=3, mc_seed=5,
                             mc_batched=False)
    snap = REUSE_COUNTERS.snapshot()
    batched, v_b = t.predict("test", mc_samples=3, mc_seed=5,
                             mc_batched=True)
    assert REUSE_COUNTERS.delta(snap)["jit_traces"] == 1  # mc_forward
    np.testing.assert_array_equal(v_loop, v_b)
    np.testing.assert_array_equal(loop, batched)
    snap = REUSE_COUNTERS.snapshot()
    again, _ = t.predict("test", mc_samples=3, mc_seed=5, mc_batched=True)
    assert REUSE_COUNTERS.delta(snap)["jit_traces"] == 0
    np.testing.assert_array_equal(batched, again)
