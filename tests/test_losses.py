"""Loss/metric (ops) tests against hand-computed and scipy fixtures.

SURVEY.md §8 step 4: the rank-IC math is "the subtlest math in the repo;
fixture-tested first".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats

from lfm_quant_tpu.ops import (
    finalize_loss,
    gaussian_nll,
    make_loss_parts,
    masked_huber,
    masked_mse,
    pearson_ic,
    rank_ic_loss,
    soft_rank,
    spearman_ic,
)

pytestmark = pytest.mark.fast  # whole module is smoke-lane cheap


@pytest.mark.parametrize("name", ["mse", "huber", "rank_ic", "nll"])
def test_loss_parts_reassemble_exactly(name):
    """finalize_loss(*parts(out, y, w)) must equal the canonical loss —
    the invariant the shard_map psum assembly (train/loop.py) rests on."""
    rng = np.random.default_rng(5)
    y = jnp.asarray(rng.standard_normal((4, 9)).astype(np.float32))
    p = jnp.asarray(rng.standard_normal((4, 9)).astype(np.float32))
    lv = jnp.asarray(rng.standard_normal((4, 9)).astype(np.float32))
    w = jnp.asarray((rng.random((4, 9)) < 0.8).astype(np.float32))
    out = (p, lv) if name == "nll" else p
    ref = {
        "mse": lambda: masked_mse(p, y, w),
        "huber": lambda: masked_huber(p, y, w),
        "rank_ic": lambda: rank_ic_loss(p, y, w),
        "nll": lambda: gaussian_nll(p, lv, y, w),
    }[name]()
    got = finalize_loss(*make_loss_parts(name)(out, y, w))
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)


def test_masked_mse_ignores_padding():
    pred = jnp.asarray([[1.0, 2.0, 99.0]])
    targ = jnp.asarray([[0.0, 1.0, 0.0]])
    w = jnp.asarray([[1.0, 1.0, 0.0]])
    assert float(masked_mse(pred, targ, w)) == pytest.approx(1.0)


def test_masked_huber_quadratic_and_linear():
    pred = jnp.asarray([[0.5, 3.0]])
    targ = jnp.asarray([[0.0, 0.0]])
    w = jnp.asarray([[1.0, 1.0]])
    # |0.5| < delta → 0.5*0.25 ; |3| > 1 → 0.5 + (3-1) = 2.5
    assert float(masked_huber(pred, targ, w)) == pytest.approx(
        (0.125 + 2.5) / 2
    )


def test_gaussian_nll_matches_formula():
    mean = jnp.asarray([[1.0]])
    log_var = jnp.asarray([[np.log(4.0)]])
    targ = jnp.asarray([[3.0]])
    w = jnp.ones((1, 1))
    expect = 0.5 * (np.log(4.0) + 4.0 / 4.0)
    assert float(gaussian_nll(mean, log_var, targ, w)) == pytest.approx(
        expect, rel=1e-6
    )


def test_soft_rank_approaches_hard_rank():
    x = jnp.asarray([[0.3, -1.2, 2.5, 0.9]])
    w = jnp.ones((1, 4))
    sr = np.asarray(soft_rank(x, w, temperature=1e-4))[0]
    # hard ranks (0-based) + 0.5 self term
    expect = np.array([1, 0, 3, 2]) + 0.5
    np.testing.assert_allclose(sr, expect, atol=1e-3)


def test_soft_rank_padding_isolated():
    x = jnp.asarray([[0.3, -1.2, 2.5, 100.0]])
    w = jnp.asarray([[1.0, 1.0, 1.0, 0.0]])
    sr = np.asarray(soft_rank(x, w, temperature=1e-4))[0]
    np.testing.assert_allclose(sr[:3], np.array([1, 0, 2]) + 0.5, atol=1e-3)


def test_spearman_matches_scipy():
    rng = np.random.default_rng(0)
    for _ in range(5):
        a = rng.standard_normal(40)
        b = 0.5 * a + rng.standard_normal(40)
        ours = float(
            spearman_ic(jnp.asarray(a)[None], jnp.asarray(b)[None], jnp.ones((1, 40)))[0]
        )
        ref = stats.spearmanr(a, b).statistic
        assert ours == pytest.approx(ref, abs=1e-5)


def test_spearman_with_padding_matches_scipy_on_subset():
    rng = np.random.default_rng(1)
    a = rng.standard_normal(30)
    b = rng.standard_normal(30)
    w = np.ones(30)
    w[20:] = 0.0
    a_pad = a.copy()
    a_pad[20:] = 1e9  # garbage in padded slots must not matter
    ours = float(
        spearman_ic(jnp.asarray(a_pad)[None], jnp.asarray(b)[None], jnp.asarray(w)[None])[0]
    )
    ref = stats.spearmanr(a[:20], b[:20]).statistic
    assert ours == pytest.approx(ref, abs=1e-5)


def test_pearson_matches_numpy():
    rng = np.random.default_rng(2)
    a = rng.standard_normal(50)
    b = -0.3 * a + rng.standard_normal(50)
    ours = float(pearson_ic(jnp.asarray(a)[None], jnp.asarray(b)[None], jnp.ones((1, 50)))[0])
    ref = np.corrcoef(a, b)[0, 1]
    assert ours == pytest.approx(ref, abs=1e-5)


def test_rank_ic_loss_perfect_and_anti_correlation():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((4, 64)).astype(np.float32)
    w = np.ones((4, 64), np.float32)
    l_same = float(rank_ic_loss(jnp.asarray(x), jnp.asarray(x), jnp.asarray(w)))
    l_anti = float(rank_ic_loss(jnp.asarray(-x), jnp.asarray(x), jnp.asarray(w)))
    assert l_same < -0.95
    assert l_anti > 0.95


def test_rank_ic_loss_is_per_month():
    """Month-wise constant offsets must not change the loss (ranking is
    within month) — the sharding correctness trap from SURVEY.md §8."""
    rng = np.random.default_rng(4)
    pred = rng.standard_normal((6, 32)).astype(np.float32)
    targ = rng.standard_normal((6, 32)).astype(np.float32)
    w = np.ones((6, 32), np.float32)
    base = float(rank_ic_loss(jnp.asarray(pred), jnp.asarray(targ), jnp.asarray(w)))
    offs = rng.standard_normal((6, 1)).astype(np.float32) * 100
    shifted = float(
        rank_ic_loss(jnp.asarray(pred + offs), jnp.asarray(targ), jnp.asarray(w))
    )
    assert shifted == pytest.approx(base, abs=1e-4)


def test_rank_ic_loss_gradient_points_the_right_way():
    """One gradient step on the loss must increase the exact Spearman IC."""
    rng = np.random.default_rng(5)
    targ = jnp.asarray(rng.standard_normal((3, 48)).astype(np.float32))
    pred0 = jnp.asarray(rng.standard_normal((3, 48)).astype(np.float32))
    w = jnp.ones((3, 48))

    g = jax.grad(lambda p: rank_ic_loss(p, targ, w))(pred0)
    assert bool(jnp.isfinite(g).all())
    pred1 = pred0 - 0.5 * g
    ic0 = float(spearman_ic(pred0, targ, w).mean())
    ic1 = float(spearman_ic(pred1, targ, w).mean())
    assert ic1 > ic0


def test_rank_ic_loss_ignores_padded_slots():
    rng = np.random.default_rng(6)
    pred = rng.standard_normal((2, 20)).astype(np.float32)
    targ = rng.standard_normal((2, 20)).astype(np.float32)
    w = np.ones((2, 20), np.float32)
    w[:, 15:] = 0.0
    base = float(rank_ic_loss(jnp.asarray(pred), jnp.asarray(targ), jnp.asarray(w)))
    pred2 = pred.copy()
    pred2[:, 15:] = 1e6
    poisoned = float(
        rank_ic_loss(jnp.asarray(pred2), jnp.asarray(targ), jnp.asarray(w))
    )
    assert poisoned == pytest.approx(base, abs=1e-4)


def _numpy_rank_ic(pred, target, w, temperature=0.5, tt=1e-3):
    """Float64 numpy mirror of rank_ic_loss, chunked over rows so the
    n² pairwise matrix never materializes whole."""
    def srank(x, temp):
        out = np.zeros_like(x, dtype=np.float64)
        for d in range(x.shape[0]):
            xi = x[d].astype(np.float64)
            for lo in range(0, xi.size, 1000):
                diff = (xi[lo:lo + 1000, None] - xi[None, :]) / temp
                with np.errstate(over="ignore"):  # exp overflow → inf → p=0
                    p = np.where(w[d][None, :] > 0,
                                 1.0 / (1.0 + np.exp(-diff)), 0.0)
                out[d, lo:lo + 1000] = p.sum(axis=1)
        return out

    pr, tr = srank(pred, temperature), srank(target, tt)
    ics = []
    for d in range(pred.shape[0]):
        wd = w[d].astype(np.float64)
        a = pr[d] - (pr[d] * wd).sum() / wd.sum()
        b = tr[d] - (tr[d] * wd).sum() / wd.sum()
        a, b = a * wd, b * wd
        ics.append((a * b).sum() /
                   max(np.sqrt((a * a).sum() * (b * b).sum()), 1e-8))
    return -float(np.mean(ics))


@pytest.mark.slow  # ~1 min of 8000² pairwise sums on CPU
def test_rank_ic_loss_full_universe_n8000_matches_numpy():
    """Pin the loss at c3's FULL-cross-section width (n=8000, the
    full-universe training mode) against a float64 numpy mirror — the
    f32 pairwise sums must hold up at 8000² pair counts."""
    rng = np.random.default_rng(42)
    n = 8000
    pred = rng.standard_normal((1, n)).astype(np.float32)
    target = (0.3 * pred + 0.7 *
              rng.standard_normal((1, n))).astype(np.float32)
    w = np.ones((1, n), np.float32)
    w[0, -137:] = 0.0  # padded tail, as the full-universe sampler emits
    got = float(jax.jit(rank_ic_loss)(pred, target, w))
    want = _numpy_rank_ic(pred, target, w)
    assert abs(got - want) < 2e-4, (got, want)
    # parts must reassemble to the same value at this width too
    num, den = jax.jit(make_loss_parts("rank_ic"))(pred, target, w)
    assert abs(float(finalize_loss(num, den)) - want) < 2e-4


def test_rank_ic_loss_bf16_inputs_upcast():
    """bf16 model outputs must not quantize ranks: the loss upcasts, so
    bf16 inputs give ≈ the f32 answer even at n >> 256."""
    rng = np.random.default_rng(7)
    n = 2048
    pred = rng.standard_normal((2, n)).astype(np.float32)
    target = rng.standard_normal((2, n)).astype(np.float32)
    w = np.ones((2, n), np.float32)
    f32 = float(rank_ic_loss(pred, target, w))
    bf = float(rank_ic_loss(jnp.asarray(pred, jnp.bfloat16),
                            jnp.asarray(target, jnp.bfloat16), w))
    assert abs(f32 - bf) < 0.02, (f32, bf)
