"""Serving request-shape buckets + key/stats pure logic (serve lane).

The device-free half of the scoring-service contract
(lfm_quant_tpu/serve/): bucket quantization, program/routing key
collision-freedom, knob parsing, and the latency-percentile formula
shared (by pinned duplication) with ``scripts/trace_report.py``. The
integration half — dispatch parity, steady-state counters, refresh
under traffic — lives in tests/test_serve.py.
"""

import os

import numpy as np
import pytest

from lfm_quant_tpu.serve import buckets
from lfm_quant_tpu.serve.buckets import (
    bucket_rows,
    bucket_width,
    rows_ladder,
    width_ladder,
)
from lfm_quant_tpu.serve.stats import latency_summary, percentile
from lfm_quant_tpu.train import reuse

pytestmark = pytest.mark.serve


def test_bucket_quantization():
    assert bucket_width(1) == 8 and bucket_width(8) == 8
    assert bucket_width(9) == 16 and bucket_width(1000) == 1024
    assert bucket_rows(1, 8) == 1 and bucket_rows(3, 8) == 4
    assert bucket_rows(100, 8) == 8  # capped at the batcher's max
    with pytest.raises(ValueError):
        bucket_width(0)
    with pytest.raises(ValueError):
        bucket_rows(0, 8)


def test_bucket_ladders_are_finite_and_cover():
    """Warmup pre-traces rows_ladder × width_ladder; every shape the
    batcher can produce must be a ladder member — that totality is the
    zero-compile-steady-state argument."""
    assert rows_ladder(8) == [1, 2, 4, 8]
    assert rows_ladder(6) == [1, 2, 4, 8]  # cap rounds up to its bucket
    assert rows_ladder(1) == [1]
    assert width_ladder([5, 9, 12, 900]) == [8, 16, 1024]
    assert width_ladder([]) == []
    for n in range(1, 64):
        assert bucket_rows(n, 8) in rows_ladder(8)
    for n in (1, 7, 8, 9, 100, 513):
        assert bucket_width(n) in width_ladder([n])


def test_serve_program_key_no_collisions():
    """Keys for distinct (inner program, bucket) pairs are distinct by
    CONSTRUCTION (tagged tuples — no positional/concatenation ambiguity
    for adversarial universe names or generation numbers to exploit)."""
    inner_a = ("trainer", "cpu", ("geometry", 1))
    inner_b = ("trainer", "cpu", ("geometry", 2))
    keys = {
        reuse.serve_program_key(inner_a, (1, 64)),
        reuse.serve_program_key(inner_a, (16, 4)),   # rows/width swapped
        reuse.serve_program_key(inner_a, (4, 16)),
        reuse.serve_program_key(inner_a, (1, 128)),
        reuse.serve_program_key(inner_b, (1, 64)),
        reuse.serve_program_key(inner_b, (1, 128)),
    }
    assert len(keys) == 6
    # And none collides with a trainer/ensemble/foldstack-tagged key.
    assert all(k[0] == "serve" for k in keys)


@pytest.mark.bucketed
def test_train_bucket_key_family_collision_free():
    """The TRAINING geometry-bucket key family (PR 8,
    ``reuse.train_bucket_program_key``) cannot collide with any existing
    family — trainer/ensemble/foldstack/stacked/serve — nor with itself
    across distinct (inner, bucket) pairs. Critically, a serve bucket
    and a train bucket with the SAME numbers are DIFFERENT keys (serve's
    is (rows, width), train's is (lookback, width) — only the leading
    tag separates them, and it does)."""
    inner = ("trainer", "cpu", ("geometry", 1))
    ens = ("ensemble", inner, None, 4, 0)
    keys = [
        reuse.train_bucket_program_key(inner, (8, 64)),
        reuse.train_bucket_program_key(inner, (64, 8)),   # dims swapped
        reuse.train_bucket_program_key(inner, (16, 64)),
        reuse.train_bucket_program_key(ens, (8, 64)),     # ensemble twin
        reuse.serve_program_key(inner, (8, 64)),          # same numbers!
        reuse.foldstack_program_key(inner, None, 8, 64),
        reuse.stacked_program_key(inner, None, 8, 64, "config", ()),
        reuse.ensemble_program_key(inner, None, 8, 64),
    ]
    assert len(set(keys)) == len(keys), keys
    tags = {k[0] for k in keys}
    assert tags == {"trainbucket", "serve", "foldstack", "stacked",
                    "ensemble"}
    # The shared ladder helpers serve re-exports ARE the shared module's
    # (promotion left one implementation, not a fork).
    from lfm_quant_tpu import buckets as shared

    assert buckets.next_pow2 is shared.next_pow2
    assert buckets.bucket_width is shared.bucket_width
    assert buckets.rows_ladder is shared.rows_ladder
    assert buckets.width_ladder is shared.width_ladder
    assert buckets.MIN_WIDTH == shared.MIN_WIDTH


@pytest.mark.stacked
def test_stacked_program_key_families_collision_free():
    """The three stacked program-key families — foldstack, generic
    stacked (train/stacked.py), serve — plus the trainer/ensemble keys
    they wrap cannot collide, whatever their inner components: every
    family leads with its own tag and every varying field is a tagged
    tuple component (no positional ambiguity for adversarial geometry
    to exploit). The stacked key carries operand NAMES, never values —
    that absence is the engine's compile-once property."""
    inner = ("trainer", "cpu", ("cpu", 0), 1)
    keys = [
        reuse.foldstack_program_key(inner, None, 4, 5),
        reuse.foldstack_program_key(inner, None, 4, 5, block=2),
        reuse.foldstack_program_key(inner, None, 5, 4),
        reuse.stacked_program_key(inner, None, 4, 5, "config", ()),
        reuse.stacked_program_key(inner, None, 4, 5, "config",
                                  ("lr", "weight_decay")),
        reuse.stacked_program_key(inner, None, 4, 5, "config",
                                  ("lr", "weight_decay"), block=2),
        reuse.stacked_program_key(inner, None, 4, 5, "seed",
                                  ("lr", "weight_decay")),
        reuse.stacked_program_key(inner, None, 5, 4, "config",
                                  ("lr", "weight_decay")),
        reuse.serve_program_key(inner, (4, 5)),
        reuse.serve_program_key(inner, (5, 4)),
        reuse.ensemble_program_key(inner, None, 4, 5),
    ]
    assert len(set(keys)) == len(keys), keys
    # Distinct families stay distinct even with identical geometry
    # numbers — the leading tag is the separator.
    tags = {k[0] for k in keys}
    assert {"foldstack", "stacked", "serve", "ensemble"} <= tags


@pytest.mark.amp
def test_precision_key_membership_all_families_collision_free(monkeypatch):
    """The compute-precision lane (LFM_PRECISION / RunConfig.precision,
    DESIGN.md §17) is a tagged member of the TRAINER program key — and
    because every other family (ensemble / foldstack / stacked / serve /
    trainbucket) embeds that inner key, the lane is a member of ALL SIX
    families: the same geometry under f32 vs bf16 yields twelve distinct
    keys, collision-free across lanes and families alike."""
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)

    cfg = RunConfig(name="k", data=DataConfig(), model=ModelConfig(),
                    optim=OptimConfig())

    def trainer_key():
        return reuse.trainer_program_key(cfg, None, 1, "xla", "xla",
                                         "xla", 6, 10)

    monkeypatch.delenv("LFM_PRECISION", raising=False)
    k32 = trainer_key()
    monkeypatch.setenv("LFM_PRECISION", "bf16")
    k16 = trainer_key()
    assert ("precision", "f32") in k32
    assert ("precision", "bf16") in k16
    assert k32 != k16
    # The config field routes into the key too (env deleted).
    monkeypatch.delenv("LFM_PRECISION", raising=False)
    import dataclasses

    cfg = dataclasses.replace(cfg, precision="bf16")
    assert trainer_key() == k16

    def family(inner):
        return [
            inner,
            reuse.ensemble_program_key(inner, None, 4, 0),
            reuse.foldstack_program_key(inner, None, 4, 5),
            reuse.stacked_program_key(inner, None, 4, 5, "config",
                                      ("lr", "weight_decay")),
            reuse.serve_program_key(inner, (8, 64)),
            reuse.train_bucket_program_key(inner, (8, 64)),
        ]

    keys = family(k32) + family(k16)
    assert len(set(keys)) == 12, keys
    # Equal-but-for-precision pairs differ ONLY through the inner key —
    # proving membership in every derived family, not just the trainer's.
    for a, b in zip(family(k32), family(k16)):
        assert a != b


def test_serve_knob_defaults(monkeypatch):
    for var in ("LFM_SERVE_MAX_ROWS", "LFM_SERVE_MAX_WAIT_MS",
                "LFM_SERVE_ZOO"):
        monkeypatch.delenv(var, raising=False)
    assert buckets.max_rows_default() == 8
    assert buckets.max_wait_ms_default() == 2.0
    assert buckets.zoo_capacity_default() == 8
    monkeypatch.setenv("LFM_SERVE_MAX_ROWS", "16")
    monkeypatch.setenv("LFM_SERVE_MAX_WAIT_MS", "0.5")
    monkeypatch.setenv("LFM_SERVE_ZOO", "0")  # floored at 1
    assert buckets.max_rows_default() == 16
    assert buckets.max_wait_ms_default() == 0.5
    assert buckets.zoo_capacity_default() == 1


def _load_trace_report():
    from lfm_quant_tpu.serve.stats import load_trace_report

    return load_trace_report(os.path.join(os.path.dirname(__file__), ".."))


def test_percentile_formula_matches_trace_report_twin():
    """The duplicated percentile implementations (serve/stats.py and
    scripts/trace_report.py — the script must stay dependency-free)
    are pinned equal on adversarial samples, and to numpy."""
    tr = _load_trace_report()
    rng = np.random.default_rng(0)
    for vals in ([1.0], [3.0, 1.0], list(rng.uniform(0, 50, 97)),
                 [2.0] * 10, list(rng.exponential(5, 256))):
        for q in (50.0, 90.0, 99.0):
            a, b = percentile(vals, q), tr._pctl(list(vals), q)
            assert a == b
            assert a == pytest.approx(float(np.percentile(vals, q)))
    assert percentile([], 50.0) is None and tr._pctl([], 50.0) is None


def test_latency_summary_fields():
    s = latency_summary([4.0, 1.0, 2.0, 3.0])
    assert s["requests"] == 4
    assert s["p50_ms"] == pytest.approx(2.5)
    assert s["max_ms"] == 4.0
    empty = latency_summary([])
    assert empty["requests"] == 0
    assert empty["p50_ms"] is None and empty["max_ms"] is None
