"""Test configuration: force an 8-device virtual CPU platform.

SURVEY.md §5: distributed code paths are exercised in CI via a virtual
multi-device CPU platform, no pod needed. NOTE: a pytest plugin imports
jax before this conftest runs, so env vars set here are only honored as
long as no backend has been initialized yet — importing jax does NOT
initialize a backend, so both knobs below normally land in time.

Two mechanisms, newest first:
  * ``jax.config.update("jax_num_cpu_devices", 8)`` — the first-class
    option on newer jax. On jax 0.4.x it raises AttributeError
    ("Unrecognized config option"), which used to kill the ENTIRE suite
    at conftest import.
  * ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` — the legacy
    fallback, read at backend init. Only appended when the config option
    is missing (setting both on newer jax can conflict).

If a plugin already initialized the backend before this ran (the race
the old comment warned about), both knobs are too late; rather than
hard-crash every mesh test on a 1-device platform, multi-device tests
are skip-marked at collection (see ``pytest_collection_modifyitems``)
and ``pytest_report_header`` shows the device count actually in effect.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # belt-and-braces for subprocesses
os.environ.setdefault("JAX_ENABLE_X64", "0")
# bench.main() preempts live campaign/watcher processes to clear the chip
# for a driver capture (bench._preempt_campaign). Tests exercising main()
# must NEVER signal a real watcher running on this machine (it happened:
# a wedged-path test killed the armed recovery watcher). The dedicated
# preemption test re-enables it against monkeypatched marker patterns.
os.environ["LFM_BENCH_NO_PREEMPT"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax 0.4.x: the option does not exist — fall back to XLA_FLAGS,
    # which the CPU client reads when the backend initializes.
    _FLAG = "--xla_force_host_platform_device_count=8"
    if _FLAG not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()

# Test modules whose in-process tests build >1-device meshes (or assert
# the 8-device platform outright). Skipped — not crashed — when the
# fallback lost the init race and only 1 device exists. Subprocess-based
# suites (test_pod_scale, test_distributed) set their own XLA_FLAGS in
# the child and need no mark.
_MULTI_DEVICE_MODULES = ("test_parallel.py", "test_ring.py")


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"


def pytest_collection_modifyitems(config, items):
    if jax.device_count() >= 8:
        return
    import pytest

    skip = pytest.mark.skip(
        reason=f"needs the 8-device virtual CPU platform, have "
               f"{jax.device_count()} (backend initialized before "
               "conftest could configure it)")
    for item in items:
        if os.path.basename(str(item.fspath)) in _MULTI_DEVICE_MODULES:
            item.add_marker(skip)
