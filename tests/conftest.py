"""Test configuration: force an 8-device virtual CPU platform.

Must run before jax initializes (SURVEY.md §5: distributed code paths are
exercised in CI via ``--xla_force_host_platform_device_count=8`` with no
pod). Keeping tests on CPU also keeps them hermetic w.r.t. the single real
TPU chip used for benchmarking.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_debug_nans", False)


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"
