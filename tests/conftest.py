"""Test configuration: force an 8-device virtual CPU platform.

SURVEY.md §5: distributed code paths are exercised in CI via a virtual
multi-device CPU platform, no pod needed. NOTE: a pytest plugin imports
jax before this conftest runs, so env vars (JAX_PLATFORMS/XLA_FLAGS) are
too late — we must go through jax.config, which takes effect as long as no
backend has been initialized yet.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # belt-and-braces for subprocesses
os.environ.setdefault("JAX_ENABLE_X64", "0")
# bench.main() preempts live campaign/watcher processes to clear the chip
# for a driver capture (bench._preempt_campaign). Tests exercising main()
# must NEVER signal a real watcher running on this machine (it happened:
# a wedged-path test killed the armed recovery watcher). The dedicated
# preemption test re-enables it against monkeypatched marker patterns.
os.environ["LFM_BENCH_NO_PREEMPT"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


def pytest_report_header(config):
    return f"jax devices: {jax.device_count()} ({jax.default_backend()})"
