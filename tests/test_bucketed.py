"""Geometry-bucket lane (LFM_BUCKETS; data/windows.py bucket ladder,
train/loop.py BucketPrograms, DESIGN.md §16).

The tentpole contracts, all measured:

* **Bit-parity** — a bucketed batch run through the bucket programs
  equals the SAME batch padded to max shape run through the max-shape
  programs, bit for bit: training loss/updated params, eval forecasts
  and per-month ICs, and the stacked engine's shared gradient path.
  The mask contract (weight-0 pad columns are exact no-ops; masked RNN
  steps hold state exactly) is what makes this an equality, not a
  tolerance.
* **Compile-once** — a warm same-geometry bucketed fit pays ZERO jit
  traces and ZERO panel H2D, with ONE host sync per epoch (the reuse
  contract with bucketing ON); per-bucket programs ride the tagged
  ``trainbucket`` key family through the shared program cache.
* **Loud degrade** — the stacked-run engines reject LFM_BUCKETS with
  ``StackUnavailable`` and the drivers degrade to the (bucket-capable)
  sequential path with a warning + ``stack_degraded`` instant +
  ``stack_degrades`` counter, never silently.

Pure-ladder arithmetic and key-family collision tests live in
tests/test_buckets.py (the device-free early lane).
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.buckets import (
    bucket_lookback,
    buckets_enabled,
    capped_width,
    lookback_rungs,
    width_rungs,
)
from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import DateBatchSampler, clear_panel_cache
from lfm_quant_tpu.train import reuse
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.bucketed


@pytest.fixture(autouse=True)
def _fresh_caches(monkeypatch):
    """Deterministic counters + the knob OFF unless a test opts in."""
    monkeypatch.delenv("LFM_BUCKETS", raising=False)
    reuse.clear_program_cache()
    clear_panel_cache()
    yield
    reuse.clear_program_cache()
    clear_panel_cache()


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)


def _cfg(tmp, n_seeds=1, epochs=2, kind="mlp", **model_kwargs):
    kwargs = {"hidden": (16,)} if kind == "mlp" else {"hidden": 8}
    kwargs.update(model_kwargs)
    return RunConfig(
        name="bk",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind=kind, kwargs=kwargs),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=5,
                          early_stop_patience=epochs + 1, loss="mse"),
        seed=0,
        n_seeds=n_seeds,
        out_dir=str(tmp),
    )


def _splits(panel):
    return PanelSplits.by_date(panel, 198001, 198201)


# ---- ladder / geometry (host-side) ---------------------------------------


def test_knob_default_off(monkeypatch):
    monkeypatch.delenv("LFM_BUCKETS", raising=False)
    assert not buckets_enabled()
    monkeypatch.setenv("LFM_BUCKETS", "1")
    assert buckets_enabled()
    monkeypatch.setenv("LFM_BUCKETS", "0")
    assert not buckets_enabled()


def test_ladders_are_finite_and_cover():
    assert width_rungs(32) == [8, 16, 32]
    assert width_rungs(77) == [8, 16, 32, 64, 77]  # cap is a member
    for n in range(1, 200):
        assert capped_width(n, 77) in width_rungs(77)
    assert lookback_rungs(60) == [8, 16, 32, 60]
    assert lookback_rungs(12) == [8, 12]
    assert lookback_rungs(8) == [8]
    for d in range(0, 61):
        assert bucket_lookback(d, 60) in lookback_rungs(60)
        assert bucket_lookback(d, 60) >= min(d, 60)


def test_bucket_geometry_partitions_and_fills(panel):
    s = DateBatchSampler(panel, 12, 4, 32, seed=0)
    geo = s.bucket_geometry()
    # Every training date lands in exactly one bucket; every bucket
    # fills at least one whole [D]-date batch (the fold rule).
    all_dates = np.concatenate(list(geo.train_buckets.values()))
    assert sorted(all_dates.tolist()) == sorted(s._dates.tolist())
    for (lb, w), dates in geo.train_buckets.items():
        assert dates.size >= s.dates_per_batch
        assert lb in lookback_rungs(12) and w in width_rungs(32)
    # Eval buckets cover every stacked month position exactly once.
    pos = np.concatenate(list(geo.eval_buckets.values()))
    assert sorted(pos.tolist()) == list(range(s.stacked_eval_months()))
    # The summary's cell budgets are consistent.
    summ = geo.summary(4)
    assert summ["train_cells_bucketed"] <= summ["train_cells_max_shape"]
    assert summ["eval_cells_bucketed"] < summ["eval_cells_max_shape"]


def test_bucketed_epoch_deterministic_and_shape_stable(panel):
    s = DateBatchSampler(panel, 12, 4, 32, seed=3)
    a = s.bucketed_epoch(1)
    b = s.bucketed_epoch(1)
    assert [k for k, _ in a] == [k for k, _ in b]
    for (_, x), (_, y) in zip(a, b):
        assert np.array_equal(x.firm_idx, y.firm_idx)
        assert np.array_equal(x.weight, y.weight)
    c = s.bucketed_epoch(2)  # different shuffle, SAME shapes
    for (ka, x), (kc, y) in zip(a, c):
        assert ka == kc and x.firm_idx.shape == y.firm_idx.shape
    assert any(not np.array_equal(x.firm_idx, y.firm_idx)
               for (_, x), (_, y) in zip(a, c))
    assert (sum(x.firm_idx.shape[0] for _, x in a)
            == s.bucketed_batches_per_epoch())


def test_lookback_rung_respects_history_gaps(panel):
    """A firm with a valid month DEEP in the window must pin its months
    to the full window — counting valid months alone would truncate
    gapped histories and break bit-parity."""
    s = DateBatchSampler(panel, 12, 4, 32, seed=0)
    months = s._all_dates
    rung = s._safe_lookback_rung(months)
    full = np.cumsum(s._valid.astype(np.int64), axis=1)
    for t in months[:40]:
        t = int(t)
        pool = s._firms_by_date[t]
        r = rung[t]
        if r < s.window:
            lo = max(0, t - s.window + 1)
            hi = t - r  # inclusive end of the dropped gap
            if hi >= lo:
                gap = (full[pool, hi]
                       - (full[pool, lo - 1] if lo else 0)).max()
                assert gap == 0


# ---- bit-parity vs max-shape padding --------------------------------------


def _pad_train_batch(b, bf):
    """Pad a [D, w] train batch to [D, bf] with weight-0 repeats of the
    first column — the max-shape twin of the same batch."""
    d, w = b.firm_idx.shape
    fi = np.concatenate(
        [b.firm_idx,
         np.repeat(b.firm_idx[:, :1], bf - w, axis=1)], axis=1)
    wt = np.concatenate(
        [b.weight, np.zeros((d, bf - w), np.float32)], axis=1)
    return fi, b.time_idx, wt


def test_train_step_bit_parity(panel, tmp_path, monkeypatch):
    """One bucketed multi-step dispatch == the same batch padded to max
    shape through the max-shape program: loss and updated params bit
    identical (GRU as well as MLP — the masked-scan contract)."""
    for kind in ("mlp", "gru"):
        monkeypatch.setenv("LFM_BUCKETS", "1")
        tr = Trainer(_cfg(tmp_path, kind=kind), _splits(panel))
        state = tr.init_state()
        parts = tr.train_sampler.bucketed_epoch(0)
        # A genuinely narrow bucket (below the cap) when one exists.
        bucket, b = min(parts, key=lambda p: p[0][1] * p[0][0])
        bp = tr.programs.bucket_programs(tr.program_key, bucket)
        one = lambda a: jnp.asarray(a[:1])  # [1, D, w] single-step stack
        st_b, ms_b = bp._jit_multi_step(
            jax.tree.map(jnp.copy, state), tr.dev,
            one(b.firm_idx), one(b.time_idx), one(b.weight))
        fi, ti, wt = _pad_train_batch(
            dataclasses.replace(b, firm_idx=b.firm_idx[0],
                                time_idx=b.time_idx[0],
                                weight=b.weight[0]),
            tr.cfg.data.firms_per_date)
        st_m, ms_m = tr._jit_multi_step(
            jax.tree.map(jnp.copy, state), tr.dev,
            jnp.asarray(fi[None]), jnp.asarray(ti[None]),
            jnp.asarray(wt[None]))
        assert np.array_equal(np.asarray(ms_b["loss"]),
                              np.asarray(ms_m["loss"])), kind
        for a, c in zip(jax.tree.leaves(st_b.params),
                        jax.tree.leaves(st_m.params)):
            assert np.array_equal(np.asarray(a), np.asarray(c)), kind
        reuse.clear_program_cache()
        clear_panel_cache()


def test_eval_forward_bit_parity(panel, tmp_path, monkeypatch):
    """Bucketed eval forward == max-shape eval forward per month:
    forecasts at real cells and per-month ICs bit-identical."""
    monkeypatch.setenv("LFM_BUCKETS", "1")
    tr = Trainer(_cfg(tmp_path, kind="gru"), _splits(panel))
    params = tr.init_state().params
    vb = tr.val_sampler.stacked_cross_sections()
    pred_m, ic_m, _ = tr._jit_forward(
        params, tr.dev, jnp.asarray(vb.firm_idx), jnp.asarray(vb.time_idx),
        jnp.asarray(vb.weight))
    pred_m, ic_m = np.asarray(pred_m), np.asarray(ic_m)
    for bucket, b, pos in tr.val_sampler.bucketed_cross_sections():
        bp = tr.programs.bucket_programs(tr.program_key, bucket)
        pred_b, ic_b, _ = bp._jit_forward(
            params, tr.dev, jnp.asarray(b.firm_idx),
            jnp.asarray(b.time_idx), jnp.asarray(b.weight))
        pred_b, ic_b = np.asarray(pred_b), np.asarray(ic_b)
        assert np.array_equal(ic_b, ic_m[pos])
        real = b.weight > 0
        w = real.shape[1]
        assert np.array_equal(pred_b[real], pred_m[pos][:, :w][real])


def test_stacked_grads_path_parity(panel, tmp_path, monkeypatch):
    """The stacked engine's shared gradient code (_grads_impl — what the
    per-run-operand hyper step consumes) honors the parity: a bucketed
    batch's LOSS equals the max-shape-padded twin's bit-for-bit. The
    gradients are pinned to last-ulp only: these standalone-jitted
    programs let XLA pick width-dependent reduction tilings whose
    partition boundaries re-associate the REAL rows (padding itself is
    exact) — the production multi-step programs come out bit-equal end
    to end (test_train_step_bit_parity pins params after an update),
    which is the contract that matters."""
    monkeypatch.setenv("LFM_BUCKETS", "1")
    tr = Trainer(_cfg(tmp_path), _splits(panel))
    state = tr.init_state()
    bucket, b = min(tr.train_sampler.bucketed_epoch(0),
                    key=lambda p: p[0][1] * p[0][0])
    lb, _w = bucket
    g_b = jax.jit(lambda s, f, t, w: tr.programs._grads_impl(
        s, tr.dev, f, t, w, window=lb))(
            state, jnp.asarray(b.firm_idx[0]), jnp.asarray(b.time_idx[0]),
            jnp.asarray(b.weight[0]))
    fi, ti, wt = _pad_train_batch(
        dataclasses.replace(b, firm_idx=b.firm_idx[0],
                            time_idx=b.time_idx[0], weight=b.weight[0]),
        tr.cfg.data.firms_per_date)
    g_m = jax.jit(lambda s, f, t, w: tr.programs._grads_impl(
        s, tr.dev, f, t, w))(
            state, jnp.asarray(fi), jnp.asarray(ti), jnp.asarray(wt))
    assert np.array_equal(np.asarray(g_b[0]), np.asarray(g_m[0]))
    for a, c in zip(jax.tree.leaves(g_b[1]), jax.tree.leaves(g_m[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-6, atol=1e-8)


def test_predict_bit_identical_to_max_shape(panel, tmp_path, monkeypatch):
    """Pure inference: bucketed predict == max-shape predict for the
    same params, over the whole panel scatter (single-seed + ensemble)."""
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    cfg = _cfg(tmp_path, kind="gru")
    tr = Trainer(cfg, _splits(panel))
    tr.state = tr.init_state()
    pred0, valid0 = tr.predict()
    monkeypatch.setenv("LFM_BUCKETS", "1")
    trb = Trainer(cfg, _splits(panel))
    trb.state = tr.state
    predb, validb = trb.predict()
    assert np.array_equal(pred0, predb) and np.array_equal(valid0, validb)

    cfg2 = _cfg(tmp_path, n_seeds=2)
    etb = EnsembleTrainer(cfg2, _splits(panel))
    etb.state = etb.init_state()
    pe_b, ve_b = etb.predict()
    monkeypatch.delenv("LFM_BUCKETS")
    reuse.clear_program_cache()
    et = EnsembleTrainer(cfg2, _splits(panel))
    et.state = etb.state
    pe, ve = et.predict()
    assert np.array_equal(pe, pe_b) and np.array_equal(ve, ve_b)


# ---- compile-once / reuse contract ---------------------------------------


@pytest.mark.reuse
def test_warm_bucketed_fit_zero_traces_zero_h2d(panel, tmp_path,
                                                monkeypatch):
    """The reuse-lane guard with bucketing ON: a warm same-geometry
    bucketed fit pays zero jit traces, zero panel H2D, zero program
    rebuilds — and exactly ONE host sync per epoch (the PR 3 contract
    through the per-bucket dispatch chain)."""
    monkeypatch.setenv("LFM_BUCKETS", "1")
    epochs = 3
    tr = Trainer(_cfg(tmp_path, epochs=epochs), _splits(panel))
    tr.fit()  # cold: every bucket program traces once
    snap = REUSE_COUNTERS.snapshot()
    tr.rebind(splits=_splits(panel))
    fit = tr.fit()
    d = REUSE_COUNTERS.delta(snap)
    assert fit["epochs_run"] == epochs
    assert d["jit_traces"] == 0
    assert d["panel_transfers"] == 0
    assert d["program_cache_misses"] == 0
    assert d["host_syncs"] == epochs


def test_bucketed_fit_trains_and_val_ic_matches_max_shape_eval(
        panel, tmp_path, monkeypatch):
    """End-to-end bucketed fit sanity + the val-sweep parity corollary:
    the recorded val IC of the final state equals the max-shape
    evaluate() of the same params (per-month ICs are bit-identical, and
    finish() aggregates them identically)."""
    monkeypatch.setenv("LFM_BUCKETS", "1")
    tr = Trainer(_cfg(tmp_path, kind="gru"), _splits(panel))
    fit = tr.fit()
    assert fit["epochs_run"] == 2
    assert np.isfinite(fit["history"][-1]["val_ic"])
    monkeypatch.delenv("LFM_BUCKETS")
    reuse.clear_program_cache()
    tr2 = Trainer(_cfg(tmp_path, kind="gru"), _splits(panel))
    ev = tr2.evaluate(tr.state.params)
    assert fit["history"][-1]["val_ic"] == pytest.approx(ev["ic"], abs=0)


def test_bucketed_steps_drive_schedule_and_harness(panel, tmp_path,
                                                   monkeypatch):
    """The LR-schedule horizon and FitHarness step arithmetic follow the
    BUCKETED step count (per-bucket flooring), not the max-shape one."""
    monkeypatch.setenv("LFM_BUCKETS", "1")
    tr = Trainer(_cfg(tmp_path), _splits(panel))
    want = tr.train_sampler.bucketed_batches_per_epoch()
    assert tr._steps_per_epoch == want
    assert tr.program_key[5][-1] == want  # optimizer tuple's last field
    fit = tr.fit()
    assert fit["steps"] == want * fit["epochs_run"]


# ---- loud degrade (stacked engines) --------------------------------------


def test_stacked_sweep_degrades_loudly_under_buckets(panel, tmp_path,
                                                     monkeypatch):
    from lfm_quant_tpu.train.stacked import run_config_sweep
    from lfm_quant_tpu.utils import telemetry

    monkeypatch.setenv("LFM_BUCKETS", "1")
    before = telemetry.COUNTERS.get("stack_degrades")
    grid = [{"lr": 1e-3}, {"lr": 5e-4}]
    with pytest.warns(UserWarning, match="LFM_BUCKETS"):
        summary = run_config_sweep(_cfg(tmp_path), grid, panel=panel,
                                   out_dir=str(tmp_path / "sweep"))
    assert summary["stacked"] is None  # sequential (bucketed) path ran
    assert telemetry.COUNTERS.get("stack_degrades") == before + 1
    assert len(summary["runs"]) == 2
    assert all(np.isfinite(r["best_val_ic"]) for r in summary["runs"])


# ---- fold × config product driver (satellite) ----------------------------


def test_walkforward_sweep_product(panel, tmp_path):
    """--sweep-grid × --walk-forward wiring: the F × C product trains as
    ONE stack (per-run (cfg, splits) pairs) and the summary ranks
    configs by mean best val IC across folds."""
    from lfm_quant_tpu.train.stacked import run_walkforward_sweep

    grid = [{"lr": 1e-3}, {"lr": 3e-4}]
    out = str(tmp_path / "wfs")
    summary = run_walkforward_sweep(
        _cfg(tmp_path, epochs=2), grid, panel=panel, start=198001,
        step_months=12, val_months=24, n_folds=2, train_months=60,
        out_dir=out)
    assert summary["n_folds"] == 2 and summary["n_configs"] == 2
    assert summary["stacked"] and summary["stacked"]["enabled"]
    assert summary["stacked"]["run_count"] == 4
    assert len(summary["by_config"]) == 2
    for bc in summary["by_config"]:
        assert len(bc["per_fold"]) == 2
        assert bc["mean_best_val_ic"] == pytest.approx(
            np.mean(bc["per_fold"]))
    assert summary["best_config"] == grid[summary["best_index"]]
    for k in range(2):
        for j in range(2):
            rd = os.path.join(out, f"fold_{k}", f"config_{j:03d}")
            assert os.path.exists(os.path.join(rd, "config.json"))
    assert os.path.exists(os.path.join(out, "sweep_summary.json"))


def test_walkforward_sweep_cli_guard():
    """Parse-time guard: the product mode rejects stitching-only flags."""
    import train as train_cli

    with pytest.raises(SystemExit):
        train_cli.main(["--preset", "c1", "--walk-forward", "12",
                        "--sweep-grid", "lr=1e-3,5e-4",
                        "--wf-score", "mean"])
