"""Real-data loader tests: long-format CSV → Panel with per-month
standardization, target alignment, and return-convention conversion."""

import numpy as np
import pandas as pd
import pytest

from lfm_quant_tpu.data.compustat import (
    load_compustat_csv,
    to_long_frame,
)
from lfm_quant_tpu.data.panel import synthetic_panel


def make_csv(tmp_path, n=40, t=60, f=3, seed=0, gaps=True):
    """Hand-built long-format fixture with known raw values."""
    rng = np.random.default_rng(seed)
    rows = []
    y0, m0 = 1990, 1
    months = [(y0 + (m0 + k - 1) // 12) * 100 + ((m0 + k - 1) % 12 + 1)
              for k in range(t)]
    for g in range(1, n + 1):
        start = int(rng.integers(0, t // 4)) if gaps else 0
        for j in range(start, t):
            if gaps and rng.random() < 0.02:
                continue
            rows.append({
                "gvkey": g,
                "yyyymm": months[j],
                "ebit_ev": rng.normal(loc=g * 0.01, scale=1.0),
                "bm": rng.normal(),
                "mom": rng.normal(),
                "ret": rng.normal() * 0.05,
            })
    path = str(tmp_path / "panel.csv")
    pd.DataFrame(rows).to_csv(path, index=False)
    return path, months


def test_load_shapes_and_masks(tmp_path):
    path, months = make_csv(tmp_path)
    p = load_compustat_csv(path, horizon=6)
    assert p.n_firms == 40
    assert p.n_months == 60
    assert p.feature_names == ["ebit_ev", "bm", "mom"]
    assert list(p.dates) == months
    p.validate()


def test_per_month_standardization(tmp_path):
    path, _ = make_csv(tmp_path)
    p = load_compustat_csv(path, horizon=6)
    for j in (5, 30, 55):
        sel = p.valid[:, j]
        if sel.sum() < 5:
            continue
        x = p.features[sel, j, :]
        np.testing.assert_allclose(x.mean(axis=0), 0.0, atol=1e-4)
        np.testing.assert_allclose(x.std(axis=0), 1.0, atol=1e-3)


def test_winsorization_tames_outliers(tmp_path):
    rng = np.random.default_rng(1)
    rows = []
    for g in range(1, 101):
        rows.append({"gvkey": g, "yyyymm": 200001,
                     "ebit_ev": 1e6 if g == 1 else rng.normal(),
                     "ret": 0.0})
    path = str(tmp_path / "w.csv")
    pd.DataFrame(rows).to_csv(path, index=False)
    p = load_compustat_csv(path, horizon=1, winsor=(0.01, 0.99))
    # The 1e6 outlier must be clipped to the 99th pct before z-scoring.
    assert abs(p.features[0, 0, 0]) < 5.0


def test_target_is_future_standardized_value(tmp_path):
    path, _ = make_csv(tmp_path, gaps=False)
    h = 6
    p = load_compustat_csv(path, target_col="ebit_ev", horizon=h)
    k = p.feature_names.index("ebit_ev")
    tv = p.target_valid
    # target[i, t] == standardized feature at t+h wherever both ends valid.
    np.testing.assert_allclose(
        p.targets[:, :-h][tv[:, :-h]],
        p.features[:, h:, k][tv[:, :-h]],
        atol=1e-6,
    )
    assert not tv[:, -h:].any()


def test_return_convention_conversion(tmp_path):
    """File carries trailing returns; Panel.returns[t] must be the forward
    return (the file's row at t+1)."""
    rows = []
    vals = [0.01, 0.02, 0.03, 0.04]
    for j, (m, r) in enumerate(zip([200001, 200002, 200003, 200004], vals)):
        for g in (1, 2, 3, 4, 5):
            rows.append({"gvkey": g, "yyyymm": m, "ebit_ev": g * 0.1 + j,
                         "ret": r if g == 1 else 0.0})
    path = str(tmp_path / "r.csv")
    pd.DataFrame(rows).to_csv(path, index=False)
    p = load_compustat_csv(path, horizon=1)
    np.testing.assert_allclose(p.returns[0, :3], [0.02, 0.03, 0.04], atol=1e-6)
    assert p.returns[0, 3] == 0.0  # no forward month


def test_missing_months_invalid(tmp_path):
    path, months = make_csv(tmp_path, gaps=True)
    df = pd.read_csv(path)
    p = load_compustat_csv(path, horizon=6)
    present = set(zip(df["gvkey"], df["yyyymm"]))
    fpos = {g: i for i, g in enumerate(p.firm_ids)}
    dpos = {d: j for j, d in enumerate(p.dates)}
    rng = np.random.default_rng(0)
    for _ in range(200):
        g = int(rng.integers(1, 41))
        m = months[int(rng.integers(0, len(months)))]
        assert p.valid[fpos[g], dpos[m]] == ((g, m) in present)


def test_delisting_excluded_from_universe(tmp_path):
    """A firm with features at t but no t+1 row must be flagged
    ret_valid=False at t and excluded by the backtest universe — never
    credited a fabricated 0% return (delisting bias)."""
    rows = []
    for g in range(1, 31):
        last = 200004 if g == 1 else 200006  # firm 1 delists after April
        for m in [200001, 200002, 200003, 200004, 200005, 200006]:
            if m > last:
                continue
            rows.append({"gvkey": g, "yyyymm": m, "ebit_ev": g * 0.1 + m % 7,
                         "ret": 0.01})
    path = str(tmp_path / "dl.csv")
    pd.DataFrame(rows).to_csv(path, index=False)
    p = load_compustat_csv(path, horizon=1)
    i = list(p.firm_ids).index(1)
    assert p.valid[i, 3]           # April features exist
    assert not p.ret_valid[i, 3]   # but April's forward return is unobserved
    assert not p.tradeable()[i, 3]
    assert p.tradeable()[i, 2]     # March still tradeable (April row exists)

    from lfm_quant_tpu.backtest import run_backtest
    fc = np.tile(np.linspace(1, 0, 30)[:, None], (1, 6)).astype(np.float32)
    rep = run_backtest(fc, np.ones_like(p.valid), p, quantile=0.1,
                       min_universe=5)
    assert rep.n_months > 0  # engine consumed the masked panel cleanly


def test_duplicate_rows_rejected(tmp_path):
    rows = [{"gvkey": 1, "yyyymm": 200001, "ebit_ev": 1.0, "ret": 0.0}] * 2
    path = str(tmp_path / "d.csv")
    pd.DataFrame(rows).to_csv(path, index=False)
    with pytest.raises(ValueError, match="duplicate"):
        load_compustat_csv(path)


def test_missing_required_columns(tmp_path):
    path = str(tmp_path / "m.csv")
    pd.DataFrame([{"firm": 1, "month": 200001}]).to_csv(path, index=False)
    with pytest.raises(ValueError, match="gvkey"):
        load_compustat_csv(path)


def test_bad_target_col(tmp_path):
    path, _ = make_csv(tmp_path)
    with pytest.raises(ValueError, match="target_col"):
        load_compustat_csv(path, target_col="nonexistent")


def test_roundtrip_through_long_frame(tmp_path):
    """Panel → long frame → CSV → loader reproduces masks and date grid
    (values get re-standardized, so compare structure + rank order)."""
    p0 = synthetic_panel(n_firms=60, n_months=100, n_features=3, seed=9)
    df = to_long_frame(p0)
    path = str(tmp_path / "rt.csv")
    df.to_csv(path, index=False)
    p1 = load_compustat_csv(path, horizon=p0.horizon, winsor=None)
    assert p1.n_months == p0.n_months
    np.testing.assert_array_equal(p1.dates, p0.dates)
    # Months with a cross-section below the loader's min_cross_section are
    # invalidated by policy (degenerate z-scores); compare the rest.
    ok = p0.valid.sum(axis=0) >= 5
    assert ok.sum() > 90
    np.testing.assert_array_equal(p1.valid[:, ok], p0.valid[:, ok])
    assert not p1.valid[:, ~ok].any()
    # Cross-sectional rank order of feature 0 preserved by z-scoring.
    j = 50
    sel = p0.valid[:, j]
    a = p0.features[sel, j, 0]
    b = p1.features[:, j, 0][p1.valid[:, j]]
    assert np.array_equal(np.argsort(a), np.argsort(b))


def test_csv_reachable_from_config(tmp_path):
    """A CSV panel_path in the config must route through the CSV loader
    (the train.py surface for real data)."""
    from lfm_quant_tpu.config import DataConfig
    from lfm_quant_tpu.train.loop import resolve_panel

    path, _ = make_csv(tmp_path)
    p = resolve_panel(DataConfig(panel_path=path, horizon=6))
    assert p.feature_names == ["ebit_ev", "bm", "mom"]
    assert p.n_firms == 40
    # target_col flows through the config surface: targets become the
    # chosen column's standardized lead, not the first column's.
    p_bm = resolve_panel(DataConfig(panel_path=path, horizon=6,
                                    target_col="bm"))
    both = p.target_valid & p_bm.target_valid
    assert not np.allclose(p.targets[both], p_bm.targets[both])


def test_train_on_loaded_panel(tmp_path):
    """End-to-end: loader output trains through the standard pipeline."""
    from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
    from lfm_quant_tpu.data import PanelSplits
    from lfm_quant_tpu.train import Trainer

    p0 = synthetic_panel(n_firms=100, n_months=140, n_features=3, seed=10)
    df = to_long_frame(p0)
    path = str(tmp_path / "t.csv")
    df.to_csv(path, index=False)
    panel = load_compustat_csv(path, horizon=12, winsor=None)
    splits = PanelSplits.by_date(panel, 197808, 198001)
    cfg = RunConfig(
        name="csv",
        data=DataConfig(window=12, dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=3e-3, epochs=2, warmup_steps=5, loss="mse"),
        out_dir=str(tmp_path),
    )
    t = Trainer(cfg, splits)
    summary = t.fit()
    assert np.isfinite(summary["history"][-1]["train_loss"])
