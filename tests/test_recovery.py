"""Failure recovery, sanitizer, and profiler subsystems (SURVEY.md §6)."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import PanelSplits, synthetic_panel
from lfm_quant_tpu.train import Trainer
from lfm_quant_tpu.train.ensemble import EnsembleTrainer
from lfm_quant_tpu.utils import StepTimer, sanitized, trace_context
from lfm_quant_tpu.utils.debug import assert_finite_tree


def cfg_for(tmp, epochs, patience=99, n_seeds=1):
    return RunConfig(
        name="rec",
        data=DataConfig(n_firms=150, n_months=150, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=2e-3, epochs=epochs, warmup_steps=5,
                          early_stop_patience=patience, loss="mse"),
        seed=0,
        n_seeds=n_seeds,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=150, n_months=150, n_features=5, seed=41)


@pytest.fixture(scope="module")
def splits(panel):
    return PanelSplits.by_date(panel, 197910, 198101)


def test_resume_continues_from_crash(panel, splits, tmp_path):
    """Simulated preemption: 2 epochs, 'crash', resume to 5 — the resumed
    run continues at epoch 2 and ends with 5 epochs of metrics."""
    run_dir = str(tmp_path / "run")
    t1 = Trainer(cfg_for(tmp_path, epochs=2), splits, run_dir=run_dir)
    t1.fit()
    prog = json.load(open(os.path.join(run_dir, "fit_progress.json")))
    assert prog["epoch"] == 1

    t2 = Trainer(cfg_for(tmp_path, epochs=5), splits, run_dir=run_dir)
    summary = t2.fit(resume=True)
    assert summary["history"][0]["epoch"] == 2
    assert summary["history"][-1]["epoch"] == 4
    lines = [json.loads(l) for l in open(os.path.join(run_dir, "metrics.jsonl"))]
    assert [l["epoch"] for l in lines] == [0, 1, 2, 3, 4]
    # step counter carried through the crash (no restart from 0).
    assert lines[2]["step"] > lines[1]["step"]


def test_resume_with_no_checkpoint_starts_fresh(splits, tmp_path):
    run_dir = str(tmp_path / "fresh")
    t = Trainer(cfg_for(tmp_path, epochs=2), splits, run_dir=run_dir)
    summary = t.fit(resume=True)
    assert summary["history"][0]["epoch"] == 0


def test_resume_past_end_is_noop(splits, tmp_path):
    run_dir = str(tmp_path / "done")
    t1 = Trainer(cfg_for(tmp_path, epochs=3), splits, run_dir=run_dir)
    t1.fit()
    t2 = Trainer(cfg_for(tmp_path, epochs=3), splits, run_dir=run_dir)
    summary = t2.fit(resume=True)
    assert summary["history"] == []
    assert summary["epochs_run"] == 3  # reported from the completed run


def test_resume_after_early_stop_does_not_restart(splits, tmp_path):
    """A run that ended via early stopping must not train further on
    --resume (an automatic retry wrapper would otherwise change results)."""
    run_dir = str(tmp_path / "es")
    t1 = Trainer(cfg_for(tmp_path, epochs=10, patience=2), splits,
                 run_dir=run_dir)
    t1.cfg.optim.lr = 0.0  # no improvement after epoch 0 → stops at 3
    s1 = t1.fit()
    assert s1["epochs_run"] < 10
    t2 = Trainer(cfg_for(tmp_path, epochs=10, patience=2), splits,
                 run_dir=run_dir)
    s2 = t2.fit(resume=True)
    assert s2["history"] == [], "early-stopped run must stay stopped"


def test_resume_with_corrupt_sidecar_degrades_gracefully(splits, tmp_path):
    """A crash inside the persist window can corrupt fit_progress.json;
    resume must fall back to checkpoint-derived counters, not die."""
    run_dir = str(tmp_path / "corrupt")
    t1 = Trainer(cfg_for(tmp_path, epochs=2), splits, run_dir=run_dir)
    t1.fit()
    with open(os.path.join(run_dir, "fit_progress.json"), "w") as fh:
        fh.write('{"epoch": 1, "best_')  # truncated mid-dump
    t2 = Trainer(cfg_for(tmp_path, epochs=3), splits, run_dir=run_dir)
    summary = t2.fit(resume=True)
    assert summary["history"][0]["epoch"] == 2  # derived from ckpt step
    assert summary["history"][-1]["epoch"] == 2


def test_best_checkpoint_separate_from_latest(splits, tmp_path):
    run_dir = str(tmp_path / "bl")
    t = Trainer(cfg_for(tmp_path, epochs=3), splits, run_dir=run_dir)
    t.fit()
    assert glob.glob(os.path.join(run_dir, "ckpt", "latest", "*"))
    assert glob.glob(os.path.join(run_dir, "ckpt", "best", "*"))


def test_ensemble_resume(panel, splits, tmp_path):
    run_dir = str(tmp_path / "ens")
    e1 = EnsembleTrainer(cfg_for(tmp_path, epochs=2, n_seeds=2), splits,
                         run_dir=run_dir)
    e1.fit()
    e2 = EnsembleTrainer(cfg_for(tmp_path, epochs=4, n_seeds=2), splits,
                         run_dir=run_dir)
    summary = e2.fit(resume=True)
    assert summary["history"][0]["epoch"] == 2
    assert summary["history"][-1]["epoch"] == 3


def test_zero_epochs_rejected(splits, tmp_path):
    t = Trainer(cfg_for(tmp_path, epochs=1), splits)
    t.cfg.optim.epochs = 0
    with pytest.raises(ValueError, match="epochs"):
        t.fit()


def test_sanitized_raises_on_nan():
    with sanitized():
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)).block_until_ready()
    # and restores config afterwards
    assert not jax.config.jax_debug_nans


def test_assert_finite_tree():
    assert_finite_tree({"a": jnp.ones(3)}, "ok")
    with pytest.raises(FloatingPointError, match="bad"):
        assert_finite_tree({"x": jnp.asarray([1.0, np.nan])}, "bad")


def test_trace_context_writes_profile(tmp_path):
    d = str(tmp_path / "prof")
    with trace_context(d):
        jax.jit(lambda x: x * 2)(jnp.ones(64)).block_until_ready()
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace written"


def test_step_timer_accounting():
    t = StepTimer()
    t.start()
    x = jnp.ones(8) + 1
    t.stop(x, firm_months=100.0)
    assert t.steps == 1 and t.firm_months == 100.0
    assert t.throughput() > 0


def test_restore_under_dp_mesh(panel, splits, tmp_path):
    """Orbax-restored states arrive committed to one device; predict and
    resume must re-place them on the data-parallel mesh (regression: a
    restored trainer with n_data_shards>1 crashed with 'incompatible
    devices' inside jit)."""
    import dataclasses
    from lfm_quant_tpu.train.loop import load_trainer

    cfg = dataclasses.replace(cfg_for(tmp_path, epochs=2), n_data_shards=4)
    run_dir = str(tmp_path / "dp" / cfg.name / "seed0")
    t1 = Trainer(cfg, splits, run_dir=run_dir)
    t1.fit()
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "config.json"), "w") as fh:
        fh.write(cfg.to_json())
    t2, sp2 = load_trainer(run_dir, panel=panel)
    assert t2.mesh is not None
    fc, fv = t2.predict("test")
    assert fv.any()
    # resume path under the mesh must also re-place the restored state
    cfg3 = dataclasses.replace(cfg, optim=dataclasses.replace(cfg.optim, epochs=3))
    t3 = Trainer(cfg3, splits, run_dir=run_dir)
    s = t3.fit(resume=True)
    assert s["history"][-1]["epoch"] == 2
