"""Multi-host init gating logic + a real two-process CPU smoke test.

The gating tests validate the configuration contract; the smoke test
launches two actual processes against a localhost coordinator and proves
``maybe_initialize`` produces a global runtime (device_count spans both
processes, and a psum crosses them) — turning "host-count agnostic by
construction" from a claim into a test."""

import socket
import subprocess
import sys
import textwrap

import pytest

from lfm_quant_tpu.utils.distributed import maybe_initialize


def test_empty_env_is_noop():
    assert maybe_initialize(env={}) is False


def test_partial_config_refuses():
    with pytest.raises(ValueError, match="partial multi-host config"):
        maybe_initialize(env={"LFM_COORDINATOR": "host:1234"})
    with pytest.raises(ValueError, match="LFM_PROCESS_ID"):
        maybe_initialize(env={"LFM_COORDINATOR": "host:1234",
                              "LFM_NUM_PROCESSES": "4"})


def test_unrelated_env_ignored():
    assert maybe_initialize(env={"PATH": "/bin", "LFM_OTHER": "x"}) is False


_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)  # 2 local → 4 global
    from lfm_quant_tpu.utils.distributed import maybe_initialize
    assert maybe_initialize() is True
    import jax.numpy as jnp
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()
    assert jax.local_device_count() == 2
    # A collective over every global device: each process contributes its
    # local shard; psum must see all four devices.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(jax.devices(), ("d",))
    ones = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("d")), jnp.ones((2,), jnp.float32), (4,))
    total = jax.jit(
        jax.shard_map(lambda x: jax.lax.psum(x, "d"),
                      mesh=mesh, in_specs=P("d"), out_specs=P()),
    )(ones)
    assert float(total[0]) == 4.0, total
    print(f"proc {os.environ['LFM_PROCESS_ID']} OK", flush=True)
""")


def test_two_process_smoke(tmp_path):
    """Two real processes, localhost coordinator, CPU backend. Skipped
    where localhost sockets are unavailable (sandboxed CI)."""
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    except OSError:
        pytest.skip("no localhost socket access")

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env_base = {
        "LFM_COORDINATOR": f"127.0.0.1:{port}",
        "LFM_NUM_PROCESSES": "2",
        "JAX_PLATFORMS": "cpu",
        "PATH": "/usr/bin:/bin",
        "PYTHONPATH": ":".join(sys.path),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script)],
            env={**env_base, "LFM_PROCESS_ID": str(rank)},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"two-process smoke timed out; partial output: {outs}")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"proc {rank} OK" in out
