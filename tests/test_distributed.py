"""Multi-host init gating logic + a real two-process CPU smoke test.

The gating tests validate the configuration contract; the smoke test
launches two actual processes against a localhost coordinator and proves
``maybe_initialize`` produces a global runtime (device_count spans both
processes, and a psum crosses them) — turning "host-count agnostic by
construction" from a claim into a test."""

import socket
import subprocess
import sys
import textwrap

import jax
import pytest

from lfm_quant_tpu.utils.distributed import maybe_initialize

# jax 0.4.x's CPU client has no cross-process collectives at all
# ("Multiprocess computations aren't implemented on the CPU backend") —
# the two-process smoke tests need a jax whose CPU backend can.
_CPU_MULTIPROCESS = pytest.mark.skipif(
    jax.__version__.startswith("0.4."),
    reason="CPU backend lacks multiprocess collectives on jax 0.4.x")


def test_empty_env_is_noop():
    assert maybe_initialize(env={}) is False


def test_partial_config_refuses():
    with pytest.raises(ValueError, match="partial multi-host config"):
        maybe_initialize(env={"LFM_COORDINATOR": "host:1234"})
    with pytest.raises(ValueError, match="LFM_PROCESS_ID"):
        maybe_initialize(env={"LFM_COORDINATOR": "host:1234",
                              "LFM_NUM_PROCESSES": "4"})


def test_unrelated_env_ignored():
    assert maybe_initialize(env={"PATH": "/bin", "LFM_OTHER": "x"}) is False


_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)  # 2 local → 4 global
    except AttributeError:  # jax 0.4.x — legacy spelling (see conftest.py)
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()
    from lfm_quant_tpu.utils.distributed import maybe_initialize
    assert maybe_initialize() is True
    import jax.numpy as jnp
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()
    assert jax.local_device_count() == 2
    # A collective over every global device: each process contributes its
    # local shard; psum must see all four devices.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(jax.devices(), ("d",))
    ones = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("d")), jnp.ones((2,), jnp.float32), (4,))
    from lfm_quant_tpu.parallel.mesh import shard_map_compat
    total = jax.jit(
        shard_map_compat(lambda x: jax.lax.psum(x, "d"),
                         mesh=mesh, in_specs=P("d"), out_specs=P()),
    )(ones)
    assert float(total[0]) == 4.0, total
    print(f"proc {os.environ['LFM_PROCESS_ID']} OK", flush=True)
""")


@_CPU_MULTIPROCESS
def test_two_process_smoke(tmp_path):
    """Two real processes, localhost coordinator, CPU backend. Skipped
    where localhost sockets are unavailable (sandboxed CI)."""
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    except OSError:
        pytest.skip("no localhost socket access")

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env_base = {
        "LFM_COORDINATOR": f"127.0.0.1:{port}",
        "LFM_NUM_PROCESSES": "2",
        "JAX_PLATFORMS": "cpu",
        "PATH": "/usr/bin:/bin",
        "PYTHONPATH": ":".join(sys.path),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script)],
            env={**env_base, "LFM_PROCESS_ID": str(rank)},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"two-process smoke timed out; partial output: {outs}")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"proc {rank} OK" in out


_MH_SETUP = textwrap.dedent("""
    # Shared single-source setup for the two-process Trainer equivalence
    # test: BOTH the worker subprocesses and the in-test reference import
    # this module, so the two runs cannot drift apart.
    from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer


    def build_trainer():
        cfg = RunConfig(
            name="mh",
            data=DataConfig(n_firms=120, n_months=140, n_features=4,
                            window=8, dates_per_batch=4, firms_per_date=16),
            model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
            optim=OptimConfig(lr=1e-2, epochs=1, warmup_steps=1,
                              loss="mse"),
            n_data_shards=4,
        )
        panel = synthetic_panel(n_firms=120, n_months=140, n_features=4,
                                seed=7, min_history=60)
        splits = PanelSplits.by_date(panel, 197706, 197901)
        return Trainer(cfg, splits)


    def run_three_steps(tr):
        state = tr.init_state()
        losses = []
        it = tr.train_sampler.epoch(0)
        for _ in range(3):
            b = next(it)
            fi, ti, w = tr._batch_args(b, train=True)
            state, ms = tr._jit_step(state, tr.dev, fi, ti, w)
            losses.append(float(ms["loss"]))
        return losses
""")

_TRAIN_WORKER = textwrap.dedent("""
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2)  # 2 local -> 4 global
    except AttributeError:  # jax 0.4.x — legacy spelling (see conftest.py)
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()
    from lfm_quant_tpu.utils.distributed import maybe_initialize
    assert maybe_initialize() is True
    assert jax.process_count() == 2 and jax.device_count() == 4
    # Every process builds the SAME panel and the SAME (seed-keyed)
    # sampler batches - host-replicated inputs, globally sharded arrays.
    import mh_setup
    tr = mh_setup.build_trainer()
    assert tr.mesh is not None and dict(tr.mesh.shape)["data"] == 4
    losses = mh_setup.run_three_steps(tr)
    print("LOSSES", " ".join(f"{x:.8f}" for x in losses), flush=True)
""")


@_CPU_MULTIPROCESS
def test_two_process_trainer_matches_single_process(tmp_path, monkeypatch):
    """The REAL multi-host surface: a Trainer with a 4-way date-sharded
    mesh spanning two processes must produce (nearly) the same losses as
    the identical single-process run - host-replicated index batches in,
    globally-sharded step with psum'd gradients out."""
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    except OSError:
        pytest.skip("no localhost socket access")

    (tmp_path / "mh_setup.py").write_text(_MH_SETUP)
    script = tmp_path / "train_worker.py"
    script.write_text(_TRAIN_WORKER)
    env_base = {
        "LFM_COORDINATOR": f"127.0.0.1:{port}",
        "LFM_NUM_PROCESSES": "2",
        "JAX_PLATFORMS": "cpu",
        "PATH": "/usr/bin:/bin",
        "PYTHONPATH": ":".join(sys.path + [str(tmp_path)]),
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script)],
            env={**env_base, "LFM_PROCESS_ID": str(rank)},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"two-process trainer timed out; partial: {outs}")
    loss_lines = []
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        line = [l for l in out.splitlines() if l.startswith("LOSSES")]
        assert line, out
        loss_lines.append(line[0])
    # Both processes computed the same global losses.
    assert loss_lines[0] == loss_lines[1]

    # Single-process reference on a 4-device mesh: same module, same setup.
    import numpy as np

    monkeypatch.syspath_prepend(str(tmp_path))
    import mh_setup

    ref = mh_setup.run_three_steps(mh_setup.build_trainer())
    got = [float(x) for x in loss_lines[0].split()[1:]]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-7)
