"""Multi-host init gating logic (the initialize() call itself needs a real
pod; CI validates the configuration contract)."""

import pytest

from lfm_quant_tpu.utils.distributed import maybe_initialize


def test_empty_env_is_noop():
    assert maybe_initialize(env={}) is False


def test_partial_config_refuses():
    with pytest.raises(ValueError, match="partial multi-host config"):
        maybe_initialize(env={"LFM_COORDINATOR": "host:1234"})
    with pytest.raises(ValueError, match="LFM_PROCESS_ID"):
        maybe_initialize(env={"LFM_COORDINATOR": "host:1234",
                              "LFM_NUM_PROCESSES": "4"})


def test_unrelated_env_ignored():
    assert maybe_initialize(env={"PATH": "/bin", "LFM_OTHER": "x"}) is False
