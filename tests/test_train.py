"""Training loop (L4) tests: end-to-end config-1 slice on the synthetic
panel — loss decreases, planted signal recovered, checkpoint roundtrip.
(SURVEY.md §5: "integration test = config-1 end-to-end on CPU asserting
loss decrease and recovery of the planted signal".)
"""

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import PanelSplits, synthetic_panel
from lfm_quant_tpu.train import Trainer
from lfm_quant_tpu.train.loop import TrainState, make_loss_fn, run_experiment


def tiny_cfg(**over):
    base = dict(
        name="t_mlp",
        data=DataConfig(
            n_firms=200, n_months=160, n_features=5, window=12,
            dates_per_batch=4, firms_per_date=64, panel_seed=21,
        ),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (32,)}),
        optim=OptimConfig(lr=3e-3, epochs=6, warmup_steps=10,
                          early_stop_patience=6, loss="mse"),
        seed=0,
    )
    base.update(over)
    return RunConfig(**base)


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=200, n_months=160, n_features=5, seed=21)


@pytest.fixture(scope="module")
def fitted(panel, tmp_path_factory):
    cfg = tiny_cfg(out_dir=str(tmp_path_factory.mktemp("runs")))
    summary, trainer, splits = run_experiment(cfg, panel=panel)
    return cfg, summary, trainer, splits


@pytest.mark.fast
def test_loss_decreases(fitted):
    _, summary, _, _ = fitted
    hist = summary["history"]
    assert len(hist) >= 3
    first, last = hist[0]["train_loss"], hist[-1]["train_loss"]
    assert last < first * 0.9, f"train loss did not decrease: {first} -> {last}"


@pytest.mark.fast
def test_recovers_planted_signal(fitted):
    """Val Spearman IC must be materially positive — the planted signal is
    forecastable, so a working pipeline must find it."""
    _, summary, _, _ = fitted
    assert summary["best_val_ic"] > 0.15, summary["best_val_ic"]


@pytest.mark.fast
def test_metrics_logged(fitted):
    import json, os
    _, summary, _, _ = fitted
    path = os.path.join(summary["run_dir"], "metrics.jsonl")
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == summary["epochs_run"]
    assert {"epoch", "train_loss", "val_ic", "firm_months_per_sec"} <= set(lines[0])
    assert lines[0]["firm_months_per_sec"] > 0


def test_checkpoint_roundtrip(fitted, tmp_path):
    from lfm_quant_tpu.train import CheckpointManager
    import jax

    _, _, trainer, _ = fitted
    state = trainer.state
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(7, state._asdict(), wait=True)
    restored = TrainState(**mgr.restore(state._asdict()))
    mgr.close()
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == int(state.step)


def test_legacy_checkpoint_without_rng_restores(fitted, tmp_path):
    """Checkpoints written before TrainState grew the rng field must
    still restore (crash-resume compatibility): the missing leaf takes
    the freshly-initialized key."""
    from lfm_quant_tpu.train import CheckpointManager
    from lfm_quant_tpu.train.loop import restore_state_dict
    import jax

    _, _, trainer, _ = fitted
    state = trainer.state
    legacy = {k: v for k, v in state._asdict().items() if k != "rng"}
    mgr = CheckpointManager(str(tmp_path / "legacy_ck"))
    mgr.save(3, legacy, wait=True)
    restored = restore_state_dict(mgr, state._asdict())
    mgr.close()
    assert set(restored) == set(state._asdict())
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(restored["rng"]),
                                  np.asarray(state.rng))


def test_predict_covers_eligible_test_anchors(fitted):
    _, _, trainer, splits = fitted
    fc, fc_valid = trainer.predict("test")
    from lfm_quant_tpu.data import anchor_index
    elig = anchor_index(splits.panel, trainer.window,
                        trainer.cfg.data.min_valid_months)
    lo, hi = splits.test_range
    expected = np.zeros_like(elig)
    expected[:, lo:hi] = elig[:, lo:hi]
    np.testing.assert_array_equal(fc_valid, expected)
    assert fc_valid.any()
    assert np.isfinite(fc[fc_valid]).all()
    # Out-of-sample predictions correlate with realized targets.
    p = splits.panel
    ic = np.corrcoef(fc[fc_valid], p.targets[fc_valid])[0, 1]
    assert ic > 0.1, f"test-set forecast useless: corr={ic:.3f}"


def test_predict_live_anchors_without_targets(fitted):
    """require_target=False must reach the panel's live block — the last
    `horizon` months have NO observable targets by construction, which
    default eligibility excludes — while agreeing exactly with the
    default path on every shared anchor (same model, same per-firm
    forward; only eligibility differs)."""
    _, _, trainer, splits = fitted
    panel = splits.panel
    live_lo = panel.n_months - panel.horizon
    rng = (live_lo - 3, panel.n_months)

    fc_d, val_d = trainer.predict(date_range=rng)
    fc_l, val_l = trainer.predict(date_range=rng, require_target=False)

    # Live eligibility strictly extends backtest eligibility...
    assert (val_d & ~val_l).sum() == 0
    # ...and actually reaches the live block (zero targets there).
    live = val_l[:, live_lo:]
    assert live.any(), "no live anchors forecast"
    assert not panel.target_valid[:, live_lo:].any()
    assert not val_d[:, live_lo:].any()
    assert np.isfinite(fc_l[val_l]).all()
    # Shared anchors: bitwise-identical forecasts.
    shared = val_d & val_l
    assert shared.any()
    np.testing.assert_array_equal(fc_d[shared], fc_l[shared])


@pytest.mark.fast
def test_forecast_cli_ranks_live_months(fitted, tmp_path, capsys):
    """forecast.py end-to-end: run dir → live rankings (npz + csv), the
    deployment surface backtest.py cannot provide."""
    import csv as _csv

    import forecast as forecast_cli

    cfg, summary, trainer, splits = fitted
    run_dir = summary["run_dir"]
    out = tmp_path / "fc.npz"
    csv_path = tmp_path / "fc.csv"
    rc = forecast_cli.main(["--run-dir", run_dir, "--out", str(out),
                            "--csv", str(csv_path), "--top", "3"])
    assert rc == 0
    stdout = capsys.readouterr().out
    assert "live" in stdout and "#1" in stdout

    data = np.load(out)
    panel = splits.panel
    live_lo = panel.n_months - panel.horizon
    assert data["valid"][:, live_lo:].any()
    assert data["forecast"].shape == (panel.n_firms, panel.n_months)

    with open(csv_path) as fh:
        rows = list(_csv.DictReader(fh))
    assert rows, "empty rankings csv"
    months = {int(r["yyyymm"]) for r in rows}
    assert int(panel.dates[-1]) in months  # the very last month is ranked
    # Ranks are 1..n and ordered by forecast within each month.
    last = [r for r in rows if int(r["yyyymm"]) == int(panel.dates[-1])]
    ranks = [int(r["rank"]) for r in last]
    assert ranks == list(range(1, len(last) + 1))
    fcs = [float(r["forecast"]) for r in last]
    assert fcs == sorted(fcs, reverse=True)


def test_nll_head_recovers_heteroscedastic_noise_profile(tmp_path):
    """On a panel with KNOWN per-firm noise scales (het_noise=1.0), an
    NLL-trained heteroscedastic head must rank firms by noisiness: its
    predicted aleatoric std should correlate with each firm's realized
    residual spread. This is the uncertainty stack's ground-truth test —
    on the legacy homoscedastic generator the head has nothing to learn
    and the correlation would be noise."""
    from lfm_quant_tpu.ops.metrics import noise_recovery_rho

    het_panel = synthetic_panel(n_firms=300, n_months=160, n_features=5,
                                seed=9, het_noise=1.0)
    cfg = tiny_cfg(out_dir=str(tmp_path),
                   optim=OptimConfig(lr=3e-3, epochs=8, warmup_steps=10,
                                     early_stop_patience=8, loss="nll"),
                   data=DataConfig(n_firms=300, n_months=160, n_features=5,
                                   window=12, dates_per_batch=4,
                                   firms_per_date=64, panel_seed=9,
                                   het_noise=1.0))
    # The config now fully DESCRIBES the panel: resolve_panel reproduces it.
    from lfm_quant_tpu.train.loop import resolve_panel
    np.testing.assert_array_equal(resolve_panel(cfg.data).targets,
                                  het_panel.targets)
    splits = PanelSplits.by_date(het_panel, 198001, 198201)
    trainer = Trainer(cfg, splits)
    trainer.fit()
    fc, avar, valid = trainer.predict("val", return_variance=True)
    rho = noise_recovery_rho(het_panel.targets, fc, np.sqrt(avar), valid)
    assert rho > 0.3, f"NLL head failed to rank firm noise: rho={rho:.3f}"


def test_early_stopping_triggers(panel, tmp_path):
    cfg = tiny_cfg(
        optim=OptimConfig(lr=0.0, epochs=10, warmup_steps=0,
                          early_stop_patience=2, loss="mse"),
        out_dir=str(tmp_path),
    )
    summary, _, _ = run_experiment(cfg, panel=panel)
    # lr=0 → no improvement after epoch 0 → stop at patience.
    assert summary["epochs_run"] <= 4


@pytest.mark.fast
def test_make_loss_fn_rejects_unknown():
    with pytest.raises(ValueError, match="unknown loss"):
        make_loss_fn("hinge")


@pytest.mark.parametrize("loss", ["huber", "rank_ic"])
def test_alternative_losses_train(panel, tmp_path, loss):
    cfg = tiny_cfg(
        optim=OptimConfig(lr=3e-3, epochs=2, warmup_steps=5,
                          early_stop_patience=5, loss=loss),
        out_dir=str(tmp_path),
    )
    summary, _, _ = run_experiment(cfg, panel=panel)
    assert np.isfinite(summary["history"][-1]["train_loss"])


def test_nll_loss_with_heteroscedastic_head(panel, tmp_path):
    cfg = tiny_cfg(
        model=ModelConfig(kind="mlp", kwargs={"hidden": (32,)},
                          heteroscedastic=True),
        optim=OptimConfig(lr=3e-3, epochs=2, warmup_steps=5,
                          early_stop_patience=5, loss="nll"),
        out_dir=str(tmp_path),
    )
    summary, _, _ = run_experiment(cfg, panel=panel)
    assert np.isfinite(summary["history"][-1]["train_loss"])


def test_bench_scan_impl_override(monkeypatch):
    """LFM_BENCH_SCAN_IMPL must reroute the benched model's scan_impl —
    the on-chip validation hook for new kernel variants."""
    import bench
    from lfm_quant_tpu.config import get_preset

    monkeypatch.setenv("LFM_BENCH_SCAN_IMPL", "pallas_fused")
    cfg = bench._scan_impl_override(get_preset("c2"))
    assert cfg.model.kwargs["scan_impl"] == "pallas_fused"
    monkeypatch.delenv("LFM_BENCH_SCAN_IMPL")
    cfg = bench._scan_impl_override(get_preset("c2"))
    assert "scan_impl" not in cfg.model.kwargs


@pytest.mark.fast
def test_lamb_optimizer_trains(panel, tmp_path):
    """optimizer="lamb" (the large-batch recipe, PAPERS.md) plugs into
    the same loop: loss decreases, signal recovered; unknown optimizers
    fail loudly at build time."""
    cfg = tiny_cfg(
        optim=OptimConfig(lr=3e-3, epochs=4, warmup_steps=10,
                          early_stop_patience=6, loss="mse",
                          optimizer="lamb"),
        out_dir=str(tmp_path),
    )
    summary, _, _ = run_experiment(cfg, panel=panel)
    hist = summary["history"]
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    assert summary["best_val_ic"] > 0.05

    bad = tiny_cfg(optim=OptimConfig(optimizer="sgd"),
                   out_dir=str(tmp_path / "bad"))
    dates = panel.dates
    splits = PanelSplits.by_date(panel, int(dates[100]), int(dates[120]))
    with pytest.raises(ValueError, match="optimizer"):
        Trainer(bad, splits)


def test_lru_trains_end_to_end(panel, tmp_path):
    """The time-parallel LRU family plugs into the same train stack and
    learns the planted signal (val IC clears noise)."""
    cfg = tiny_cfg(
        name="t_lru",
        model=ModelConfig(kind="lru",
                          kwargs={"hidden": 32, "state_dim": 32}),
        out_dir=str(tmp_path),
    )
    summary, trainer, _ = run_experiment(cfg, panel=panel)
    assert summary["history"][-1]["train_loss"] < summary["history"][0][
        "train_loss"]
    assert summary["best_val_ic"] > 0.05


def test_bench_ladder_gather_override(monkeypatch):
    """LFM_BENCH_GATHER_IMPL must reroute the window gather; scan_impl
    overrides must not leak onto non-RNN models (the lru target)."""
    import os as _os

    monkeypatch.syspath_prepend(
        _os.path.join(_os.path.dirname(__file__), "..", "scripts"))
    import bench_ladder

    from lfm_quant_tpu.config import get_preset

    monkeypatch.setenv("LFM_BENCH_GATHER_IMPL", "xla")
    cfg = bench_ladder._overrides(get_preset("c2"))
    assert cfg.data.gather_impl == "xla"
    monkeypatch.setenv("LFM_BENCH_SCAN_IMPL", "pallas_fused")
    cfg = bench_ladder._overrides(get_preset("lru"))
    assert "scan_impl" not in cfg.model.kwargs  # lru: RNN-only knob
    cfg = bench_ladder._overrides(get_preset("c2"))
    assert cfg.model.kwargs["scan_impl"] == "pallas_fused"


def test_full_universe_rank_ic_trains(panel, tmp_path):
    """c3's training mode: firms_per_date=0 ranks each month's FULL
    eligible cross-section. The planted signal must be recovered and the
    sampler must report a rounded full-width Bf."""
    cfg = tiny_cfg(
        name="t_full_universe",
        data=DataConfig(
            n_firms=200, n_months=160, n_features=5, window=12,
            dates_per_batch=4, firms_per_date=0,
        ),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (32,)}),
        optim=OptimConfig(lr=3e-3, epochs=4, warmup_steps=10,
                          early_stop_patience=6, loss="rank_ic"),
        out_dir=str(tmp_path),
    )
    summary, trainer, splits = run_experiment(cfg, panel=panel)
    from lfm_quant_tpu.data import anchor_index
    elig = anchor_index(splits.panel, trainer.window)
    mx = max(int(elig[:, t].sum())
             for t in trainer.train_sampler._dates)
    assert trainer.train_sampler.firms_per_date >= mx
    assert trainer.train_sampler.firms_per_date % 8 == 0
    assert np.isfinite(summary["history"][-1]["train_loss"])
    assert summary["best_val_ic"] > 0.1, summary["best_val_ic"]


def test_bench_ladder_dates_override(monkeypatch):
    """LFM_BENCH_DATES must set the on-device dates_per_batch and drop the
    data-shard count to 1 — the per-shard-batch hook for benching sharded
    configs on the one visible chip."""
    import os as _os

    monkeypatch.syspath_prepend(
        _os.path.join(_os.path.dirname(__file__), "..", "scripts"))
    import bench_ladder

    from lfm_quant_tpu.config import get_preset

    monkeypatch.setenv("LFM_BENCH_DATES", "1")
    cfg = bench_ladder._overrides(get_preset("c3"))
    assert cfg.data.dates_per_batch == 1 and cfg.n_data_shards == 1
    monkeypatch.delenv("LFM_BENCH_DATES")
    cfg = bench_ladder._overrides(get_preset("c3"))
    assert cfg.data.dates_per_batch == 8 and cfg.n_data_shards == 8


@pytest.mark.fast
def test_bench_wedged_tunnel_emits_status_record(monkeypatch, capsys):
    """A wedged tunnel must still put a machine-parseable record on stdout
    (round 3's driver capture was rc=1/parsed=null because only stderr
    probe chatter preceded the timeout). Under a fake always-hanging probe
    subprocess, bench.main() must give up INSIDE the wait window, emit
    {"metric": "bench_status", "status": "tunnel_wedged", ...}, and exit
    nonzero — the TERM-then-KILL escalation path included."""
    import json as _json
    import subprocess
    import time as _time

    import bench as bench_mod

    killed = []

    class HangingPopen:
        def __init__(self, *a, **kw):
            self.returncode = None

        def communicate(self, timeout=None):
            if timeout is not None and timeout > 0.2:
                raise subprocess.TimeoutExpired("probe", timeout)
            return "", ""  # post-SIGKILL reap (timeout=None)

        def terminate(self):
            pass

        def kill(self):
            killed.append(True)

    monkeypatch.setenv("LFM_BENCH_WAIT_S", "1")
    monkeypatch.setenv("LFM_BENCH_NO_PERSIST", "1")  # keep the repo ledger clean
    monkeypatch.delenv("LFM_BENCH_SKIP_PROBE", raising=False)
    monkeypatch.setattr(subprocess, "Popen", HangingPopen)
    t0 = _time.monotonic()
    rc = bench_mod.main()
    took = _time.monotonic() - t0
    assert rc == 1
    assert took < 30  # gave up inside the window, not the driver timebox
    assert killed  # SIGTERM-immune probe was SIGKILLed (advisor pattern)
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    rec = _json.loads(lines[-1])
    assert rec["metric"] == "bench_status"
    assert rec["status"] == "tunnel_wedged"
    assert rec["unit"] == "status" and rec["value"] == 0.0
    assert rec["probe_attempts"] >= 1


@pytest.mark.fast
def test_bench_status_distinguishes_env_error_and_crash(monkeypatch, capsys):
    """The machine-readable status field must not cry 'tunnel' for
    non-tunnel failures: an instant probe exit (broken env) is
    probe_env_error, and an exception escaping the harness itself still
    lands a bench_error record — no exit path may skip the record."""
    import json as _json
    import subprocess

    import bench as bench_mod

    class InstantFailPopen:
        def __init__(self, *a, **kw):
            self.returncode = 1

        def communicate(self, timeout=None):
            return "", "ModuleNotFoundError: no module named 'jax'"

        def terminate(self):
            pass

        def kill(self):
            pass

    monkeypatch.delenv("LFM_BENCH_SKIP_PROBE", raising=False)
    monkeypatch.setenv("LFM_BENCH_NO_PERSIST", "1")
    monkeypatch.setattr(subprocess, "Popen", InstantFailPopen)
    assert bench_mod.main() == 1
    rec = _json.loads(capsys.readouterr().out.splitlines()[-1])
    assert rec["status"] == "probe_env_error"

    # Harness bug (malformed env var) → bench_error via the outer guard.
    monkeypatch.setenv("LFM_BENCH_WAIT_S", "not-a-number")
    assert bench_mod.main() == 1
    rec = _json.loads(capsys.readouterr().out.splitlines()[-1])
    assert rec["status"] == "bench_error" and rec["stage"] == "harness"


@pytest.mark.fast
def test_bench_watchdog_kills_postprobe_hang():
    """A tunnel that wedges AFTER the probe passes hangs in
    uninterruptible backend init — only the watchdog thread's os._exit
    can still deliver the record. Simulate: arm the watchdog, hang the
    main thread; the process must die quickly with a bench_timeout JSON
    record on stdout."""
    import json as _json
    import os as _os
    import subprocess
    import sys as _sys

    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    code = (
        "import time, bench\n"
        "bench._arm_watchdog(0.5, {})\n"
        "time.sleep(30)\n"  # stand-in for the uninterruptible hang
    )
    proc = subprocess.run(
        [_sys.executable, "-c", code], capture_output=True, text=True,
        timeout=20, cwd=repo_root,
        # NO_PERSIST: the fire path must not append test records to the
        # repo ledger (and on a wedged axon tunnel a backend query from
        # the timer thread would hang — persist_row guards it, but the
        # test should not depend on that guard).
        env={**_os.environ, "LFM_BENCH_NO_PERSIST": "1"},
    )
    assert proc.returncode == 1
    rec = _json.loads(proc.stdout.splitlines()[-1])
    assert rec["status"] == "bench_timeout"


@pytest.mark.fast
def test_eval_gather_defaults_to_xla(panel, tmp_path, monkeypatch):
    """The eval sweep must ride the XLA gather even when the TRAIN gather
    auto-resolves to the Pallas DMA gather: the on-chip A/B (2026-07-31,
    BENCH_ROWS.jsonl) measured the XLA-gather eval 44% faster — the
    full-cross-section sweep is gather-bound in a way the train step is
    not. An EXPLICIT gather_impl='pallas' config still carries into
    single-chip eval (the A/B override path)."""
    import dataclasses

    import lfm_quant_tpu.train.loop as loop_mod

    # Simulate the TPU resolution on CPU: auto → pallas for the train
    # gather (attribute wiring only — nothing is dispatched).
    monkeypatch.setattr(loop_mod, "resolve_gather_impl",
                        lambda *a, **k: "pallas")
    t_auto = Trainer(tiny_cfg(out_dir=str(tmp_path / "a")),
                     PanelSplits.by_date(panel, 198001, 198201))
    assert t_auto._gather_impl == "pallas"
    assert t_auto._eval_gather_impl == "xla"
    # The eval sweep must actually DISPATCH through the XLA gather — on
    # CPU the Pallas path cannot run, so a finite IC proves the eval
    # program never touched the (pallas-wired) train gather.
    m = t_auto.evaluate(t_auto.init_state().params)
    assert np.isfinite(m["ic"])

    cfg_exp = tiny_cfg(out_dir=str(tmp_path / "b"))
    cfg_exp = dataclasses.replace(
        cfg_exp, data=dataclasses.replace(cfg_exp.data,
                                          gather_impl="pallas"))
    t_exp = Trainer(cfg_exp, PanelSplits.by_date(panel, 198001, 198201))
    assert t_exp._eval_gather_impl == "pallas"


@pytest.mark.fast
def test_bench_preempts_running_campaign(monkeypatch, tmp_path):
    """The driver's end-of-round capture must be able to evict a
    still-running unattended campaign (the single tunneled chip
    serializes clients; campaign rows already persisted). Patterns are
    monkeypatched to a unique marker so the test can never signal a real
    watcher/campaign on this machine."""
    import subprocess
    import sys as _sys

    import bench as bench_mod

    monkeypatch.delenv("LFM_BENCH_SKIP_PROBE", raising=False)
    monkeypatch.delenv("LFM_BENCH_NO_PREEMPT", raising=False)
    marker = "scripts/lfm-preempt-test-marker-7f3a.sh"
    monkeypatch.setattr(bench_mod, "_CAMPAIGN_PATTERNS", (marker,))
    # A shell root whose CHILD (no marker in its own argv) does the
    # sleeping — the descendant closure must take both down, like the
    # campaign's `timeout ... python ...` grandchildren holding the chip.
    script = tmp_path / marker
    script.parent.mkdir(parents=True)
    script.write_text("#!/bin/bash\nsleep 60 &\nwait\n")
    victim = subprocess.Popen(["bash", str(script)])
    try:
        import time as _time
        for _ in range(200):  # wait for the child sleep to spawn
            if any(pp == victim.pid
                   for pp, _a in bench_mod._list_procs().values()):
                break
            _time.sleep(0.05)
        # No-op the TERM→KILL grace sleep only now — bench.time IS the
        # global time module, so patching earlier would have no-op'd the
        # spawn wait above too.
        monkeypatch.setattr(bench_mod.time, "sleep", lambda s: None)
        res = bench_mod._preempt_campaign()
        assert res["killed"] >= 2 and not res["watcher"]  # root + child
        # Dead within 10 s proves the preemption (the script sleeps 60 —
        # it cannot have finished on its own). The exact exit code is a
        # bash signal-timing artifact (143/137/0 have all been observed
        # under full-suite load) — pinning it made the test flaky.
        victim.wait(timeout=10)
    finally:
        if victim.poll() is None:
            victim.kill()
    # Anchored matching: an "editor" whose ARGUMENT mentions the script
    # must never be signalled (argv[0] is not an interpreter/launcher).
    assert not bench_mod._is_campaign_proc(["vim", marker])
    assert not bench_mod._is_campaign_proc(["less", f"x/{marker}"])
    assert bench_mod._is_campaign_proc(["bash", f"/root/repo/{marker}"])
    assert not bench_mod._is_campaign_proc(
        ["bash", "-c", f"echo {marker}-suffixed"])  # suffix != path match
    # The campaign's own bench step (SKIP_PROBE=1) must never self-evict.
    monkeypatch.setenv("LFM_BENCH_SKIP_PROBE", "1")
    assert bench_mod._preempt_campaign() == {"killed": 0, "watcher": False}


@pytest.mark.fast
def test_bench_preempt_preserves_watcher_arming(monkeypatch, tmp_path):
    """Preempting the recovery watcher must capture its positional args
    (probe interval) and CAMPAIGN_* env (log path) so the re-arm restores
    the operator's arming choices instead of reverting to defaults — and
    _rearm_watcher must actually pass both through to the relaunch."""
    import os
    import subprocess

    import bench as bench_mod

    monkeypatch.delenv("LFM_BENCH_SKIP_PROBE", raising=False)
    monkeypatch.delenv("LFM_BENCH_NO_PREEMPT", raising=False)
    # Unique marker name so no real watcher on this machine can match.
    marker = "scripts/lfm-watcher-test-marker-4b9c.sh"
    monkeypatch.setattr(bench_mod, "_WATCHER_PATTERN", marker)
    monkeypatch.setattr(bench_mod, "_CAMPAIGN_PATTERNS", (marker,))
    script = tmp_path / marker
    script.parent.mkdir(parents=True)
    script.write_text("#!/bin/bash\nsleep 60\n")
    victim = subprocess.Popen(
        ["bash", str(script), "61"],
        env={**os.environ, "CAMPAIGN_WATCH_LOG": "/tmp/lfm-test-watch.log"})
    try:
        import time as _time
        for _ in range(200):
            if victim.pid in bench_mod._list_procs():
                break
            _time.sleep(0.05)
        monkeypatch.setattr(bench_mod.time, "sleep", lambda s: None)
        res = bench_mod._preempt_campaign()
        assert res["watcher"]
        assert res["watcher_args"] == ["61"]
        # Subset, not equality: the capture takes ALL CAMPAIGN_* vars, so
        # ambient ones (e.g. an exported CAMPAIGN_MAX_FIRES) ride along.
        assert (res["watcher_env"]["CAMPAIGN_WATCH_LOG"]
                == "/tmp/lfm-test-watch.log")
    finally:
        if victim.poll() is None:
            victim.kill()
    # The relaunch must carry both through (Popen faked — no real spawn).
    calls = []
    monkeypatch.setattr(
        subprocess, "Popen",
        lambda argv, env=None, **kw: calls.append((argv, env)))
    bench_mod._rearm_watcher(res)
    (argv, env), = calls
    assert argv[-1] == "61"
    assert env["CAMPAIGN_WATCH_LOG"] == "/tmp/lfm-test-watch.log"


@pytest.mark.fast
def test_bench_watchdog_fire_rearms_watcher():
    """os._exit on the watchdog fire path skips main()'s finally — the
    preempted watcher must be re-armed from the fire path itself, or a
    post-probe wedge would leave the staged campaign permanently
    disarmed."""
    import json as _json
    import os as _os
    import subprocess
    import sys as _sys

    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    code = (
        "import time, bench\n"
        "bench._rearm_watcher = lambda p: print("
        "'REARMED', p['watcher_args'][0], flush=True)\n"
        "bench._arm_watchdog(0.5, {'watcher': True, 'watcher_args': ['77']})\n"
        "time.sleep(30)\n"
    )
    proc = subprocess.run(
        [_sys.executable, "-c", code], capture_output=True, text=True,
        timeout=20, cwd=repo_root,
        env={**_os.environ, "LFM_BENCH_NO_PERSIST": "1"},
    )
    assert proc.returncode == 1
    lines = proc.stdout.splitlines()
    assert "REARMED 77" in lines
    rec = _json.loads([ln for ln in lines if ln.startswith("{")][-1])
    assert rec["status"] == "bench_timeout"


@pytest.mark.fast
def test_bench_rows_persist_and_regen(tmp_path, monkeypatch, capsys):
    """The measurement ledger: _emit/_emit_status append to
    BENCH_ROWS.jsonl the moment a record exists (a mid-campaign re-wedge
    must not lose captured rows), and regen_baseline collapses the ledger
    latest-per-key into the BASELINE.md table view."""
    import json as _json
    import os as _os

    import bench as bench_mod

    ledger = tmp_path / "rows.jsonl"
    monkeypatch.setenv("LFM_BENCH_ROWS", str(ledger))
    monkeypatch.delenv("LFM_BENCH_NO_PERSIST", raising=False)

    bench_mod._emit("train_throughput_c2_lstm", 1000.0, 5.0)
    bench_mod._emit("train_throughput_c5_ensemble", 2000.0, 7.0, n_seeds=16)
    bench_mod._emit("train_throughput_c5_ensemble", 2400.0, 8.0, n_seeds=16)
    bench_mod._emit("train_throughput_c5_ensemble", 3000.0, 9.0, n_seeds=64)
    bench_mod._emit_status("tunnel_wedged", detail="probe timeout")
    capsys.readouterr()

    rows = [_json.loads(ln) for ln in ledger.read_text().splitlines()]
    assert len(rows) == 5
    assert all("ts" in r for r in rows)

    monkeypatch.syspath_prepend(
        _os.path.join(_os.path.dirname(__file__), "..", "scripts"))
    import regen_baseline

    table = regen_baseline.render_table(regen_baseline.load_rows(str(ledger)))
    # Latest-per-key: the 16-seed row shows 2,400 (not 2,000); the 64-seed
    # geometry is its own line; the outage shows as a status footnote.
    assert "2,400.0" in table and "2,000.0" not in table
    assert "3,000.0" in table and "n_seeds=64" in table
    assert "1,000.0" in table
    assert "tunnel_wedged" in table

    # Persistence must never kill a measurement run: unwritable path.
    monkeypatch.setenv("LFM_BENCH_ROWS", str(tmp_path / "nodir" / "x.jsonl"))
    bench_mod._emit("train_throughput_c2_lstm", 1.0, 0.1)  # no raise
    out = capsys.readouterr()
    assert "could not persist" in out.err


def test_measure_eval_counts_real_firm_months(panel, tmp_path, monkeypatch):
    """bench.measure_eval's firm-month accounting, pinned exactly: with a
    frozen 2-second clock, rate == (real val weights × window [× seeds]) / 2
    for BOTH trainer kinds — the harness behind the eval_throughput rows."""
    import itertools

    import bench as bench_mod
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    cfg = tiny_cfg(out_dir=str(tmp_path))
    dates = panel.dates
    splits = PanelSplits.by_date(panel, int(dates[100]), int(dates[120]))
    # Telemetry stays at its default (ON): the ledger stopwatch now
    # reads the clock ONLY on calls that traced (an even number of
    # reads — trace-start stamp + post-call read), so warm dispatches
    # inside the timed region preserve the frozen clock's tick parity
    # and dt can never collapse to zero. This test doubles as the
    # regression guard for that fix (it used to need LFM_TELEMETRY=0).

    def frozen_clock():
        # Each measured interval reads the clock twice: t0 then t0+2.
        ticks = itertools.count()
        return lambda: float(next(ticks) % 2) * 2.0

    tr = Trainer(cfg, splits)
    fm = float(tr.val_sampler.stacked_cross_sections().weight.sum()
               ) * tr.window
    monkeypatch.setattr(bench_mod.time, "perf_counter", frozen_clock())
    v = bench_mod.measure_eval(tr, reps=1)
    assert v == pytest.approx(fm / 2.0)

    ecfg = tiny_cfg(n_seeds=2, out_dir=str(tmp_path))
    etr = EnsembleTrainer(ecfg, splits)
    efm = float(etr.val_sampler.stacked_cross_sections().weight.sum()
                ) * etr.window * etr.n_seeds
    monkeypatch.setattr(bench_mod.time, "perf_counter", frozen_clock())
    ev = bench_mod.measure_eval(etr, reps=1)
    assert ev == pytest.approx(efm / 2.0)
    assert efm == pytest.approx(2.0 * fm)  # the seed stack doubles the count

    # Under a data mesh the PRODUCTION eval program is the month-sharded
    # _forward_eval — measure_eval must time that path (round-3 advisor),
    # record it as such, and count the same real firm-months.
    scfg = tiny_cfg(n_data_shards=2, out_dir=str(tmp_path))
    str_ = Trainer(scfg, splits)
    assert bench_mod.eval_path(str_) == "month_sharded"
    assert bench_mod.eval_path(tr) == "replicated"
    monkeypatch.setattr(bench_mod.time, "perf_counter", frozen_clock())
    sv = bench_mod.measure_eval(str_, reps=1)
    assert sv == pytest.approx(fm / 2.0)
