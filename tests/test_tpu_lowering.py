"""TPU Mosaic lowering validation WITHOUT a TPU.

``jax.export(..., platforms=["tpu"])`` runs the real Mosaic lowering
pipeline on any host, enforcing the TPU block-shape/DMA constraints that
interpret-mode execution skips — exactly the class of bug (illegal
squeezed blocks from vmap batching) that once passed 184 CPU tests and
crashed on the chip. These tests cross-lower the Pallas kernels with
``interpret=False`` through the same transform stacks the trainers use —
jit(vmap(grad(...))) for the single-chip ensemble AND shard_map over a
mesh (where each kernel sees the PER-SHARD batch) — without executing
anything.

Scope caveat: export catches lowering/verifier failures only.
Compile-stage resource failures (a block past the ~16 MB VMEM budget,
layout-inference issues) still need a real chip — see README "kernel
caveat".

Only shapes/dtypes matter to lowering, so arguments are
``jax.ShapeDtypeStruct``s — nothing is allocated.
"""

import jax
import jax.numpy as jnp
import pytest
from jax import export
from jax.sharding import PartitionSpec as P

from lfm_quant_tpu.ops.pallas_gather import gather_windows_pallas
from lfm_quant_tpu.ops.pallas_rnn import rnn_scan, rnn_scan_fused
from lfm_quant_tpu.parallel.mesh import shard_map_compat

CELLS = ["lstm", "gru"]
GATES = {"lstm": 4, "gru": 3}


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _lower_tpu(fn, *args):
    exp = export.export(jax.jit(fn), platforms=["tpu"])(*args)
    assert exp.platforms == ("tpu",)


@pytest.mark.parametrize("cell", CELLS)
def test_rnn_scan_vmap_grad_lowers(cell):
    """The ensemble step's stack — jit(vmap(grad)) — must produce a legal
    Mosaic lowering via the custom_vmap seed-grid dispatch."""
    S, B, T, H = 2, 16, 4, 128
    G = GATES[cell] * H

    def loss(xw, wh, m):
        return (rnn_scan(cell, xw, wh, m, interpret=False) ** 2).sum()

    _lower_tpu(jax.vmap(jax.grad(loss, argnums=(0, 1))),
               _sds((S, B, T, G)), _sds((S, H, G)), _sds((S, B, T)))


@pytest.mark.parametrize("cell", CELLS)
def test_rnn_scan_fused_vmap_grad_lowers(cell):
    S, B, T, H = 2, 16, 4, 128
    G = GATES[cell] * H

    def loss(hin, wx, b, wh, m):
        return (rnn_scan_fused(cell, hin, wx, b, wh, m,
                               interpret=False) ** 2).sum()

    _lower_tpu(jax.vmap(jax.grad(loss, argnums=(1, 2, 3))),
               _sds((S, B, T, H)), _sds((S, H, G)), _sds((S, G)),
               _sds((S, H, G)), _sds((S, B, T)))


def test_rnn_scan_shared_weights_lowers():
    """Eval-style batching: shared data, per-seed weights — the pinned
    index maps for size-1 seed axes must lower too."""
    S, B, T, H = 2, 16, 4, 128
    G = 4 * H
    xw = jnp.zeros((B, T, G))
    m = jnp.zeros((B, T))

    _lower_tpu(jax.vmap(
        lambda w: rnn_scan("lstm", xw, w, m, interpret=False)),
        _sds((S, H, G)))


def test_gather_vmap_lowers():
    """The seed-folded gather (one kernel, S·D date grid rows)."""
    N, T, Fp, W = 32, 64, 128, 24
    xm = jnp.zeros((N, T, Fp))
    S, D, Bf = 2, 3, 8

    _lower_tpu(jax.vmap(
        lambda a, b: gather_windows_pallas(xm, a, b, window=W,
                                           interpret=False)),
        _sds((S, D, Bf), jnp.int32), _sds((S, D), jnp.int32))


@pytest.mark.parametrize("impl", ["plain", "fused"])
def test_shard_map_per_shard_geometry_lowers(impl):
    """The trainers wrap the kernels in shard_map over the data mesh, so
    each kernel sees B / n_shards rows — cross-lower THAT stack on an
    8-way mesh at the c2 global batch (per-shard B = 256), grad included.
    (Requires the 8-device CPU platform from conftest.py.)"""
    mesh = jax.make_mesh((8,), ("data",))
    B, T, H = 2048, 8, 128
    G = 4 * H

    if impl == "plain":
        def loss(xw, wh, m):
            return (rnn_scan("lstm", xw, wh, m,
                             interpret=False) ** 2).sum()

        f = shard_map_compat(jax.grad(loss, argnums=(0, 1)), mesh=mesh,
                             in_specs=(P("data"), P(), P("data")),
                             out_specs=(P("data"), P()), check_vma=False)
        args = (_sds((B, T, G)), _sds((H, G)), _sds((B, T)))
    else:
        def loss(hin, wx, b, wh, m):
            return (rnn_scan_fused("lstm", hin, wx, b, wh, m,
                                   interpret=False) ** 2).sum()

        f = shard_map_compat(jax.grad(loss, argnums=(1, 2, 3)), mesh=mesh,
                             in_specs=(P("data"), P(), P(), P(), P("data")),
                             out_specs=(P(), P(), P()), check_vma=False)
        args = (_sds((B, T, H)), _sds((H, G)), _sds((G,)), _sds((H, G)),
                _sds((B, T)))
    _lower_tpu(f, *args)


@pytest.mark.parametrize("B", [64, 128, 256])
def test_per_shard_batch_sizes_lower(B):
    """Block legality across the per-shard batch sizes a v5e-8/-16/-64
    mesh produces from the ladder's global batches."""
    T, H = 4, 128
    G = 4 * H

    def loss(xw, wh, m):
        return (rnn_scan("lstm", xw, wh, m, interpret=False) ** 2).sum()

    _lower_tpu(jax.grad(loss, argnums=(0, 1)),
               _sds((B, T, G)), _sds((H, G)), _sds((B, T)))


def test_bf16_c2_geometry_lowers():
    """One full-width bf16 lowering at the real config-2 kernel geometry
    (B = 2048, T = 60, H = 128) — the shapes the bench runs."""
    B, T, H = 2048, 60, 128
    G = 4 * H

    def loss(xw, wh, m):
        return (rnn_scan("lstm", xw, wh, m,
                         interpret=False).astype(jnp.float32) ** 2).sum()

    _lower_tpu(jax.grad(loss, argnums=(0, 1)),
               _sds((B, T, G), jnp.bfloat16), _sds((H, G), jnp.bfloat16),
               _sds((B, T), jnp.bfloat16))


def test_gather_c1_geometry_f32_lowers():
    """The c1 ladder shape family — f32 panel (no bf16), window=12,
    lane-padded width 128 — the exact geometry whose on-chip run wedged
    the tunnel once; pin at least the Mosaic lowering stage."""
    N, T, Fp, W = 1000, 240, 128, 12

    _lower_tpu(
        lambda xm, a, b: gather_windows_pallas(xm, a, b, window=W, fp=6,
                                               interpret=False),
        _sds((N, T, Fp), jnp.float32),
        _sds((8, 128), jnp.int32), _sds((8,), jnp.int32))


def test_c3_full_universe_geometry_lowers():
    """The c3 full-universe bench geometry: GRU fused kernel in bf16 at
    the per-shard batch (D=1 date × Bf=8192 full cross-section = 8192
    rows, T=60, H=128) plus the Pallas DMA gather at the same width —
    the exact shapes `scripts/chip_campaign.sh ladder-c3` dispatches.
    Lowered here so scarce chip time never dies on a Mosaic verifier
    error."""
    B, T, H = 8192, 60, 128
    G = 3 * H  # GRU

    def loss(hin, wx, b, wh, m):
        return (rnn_scan_fused("gru", hin, wx, b, wh, m,
                               interpret=False).astype(jnp.float32)
                ** 2).sum()

    _lower_tpu(jax.grad(loss, argnums=(1, 2, 3)),
               _sds((B, T, H), jnp.bfloat16), _sds((H, G), jnp.bfloat16),
               _sds((G,), jnp.bfloat16), _sds((H, G), jnp.bfloat16),
               _sds((B, T), jnp.bfloat16))

    # bench_ladder trims the c3 panel to 240 months (already 8-aligned),
    # so THIS is the panel extent ladder-c3 actually dispatches.
    N, Tp, Fp, W = 8000, 240, 128, 60
    _lower_tpu(
        lambda xm, a, b: gather_windows_pallas(xm, a, b, window=W, fp=21,
                                               interpret=False),
        _sds((N, Tp, Fp), jnp.bfloat16),
        _sds((1, 8192), jnp.int32), _sds((1,), jnp.int32))


def test_c5_64_seed_geometry_lowers():
    """The 64-seed HBM probe's kernel stack (chip_campaign.sh
    seeds64-full): jit(vmap(grad)) over S=64 at the c5 per-seed batch
    (B=2048, T=60, H=128, LSTM, bf16) — the widest seed grid any bench
    dispatches."""
    S, B, T, H = 64, 2048, 60, 128
    G = 4 * H

    def loss(hin, wx, b, wh, m):
        return (rnn_scan_fused("lstm", hin, wx, b, wh, m,
                               interpret=False).astype(jnp.float32)
                ** 2).sum()

    _lower_tpu(jax.vmap(jax.grad(loss, argnums=(1, 2, 3))),
               _sds((S, B, T, H), jnp.bfloat16),
               _sds((S, H, G), jnp.bfloat16), _sds((S, G), jnp.bfloat16),
               _sds((S, H, G), jnp.bfloat16), _sds((S, B, T), jnp.bfloat16))


def test_wide_eval_block_fwd_lowers():
    """The eval sweep's widest block point (eval_scan_block_b=4096,
    fwd-only — scripts/sweep_rnn_blocks.py's eval curve): a 4096-row
    block is a new BlockSpec geometry the train path never compiles, so
    it needs its own Mosaic legality pin before it spends chip time."""
    B, T, H = 4096, 60, 128
    G = 4 * H

    def fwd(hin, wx, b, wh, m):
        return rnn_scan_fused("lstm", hin, wx, b, wh, m,
                              block_b=4096, interpret=False).sum()

    _lower_tpu(fwd, _sds((B, T, H), jnp.bfloat16),
               _sds((H, G), jnp.bfloat16), _sds((G,), jnp.bfloat16),
               _sds((H, G), jnp.bfloat16), _sds((B, T), jnp.bfloat16))
