"""Durable-serving-state lane (``-m durable``): crash-consistent zoo
snapshots, verified zero-cold-start restore (DESIGN.md §20).

Pins, in order of importance:

* **Crash consistency, end to end** — a serving process SIGKILLed
  mid-publish at a fault-injected ``zoo_persist``/``manifest_write``
  site (utils/faults.py ``kind=sigkill``, a REAL subprocess — no
  handler, no cleanup) restores to the OLD or the NEW generation, never
  a torn one, with the restored generation's scores verified bit-equal
  to its publish-time parity probe before it may serve.
* **Zero-cold-start** — with the serialized-executable artifacts
  loading, the restore path pays ZERO jit traces (counted), and drift
  references re-stamp from the serialized sketches without re-scoring.
* **Verification ladder** — a future-schema or truncated manifest is
  rejected loudly (quarantine + fresh-start fallback, never
  half-parsed); a params-checksum or probe mismatch quarantines the
  generation and falls back to the next-older committed one, else to
  fresh retrain.
* **Retention/GC** — ``LFM_ZOO_KEEP_GENERATIONS`` prunes superseded
  snapshots under the journal discipline; orphans from a crashed
  commit are swept at startup (journal replay).
* **Non-interference** — ``LFM_ZOO_PERSIST`` unset/0 means no store
  object and byte-identical serving paths (steady state still pays
  zero traces / zero panel H2D).
* **In-process batcher recovery** — ``restart_batcher()`` resurrects a
  dead batcher with the zoo, generations and rolling stats intact.

Module named early in the alphabet on purpose: it must sort before the
tier-1 timebox cut (ROADMAP tier-1 notes).
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import clear_panel_cache
from lfm_quant_tpu.serve import ScoringService, ZooStore
from lfm_quant_tpu.serve import persist
from lfm_quant_tpu.train import reuse
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.utils import faults, metrics, telemetry
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.durable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(n_firms=48, window=6, seed=0, epochs=1, name="durable_t"):
    return RunConfig(
        name=name,
        data=DataConfig(n_firms=n_firms, n_months=140, n_features=4,
                        window=window, dates_per_batch=4,
                        firms_per_date=24),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (8,)}),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=2,
                          loss="mse"),
        seed=seed,
    )


def _universe(seed=0, panel_seed=5, fit=False):
    panel = synthetic_panel(n_firms=48, n_months=140, n_features=4,
                            seed=panel_seed)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(seed=seed), splits)
    if fit:
        tr.fit()
    else:
        tr.state = tr.init_state()
    return tr


def _service(store_dir=None, **kw):
    kw.setdefault("max_rows", 2)
    kw.setdefault("max_wait_ms", 0.5)
    return ScoringService(persist_dir=store_dir, **kw)


def _simulate_process_death():
    reuse.clear_program_cache()
    clear_panel_cache()


@pytest.fixture(autouse=True)
def _durable_hygiene(monkeypatch):
    """No persist knob, no faults, fresh caches — in AND out."""
    monkeypatch.delenv("LFM_ZOO_PERSIST", raising=False)
    monkeypatch.delenv("LFM_ZOO_KEEP_GENERATIONS", raising=False)
    monkeypatch.delenv("LFM_FAULTS", raising=False)
    faults.configure("")
    _simulate_process_death()
    yield
    faults.configure("")
    _simulate_process_death()


# ---- knobs / non-interference -------------------------------------------


def test_persist_knob_off_is_exact_noop(monkeypatch, tmp_path):
    assert persist.persist_dir_default() is None
    assert not persist.persist_enabled()
    monkeypatch.setenv("LFM_ZOO_PERSIST", "0")
    assert persist.persist_dir_default() is None
    monkeypatch.setenv("LFM_ZOO_PERSIST", str(tmp_path / "store"))
    assert persist.persist_dir_default() == str(tmp_path / "store")
    assert persist.persist_enabled()
    monkeypatch.delenv("LFM_ZOO_PERSIST")
    monkeypatch.setenv("LFM_ZOO_KEEP_GENERATIONS", "5")
    assert persist.keep_generations_default() == 5
    monkeypatch.delenv("LFM_ZOO_KEEP_GENERATIONS")
    assert persist.keep_generations_default() == 2
    # Off means NO store object — and the serving steady state keeps
    # the serve-lane contract: zero traces, zero panel H2D per request.
    svc = _service()
    assert svc.store is None
    try:
        svc.register("us", _universe())
        m = svc.serveable_months("us")[5]
        svc.score("us", m)  # settle
        snap = REUSE_COUNTERS.snapshot()
        svc.score("us", m)
        d = REUSE_COUNTERS.delta(snap)
        assert d.get("jit_traces", 0) == 0, d
        assert d.get("panel_transfers", 0) == 0, d
    finally:
        svc.close()


# ---- the roundtrip -------------------------------------------------------


def test_publish_restore_roundtrip_bit_equal(tmp_path):
    store_dir = str(tmp_path / "store")
    svc = _service(store_dir)
    try:
        svc.register("us", _universe(fit=True))
        months = svc.serveable_months("us")
        refs = {m: svc.score("us", m).scores.copy()
                for m in (months[3], months[len(months) // 2], months[-1])}
        had_sketch = svc.zoo.current("us").ref_sketch is not None
    finally:
        svc.close()
    assert os.path.exists(os.path.join(store_dir, "manifest.json"))

    _simulate_process_death()
    svc2 = _service(store_dir)
    try:
        snap = REUSE_COUNTERS.snapshot()
        restored = svc2.restore()
        d = REUSE_COUNTERS.delta(snap)
        assert [r["universe"] for r in restored] == ["us"]
        info = restored[0]
        assert info["generation"] == 0
        assert info["probe"] == "bit_equal"
        # Zero-cold-start: every warmed bucket came from a serialized
        # executable — the restore path paid ZERO jit traces.
        assert info["execs_loaded"] > 0
        assert info["execs_recompiled"] == 0
        assert d.get("jit_traces", 0) == 0, d
        # Served numbers are the published generation's, bit for bit.
        for m, ref in refs.items():
            np.testing.assert_array_equal(svc2.score("us", m).scores, ref)
        # Drift reference re-stamped from the serialized sketch — no
        # re-scoring, no new traces (metrics default-on ⇒ stamped).
        entry = svc2.zoo.current("us")
        if had_sketch:
            assert entry.ref_sketch is not None
            assert entry.live_sketch is not None
    finally:
        svc2.close()


def test_score_single_month_matches_served_path(tmp_path):
    """The probe helper and the live serving path are the same compiled
    program — bit-equal by construction, which is what makes the
    parity probe a statement about the snapshot, not about two forks."""
    svc = _service()
    try:
        svc.register("us", _universe(fit=True))
        m = svc.serveable_months("us")[7]
        served = svc.score("us", m)
        entry = svc.zoo.current("us")
        probe = persist.score_single_month(entry, m, svc.max_rows)
        np.testing.assert_array_equal(probe, served.scores)
    finally:
        svc.close()


def test_sketch_state_roundtrip():
    rng = np.random.default_rng(0)
    sk = metrics.ScoreSketch.reference(rng.normal(size=2048))
    sk.record(rng.normal(0.2, 1.1, size=512))
    state = sk.to_state()
    json.dumps(state)  # must be JSON-serializable
    sk2 = metrics.ScoreSketch.from_state(state)
    np.testing.assert_array_equal(sk.counts(), sk2.counts())
    assert sk2.n == sk.n
    live = sk.live_twin()
    live.record(rng.normal(0.5, 1.0, size=4096))
    assert sk.psi(live) == pytest.approx(sk2.psi(live))
    bad = dict(state, counts=state["counts"][:-2])
    with pytest.raises(ValueError, match="counts length"):
        metrics.ScoreSketch.from_state(bad)


# ---- manifest schema evolution / corruption ------------------------------


def _tamper_manifest(store_dir, fn):
    path = os.path.join(store_dir, "manifest.json")
    with open(path) as fh:
        m = json.load(fh)
    out = fn(m)
    with open(path, "w") as fh:
        if isinstance(out, str):
            fh.write(out)
        else:
            json.dump(out, fh)


def _publish_one(store_dir, fit=False):
    svc = _service(store_dir)
    try:
        svc.register("us", _universe(fit=fit))
        m = svc.serveable_months("us")[5]
        ref = svc.score("us", m).scores.copy()
    finally:
        svc.close()
    _simulate_process_death()
    return m, ref


def test_future_schema_manifest_quarantined(tmp_path):
    store_dir = str(tmp_path / "store")
    _publish_one(store_dir)
    _tamper_manifest(store_dir, lambda m: dict(m, schema_version=99))
    svc = _service(store_dir)
    try:
        with pytest.warns(RuntimeWarning, match="QUARANTINED"):
            restored = svc.restore()
        assert restored == []  # loud fresh-start fallback, never half-parsed
        assert not os.path.exists(os.path.join(store_dir, "manifest.json"))
        assert any(".quarantined." in f for f in os.listdir(store_dir))
        # The unreadable manifest's snapshots are EVIDENCE, not orphans:
        # the sweep must not delete them.
        assert os.path.isdir(os.path.join(store_dir, "universes", "us",
                                          "gen_00000"))
    finally:
        svc.close()


def test_corrupt_manifest_quarantined(tmp_path):
    store_dir = str(tmp_path / "store")
    _publish_one(store_dir)
    _tamper_manifest(store_dir, lambda m: json.dumps(m)[:40])  # truncated
    svc = _service(store_dir)
    try:
        with pytest.warns(RuntimeWarning, match="QUARANTINED"):
            assert svc.restore() == []
        assert any(".quarantined." in f for f in os.listdir(store_dir))
    finally:
        svc.close()


def test_publish_refuses_over_unreadable_manifest(tmp_path):
    """Publishing over a corrupt committed manifest must fail LOUDLY —
    and keep failing (no quarantine side effect that would let the
    NEXT publish fork a fresh manifest silently disowning, and letting
    the next sweep delete, every other universe's committed
    snapshots). Quarantine is restore's decision, not publish's."""
    store_dir = str(tmp_path / "store")
    _publish_one(store_dir)
    _tamper_manifest(store_dir, lambda m: "{ this is not json")
    svc = _service(store_dir)
    try:
        with pytest.raises(RuntimeError, match="refusing to publish"):
            svc.register("us", _universe(seed=9))
        # NOT one-shot: the manifest is still in place and a second
        # publish refuses again instead of committing a fresh one.
        assert os.path.exists(os.path.join(store_dir, "manifest.json"))
        with pytest.raises(RuntimeError, match="refusing to publish"):
            svc.register("us", _universe(seed=10))
    finally:
        svc.close()
    # The committed snapshot is untouched evidence, and nothing was
    # quarantined — publish is read-only toward the corrupt manifest.
    assert os.path.isdir(os.path.join(store_dir, "universes", "us",
                                      "gen_00000"))
    assert os.path.exists(os.path.join(store_dir, "manifest.json"))


# ---- integrity: checksum + parity probe ----------------------------------


def test_params_checksum_mismatch_quarantines(tmp_path):
    store_dir = str(tmp_path / "store")
    _publish_one(store_dir)

    def flip(m):
        rec = m["universes"]["us"]["generations"][-1]
        rec["params_sha256"] = "0" * 64
        return m

    _tamper_manifest(store_dir, flip)
    svc = _service(store_dir)
    try:
        with pytest.warns(RuntimeWarning, match="fresh retrain"):
            assert svc.restore() == []
        udir = os.path.join(store_dir, "universes", "us")
        assert any(".quarantined." in f for f in os.listdir(udir))
    finally:
        svc.close()


def test_probe_mismatch_quarantines_and_falls_back(tmp_path):
    """A tampered snapshot whose scores would come out wrong is
    quarantined; restore falls back to the next-older COMMITTED
    generation and serves ITS (verified) numbers."""
    store_dir = str(tmp_path / "store")
    svc = _service(store_dir)
    try:
        svc.register("us", _universe(seed=0, fit=False))   # gen 0
        svc.register("us", _universe(seed=1, fit=False))   # gen 1
        m = svc.serveable_months("us")[5]
        gen1_scores = svc.score("us", m).scores.copy()
    finally:
        svc.close()
    _simulate_process_death()
    # Corrupt gen 1's probe artifact: verification must now fail.
    gdir = os.path.join(store_dir, "universes", "us", "gen_00001")
    probe_path = os.path.join(gdir, "probe.npz")
    with np.load(probe_path, allow_pickle=False) as z:
        month, fi, scores = int(z["month"]), z["firm_idx"], z["scores"]
    np.savez(probe_path, month=np.asarray(month, np.int64), firm_idx=fi,
             scores=scores + np.float32(1e-3))
    svc2 = _service(store_dir)
    try:
        with pytest.warns(RuntimeWarning, match="QUARANTINED"):
            restored = svc2.restore()
        assert [r["generation"] for r in restored] == [0]
        udir = os.path.join(store_dir, "universes", "us")
        assert any(f.startswith("gen_00001.quarantined.")
                   for f in os.listdir(udir))
        # Gen 0 serves — verified — and its numbers differ from gen 1's
        # (different seeds), i.e. the fallback did not serve the
        # corrupt generation's numbers.
        r = svc2.score("us", m)
        assert r.generation == 0
        assert not np.array_equal(r.scores, gen1_scores)
    finally:
        svc2.close()


def test_corrupt_shared_panel_quarantines_panel_not_generations(tmp_path):
    """Generations share a content-addressed panel file; one flipped
    bit in it must quarantine THAT file — not cascade renames over the
    healthy generation directories (which are the operator's path back
    once the panel is re-materialized)."""
    store_dir = str(tmp_path / "store")
    _publish_one(store_dir)
    udir = os.path.join(store_dir, "universes", "us")
    panel_file = next(f for f in os.listdir(udir)
                      if f.startswith("panel_") and f.endswith(".npz"))
    path = os.path.join(udir, panel_file)
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(path, "wb") as fh:
        fh.write(bytes(blob))
    svc = _service(store_dir)
    try:
        with pytest.warns(RuntimeWarning, match="QUARANTINED"):
            assert svc.restore() == []  # nothing verifiable to serve
    finally:
        svc.close()
    names = os.listdir(udir)
    assert any(f.startswith(panel_file + ".quarantined.") for f in names)
    # The healthy snapshot dir stayed in place, un-renamed.
    assert "gen_00000" in names


def test_environmental_restore_failure_never_quarantines(tmp_path):
    """A transient infrastructure fault DURING a restore (injected
    panel-H2D fault) must fail the attempt — loudly — without
    condemning the snapshot: once the environment heals, the same
    store restores bit-equal."""
    store_dir = str(tmp_path / "store")
    m, ref = _publish_one(store_dir)
    svc = _service(store_dir)
    try:
        faults.configure("panel_h2d:n=1,kind=permanent")
        with pytest.warns(RuntimeWarning, match="NOT quarantined"):
            assert svc.restore() == []  # the attempt fails...
        faults.configure("")
        udir = os.path.join(store_dir, "universes", "us")
        assert not any(".quarantined." in f for f in os.listdir(udir))
        restored = svc.restore()  # ...and the healed retry serves
        assert [r["generation"] for r in restored] == [0]
        np.testing.assert_array_equal(svc.score("us", m).scores, ref)
    finally:
        faults.configure("")
        svc.close()


# ---- retention / GC / sweep ----------------------------------------------


def test_retention_prunes_superseded_generations(tmp_path):
    store_dir = str(tmp_path / "store")
    svc = _service(store_dir, keep_generations=2)
    try:
        for seed in range(3):  # gens 0, 1, 2
            svc.register("us", _universe(seed=seed))
    finally:
        svc.close()
    udir = os.path.join(store_dir, "universes", "us")
    gens = sorted(f for f in os.listdir(udir) if f.startswith("gen_")
                  and ".quarantined." not in f)
    assert gens == ["gen_00001", "gen_00002"]  # gen 0 pruned by GC
    with open(os.path.join(store_dir, "manifest.json")) as fh:
        m = json.load(fh)
    assert [g["generation"] for g in
            m["universes"]["us"]["generations"]] == [1, 2]
    _simulate_process_death()
    svc2 = _service(store_dir)
    try:
        restored = svc2.restore()
        assert [r["generation"] for r in restored] == [2]
    finally:
        svc2.close()


def test_sweep_reclaims_orphans_and_replays_journal(tmp_path):
    store_dir = str(tmp_path / "store")
    _publish_one(store_dir)
    store = ZooStore(store_dir)
    # Forge a crashed publish: dangling journal begin + staged debris.
    orphan_rel = os.path.join("universes", "us", "gen_00007")
    os.makedirs(os.path.join(store_dir, orphan_rel))
    store._journal({"op": "publish", "universe": "us", "generation": 7,
                    "dir": orphan_rel, "state": "begin", "ts": 0.0})
    with open(os.path.join(store_dir, "tmp", "leftover.bin"), "wb") as fh:
        fh.write(b"x" * 16)
    out = store.sweep()
    assert out["journal_replays"] == 1
    assert out["orphans"] >= 2  # the staged dir + the tmp leftover
    assert not os.path.exists(os.path.join(store_dir, orphan_rel))
    assert os.listdir(os.path.join(store_dir, "tmp")) == []
    # The journal is folded down and truncated; committed state intact.
    assert os.path.getsize(store.journal_path) == 0
    assert os.path.isdir(os.path.join(store_dir, "universes", "us",
                                      "gen_00000"))
    assert store.sweep() == {"journal_replays": 0, "orphans": 0}


# ---- fault sites: crash-consistency in-process ---------------------------


@pytest.mark.parametrize("spec", ["zoo_persist:at=0,kind=permanent",
                                  "manifest_write:at=0,kind=permanent"])
def test_publish_fault_leaves_old_generation_committed(tmp_path, spec):
    """A publish that dies anywhere before the manifest rename commits
    NOTHING: the old manifest — and therefore the old generation — is
    what a restore recovers, never a torn mix."""
    store_dir = str(tmp_path / "store")
    m, ref = _publish_one(store_dir)
    svc = _service(store_dir)
    try:
        svc.restore()
        faults.configure(spec)
        with pytest.raises(faults.PermanentFault):
            svc.register("us", _universe(seed=9))
        faults.configure("")
    finally:
        svc.close()
    _simulate_process_death()
    svc2 = _service(store_dir)
    try:
        restored = svc2.restore()
        assert [r["generation"] for r in restored] == [0]
        np.testing.assert_array_equal(svc2.score("us", m).scores, ref)
    finally:
        svc2.close()


def test_same_generation_republish_never_guts_committed_snapshot(tmp_path):
    """A cold re-register over an existing store re-publishes the SAME
    generation number. Staging must never touch the committed snapshot
    before the commit point: a crash mid-republish leaves the ORIGINAL
    generation restorable bit for bit; a clean republish supersedes it
    and reclaims the old directory."""
    store_dir = str(tmp_path / "store")
    m, ref = _publish_one(store_dir)
    # Crashed republish of gen 0 (different params — seed 9), dying
    # right before the manifest rename: the original must survive.
    svc = _service(store_dir)
    try:
        faults.configure("manifest_write:at=0,kind=permanent")
        with pytest.raises(faults.PermanentFault):
            svc.register("us", _universe(seed=9))
        faults.configure("")
    finally:
        svc.close()
    _simulate_process_death()
    svc2 = _service(store_dir)
    try:
        restored = svc2.restore()
        assert [r["generation"] for r in restored] == [0]
        np.testing.assert_array_equal(svc2.score("us", m).scores, ref)
    finally:
        svc2.close()
    _simulate_process_death()
    # Clean republish of gen 0: supersedes, old snapshot dir reclaimed.
    svc3 = _service(store_dir)
    try:
        svc3.register("us", _universe(seed=9))
        new_ref = svc3.score("us", m).scores.copy()
    finally:
        svc3.close()
    assert not np.array_equal(new_ref, ref)  # genuinely new params
    udir = os.path.join(store_dir, "universes", "us")
    gens = [f for f in os.listdir(udir) if f.startswith("gen_")
            and ".quarantined." not in f]
    assert len(gens) == 1  # exactly one committed gen-0 snapshot
    _simulate_process_death()
    svc4 = _service(store_dir)
    try:
        restored = svc4.restore()
        assert [r["generation"] for r in restored] == [0]
        np.testing.assert_array_equal(svc4.score("us", m).scores, new_ref)
    finally:
        svc4.close()


def test_publish_fault_after_rename_is_committed(tmp_path):
    """Past the rename the NEW generation is durable even though the
    journal's commit line (and the in-memory zoo.publish) never ran —
    the manifest is the single commit point."""
    store_dir = str(tmp_path / "store")
    _publish_one(store_dir)
    svc = _service(store_dir)
    try:
        svc.restore()
        faults.configure("manifest_write:at=1,kind=permanent")
        with pytest.raises(faults.PermanentFault):
            svc.register("us", _universe(seed=9))
        faults.configure("")
    finally:
        svc.close()
    _simulate_process_death()
    svc2 = _service(store_dir)
    try:
        restored = svc2.restore()
        assert [r["generation"] for r in restored] == [1]
        assert restored[0]["probe"] == "bit_equal"
    finally:
        svc2.close()


# ---- the acceptance pin: SIGKILL mid-publish, real subprocess ------------


_CHILD = """\
import sys
sys.path.insert(0, sys.argv[3])
from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, \\
    RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.serve import ScoringService
from lfm_quant_tpu.train.loop import Trainer

mode, store_dir = sys.argv[1], sys.argv[2]
seed = 0 if mode == "gen0" else 9
cfg = RunConfig(
    name="durable_child",
    data=DataConfig(n_firms=48, n_months=140, n_features=4, window=6,
                    dates_per_batch=4, firms_per_date=24),
    model=ModelConfig(kind="mlp", kwargs={"hidden": (8,)}),
    optim=OptimConfig(lr=1e-3, epochs=1, warmup_steps=2, loss="mse"),
    seed=seed)
panel = synthetic_panel(n_firms=48, n_months=140, n_features=4, seed=5)
splits = PanelSplits.by_date(panel, 197801, 198001)
tr = Trainer(cfg, splits)
tr.state = tr.init_state()
svc = ScoringService(max_rows=2, max_wait_ms=0.5, persist_dir=store_dir)
if mode == "gen1":
    assert [r["generation"] for r in svc.restore()] == [0]
svc.register("us", tr)  # gen1 mode: the SIGKILL lands inside this publish
svc.close()
print("PUBLISHED")
"""


@pytest.mark.parametrize("spec,expect_gen", [
    ("zoo_persist:at=0,kind=sigkill", 0),       # killed before staging
    ("manifest_write:at=0,kind=sigkill", 0),    # killed before the rename
    ("manifest_write:at=1,kind=sigkill", 1),    # killed after the rename
])
def test_sigkill_mid_publish_subprocess_recovers(tmp_path, spec,
                                                 expect_gen):
    """The acceptance pin, as a REAL subprocess killed with SIGKILL —
    no handler, no cleanup, no atexit — at a fault-injected
    ``zoo_persist``/``manifest_write`` site mid-publish. A restore
    recovers to the old or the new generation (never torn), serves
    scores the restore has verified BIT-EQUAL to that generation's
    publish-time probe, and sweeps the crashed commit's debris."""
    script = tmp_path / "child_publish.py"
    script.write_text(_CHILD)
    store_dir = str(tmp_path / "store")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("LFM_FAULTS", None)
    env.pop("LFM_ZOO_PERSIST", None)

    out0 = subprocess.run(
        [sys.executable, str(script), "gen0", store_dir, REPO],
        env=env, capture_output=True, text=True, timeout=240)
    assert out0.returncode == 0, (out0.returncode, out0.stderr[-800:])

    env_kill = dict(env, LFM_FAULTS=spec)
    out1 = subprocess.run(
        [sys.executable, str(script), "gen1", store_dir, REPO],
        env=env_kill, capture_output=True, text=True, timeout=240)
    assert out1.returncode == -signal.SIGKILL, (
        out1.returncode, out1.stderr[-800:])
    assert "PUBLISHED" not in out1.stdout  # it really died mid-publish

    # The restarted "process": restore recovers exactly one committed
    # generation, verified, and serving works with zero incorrect
    # responses (the probe gate ran before publish).
    svc = _service(store_dir)
    try:
        restored = svc.restore()
        assert [r["universe"] for r in restored] == ["us"]
        assert restored[0]["generation"] == expect_gen
        assert restored[0]["probe"] == "bit_equal"
        m = svc.serveable_months("us")[5]
        r = svc.score("us", m)
        assert r.generation == expect_gen and r.scores.size > 0
        # The crashed commit left no torn state behind: every
        # non-quarantined gen dir is referenced by the manifest.
        with open(os.path.join(store_dir, "manifest.json")) as fh:
            manifest = json.load(fh)
        referenced = {os.path.basename(g["dir"]) for g in
                      manifest["universes"]["us"]["generations"]}
        udir = os.path.join(store_dir, "universes", "us")
        on_disk = {f for f in os.listdir(udir) if f.startswith("gen_")
                   and ".quarantined." not in f}
        assert on_disk == referenced
    finally:
        svc.close()


# ---- in-process batcher recovery (serve/batcher.py satellite) ------------


def test_restart_batcher_recovers_dead_service(recwarn):
    """The ``BatcherDeadError`` "unready until restarted" path now has
    an in-process remedy: restart_batcher() replaces the thread with
    the zoo, generations and rolling stats intact; pending submits were
    failed loudly exactly once (by the death guard), and post-restart
    requests serve bit-equal."""
    svc = _service()
    try:
        svc.register("us", _universe(fit=True))
        m = svc.serveable_months("us")[5]
        ref = svc.score("us", m).scores.copy()
        boom = RuntimeError("boom in _next_batch")
        # After the swap the loop dies at its NEXT _next_batch call:
        # if it was still blocked inside the real one, it serves one
        # more request first; if it had not re-entered yet, the very
        # next submit meets a dead batcher. Both orderings are the
        # death guard working (fails pending loudly, marks unready).
        svc.batcher._next_batch = lambda: (_ for _ in ()).throw(boom)
        from lfm_quant_tpu.serve.errors import BatcherDeadError

        try:
            svc.score("us", m)
        except BatcherDeadError:
            pass
        deadline = time.perf_counter() + 5.0
        while svc.batcher._dead is None and time.perf_counter() < deadline:
            time.sleep(0.001)
        assert svc.batcher._dead is not None
        assert not svc.health()["ok"]
        with pytest.raises(BatcherDeadError):
            svc.score("us", m)  # dead batcher fast-fails submits
        completed_at_death = svc.batcher.stats()["completed"]
        gen_before = svc.zoo.generation("us")

        out = svc.restart_batcher()
        assert out["ok"] and out["was_dead"]
        h = svc.health()
        assert h["ok"] and h["circuit"] == "closed"
        assert svc.zoo.generation("us") == gen_before  # zoo untouched
        r = svc.score("us", m)
        np.testing.assert_array_equal(r.scores, ref)
        stats = svc.batcher.stats()
        # Rolling stats carried across the restart (continuity), plus
        # exactly the one post-restart request.
        assert stats["completed"] == completed_at_death + 1
        assert telemetry.COUNTERS.get("serve_batcher_dead") == 0
        assert telemetry.COUNTERS.get("serve_batcher_restarts") >= 1
    finally:
        telemetry.COUNTERS.set("serve_batcher_dead", 0)
        svc.close()


# ---- observability -------------------------------------------------------


def test_restore_section_in_trace_report(tmp_path):
    store_dir = str(tmp_path / "store")
    run_dir = str(tmp_path / "run")
    _publish_one(store_dir)
    svc = _service(store_dir)
    try:
        with telemetry.run_scope(run_dir, extra={"entry": "test_durable"}):
            restored = svc.restore()
    finally:
        svc.close()
    from lfm_quant_tpu.serve.stats import load_trace_report

    tr_mod = load_trace_report(REPO)
    rep = tr_mod.build_report(tr_mod.load_run(run_dir))
    rs = rep.get("restore")
    assert rs is not None
    assert rs["universes_restored"] == 1
    assert rs["restore_wall_s"] > 0
    assert rs["integrity"] == "bit_equal"
    assert rs["execs_loaded"] == restored[0]["execs_loaded"]
    assert rs["execs_recompiled"] == 0
    assert rs["probes_ok"] == 1 and rs["integrity_failures"] == 0
    assert rs["generations"][0]["universe"] == "us"
