"""Async epoch pipeline (train/pipeline.py): parity + crash semantics.

The pipeline's contract is that it reorders work, never results:
``LFM_ASYNC=1`` must produce the same epoch history, best-val-IC epoch,
early-stop epoch and restored best params as the lock-step reference
(``LFM_ASYNC=0``), the speculative lookahead epoch must never leak into
history or checkpoints, and a crash with an async checkpoint in flight
must resume from the last DURABLE step. All tests carry the
``pipeline`` marker — the fast CI guard (``pytest -m pipeline``)
against a refactor that quietly breaks the overlap's determinism.
"""

import json
import os

import jax
import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.train.loop import FitHarness, Trainer
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.pipeline

#: History fields that must match bit-for-bit across pipeline modes
#: (timing fields — ts, firm_months_per_sec — legitimately differ).
_DET_FIELDS = ("epoch", "train_loss", "grad_norm", "val_ic", "val_mse")


def _cfg(tmp, epochs=4, patience=99, lr=1e-3, n_seeds=1):
    return RunConfig(
        name="pipe",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=lr, epochs=epochs, warmup_steps=5, loss="mse",
                          early_stop_patience=patience),
        seed=0,
        n_seeds=n_seeds,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)


@pytest.fixture(scope="module")
def splits(panel):
    return PanelSplits.by_date(panel, 198001, 198201)


def _fit(tmp, splits, monkeypatch, async_on, name, **cfg_kw):
    monkeypatch.setenv("LFM_ASYNC", "1" if async_on else "0")
    monkeypatch.setenv("LFM_ASYNC_CKPT", "1" if async_on else "0")
    cfg = _cfg(tmp, **cfg_kw)
    run_dir = str(tmp / name)
    ctor = Trainer
    if cfg.n_seeds > 1:
        from lfm_quant_tpu.train.ensemble import EnsembleTrainer

        ctor = EnsembleTrainer
    trainer = ctor(cfg, splits, run_dir=run_dir)
    summary = trainer.fit()
    return trainer, summary, run_dir


def _det(history):
    return [tuple((k, r[k]) for k in _DET_FIELDS if k in r) for r in history]


def _params_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def test_async_sync_parity(splits, tmp_path, monkeypatch):
    """The acceptance contract: identical epoch history (losses, ICs,
    mses bit-for-bit), best epoch, epochs run, and restored best params
    between LFM_ASYNC=0 and LFM_ASYNC=1."""
    t0, s0, _ = _fit(tmp_path, splits, monkeypatch, False, "sync")
    t1, s1, _ = _fit(tmp_path, splits, monkeypatch, True, "async")
    assert _det(s0["history"]) == _det(s1["history"])
    assert s0["best_epoch"] == s1["best_epoch"]
    assert s0["epochs_run"] == s1["epochs_run"]
    assert s0["best_val_ic"] == s1["best_val_ic"]
    # Both ended on the best-checkpoint restore — same params.
    assert _params_equal(t0.state.params, t1.state.params)


def test_async_sync_parity_under_early_stop(splits, tmp_path, monkeypatch):
    """lr=0 freezes val IC after epoch 0, so patience=1 stops the run
    deterministically: with lookahead, epoch 2 is already dispatched
    when the decision lands — it must be discarded, leaving history,
    epochs_run, the early-stop epoch and the checkpoint lines identical
    to the lock-step run (at most one WASTED epoch, never a recorded
    one)."""
    kw = dict(epochs=8, patience=1, lr=0.0)
    t0, s0, d0 = _fit(tmp_path, splits, monkeypatch, False, "es_sync", **kw)
    t1, s1, d1 = _fit(tmp_path, splits, monkeypatch, True, "es_async", **kw)
    assert s0["epochs_run"] < 8, "geometry must actually early-stop"
    assert s0["epochs_run"] == s1["epochs_run"]
    assert _det(s0["history"]) == _det(s1["history"])
    assert s1["lookahead_overrun"], "async stop should strand one dispatch"
    assert not s0["lookahead_overrun"]
    assert _params_equal(t0.state.params, t1.state.params)
    # The overrun epoch never reached either checkpoint line or the
    # metrics stream.
    spe = t1.train_sampler.batches_per_epoch()
    from lfm_quant_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(os.path.join(d1, "ckpt", "latest"))
    assert mgr.latest_step() == s1["epochs_run"] * spe
    mgr.close()
    lines = [json.loads(l)
             for l in open(os.path.join(d1, "metrics.jsonl"))]
    assert [l["epoch"] for l in lines] == list(range(s1["epochs_run"]))


def test_overrun_rollback_without_run_dir(splits, tmp_path, monkeypatch):
    """Early stop with a stranded lookahead epoch and NO run dir (no
    best checkpoint to restore): the driver must roll the final state
    back to the last recorded epoch's snapshot, so downstream consumers
    (predict, walk-forward warm starts) see identical params in both
    pipeline modes."""
    params = {}
    for async_on in (False, True):
        monkeypatch.setenv("LFM_ASYNC", "1" if async_on else "0")
        monkeypatch.setenv("LFM_ASYNC_CKPT", "1" if async_on else "0")
        # Real lr (not the frozen lr=0 shortcut): the stranded epoch
        # genuinely trains, so an un-rolled-back state WOULD differ.
        trainer = Trainer(_cfg(tmp_path, epochs=8, patience=1, lr=1e-3),
                          splits, run_dir=None)
        s = trainer.fit()
        assert s["epochs_run"] < 8
        assert s["lookahead_overrun"] == async_on
        params[async_on] = trainer.state.params
    assert _params_equal(params[False], params[True])


def test_ensemble_async_sync_parity(splits, tmp_path, monkeypatch):
    """Same contract through the seed-vmapped ensemble loop (stacked
    state snapshot + one device_get of the [S, M] IC panel)."""
    kw = dict(n_seeds=2, epochs=3)
    t0, s0, _ = _fit(tmp_path, splits, monkeypatch, False, "ens_sync", **kw)
    t1, s1, _ = _fit(tmp_path, splits, monkeypatch, True, "ens_async", **kw)
    assert _det(s0["history"]) == _det(s1["history"])
    assert s0["best_epoch"] == s1["best_epoch"]
    assert _params_equal(t0.state.params, t1.state.params)


def test_resume_reconciles_inflight_async_checkpoint(splits, tmp_path,
                                                     monkeypatch):
    """Crash with an async save in flight: the progress sidecar (written
    when the save STARTS) can run ahead of the last COMMITTED step.
    Resume must trust the durable checkpoint — deriving its counters
    from the checkpoint step — and retrain the lost epochs instead of
    skipping them."""
    t1, s1, run_dir = _fit(tmp_path, splits, monkeypatch, True, "crash",
                           epochs=2)
    spe = t1.train_sampler.batches_per_epoch()
    # Forge the in-flight-crash artifact: sidecar claims epoch 3 done,
    # but the latest durable checkpoint is epoch 1's.
    with open(os.path.join(run_dir, "fit_progress.json"), "w") as fh:
        json.dump({"epoch": 3, "best_ic": 99.0, "best_epoch": 3,
                   "bad_epochs": 0}, fh)
    t2 = Trainer(_cfg(tmp_path, epochs=4), splits, run_dir=run_dir)
    s2 = t2.fit(resume=True)
    # Counters came from the checkpoint (step 2·spe → epoch 2), not the
    # bogus sidecar (which would have resumed at epoch 4 with a fake
    # best_ic pinning best forever).
    assert [r["epoch"] for r in s2["history"]] == [2, 3]
    assert s2["best_val_ic"] != 99.0
    assert s2["steps"] == 4 * spe
    # Best tracking was RECOVERED from the durable best line (not reset
    # to -inf): the resumed best can only improve on the committed one,
    # so a bad retrained epoch can never overwrite a better durable best.
    assert s2["best_val_ic"] >= s1["best_val_ic"]


def test_resume_discards_phantom_best_claim(splits, tmp_path, monkeypatch):
    """Crash with the BEST save in flight but the latest save committed:
    the sidecar claims a best epoch the best line never durably holds.
    Resume must fall back to the committed best (IC recovered from
    metrics.jsonl) — pinning the phantom IC would make finalize restore
    a checkpoint that never matched the reported best."""
    t1, s1, run_dir = _fit(tmp_path, splits, monkeypatch, True, "phantom",
                           epochs=2, lr=0.0)
    assert s1["best_epoch"] == 0  # lr=0: only epoch 0 ever improves
    real_best_ic = s1["history"][0]["val_ic"]
    # Forge the crash artifact: sidecar consistent with the LATEST line
    # (epoch 1 done) but claiming an epoch-1 best whose save never
    # committed (the durable best is still epoch 0's).
    with open(os.path.join(run_dir, "fit_progress.json"), "w") as fh:
        json.dump({"epoch": 1, "best_ic": 99.0, "best_epoch": 1,
                   "bad_epochs": 0}, fh)
    t2 = Trainer(_cfg(tmp_path, epochs=4, lr=0.0), splits, run_dir=run_dir)
    s2 = t2.fit(resume=True)
    assert [r["epoch"] for r in s2["history"]] == [2, 3]
    assert s2["best_epoch"] == 0
    assert s2["best_val_ic"] == real_best_ic
    # finalize restored the checkpoint the counters describe.
    assert _params_equal(t1.state.params, t2.state.params)


def test_resume_rejects_stale_sidecar_behind_checkpoint(splits, tmp_path,
                                                        monkeypatch):
    """The inverse crash window: saves committed, sidecar write lost.
    A sidecar BEHIND the latest line must also be rejected — trusting
    it would retrain the committed epoch on top of its own result."""
    t1, s1, run_dir = _fit(tmp_path, splits, monkeypatch, True, "stale",
                           epochs=2)
    spe = t1.train_sampler.batches_per_epoch()
    with open(os.path.join(run_dir, "fit_progress.json"), "w") as fh:
        json.dump({"epoch": 0, "best_ic": s1["history"][0]["val_ic"],
                   "best_epoch": 0, "bad_epochs": 0}, fh)
    t2 = Trainer(_cfg(tmp_path, epochs=4), splits, run_dir=run_dir)
    s2 = t2.fit(resume=True)
    # Epoch 1 (committed) was NOT retrained; training resumed at 2.
    assert [r["epoch"] for r in s2["history"]] == [2, 3]
    assert s2["steps"] == 4 * spe


def test_sidecar_consistent_resume_unchanged(splits, tmp_path, monkeypatch):
    """The reconciliation guard must NOT fire on a healthy sidecar: a
    clean async-ckpt run resumes exactly where it stopped, with the
    sidecar's best/bad counters intact."""
    _fit(tmp_path, splits, monkeypatch, True, "clean", epochs=2)
    run_dir = str(tmp_path / "clean")
    t = Trainer(_cfg(tmp_path, epochs=4), splits, run_dir=run_dir)
    harness = FitHarness(run_dir, 4, 99, t.train_sampler.batches_per_epoch())
    restored = harness.resume(t.init_state()._asdict())
    assert restored is not None
    prog = json.load(open(os.path.join(run_dir, "fit_progress.json")))
    assert harness.start_epoch == prog["epoch"] + 1 == 2
    assert harness.best_ic == prog["best_ic"]


def test_one_host_sync_per_epoch(splits, tmp_path, monkeypatch):
    """The fused-fetch contract, measured: a fit's training loop pays
    exactly ONE counted blocking device→host fetch per recorded epoch
    (loss + grad-norm + val ICs + mse + step in a single device_get) —
    in BOTH pipeline modes."""
    for async_on, name in ((False, "sync1"), (True, "async1")):
        snap = REUSE_COUNTERS.snapshot()
        _, s, _ = _fit(tmp_path, splits, monkeypatch, async_on, name,
                       epochs=3)
        d = REUSE_COUNTERS.delta(snap)
        assert d["host_syncs"] == s["epochs_run"], (name, d)


def test_async_knobs_are_independent(splits, tmp_path, monkeypatch):
    """The two kill switches compose: lookahead with synchronous saves
    (LFM_ASYNC=1, LFM_ASYNC_CKPT=0) and lock-step with async saves
    (0, 1) both preserve the reference results — the four-way knob
    matrix shares one numerical identity."""
    t_ref, s_ref, _ = _fit(tmp_path, splits, monkeypatch, False, "ref")
    for async_loop, async_ckpt in ((True, False), (False, True)):
        monkeypatch.setenv("LFM_ASYNC", "1" if async_loop else "0")
        monkeypatch.setenv("LFM_ASYNC_CKPT", "1" if async_ckpt else "0")
        name = f"mix_{int(async_loop)}{int(async_ckpt)}"
        trainer = Trainer(_cfg(tmp_path), splits,
                          run_dir=str(tmp_path / name))
        s = trainer.fit()
        assert _det(s["history"]) == _det(s_ref["history"]), name
        assert _params_equal(t_ref.state.params, trainer.state.params), name
