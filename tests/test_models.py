"""Model (L3) tests: shapes, masking semantics, dtype policy, jit/grad."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.models import build_model

B, W, F = 8, 24, 6
KINDS = ["mlp", "lstm", "gru", "transformer", "lru"]


def make_batch(seed=0, all_valid=False):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((B, W, F)).astype(np.float32)
    if all_valid:
        m = np.ones((B, W), dtype=bool)
    else:
        m = rng.random((B, W)) < 0.8
        m[:, -1] = True  # anchor month always valid
        m[0, : W // 2] = False  # one firm with a short history
    x = np.where(m[..., None], x, 0.0)
    return jnp.asarray(x), jnp.asarray(m)


def init_and_apply(kind, x, m, **kw):
    model = build_model(kind, **kw)
    params = model.init(jax.random.key(0), x, m)
    return model, params, model.apply(params, x, m)


@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.fast
def test_forward_shape_and_dtype(kind):
    x, m = make_batch()
    _, _, y = init_and_apply(kind, x, m)
    assert y.shape == (B,)
    assert y.dtype == jnp.float32
    assert bool(jnp.isfinite(y).all())


@pytest.mark.parametrize("kind", KINDS)
def test_bf16_compute_fp32_params_fp32_out(kind):
    x, m = make_batch()
    model = build_model(kind, dtype=jnp.bfloat16)
    params = model.init(jax.random.key(0), x, m)
    leaves = jax.tree.leaves(params)
    assert all(l.dtype == jnp.float32 for l in leaves), "params must stay fp32"
    y = model.apply(params, x, m)
    assert y.dtype == jnp.float32, "head output must be fp32"
    assert bool(jnp.isfinite(y).all())


@pytest.mark.parametrize("kind", KINDS)
def test_masked_steps_do_not_affect_output(kind):
    """Changing features inside masked months must not change the forecast."""
    x, m = make_batch()
    model = build_model(kind)
    params = model.init(jax.random.key(0), x, m)
    y0 = model.apply(params, x, m)
    noise = jnp.asarray(
        np.random.default_rng(1).standard_normal(x.shape).astype(np.float32)
    )
    x_perturbed = jnp.where(m[..., None], x, noise)
    y1 = model.apply(params, x_perturbed, m)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), atol=1e-5)


@pytest.mark.parametrize("kind", ["lstm", "gru", "lru"])
def test_rnn_ignores_leading_padding_entirely(kind):
    """A left-padded short history must equal the same history without pad."""
    rng = np.random.default_rng(3)
    w_short = W // 2
    x_short = rng.standard_normal((B, w_short, F)).astype(np.float32)
    m_short = np.ones((B, w_short), dtype=bool)
    x_pad = np.concatenate([np.zeros((B, W - w_short, F), np.float32), x_short], 1)
    m_pad = np.concatenate([np.zeros((B, W - w_short), bool), m_short], 1)
    model = build_model(kind)
    params = model.init(jax.random.key(0), jnp.asarray(x_pad), jnp.asarray(m_pad))
    y_pad = model.apply(params, jnp.asarray(x_pad), jnp.asarray(m_pad))
    y_short = model.apply(params, jnp.asarray(x_short), jnp.asarray(m_short))
    np.testing.assert_allclose(np.asarray(y_pad), np.asarray(y_short), atol=1e-5)


@pytest.mark.parametrize("kind", KINDS)
def test_grad_flows_and_is_finite(kind):
    x, m = make_batch()
    model = build_model(kind)
    params = model.init(jax.random.key(0), x, m)

    def loss(p):
        return jnp.mean(model.apply(p, x, m) ** 2)

    g = jax.jit(jax.grad(loss))(params)
    leaves = jax.tree.leaves(g)
    assert all(bool(jnp.isfinite(l).all()) for l in leaves)
    total = sum(float(jnp.abs(l).sum()) for l in leaves)
    assert total > 0.0, "gradient identically zero"


@pytest.mark.parametrize("kind", KINDS)
@pytest.mark.fast
def test_jit_matches_eager(kind):
    x, m = make_batch()
    model, params, y = init_and_apply(kind, x, m)
    yj = jax.jit(lambda p, x, m: model.apply(p, x, m))(params, x, m)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yj), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("kind", KINDS)
def test_heteroscedastic_head(kind):
    x, m = make_batch()
    model = build_model(kind, heteroscedastic=True)
    params = model.init(jax.random.key(0), x, m)
    mean, log_var = model.apply(params, x, m)
    assert mean.shape == (B,) and log_var.shape == (B,)
    assert bool(jnp.isfinite(mean).all()) and bool(jnp.isfinite(log_var).all())
    assert float(jnp.abs(log_var).max()) <= 8.0


def test_lstm_differs_from_gru():
    x, m = make_batch(all_valid=True)
    _, _, y_lstm = init_and_apply("lstm", x, m)
    _, _, y_gru = init_and_apply("gru", x, m)
    assert not np.allclose(np.asarray(y_lstm), np.asarray(y_gru))


def test_rnn_multilayer():
    x, m = make_batch()
    _, _, y = init_and_apply("lstm", x, m, layers=2, hidden=32)
    assert y.shape == (B,)
    assert bool(jnp.isfinite(y).all())


@pytest.mark.fast
def test_unknown_kind_raises():
    with pytest.raises(ValueError, match="unknown model kind"):
        build_model("resnet")


def test_mlp_anchor_only_mode():
    x, m = make_batch()
    _, _, y = init_and_apply("mlp", x, m, window_input=False)
    assert y.shape == (B,)


@pytest.mark.parametrize("kind", ["lstm", "lru"])
def test_rnn_uses_time_structure(kind):
    """Reversing the window order must change a recurrent forecast (the
    planted trend term in the synthetic panel is only learnable this way)."""
    x, m = make_batch(all_valid=True)
    model = build_model(kind)
    params = model.init(jax.random.key(0), x, m)
    y = model.apply(params, x, m)
    y_rev = model.apply(params, x[:, ::-1], m)
    assert not np.allclose(np.asarray(y), np.asarray(y_rev), atol=1e-4)


def test_lru_linear_scan_matches_serial_reference():
    """The associative-scan recurrence must equal the serial lax.scan
    h_t = a_t·h_{t-1} + b_t (complex, carried as re/im pairs)."""
    from lfm_quant_tpu.models.lru import _linear_scan

    rng = np.random.default_rng(7)
    Bn, T, N = 4, 31, 8
    ar, ai, br, bi = (
        jnp.asarray(rng.standard_normal((Bn, T, N)).astype(np.float32) * 0.5)
        for _ in range(4))
    h_re, h_im = _linear_scan(ar, ai, br, bi)

    def step(carry, inp):
        hr, hi = carry
        a_r, a_i, b_r, b_i = inp
        nr = a_r * hr - a_i * hi + b_r
        ni = a_r * hi + a_i * hr + b_i
        return (nr, ni), (nr, ni)

    _, (sr, si) = jax.lax.scan(
        step, (jnp.zeros((Bn, N)), jnp.zeros((Bn, N))),
        tuple(jnp.swapaxes(v, 0, 1) for v in (ar, ai, br, bi)))
    np.testing.assert_allclose(np.asarray(h_re),
                               np.asarray(jnp.swapaxes(sr, 0, 1)),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(h_im),
                               np.asarray(jnp.swapaxes(si, 0, 1)),
                               rtol=1e-4, atol=1e-4)


def test_lru_state_magnitude_stable():
    """|λ| < 1 by construction: an all-valid constant input must not blow
    up over a window 10× the init's implied memory horizon."""
    x = jnp.ones((2, 240, F), jnp.float32)
    m = jnp.ones((2, 240), bool)
    model = build_model("lru", hidden=16, state_dim=16)
    params = model.init(jax.random.key(0), x, m)
    y = model.apply(params, x, m)
    assert bool(jnp.isfinite(y).all())
    assert float(jnp.abs(y).max()) < 1e3


def test_lru_invalid_anchor_features_do_not_leak():
    """Even an INVALID anchor month must not leak its features into the
    forecast (the RNN mask contract: forecast = f(valid history only))."""
    x, m = make_batch(all_valid=True)
    m = m.at[:, -1].set(False)  # invalidate every anchor
    model = build_model("lru")
    params = model.init(jax.random.key(0), x, m)
    y0 = model.apply(params, x, m)
    x2 = x.at[:, -1].add(100.0)  # garbage in the masked anchor
    y1 = model.apply(params, x2, m)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), atol=1e-5)


# ---- factorized recurrences (PAPERS.md F-/G-LSTM tricks) ---------------

def _n_params(params):
    return sum(int(np.prod(a.shape)) for a in jax.tree.leaves(params))


@pytest.mark.parametrize("kind", ["lstm", "gru"])
@pytest.mark.parametrize("kw", [{"factor_rank": 8}, {"n_groups": 4}])
@pytest.mark.fast
def test_factorized_rnn_forward_and_params_shrink(kind, kw):
    """F-LSTM (low-rank) and G-LSTM (grouped) variants: finite masked
    forward, fewer params than dense, and finite grads."""
    x, m = make_batch()
    _, p_dense, _ = init_and_apply(kind, x, m, hidden=32)
    model, params, y = init_and_apply(kind, x, m, hidden=32, **kw)
    assert y.shape == (B,) and bool(jnp.isfinite(y).all())
    assert _n_params(params) < _n_params(p_dense)

    def loss(p):
        return jnp.sum(model.apply(p, x, m) ** 2)

    g = jax.grad(loss)(params)
    assert all(bool(jnp.isfinite(a).all()) for a in jax.tree.leaves(g))


@pytest.mark.parametrize("kw", [{"factor_rank": 8}, {"n_groups": 4}])
@pytest.mark.fast
def test_factorized_rnn_masking_holds_state(kw):
    """The factorizations change only the projections — masked steps must
    still hold the carried state exactly (same invariant as dense)."""
    x, m = make_batch(all_valid=True)
    model = build_model("lstm", hidden=32, **kw)
    params = model.init(jax.random.key(0), x, m)
    y_full = model.apply(params, x, m)
    # Invalidate (and zero) a mid-window step: outputs must equal the
    # same history with that month never observed.
    m2 = np.asarray(m).copy()
    m2[:, W // 2] = False
    x2 = np.asarray(x).copy()
    x2[:, W // 2] = 0.0
    x3 = np.asarray(x).copy()
    x3[:, W // 2] = 123.0  # garbage behind the mask must not matter
    y_masked = model.apply(params, jnp.asarray(x2), jnp.asarray(m2))
    y_garbage = model.apply(params, jnp.asarray(x3), jnp.asarray(m2))
    np.testing.assert_allclose(np.asarray(y_masked), np.asarray(y_garbage),
                               atol=1e-6)
    assert not np.allclose(np.asarray(y_full), np.asarray(y_masked))


@pytest.mark.fast
def test_factorized_rnn_validation():
    x, m = make_batch()
    with pytest.raises(ValueError, match="alternative factorizations"):
        init_and_apply("lstm", x, m, hidden=32, factor_rank=4, n_groups=2)
    with pytest.raises(ValueError, match="divide evenly"):
        init_and_apply("lstm", x, m, hidden=30, n_groups=4)
    with pytest.raises(ValueError, match="scan_impl='xla'"):
        init_and_apply("lstm", x, m, hidden=32, factor_rank=4,
                       scan_impl="pallas")
    with pytest.raises(ValueError, match="n_groups must be >= 1"):
        init_and_apply("lstm", x, m, hidden=32, n_groups=0)
    with pytest.raises(ValueError, match="factor_rank must be >= 1"):
        init_and_apply("lstm", x, m, hidden=32, factor_rank=0)


@pytest.mark.fast
def test_factorized_auto_resolves_to_xla_scan():
    """config.model_kwargs must route factorized models to the XLA scan
    even where auto would pick the Pallas kernel."""
    from unittest import mock

    from lfm_quant_tpu.config import get_preset, model_kwargs
    import dataclasses

    cfg = get_preset("c2")
    kw = dict(cfg.model.kwargs)
    kw["n_groups"] = 4
    cfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, kwargs=kw))
    with mock.patch("jax.default_backend", return_value="tpu"):
        kind, resolved = model_kwargs(cfg)
    assert resolved["scan_impl"] == "xla"
    # Dense c2 on the same (mocked) backend keeps the fused kernel.
    with mock.patch("jax.default_backend", return_value="tpu"):
        _, dense = model_kwargs(get_preset("c2"))
    assert dense["scan_impl"] == "pallas_fused"


@pytest.mark.parametrize("impl", ["pallas", "pallas_fused"])
def test_eval_scan_block_b_routes_deterministic_only(impl):
    """`eval_scan_block_b` (the fwd-only eval block-width lever, DESIGN.md
    §9) must change ONLY the deterministic forward's kernel tiling — both
    passes stay numerically identical to the default-block model, and the
    train-mode (non-deterministic) forward keeps scan_block_b."""
    x, m = make_batch()
    base = build_model("lstm", hidden=16, scan_impl=impl, scan_block_b=8)
    wide = build_model("lstm", hidden=16, scan_impl=impl, scan_block_b=8,
                       eval_scan_block_b=16)
    params = base.init(jax.random.key(0), x, m)
    np.testing.assert_allclose(
        np.asarray(base.apply(params, x, m, deterministic=True)),
        np.asarray(wide.apply(params, x, m, deterministic=True)),
        rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(base.apply(params, x, m, deterministic=False)),
        np.asarray(wide.apply(params, x, m, deterministic=False)),
        rtol=2e-5)
