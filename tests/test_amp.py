"""Mixed-precision lane (LFM_PRECISION, DESIGN.md §17): the `amp` lane.

The lane's contract, each half measured rather than asserted:

* **Knob routing** — ``RunConfig.precision`` wins over the
  ``LFM_PRECISION`` env, default f32, invalid values fail loudly, and
  the resolved lane lands in the telemetry manifest's probed knobs.
* **Cast boundaries** — bf16 model compute + bf16 resident panel with
  f32 MASTER params, f32 Adam moments and an f32 head/loss/IC boundary:
  the dtypes are inspected on the live TrainState/panel before and
  after real fits (quantized masters would silently stall Adam once
  updates drop below bf16 resolution).
* **Decision semantics** — early-stop decisions (best epoch, stop
  epoch) EXACT vs the f32 fit at equal seeds, val IC within tolerance:
  reductions and comparisons never ride the bf16 path.
* **Reuse** — warm bf16 fits pay zero jit traces / zero panel H2D, and
  a lane flip is a program-cache MISS plus a fresh panel residency
  entry (never a stale-precision executable or a wrong-dtype panel).

Module name sorts before the tier-1 timebox cut (the cut lands in
test_ring.py), so this lane always runs. The program-KEY membership
tests live with the other key-family collision suites in
tests/test_buckets.py.
"""

import dataclasses
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.config import (
    DataConfig,
    ModelConfig,
    OptimConfig,
    RunConfig,
    compute_dtype,
    resolve_precision,
)
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import clear_panel_cache
from lfm_quant_tpu.train import reuse
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.amp


def _cfg(tmp=None, **opt):
    return RunConfig(
        name="amp",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        # A recurrent trunk on purpose: the scan carries bf16 state, the
        # widest cast surface the lane has.
        model=ModelConfig(kind="gru", kwargs={"hidden": 8}),
        optim=OptimConfig(**{"lr": 1e-3, "epochs": 3, "warmup_steps": 5,
                             "loss": "mse", **opt}),
        seed=0,
        out_dir=str(tmp) if tmp else "runs",
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)


@pytest.fixture(scope="module")
def splits(panel):
    return PanelSplits.by_date(panel, 198001, 198201)


@pytest.fixture(autouse=True)
def _fresh_caches():
    """Deterministic counter/cache arithmetic per test (the reuse-lane
    convention): precision flips must start from empty caches or another
    module's donor entries would blur hit/miss assertions."""
    reuse.clear_program_cache()
    clear_panel_cache()
    yield
    reuse.clear_program_cache()
    clear_panel_cache()


def _float_leaves(tree):
    return [x for x in jax.tree.leaves(tree)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)]


# ---- knob routing --------------------------------------------------------


def test_knob_routing(monkeypatch):
    monkeypatch.delenv("LFM_PRECISION", raising=False)
    cfg = _cfg()
    assert resolve_precision() == "f32"
    assert resolve_precision(cfg) == "f32"
    assert compute_dtype(cfg) is None

    monkeypatch.setenv("LFM_PRECISION", "bf16")
    assert resolve_precision() == "bf16"
    assert resolve_precision(cfg) == "bf16"
    assert compute_dtype(cfg) == jnp.bfloat16
    # Config field WINS over the env (per-run pin beats fleet switch).
    pinned = dataclasses.replace(cfg, precision="f32")
    assert resolve_precision(pinned) == "f32"
    assert compute_dtype(pinned) is None

    monkeypatch.delenv("LFM_PRECISION", raising=False)
    assert resolve_precision(dataclasses.replace(cfg, precision="bf16")) \
        == "bf16"
    # The per-model bf16 flag still selects bf16 compute on its own.
    mdl = dataclasses.replace(cfg, model=dataclasses.replace(
        cfg.model, bf16=True))
    assert compute_dtype(mdl) == jnp.bfloat16

    monkeypatch.setenv("LFM_PRECISION", "fp16")
    with pytest.raises(ValueError, match="precision"):
        resolve_precision()
    monkeypatch.delenv("LFM_PRECISION", raising=False)
    with pytest.raises(ValueError, match="precision"):
        resolve_precision(dataclasses.replace(cfg, precision="half"))


def test_precision_roundtrips_config_json(monkeypatch):
    monkeypatch.delenv("LFM_PRECISION", raising=False)
    cfg = dataclasses.replace(_cfg(), precision="bf16")
    back = RunConfig.from_json(cfg.to_json())
    assert back.precision == "bf16"
    assert resolve_precision(back) == "bf16"


def test_manifest_probes_precision(monkeypatch):
    from lfm_quant_tpu.utils import telemetry

    monkeypatch.setenv("LFM_PRECISION", "bf16")
    m = telemetry.build_manifest()
    assert m["knobs"]["precision"] == "bf16"
    assert m["env_lfm"].get("LFM_PRECISION") == "bf16"


# ---- cast boundaries -----------------------------------------------------


def test_master_params_and_moments_stay_f32(splits, tmp_path, monkeypatch):
    """The core invariant: bf16 COMPUTE (model dtype + resident panel),
    f32 STATE — params, Adam moments, step. Checked on the fresh init
    AND after a real fit (an optimizer update must never launder a
    bf16 cast back into the masters)."""
    monkeypatch.setenv("LFM_PRECISION", "bf16")
    tr = Trainer(_cfg(tmp_path), splits)
    assert tr._compute_dtype == jnp.bfloat16
    assert tr.dev["xm"].dtype == jnp.bfloat16
    # Targets feed the loss and must NOT ride the compute cast.
    assert tr.dev["targets"].dtype == jnp.float32
    assert tr.model.dtype == jnp.bfloat16
    assert tr.eval_model.dtype == jnp.bfloat16

    state = tr.init_state()
    assert {str(x.dtype) for x in _float_leaves(state.params)} == {"float32"}
    assert {str(x.dtype) for x in _float_leaves(state.opt_state)} \
        == {"float32"}

    tr.fit()
    assert {str(x.dtype) for x in _float_leaves(tr.state.params)} \
        == {"float32"}
    assert {str(x.dtype) for x in _float_leaves(tr.state.opt_state)} \
        == {"float32"}
    # The f32 head boundary: forecasts and eval metrics come back f32.
    pred, valid = tr.predict(split="val")
    assert pred.dtype == np.float32 and valid.any()
    ev = tr.evaluate(tr.state.params)
    assert np.isfinite(ev["ic"]) and np.isfinite(ev["mse"])


def test_bf16_trunk_actually_computes_in_bf16(splits, monkeypatch):
    """The lane must not be a no-op: the gathered windows a bf16-lane
    step consumes are bf16 (half the gather bytes — the panel side),
    while the same gather under f32 stays f32."""
    monkeypatch.setenv("LFM_PRECISION", "bf16")
    tr = Trainer(_cfg(), splits)
    b = tr.val_sampler.stacked_cross_sections()
    x, m = tr._gather(tr.dev["xm"], jnp.asarray(b.firm_idx[:2]),
                      jnp.asarray(b.time_idx[:2]))
    assert x.dtype == jnp.bfloat16 and m.dtype == jnp.bool_


# ---- decision semantics --------------------------------------------------


def test_decisions_exact_vs_f32_at_equal_seeds(splits, tmp_path,
                                               monkeypatch):
    """The parity contract bench gates on, pinned in-tier: same seeds,
    f32 vs bf16 lane — identical epoch count, identical best epoch
    (early-stop DECISIONS exact; ICs compare in f32 on both lanes), val
    ICs within the pre-registered tolerance every epoch."""
    cfg = _cfg(tmp_path, epochs=4, early_stop_patience=2)
    monkeypatch.delenv("LFM_PRECISION", raising=False)
    f32 = Trainer(cfg, splits).fit()
    reuse.clear_program_cache()
    monkeypatch.setenv("LFM_PRECISION", "bf16")
    b16 = Trainer(cfg, splits).fit()

    assert b16["epochs_run"] == f32["epochs_run"]
    assert b16["best_epoch"] == f32["best_epoch"]
    assert abs(b16["best_val_ic"] - f32["best_val_ic"]) <= 0.02
    ic32 = [h["val_ic"] for h in f32["history"]]
    ic16 = [h["val_ic"] for h in b16["history"]]
    assert len(ic16) == len(ic32)
    np.testing.assert_allclose(ic16, ic32, atol=0.02)


# ---- reuse / residency ---------------------------------------------------


def test_warm_bf16_fit_zero_traces_zero_h2d(splits, tmp_path, monkeypatch):
    """The reuse contract with the knob ON: a second same-key bf16
    trainer binds the first one's executables and bf16 resident panel —
    zero new jit traces, zero panel H2D."""
    monkeypatch.setenv("LFM_PRECISION", "bf16")
    cfg = _cfg(tmp_path)
    Trainer(cfg, splits).fit()
    snap = REUSE_COUNTERS.snapshot()
    Trainer(cfg, splits).fit()
    d = REUSE_COUNTERS.delta(snap)
    assert d["jit_traces"] == 0, d
    assert d["panel_transfers"] == 0, d
    assert d["program_cache_hits"] >= 1


def test_lane_flip_is_a_cache_miss_never_stale_reuse(splits, tmp_path,
                                                     monkeypatch):
    """Flipping LFM_PRECISION mid-process changes the trainer program
    key (tagged member) and the panel residency key (dtype member):
    fresh programs, fresh bf16 panel transfer — the f32 executables and
    f32 panel are never served to the bf16 lane or vice versa."""
    monkeypatch.delenv("LFM_PRECISION", raising=False)
    cfg = _cfg(tmp_path)
    t32 = Trainer(cfg, splits)
    t32.fit()
    snap = REUSE_COUNTERS.snapshot()
    monkeypatch.setenv("LFM_PRECISION", "bf16")
    t16 = Trainer(cfg, splits)
    t16.fit()
    d = REUSE_COUNTERS.delta(snap)
    assert t16.program_key != t32.program_key
    assert ("precision", "bf16") in t16.program_key
    assert ("precision", "f32") in t32.program_key
    assert d["program_cache_misses"] >= 1
    assert d["jit_traces"] > 0          # really recompiled
    assert d["panel_transfers"] == 1    # a NEW bf16 residency entry
    assert t16.dev["xm"].dtype == jnp.bfloat16
    assert t32.dev["xm"].dtype == jnp.float32


# ---- bench rows / knob tooling ------------------------------------------


def test_bench_rows_record_dtype_and_backend(tmp_path, monkeypatch):
    """Satellite: every BENCH_ROWS.jsonl row carries the compute
    precision and backend, so mixed-precision rows are distinguishable
    from the f32 CPU-fallback trajectory."""
    import bench as bench_mod

    rows = tmp_path / "rows.jsonl"
    monkeypatch.setenv("LFM_BENCH_ROWS", str(rows))
    monkeypatch.delenv("LFM_BENCH_NO_PERSIST", raising=False)
    monkeypatch.delenv("LFM_PRECISION", raising=False)
    bench_mod._emit("amp_probe_metric", 1.0, 0.0)
    monkeypatch.setenv("LFM_PRECISION", "bf16")
    bench_mod._emit("amp_probe_metric", 2.0, 0.0)
    bench_mod._emit_status("ok", persist=True)
    recs = [json.loads(ln) for ln in rows.read_text().splitlines()]
    assert [r["dtype"] for r in recs] == ["f32", "bf16", "bf16"]
    assert recs[0]["backend"] == "cpu"
    assert all("dtype" in r for r in recs)


def _load_check_knobs():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "check_knobs.py")
    spec = importlib.util.spec_from_file_location("check_knobs", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_knobs_repo_is_clean():
    """Satellite: the static LFM_* knob cross-check passes on the repo
    as committed — every env read documented, every manifest probe
    resolvable. A new undocumented knob fails HERE, inside tier-1."""
    ck = _load_check_knobs()
    assert ck.check() == []
    # And the checker itself sees the lane's knob + probe.
    assert "LFM_PRECISION" in ck.env_reads()
    assert "LFM_PRECISION" in ck.documented_knobs()
    assert any(n == "precision" for n, _, _ in ck.manifest_probes())


def test_check_knobs_flags_undocumented_reads(tmp_path):
    """The checker actually detects: a fabricated mini-repo with one
    undocumented read fails, and documenting it clears the failure."""
    ck = _load_check_knobs()
    pkg = tmp_path / "lfm_quant_tpu" / "utils"
    pkg.mkdir(parents=True)
    (pkg / "telemetry.py").write_text("_KNOB_PROBES = ()\n")
    (tmp_path / "mod.py").write_text(
        'import os\nX = os.environ.get("LFM_SHINY_NEW", "0")\n')
    (tmp_path / "README.md").write_text("no knobs here\n")
    probs = ck.check(str(tmp_path))
    assert len(probs) == 1 and "LFM_SHINY_NEW" in probs[0]
    (tmp_path / "README.md").write_text("`LFM_SHINY_NEW` does things\n")
    assert ck.check(str(tmp_path)) == []
