"""Native C++ data layer (lfm_quant_tpu/native/): CSV parse equivalence
with the pandas engine, and the structural determinism contract of the C++
epoch sampler.

Skipped wholesale when no toolchain can build the library (native code is
an accelerator, never a requirement — every consumer falls back).
"""

import numpy as np
import pytest

from lfm_quant_tpu import native
from lfm_quant_tpu.data.compustat import load_compustat_csv, to_long_frame
from lfm_quant_tpu.data.panel import synthetic_panel
from lfm_quant_tpu.data.windows import DateBatchSampler

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)")


@pytest.fixture(scope="module")
def csv_path(tmp_path_factory):
    panel = synthetic_panel(n_firms=40, n_months=96, n_features=4, seed=7)
    path = tmp_path_factory.mktemp("native") / "panel.csv"
    to_long_frame(panel).to_csv(path, index=False)
    return str(path)


@pytest.mark.fast
def test_csv_engines_identical(csv_path):
    a = load_compustat_csv(csv_path, engine="pandas")
    b = load_compustat_csv(csv_path, engine="native")
    assert a.feature_names == b.feature_names
    np.testing.assert_array_equal(a.firm_ids, b.firm_ids)
    np.testing.assert_array_equal(a.dates, b.dates)
    np.testing.assert_array_equal(a.valid, b.valid)
    np.testing.assert_array_equal(a.target_valid, b.target_valid)
    np.testing.assert_array_equal(a.ret_valid, b.ret_valid)
    # Both parsers are correctly-rounded decimal→float32; the panels must
    # agree to float32 round-trip precision after identical preprocessing.
    np.testing.assert_allclose(a.features, b.features, atol=1e-6)
    np.testing.assert_allclose(a.targets, b.targets, atol=1e-6)
    np.testing.assert_allclose(a.returns, b.returns, atol=1e-6)


def test_csv_native_handles_missing_fields(tmp_path):
    p = tmp_path / "gaps.csv"
    p.write_text(
        "gvkey,yyyymm,f0,f1,ret\n"
        "1,200001,1.0,2.0,0.01\n"
        "1,200002,,2.5,\n"        # missing feature + missing ret
        "2,200001,3.0,4.0,0.02\n"
        "\n"                       # blank line ignored
        "2,200003,5.0,6.0,0.03\n")
    panel = load_compustat_csv(str(p), engine="native", min_cross_section=1,
                               horizon=1)
    ref = load_compustat_csv(str(p), engine="pandas", min_cross_section=1,
                             horizon=1)
    np.testing.assert_array_equal(panel.valid, ref.valid)
    np.testing.assert_allclose(panel.features, ref.features, atol=1e-6)
    # the missing-f0 month is invalid for firm 1
    assert not panel.valid[0, 1]


def test_csv_engines_handle_quoted_fields(tmp_path):
    p = tmp_path / "quoted.csv"
    p.write_text(
        'gvkey,yyyymm,f0,f1,ret\n'
        '"1","200001","1.25","2.0","0.01"\n'
        '1,200002,1.5,"2.5",0.02\n'
        '"2",200001,3.0,4.0,"0.03"\n'
        '2,200002,5.0,6.0,0.04\n')
    a = load_compustat_csv(str(p), engine="pandas", min_cross_section=1,
                           horizon=1)
    b = load_compustat_csv(str(p), engine="native", min_cross_section=1,
                           horizon=1)
    np.testing.assert_array_equal(a.valid, b.valid)
    assert a.valid.all()
    np.testing.assert_allclose(a.features, b.features, atol=1e-6)
    np.testing.assert_allclose(a.returns, b.returns, atol=1e-6)


def test_csv_engines_handle_quoted_header(tmp_path):
    p = tmp_path / "qhead.csv"
    p.write_text('"gvkey","yyyymm","f0","f1"\n'
                 '1,200001,1.0,2.0\n1,200002,1.1,2.1\n'
                 '2,200001,3.0,4.0\n2,200002,3.1,4.1\n')
    a = load_compustat_csv(str(p), engine="pandas", min_cross_section=1,
                           horizon=1)
    b = load_compustat_csv(str(p), engine="native", min_cross_section=1,
                           horizon=1)
    assert a.feature_names == b.feature_names == ["f0", "f1"]
    np.testing.assert_allclose(a.features, b.features, atol=1e-6)


def test_csv_sniff_excludes_sparse_text_column(tmp_path):
    """A text column whose FIRST data value is blank must still be
    excluded from the auto-sniffed feature set (the sniff scans many rows,
    not just the first — a single-row sniff silently included it as an
    all-NaN feature and invalidated every row of the panel)."""
    p = tmp_path / "sparse_text.csv"
    p.write_text(
        "gvkey,yyyymm,f0,sector,ret\n"
        "1,200001,1.0,,0.01\n"        # sector blank on the first row
        "1,200002,1.1,tech,0.02\n"    # ...but text later
        "2,200001,3.0,,0.03\n"
        "2,200002,3.1,energy,0.04\n")
    a = load_compustat_csv(str(p), engine="pandas", min_cross_section=1,
                           horizon=1)
    b = load_compustat_csv(str(p), engine="native", min_cross_section=1,
                           horizon=1)
    assert a.feature_names == b.feature_names == ["f0"]
    np.testing.assert_array_equal(a.valid, b.valid)
    assert b.valid.all()
    np.testing.assert_allclose(a.features, b.features, atol=1e-6)


def test_csv_sniff_all_empty_column_matches_pandas(tmp_path):
    """An entirely-empty column parses as numeric NaN in pandas (float
    dtype → included as a feature); the native sniff must agree, and the
    resulting all-NaN feature invalidates rows identically."""
    p = tmp_path / "empty_col.csv"
    p.write_text(
        "gvkey,yyyymm,f0,f1,ret\n"
        "1,200001,1.0,,0.01\n"
        "1,200002,1.1,,0.02\n"
        "2,200001,3.0,,0.03\n"
        "2,200002,3.1,,0.04\n")
    a = load_compustat_csv(str(p), engine="pandas", min_cross_section=1,
                           horizon=1)
    b = load_compustat_csv(str(p), engine="native", min_cross_section=1,
                           horizon=1)
    assert a.feature_names == b.feature_names == ["f0", "f1"]
    np.testing.assert_array_equal(a.valid, b.valid)
    assert not b.valid.any()  # all-NaN f1 ⇒ no valid cells anywhere


def test_csv_rejects_off_grid_month(tmp_path):
    # 199913 is inside the [min, max] yyyymm range but not a real month —
    # searchsorted must not silently bucket it into 200001.
    p = tmp_path / "offgrid.csv"
    p.write_text("gvkey,yyyymm,f0\n"
                 "1,199911,1.0\n1,199912,1.1\n1,199913,9.9\n"
                 "1,200001,1.2\n2,199911,2.0\n2,199912,2.1\n2,200001,2.2\n")
    for engine in ("pandas", "native"):
        with pytest.raises(ValueError, match="invalid yyyymm"):
            load_compustat_csv(str(p), engine=engine, min_cross_section=1,
                               horizon=1)


def test_csv_native_rejects_bad_ids(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("gvkey,yyyymm,f0\n1,200001,1.0\nxx,200002,2.0\n")
    with pytest.raises(ValueError, match="malformed data row 2"):
        load_compustat_csv(str(p), engine="native", min_cross_section=1)


@pytest.fixture(scope="module")
def sampler_pair():
    panel = synthetic_panel(n_firms=60, n_months=120, n_features=3, seed=1)
    mk = lambda engine: DateBatchSampler(  # noqa: E731
        panel, window=12, dates_per_batch=4, firms_per_date=16, seed=5,
        engine=engine)
    return mk("python"), mk("native")


@pytest.mark.fast
def test_native_sampler_structure(sampler_pair):
    py, nat = sampler_pair
    assert nat.batches_per_epoch() == py.batches_per_epoch()
    b_nat = nat.stacked_epoch(0)
    b_py = py.stacked_epoch(0)
    assert b_nat.firm_idx.shape == b_py.firm_idx.shape
    assert b_nat.weight.shape == b_py.weight.shape
    # Same dates covered exactly once per epoch.
    np.testing.assert_array_equal(np.sort(b_nat.time_idx.ravel()),
                                  np.sort(b_py.time_idx.ravel()))
    # Real (weight=1) firms per date: drawn from the eligible pool, no
    # replacement; padded slots also from the pool, weight 0.
    pools = {int(t): set(map(int, nat._firms_by_date[int(t)]))
             for t in nat._dates}
    K, D, Bf = b_nat.firm_idx.shape
    for k in range(K):
        for j in range(D):
            t = int(b_nat.time_idx[k, j])
            fi = b_nat.firm_idx[k, j]
            w = b_nat.weight[k, j]
            assert set(map(int, fi)) <= pools[t]
            real = fi[w > 0]
            assert len(set(map(int, real))) == real.size  # no replacement
            assert (w > 0).sum() == min(len(pools[t]), Bf)


def test_native_sampler_deterministic_and_seed_sensitive(sampler_pair):
    _, nat = sampler_pair
    a = nat.stacked_epoch(3)
    b = nat.stacked_epoch(3)
    np.testing.assert_array_equal(a.firm_idx, b.firm_idx)
    np.testing.assert_array_equal(a.time_idx, b.time_idx)
    c = nat.stacked_epoch(4)
    assert not np.array_equal(a.firm_idx, c.firm_idx)  # epochs reshuffle


def test_trainer_runs_with_native_sampler():
    """End-to-end: one tiny training epoch with sampler_engine='native'."""
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data.panel import PanelSplits
    from lfm_quant_tpu.train import Trainer

    cfg = RunConfig(
        name="native_smoke",
        data=DataConfig(n_firms=80, n_months=96, n_features=4, window=8,
                        dates_per_batch=2, firms_per_date=16,
                        sampler_engine="native"),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (8,)}),
        optim=OptimConfig(epochs=1, warmup_steps=1),
    )
    panel = synthetic_panel(n_firms=80, n_months=96, n_features=4, seed=3,
                            min_history=40)
    splits = PanelSplits.by_date(panel, 197506, 197610)
    trainer = Trainer(cfg, splits)
    out = trainer.fit()
    assert out["steps"] > 0 and np.isfinite(out["best_val_ic"])
