"""Backtest engine tests: hand-computed portfolio math + planted-alpha
recovery on the synthetic panel (SURVEY.md §4.3 parity)."""

import json

import numpy as np
import pytest

from lfm_quant_tpu.backtest import aggregate_ensemble, run_backtest
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import Panel

pytestmark = pytest.mark.fast  # whole module is smoke-lane cheap


def toy_panel(n=10, t=36, seed=0):
    """Minimal hand-controllable panel: all firms always valid."""
    rng = np.random.default_rng(seed)
    feats = rng.standard_normal((n, t, 2)).astype(np.float32)
    valid = np.ones((n, t), bool)
    tv = np.ones((n, t), bool)
    targets = rng.standard_normal((n, t)).astype(np.float32)
    returns = rng.standard_normal((n, t)).astype(np.float32) * 0.01
    dates = np.arange(t, dtype=np.int32) + 200001
    # make dates valid YYYYMM
    y, m = 2000 + np.arange(t) // 12, np.arange(t) % 12 + 1
    dates = (y * 100 + m).astype(np.int32)
    return Panel(feats, targets, tv, valid, returns, dates,
                 np.arange(1, n + 1, dtype=np.int32), ["a", "b"], horizon=1)


def test_top_quantile_selection_hand_computed():
    p = toy_panel(n=10, t=36)
    # Forecast = exactly the forward return → top-10% (1 firm) portfolio
    # earns each month's max return.
    fc = p.returns.copy()
    rep = run_backtest(fc, np.ones_like(p.valid), p, quantile=0.1,
                       min_universe=5)
    expect = p.returns.max(axis=0)
    np.testing.assert_allclose(rep.monthly_returns, expect, atol=1e-6)
    assert rep.n_months == 36
    assert rep.mean_ret_ic == pytest.approx(1.0)


def test_long_short_and_costs():
    p = toy_panel(n=20, t=24, seed=1)
    fc = p.returns.copy()
    ls = run_backtest(fc, np.ones_like(p.valid), p, quantile=0.25,
                      long_short=True, min_universe=5)
    lo = run_backtest(fc, np.ones_like(p.valid), p, quantile=0.25,
                      min_universe=5)
    assert ls.monthly_returns.mean() > lo.monthly_returns.mean()
    costly = run_backtest(fc, np.ones_like(p.valid), p, quantile=0.25,
                          min_universe=5, costs_bps=50.0)
    assert costly.monthly_returns[1:].sum() <= lo.monthly_returns[1:].sum()


def test_skips_thin_months_and_raises_when_empty():
    p = toy_panel(n=10, t=12)
    fc_valid = np.ones_like(p.valid)
    fc_valid[:, 3] = False  # one month with no forecasts
    rep = run_backtest(p.returns.copy(), fc_valid, p, min_universe=5)
    assert rep.n_skipped_months == 1
    assert rep.n_months == 11
    with pytest.raises(ValueError, match="no month"):
        run_backtest(p.returns.copy(), np.zeros_like(p.valid), p)


def test_perfect_vs_random_forecast_on_planted_panel():
    """On the synthetic panel, ranking by the true target must beat a
    random forecast in CAGR and IC — the alpha-recovery integration check."""
    panel = synthetic_panel(n_firms=300, n_months=150, n_features=5, seed=5)
    oracle = run_backtest(panel.targets, panel.target_valid, panel)
    rng = np.random.default_rng(0)
    noise = run_backtest(
        rng.standard_normal(panel.targets.shape).astype(np.float32),
        panel.target_valid, panel,
    )
    assert oracle.mean_ret_ic > 0.05
    assert oracle.cagr > noise.cagr
    assert oracle.sharpe_ann > noise.sharpe_ann + 0.5
    assert abs(noise.mean_ic) < 0.05


def test_turnover_and_hit_rate_bounds():
    p = toy_panel(n=30, t=24, seed=2)
    rep = run_backtest(p.returns.copy(), np.ones_like(p.valid), p,
                       quantile=0.2, min_universe=5)
    assert 0.0 <= rep.turnover <= 1.0
    assert 0.0 <= rep.hit_rate <= 1.0
    # Persistent forecast → zero turnover.
    const_fc = np.tile(np.arange(30, dtype=np.float32)[:, None], (1, 24))
    rep2 = run_backtest(const_fc, np.ones_like(p.valid), p, quantile=0.2,
                        min_universe=5)
    assert rep2.turnover == 0.0


def test_report_json_roundtrip():
    p = toy_panel()
    rep = run_backtest(p.returns.copy(), np.ones_like(p.valid), p,
                       min_universe=5)
    d = json.loads(rep.to_json())
    assert d["n_months"] == rep.n_months
    assert len(d["monthly_returns"]) == rep.n_months
    assert isinstance(rep.summary(), str) and "Sharpe" in rep.summary()


def test_aggregate_ensemble_modes():
    rng = np.random.default_rng(3)
    fc = rng.standard_normal((8, 20, 12)).astype(np.float32)
    valid = np.ones((20, 12), bool)
    mean, v = aggregate_ensemble(fc, valid, "mean")
    np.testing.assert_allclose(mean, fc.mean(axis=0), atol=1e-6)
    pen, _ = aggregate_ensemble(fc, valid, "mean_minus_std", risk_lambda=2.0)
    np.testing.assert_allclose(pen, fc.mean(0) - 2.0 * fc.std(0), atol=1e-5)
    with pytest.raises(ValueError, match="unknown ensemble mode"):
        aggregate_ensemble(fc, valid, "median")
    with pytest.raises(ValueError, match="expected"):
        aggregate_ensemble(fc[0], valid, "mean")
    # Per-seed validity: cell valid only if all seeds predicted it.
    pv = np.ones((8, 20, 12), bool)
    pv[3, 5, 5] = False
    _, v2 = aggregate_ensemble(fc, pv, "mean")
    assert not v2[5, 5] and v2[0, 0]


def test_benchmark_relative_and_quantile_profile():
    """Perfect forecast: positive excess over the EW-universe benchmark,
    positive IR, and a rising quantile profile (bottom bucket < top)."""
    p = toy_panel(n=50, t=36, seed=3)
    fc = p.returns.copy()
    rep = run_backtest(fc, np.ones_like(p.valid), p, quantile=0.1,
                       min_universe=5)
    assert rep.excess_cagr > 0.0
    assert rep.ir_ann > 1.0
    assert rep.t_stat > 0.0
    assert rep.quantile_profile.shape == (10,)
    assert rep.quantile_profile[-1] > rep.quantile_profile[0]
    # benchmark = EW universe: monthly_bench must average the universe
    np.testing.assert_allclose(rep.monthly_bench,
                               p.returns.mean(axis=0), atol=1e-6)
    # The profile buckets partition the universe: their mean matches the
    # benchmark's overall mean up to equal-split rounding.
    assert abs(float(rep.quantile_profile.mean())
               - float(p.returns.mean())) < 5e-3


def test_thin_month_profile_keeps_rank_position():
    """When a month's universe is smaller than profile_buckets, each name
    must land in the bucket matching its forecast rank — the single
    top-forecast name goes to the TOP bucket, not bucket 0 (the old
    array_split behavior filled from the bottom)."""
    p = toy_panel(n=6, t=36, seed=9)
    fc = p.returns.copy()  # perfect forecast: rank == realized return rank
    rep = run_backtest(fc, np.ones_like(p.valid), p, quantile=0.2,
                       min_universe=5, profile_buckets=10)
    # 6 names → bucket floor(rank*10/6) ∈ {0,1,3,5,6,8}: top bucket index
    # used is 8, and the top-ranked (highest-return) name populates it.
    prof = rep.quantile_profile
    top = p.returns.max(axis=0).mean()
    bottom = p.returns.min(axis=0).mean()
    np.testing.assert_allclose(prof[8], top, atol=1e-6)
    np.testing.assert_allclose(prof[0], bottom, atol=1e-6)
    # Buckets no name ever maps to stay empty (NaN or 0 count → reported 0)
    assert prof[9] == 0.0 and prof[2] == 0.0


def test_random_forecast_flat_profile():
    """A random forecast must show no material quantile spread."""
    p = toy_panel(n=100, t=36, seed=4)
    rng = np.random.default_rng(7)
    fc = rng.standard_normal(p.returns.shape).astype(np.float32)
    rep = run_backtest(fc, np.ones_like(p.valid), p, quantile=0.1,
                       min_universe=5)
    spread = float(rep.quantile_profile[-1] - rep.quantile_profile[0])
    assert abs(spread) < 5e-3
    assert abs(rep.ir_ann) < 1.5


def test_yearly_breakdown_compounds_to_total():
    p = toy_panel(n=30, t=36, seed=6)
    rep = run_backtest(p.returns.copy(), np.ones_like(p.valid), p,
                       quantile=0.2, min_universe=5)
    ys = rep.yearly()
    assert sum(v["n_months"] for v in ys.values()) == rep.n_months
    total = 1.0
    for v in ys.values():
        total *= 1.0 + v["ret"]
    np.testing.assert_allclose(
        total, float(np.prod(1.0 + rep.monthly_returns)), rtol=1e-6)
    parsed = json.loads(rep.to_json())
    assert "yearly" in parsed and len(parsed["yearly"]) == len(ys)
