"""Live metrics plane (utils/metrics.py + serve/monitor.py): the
``metrics`` lane (DESIGN.md §19).

What is pinned, measured not hoped:

* the instruments are EXACT where they claim exactness (multi-threaded
  hammer: total count / sum / per-bucket counts) and BOUNDED where they
  estimate (histogram p50/p99 vs the exact ``serve/stats.py
  percentile`` twins, within one bucket's relative resolution);
* the Prometheus exposition round-trips through the parse/quantile
  twins (``utils/metrics.py`` == ``scripts/trace_report.py``) on the
  same document;
* the SLO burn rates evaluate multi-window over the rings with
  deterministic injected clocks; the drift gauge fires on a forged
  N(0,1) → N(0.5,1) shift and stays quiet on identical streams; the
  knob-gated ``LFM_DRIFT_GATE`` veto blocks the atomic publish;
* ``/stats`` and ``/healthz`` share ONE snapshot (same scrape ts);
* ``scripts/trace_report.py``'s metrics section cross-checks a saved
  scrape against the span-derived numbers (1% / one-bucket contract)
  and goes LOUD on a forged scrape;
* NON-INTERFERENCE is MEASURED: with ``LFM_METRICS=1`` a warm fit pays
  zero jit traces / zero panel H2D / one host sync per epoch, serving
  steady state pays zero traces / zero panel H2D, scraping adds zero
  device work, and ``LFM_METRICS=0`` is an exact no-op.

Module named early in the alphabet on purpose: it must sort before the
tier-1 timebox cut at ``test_ring.py`` (ROADMAP tier-1 notes).
"""

import os
import re
import threading

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import clear_panel_cache
from lfm_quant_tpu.serve import ScoringService
from lfm_quant_tpu.serve.errors import DriftVetoError
from lfm_quant_tpu.serve.stats import load_trace_report, percentile
from lfm_quant_tpu.serve import monitor
from lfm_quant_tpu.train import reuse
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.utils import metrics, telemetry
from lfm_quant_tpu.utils.metrics import (
    METRICS,
    LogHistogram,
    MetricsRegistry,
    ScoreSketch,
    WindowedRing,
)
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _metrics_hygiene(monkeypatch):
    """Fresh instrument registry and default knob state in AND out, so
    a failing metrics test can never poison its neighbors (the chaos
    lane's hygiene pattern)."""
    for knob in ("LFM_METRICS", "LFM_SLO_P99_MS", "LFM_SLO_AVAIL",
                 "LFM_DRIFT_MAX", "LFM_DRIFT_GATE"):
        monkeypatch.delenv(knob, raising=False)
    METRICS.reset()
    reuse.clear_program_cache()
    clear_panel_cache()
    yield
    METRICS.reset()
    reuse.clear_program_cache()
    clear_panel_cache()


def _cfg(n_firms=60, window=8, seed=0, epochs=1, name="metrics_t"):
    return RunConfig(
        name=name,
        data=DataConfig(n_firms=n_firms, n_months=160, n_features=5,
                        window=window, dates_per_batch=4,
                        firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=2,
                          loss="mse"),
        seed=seed,
    )


def _universe(n_firms=60, window=8, seed=0, panel_seed=3):
    panel = synthetic_panel(n_firms=n_firms, n_months=160, n_features=5,
                            seed=panel_seed)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(n_firms=n_firms, window=window, seed=seed), splits)
    tr.state = tr.init_state()
    return tr


@pytest.fixture()
def service():
    svc = ScoringService(max_rows=4, max_wait_ms=1.0)
    yield svc
    svc.close()


# ---- instruments ---------------------------------------------------------


def test_log_histogram_exact_totals_and_bounds():
    h = LogHistogram(lo=1e-2, hi=1e5, buckets_per_decade=20)
    vals = [0.005, 0.01, 1.0, 99.0, 1e5, 2e5, 7.3]
    for v in vals:
        h.record(v)
    assert h.count == len(vals)
    assert h.sum == pytest.approx(sum(vals))
    assert h.vmin == 0.005 and h.vmax == 2e5
    # Underflow (<= lo) and overflow (> hi) land in their edge buckets.
    assert h._counts[0] == 2          # 0.005 and the lo boundary itself
    assert h._counts[-1] == 1         # 2e5 > hi
    # Bucket upper bounds are inclusive (the Prometheus `le` rule).
    i = h._index(1.0)
    assert h.upper_bound(i) >= 1.0 > h.upper_bound(i - 1)
    snap = h.snapshot()
    assert snap["count"] == len(vals) and snap["max"] == 2e5


def test_log_histogram_quantiles_pin_percentile_twin():
    """Satellite pin: histogram-estimated p50/p99 vs the exact
    ``serve/stats.py percentile`` on the same stream, within one
    bucket's relative resolution — the sketch can never silently drift
    from the numbers stats()/trace_report report."""
    rng = np.random.default_rng(7)
    h = LogHistogram()
    vals = list(rng.lognormal(mean=2.5, sigma=0.9, size=8000))
    for v in vals:
        h.record(v)
    for q in (50.0, 90.0, 99.0):
        exact = percentile(vals, q)
        est = h.quantile(q)
        assert abs(est - exact) / exact <= h.rel_resolution, (
            f"q={q}: histogram {est} vs exact {exact} beyond the "
            f"one-bucket bound {h.rel_resolution:.4f}")
    # Degenerate stream: all-equal values estimate EXACTLY (min/max
    # clamp), not merely within a bucket.
    h2 = LogHistogram()
    for _ in range(100):
        h2.record(42.0)
    assert h2.quantile(50.0) == 42.0 and h2.quantile(99.0) == 42.0


def test_log_histogram_merge_same_geometry_only():
    a, b = LogHistogram(), LogHistogram()
    for v in (1.0, 10.0):
        a.record(v)
    for v in (5.0, 500.0):
        b.record(v)
    a.merge(b)
    assert a.count == 4 and a.vmax == 500.0
    assert a.sum == pytest.approx(516.0)
    with pytest.raises(ValueError, match="geometry"):
        a.merge(LogHistogram(lo=1e-1))


def test_histogram_hammer_threads_exact():
    """The CounterRegistry hammer applied to the histogram: N threads ×
    M records, total count / sum / per-bucket counts EXACT — the
    per-instrument lock loses nothing under contention."""
    h = LogHistogram()
    n_threads, m = 8, 4000
    vals = [float(k + 1) for k in range(n_threads)]  # one value/thread

    def worker(v):
        for _ in range(m):
            h.record(v)

    threads = [threading.Thread(target=worker, args=(vals[k],))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n_threads * m
    assert h.sum == pytest.approx(sum(v * m for v in vals))
    # Each value's bucket holds exactly its m records (distinct values
    # may share a bucket — compare per-bucket aggregates).
    expect = {}
    for v in vals:
        expect[h._index(v)] = expect.get(h._index(v), 0) + m
    for i, c in expect.items():
        assert h._counts[i] == c
    assert sum(h._counts) == n_threads * m


def test_windowed_ring_totals_rates_and_expiry():
    r = WindowedRing(ring_s=10.0, rings=30)
    r.add(1.0, now=5.0)     # ring epoch 0
    r.add(2.0, now=15.0)    # ring epoch 1
    r.add(4.0, now=100.0)   # ring epoch 10
    assert r.total(30.0, now=100.0) == 4.0           # only the newest
    assert r.total(300.0, now=100.0) == 7.0          # all of them
    assert r.rate(300.0, now=100.0) == pytest.approx(7.0 / 300.0)
    # Slot overwrite: 300 s later the same slot is a NEW epoch — the
    # old value expired by overwrite, no allocation, no leak.
    r.add(8.0, now=305.0)   # epoch 30 → same slot as epoch 0
    assert r.total(300.0, now=305.0) == 8.0 + 2.0 + 4.0
    assert r.span_s == 300.0


def test_score_sketch_drift_fires_on_shift_not_on_identical():
    """The acceptance pin: reference N(0,1) vs served N(0.5,1) crosses
    LFM_DRIFT_MAX (default 0.2); an identical stream stays well under
    it. PSI of self is ~0 by construction."""
    rng = np.random.default_rng(11)
    ref = ScoreSketch.reference(rng.normal(0.0, 1.0, 8000))
    assert ref.psi(ref) == pytest.approx(0.0)
    same = ref.live_twin()
    same.record(rng.normal(0.0, 1.0, 8000))  # fresh draw, same dist
    shifted = ref.live_twin()
    shifted.record(rng.normal(0.5, 1.0, 8000))
    threshold = metrics.drift_max_default()
    assert ref.psi(same) < threshold / 2
    assert ref.psi(shifted) > threshold
    # Moments track the stream exactly.
    assert shifted.mean() == pytest.approx(0.5, abs=0.05)
    assert shifted.std() == pytest.approx(1.0, abs=0.05)
    # Sketches over different edges refuse to compare.
    with pytest.raises(ValueError, match="same edges"):
        ref.psi(ScoreSketch([0.0, 1.0, 2.0]))


def test_registry_disabled_is_exact_noop(monkeypatch):
    """LFM_METRICS=0: every mutator returns on one env read — nothing
    records, nothing allocates, the snapshot stays empty."""
    reg = MetricsRegistry()
    monkeypatch.setenv("LFM_METRICS", "0")
    assert not metrics.enabled()
    reg.observe("lat", 5.0, universe="u")
    reg.mark("ok", 3.0)
    reg.gauge("depth", 7.0)
    snap = reg.snapshot()
    assert snap == {"histograms": {}, "rates_per_sec": {}, "gauges": {}}
    monkeypatch.delenv("LFM_METRICS")
    reg.observe("lat", 5.0, universe="u")
    assert reg.snapshot()["histograms"]["lat{universe=u}"]["count"] == 1


# ---- exposition / parse twins --------------------------------------------


def _trace_report():
    return load_trace_report(REPO)


def test_prometheus_render_and_parse_twins_agree():
    """The exposition round-trips, and the scrape-side twins in
    scripts/trace_report.py (_parse_prom, _prom_hist_quantile) agree
    VERBATIM with utils/metrics.py on the same document — the
    percentile-twin discipline applied to parsing."""
    reg = MetricsRegistry()
    rng = np.random.default_rng(3)
    lats = rng.lognormal(2.0, 0.7, 4000)
    for v in lats:
        reg.observe("serve_latency_ms", float(v), universe="u0", width=64)
    for v in lats[:100]:
        reg.observe("serve_latency_ms", float(v), universe="u1", width=128)
    reg.mark("serve_ok", 5.0)
    reg.gauge("zoo_entries", 2.0, shard="a")
    doc = reg and metrics.render_prometheus(
        reg, counters={"serve_requests": 4100, "serve_shed": 3,
                       "not_numeric": "x"}, ts=123.0)
    tr = _trace_report()
    parsed_a = metrics.parse_prometheus(doc)
    parsed_b = tr._parse_prom(doc)
    assert parsed_a == parsed_b
    assert ({"universe": "u0", "width": "64"},
            4000.0) in parsed_a["lfm_serve_latency_ms_count"]
    assert parsed_a["lfm_serve_requests_total"] == [({}, 4100.0)]
    assert "lfm_serve_shed_total" in parsed_a
    assert "not_numeric" not in doc
    assert parsed_a["lfm_scrape_ts_seconds"] == [({}, 123.0)]
    assert ({"shard": "a"}, 2.0) in parsed_a["lfm_zoo_entries"]
    # Histogram quantile twins on the merged bucket ladder.
    pairs = tr._merged_hist_pairs(parsed_a["lfm_serve_latency_ms_bucket"])
    assert pairs[-1][1] == 4100.0  # +Inf total across label sets
    for q in (50.0, 99.0):
        assert (tr._prom_hist_quantile(pairs, q)
                == metrics.hist_quantile_from_buckets(pairs, q))
    # And the estimate still pins the exact percentile of the raw
    # stream (merged across label sets) within one bucket.
    all_lats = list(lats) + list(lats[:100])
    h = reg.merged_histogram("serve_latency_ms")
    exact = percentile(all_lats, 99.0)
    assert abs(tr._prom_hist_quantile(pairs, 99.0) - exact) / exact \
        <= h.rel_resolution


def test_prometheus_overflow_bucket_single_inf_line():
    """A value past the ladder's top lands in the overflow bucket and
    the exposition still carries exactly ONE le="+Inf" sample per label
    set (a duplicate series makes Prometheus reject the whole scrape),
    with _count equal to the +Inf cumulative count."""
    reg = MetricsRegistry()
    for v in (1.0, 50.0, 2e5, 9e5):  # two past hi=1e5
        reg.observe("serve_latency_ms", v, universe="u0", width=8)
    doc = metrics.render_prometheus(reg, ts=1.0)
    inf_lines = [ln for ln in doc.splitlines()
                 if 'le="+Inf"' in ln]
    assert len(inf_lines) == 1
    prom = metrics.parse_prometheus(doc)
    inf_cum = [v for lab, v in prom["lfm_serve_latency_ms_bucket"]
               if lab["le"] == "+Inf"]
    assert inf_cum == [4.0]
    assert prom["lfm_serve_latency_ms_count"] == [
        ({"universe": "u0", "width": "8"}, 4.0)]
    # The locked triple is self-consistent (count == +Inf cumulative).
    pairs, count, _ = reg.histogram(
        "serve_latency_ms", universe="u0", width=8).prom_snapshot()
    assert count == pairs[-1][1] == 4


# ---- SLO burn rates ------------------------------------------------------


def test_slo_burn_rates_multi_window(monkeypatch):
    """Deterministic clocks through the rings: a sustained breach burns
    BOTH windows (burning=True); a breach older than the fast window
    burns only the slow one (burning=False — the multi-window AND)."""
    monkeypatch.setenv("LFM_SLO_P99_MS", "100")
    monkeypatch.setenv("LFM_SLO_AVAIL", "0.999")
    now = 10_000.0
    METRICS.mark("serve_ok", 1000.0, now=now)
    METRICS.mark("serve_err", 5.0, now=now)
    METRICS.mark("serve_slo_lat_bad", 50.0, now=now)
    s = monitor.slo_status(now=now)
    assert s["active"] and set(s["objectives"]) == {"availability",
                                                    "latency_p99"}
    av = s["objectives"]["availability"]
    # 5/1005 errors against a 0.1% budget ≈ 5× burn in both windows.
    assert av["burn"]["60s"] == pytest.approx(4.975, abs=0.01)
    assert av["burn"]["300s"] == pytest.approx(4.975, abs=0.01)
    assert av["burning"]
    lp = s["objectives"]["latency_p99"]
    # 50/1000 over-threshold against the 1% p99 budget = 5× burn.
    assert lp["burn"]["60s"] == pytest.approx(5.0, abs=0.01)
    assert lp["burning"]
    assert s["burning"] and s["max_burn"] >= 4.9
    # Breach OLDER than the fast window: slow window still burns, fast
    # does not — no longer "burning" (a recovered incident).
    METRICS.reset()
    METRICS.mark("serve_ok", 1000.0, now=now - 200.0)
    METRICS.mark("serve_err", 5.0, now=now - 200.0)
    METRICS.mark("serve_ok", 1000.0, now=now)  # healthy recent traffic
    s2 = monitor.slo_status(now=now)
    av2 = s2["objectives"]["availability"]
    assert av2["burn"]["300s"] > 1.0 > av2["burn"]["60s"]
    assert not av2["burning"] and not s2["burning"]
    # Disabled objectives disappear from the report.
    monkeypatch.setenv("LFM_SLO_P99_MS", "0")
    monkeypatch.setenv("LFM_SLO_AVAIL", "0")
    s3 = monitor.slo_status(now=now)
    assert not s3["active"] and s3["objectives"] == {}


# ---- service integration -------------------------------------------------


def test_serve_metrics_recorded_and_pinned(service):
    """Traffic through the real service: the latency histogram is
    labeled per (universe, width-bucket), its count matches stats()'s
    completed count exactly, its p99 estimate pins the exact stats()
    p99 within one bucket, and the /metrics document carries the
    serve families + gauges."""
    tr = _universe()
    entry = service.register("u0", tr)
    months = service.serveable_months("u0")
    n = 24
    lats = [service.score("u0", m).latency_ms for m in months[:n]]
    stats = service.stats()
    assert stats["completed"] == n
    snap = service.metrics_snapshot()
    hists = snap["instruments"]["histograms"]
    # Every label set is one (universe, width-bucket) — months near the
    # panel edge occupy a smaller width bucket, so several can appear.
    assert all(k.startswith("serve_latency_ms{universe=u0,width=")
               for k in hists)
    assert sum(h["count"] for h in hists.values()) == n
    # The estimate's RIGOROUS small-n invariant: the covering bucket is
    # the one holding the order statistic at the rank, so the estimate
    # lies within one bucket factor of s[floor(rank)] — for ANY latency
    # distribution (a loaded box throws multi-bucket outliers, and the
    # exact percentile interpolates BETWEEN order stats, so an
    # estimate-vs-exact pin would flake; the tight large-n pin lives in
    # test_log_histogram_quantiles_pin_percentile_twin).
    merged = METRICS.merged_histogram("serve_latency_ms")
    s = sorted(lats)
    g = 1.0 + merged.rel_resolution
    for q in (50.0, 99.0):
        anchor = s[int((n - 1) * q / 100.0)]
        est = merged.quantile(q)
        assert anchor / g - 1e-6 <= est <= anchor * g + 1e-6, (
            f"q={q}: estimate {est} not within one bucket of the "
            f"rank's order statistic {anchor}")
    # Drift plumbing: reference stamped at publish, live streaming
    # (lazily — size() counts pending mass the readers fold down).
    assert entry.ref_sketch is not None and entry.live_sketch.size() > 0
    assert snap["drift"]["universes"]["u0"]["psi"] is not None
    # The exposition document has every family the scrape consumers
    # read, and its request count equals the span/stats count.
    doc = service.metrics_text(ts=1.0)
    prom = metrics.parse_prometheus(doc)
    assert sum(v for _, v in prom["lfm_serve_latency_ms_count"]) == n
    for family in ("lfm_serve_latency_ms_bucket", "lfm_circuit_state",
                   "lfm_zoo_entries", "lfm_zoo_param_bytes_total",
                   "lfm_zoo_panel_bytes_total", "lfm_slo_burn",
                   "lfm_score_drift_psi", "lfm_serve_queue_depth",
                   "lfm_serve_requests_total",
                   "lfm_serve_ok_rate_per_sec"):
        assert family in prom, f"{family} missing from /metrics"
    assert prom["lfm_zoo_entries"] == [({}, 1.0)]
    assert prom["lfm_zoo_param_bytes_total"][0][1] > 0
    assert prom["lfm_scrape_ts_seconds"] == [({}, 1.0)]


def test_stats_and_healthz_share_one_snapshot(service):
    """Satellite pin: /stats and /healthz derive from ONE snapshot()
    call — single locked read per owning structure, the SAME scrape ts
    in both — instead of re-deriving state per field."""
    tr = _universe()
    service.register("u0", tr)
    snap = service.snapshot()
    assert snap["stats"]["ts"] == snap["health"]["ts"] == snap["ts"]
    assert snap["stats"]["universes"] == {"u0": 0}
    assert snap["stats"]["zoo_size"] == snap["health"]["zoo_size"] == 1
    assert snap["health"]["ok"]
    # SLO/drift detail rides on health without flipping readiness.
    assert "slo" in snap["health"] and "drift" in snap["health"]
    assert snap["health"]["drift"]["breached"] == []
    # The public accessors are views of the same consistent snapshot.
    assert "ts" in service.stats() and "ts" in service.health()


def test_drift_gate_vetoes_publish_and_flips_healthz_detail(
        service, monkeypatch):
    """The acceptance pin: a forged distribution shift crosses
    LFM_DRIFT_MAX, /healthz detail flips, and with LFM_DRIFT_GATE=1 the
    next atomic publish is VETOED (DriftVetoError) leaving the served
    generation untouched; with the gate off (default) the publish
    proceeds."""
    rng = np.random.default_rng(5)
    service.register("u0", _universe(seed=0))
    entry = service.zoo.current("u0")
    assert entry.ref_sketch is not None
    # Forge served drift: stream a shifted distribution into the live
    # sketch (mean shifted by ~2 reference sigmas).
    mu, sd = entry.ref_sketch.mean(), entry.ref_sketch.std()
    entry.live_sketch.record(rng.normal(mu + 2 * sd, sd, 6000))
    psi = entry.drift_psi(min_scores=1)
    assert psi is not None and psi > metrics.drift_max_default()
    health = service.health()
    assert health["ok"]  # drift is detail, not readiness
    assert health["drift"]["breached"] == ["u0"]
    # Gauge surfaces on the scrape.
    prom = metrics.parse_prometheus(service.metrics_text())
    (labels, v), = prom["lfm_score_drift_psi"]
    assert labels["universe"] == "u0" and v > metrics.drift_max_default()
    # Gate ON: publish vetoed, generation 0 still serving.
    monkeypatch.setenv("LFM_DRIFT_GATE", "1")
    with pytest.raises(DriftVetoError, match="drift"):
        service.register("u0", _universe(seed=1))
    assert service.zoo.generation("u0") == 0
    d = telemetry.COUNTERS.get("serve_drift_vetoes")
    assert d and d >= 1
    # Gate OFF (default): the same publish goes through, and the new
    # generation starts with a FRESH reference + empty live sketch.
    monkeypatch.delenv("LFM_DRIFT_GATE")
    e2 = service.register("u0", _universe(seed=1))
    assert service.zoo.generation("u0") == 1
    assert e2.live_sketch is not None and e2.live_sketch.n == 0
    # The retired generation's PSI gauge must NOT linger in the next
    # scrape (per-entity gauges are cleared and rebuilt per collection
    # — a stale series would keep alerting on a generation that no
    # longer serves).
    prom2 = metrics.parse_prometheus(service.metrics_text())
    for labels, _ in prom2.get("lfm_score_drift_psi", []):
        assert labels["generation"] != "0"


def test_metrics_kill_switch_on_the_service(service, monkeypatch):
    """LFM_METRICS=0 end to end: no reference stamped at publish, no
    instrument recorded under traffic, gauges not collected — the
    exposition document is just the scrape timestamp."""
    monkeypatch.setenv("LFM_METRICS", "0")
    service.register("u0", _universe())
    for m in service.serveable_months("u0")[:4]:
        service.score("u0", m)
    entry = service.zoo.current("u0")
    assert entry.ref_sketch is None and entry.live_sketch is None
    snap = METRICS.snapshot()
    assert snap["histograms"] == {} and snap["rates_per_sec"] == {}
    assert snap["gauges"] == {}
    health = service.health()
    assert health["ok"] and "slo" not in health and "drift" not in health


def test_metrics_non_interference_measured(service, monkeypatch):
    """The house contract, MEASURED with metrics fully ON: a warm fit
    pays zero jit traces / zero panel H2D / ONE host sync per epoch;
    serving steady state pays zero traces / zero panel H2D; and a
    scrape (snapshot + exposition) in the middle of it all adds zero
    device work — no device fetch ever originates from the metrics
    path."""
    monkeypatch.setenv("LFM_METRICS", "1")
    # Warm-fit half (the reuse/pipeline lane numbers, unchanged).
    panel = synthetic_panel(n_firms=60, n_months=160, n_features=5, seed=3)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(epochs=2), splits)
    tr.fit()  # cold: compiles + panel transfer
    snap = REUSE_COUNTERS.snapshot()
    tr.rebind()
    out = tr.fit()  # warm
    d = REUSE_COUNTERS.delta(snap)
    assert d.get("jit_traces", 0) == 0, d
    assert d.get("panel_transfers", 0) == 0, d
    assert d.get("host_syncs", 0) == out["epochs_run"], d
    # Serving half: steady state with recording + drift streaming on.
    service.register("u0", _universe())
    months = service.serveable_months("u0")
    for m in months[:4]:
        service.score("u0", m)  # settle first-dispatch paths
    snap = REUSE_COUNTERS.snapshot()
    for m in months[:12]:
        service.score("u0", m)
    # A mid-traffic scrape: snapshot + text exposition + shared
    # stats/health snapshot.
    service.metrics_snapshot()
    service.metrics_text()
    service.snapshot()
    d = REUSE_COUNTERS.delta(snap)
    assert d.get("jit_traces", 0) == 0, d
    assert d.get("panel_transfers", 0) == 0, d
    assert d.get("host_syncs", 0) == 0, d


# ---- trace_report cross-check --------------------------------------------


def test_trace_report_metrics_section_cross_checks_scrape(
        service, tmp_path):
    """Satellite pin: the run dir's saved /metrics scrape is parsed by
    trace_report's metrics section and cross-checked against the
    span-derived serve numbers — clean on an honest scrape, LOUD
    (mismatches listed) on a forged one."""
    telemetry.COUNTERS.reset()  # scrape totals must cover the run window
    METRICS.reset()
    service.register("u0", _universe())
    months = service.serveable_months("u0")
    run_dir = str(tmp_path / "run")
    with telemetry.run_scope(run_dir, extra={"entry": "test_metrics"}):
        for m in months[:16]:
            service.score("u0", m)
        scrape = service.metrics_text()
    with open(os.path.join(run_dir, "metrics.prom"), "w") as fh:
        fh.write(scrape)
    tr = _trace_report()
    rep = tr.build_report(tr.load_run(run_dir))
    assert rep["serve"]["completed"] == 16
    mx = rep["metrics"]
    assert mx["requests"] == 16
    assert mx["mismatches"] == [], mx["mismatches"]
    assert mx["p99_ms"] is not None and mx["rel_resolution"] > 0
    # Forge the scrape: double the histogram counts — the section must
    # go loud, not shrug.
    forged = re.sub(
        r"^(lfm_serve_latency_ms_count\{[^}]*\}) (\d+)",
        lambda g: f"{g.group(1)} {int(g.group(2)) * 2}",
        scrape, flags=re.M)
    assert forged != scrape
    with open(os.path.join(run_dir, "metrics.prom"), "w") as fh:
        fh.write(forged)
    rep2 = tr.build_report(tr.load_run(run_dir))
    assert any("requests" in m for m in rep2["metrics"]["mismatches"])
