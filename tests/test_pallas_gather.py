"""Pallas DMA window gather (ops/pallas_gather.py) vs the XLA row gather.

Runs in Pallas interpret mode on the CPU test platform (the wrapper
auto-selects it off-TPU). The critical cases are panels whose month count
is NOT a multiple of 8: 8-aligned superwindow DMAs cannot reach the tail
of an unpadded month axis, so without month padding, anchors in the last
T % 8 months silently fetched windows shifted up to 7 months early —
look-ahead-shifted data at exactly the newest dates. ``pad_months`` (and
``device_panel(lane_pad=True)``) removes the case; these tests pin the
wrapper to exact parity with ``gather_windows_packed`` for every T % 8
residue and tail/young/mid anchor placement.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.data.panel import Panel
from lfm_quant_tpu.data.windows import (
    device_panel,
    gather_windows_packed,
    resolve_gather_impl,
)
from lfm_quant_tpu.ops.pallas_gather import (
    _aligned_span,
    gather_windows_pallas,
    pad_lanes,
    pad_months,
)

W = 60
N_FIRMS = 8
N_FEAT = 3  # fp = 4 packed


def _packed_panel(T, seed=0):
    """Unpadded packed panel [N, T, F+1] with ragged validity."""
    rng = np.random.default_rng(seed)
    feats = rng.standard_normal((N_FIRMS, T, N_FEAT)).astype(np.float32)
    valid = rng.random((N_FIRMS, T)) < 0.8
    valid[:, -1] = True  # keep the newest month observable somewhere
    xm = np.concatenate([feats, valid[..., None].astype(np.float32)], -1)
    # Zero invalid features like the real packed panel does not — the
    # gather contract zero-fills masked steps itself, so leave raw noise
    # in to catch any mask slip.
    return jnp.asarray(xm)


def _anchors(T):
    """[D] anchor months: the whole tail residue + young + mid anchors."""
    tail = [T - 1, T - 2, T - 5, T - 8, T - 9]
    other = [W - 2, 10, T // 2]  # young (clamp+roll) and mid
    return jnp.asarray(sorted({t for t in tail + other if 0 <= t < T}),
                       dtype=jnp.int32)


@pytest.mark.parametrize("T", [600, 601, 604, 613, 62, 64])
def test_parity_with_xla_gather(T):
    """Exact parity for every anchor placement, T % 8 in {0, 1, 4, 5, 6};
    T in {62, 64} exercises the clamped near-window-length span
    (w_pad == padded T, max_start8 == 0)."""
    xm = _packed_panel(T, seed=T)
    ti = _anchors(T)
    D = ti.shape[0]
    rng = np.random.default_rng(T + 1)
    fi = jnp.asarray(rng.integers(0, N_FIRMS, size=(D, 4)), dtype=jnp.int32)

    x_ref, m_ref = gather_windows_packed(xm, fi, ti, W)
    x, m = gather_windows_pallas(xm, fi, ti, W)

    np.testing.assert_array_equal(np.asarray(m), np.asarray(m_ref))
    np.testing.assert_array_equal(np.asarray(x), np.asarray(x_ref))


def test_parity_on_prepadded_panel():
    """The zero-copy production path: panel stored month+lane padded."""
    T = 601
    xm = _packed_panel(T, seed=3)
    xm_pad = pad_months(pad_lanes(xm))
    assert xm_pad.shape[1] % 8 == 0 and xm_pad.shape[2] % 128 == 0
    ti = _anchors(T)
    fi = jnp.asarray(
        np.random.default_rng(4).integers(0, N_FIRMS, (ti.shape[0], 4)),
        dtype=jnp.int32)
    x_ref, m_ref = gather_windows_packed(xm, fi, ti, W)
    x, m = gather_windows_pallas(xm_pad, fi, ti, W, fp=N_FEAT + 1)
    np.testing.assert_array_equal(np.asarray(m), np.asarray(m_ref))
    np.testing.assert_array_equal(np.asarray(x), np.asarray(x_ref))


def test_tail_anchor_fetches_newest_month():
    """Direct regression for the pre-fix failure: the anchor month itself
    (last position of the window) must hold the anchor's data for anchors
    in the unaligned tail residue."""
    T = 601  # T % 8 == 1: anchor T-1 was unreachable before month padding
    xm = _packed_panel(T, seed=7)
    ti = jnp.asarray([T - 1], dtype=jnp.int32)
    fi = jnp.asarray([[0, 1, 2, 3]], dtype=jnp.int32)
    x, m = gather_windows_pallas(xm, fi, ti, W)
    for j, f in enumerate([0, 1, 2, 3]):
        if bool(xm[f, T - 1, -1]):
            np.testing.assert_array_equal(
                np.asarray(x[0, j, -1]), np.asarray(xm[f, T - 1, :N_FEAT]))
            assert bool(m[0, j, -1])


def test_aligned_span_contract():
    # Unpadded month counts are rejected outright.
    assert _aligned_span(W, 601) is None
    assert _aligned_span(W, 613) is None
    # Padded counts give a span whose slack covers any 8-phase + clamp.
    span = _aligned_span(W, 608)
    assert span is not None
    w_pad, max_start8 = span
    assert w_pad - W >= 7 and max_start8 == 608 - w_pad
    assert max_start8 % 8 == 0
    # Near-window-length panels clamp the span to the whole (padded) month
    # axis and stay on the fast path (max_start8 == 0 ⇒ off <= w_pad - W).
    assert _aligned_span(W, 64) == (64, 0)
    # Panels shorter than the window fall back.
    assert _aligned_span(W, 56) is None


def test_device_panel_lane_pad_pads_months():
    T = 601
    rng = np.random.default_rng(0)
    feats = rng.standard_normal((N_FIRMS, T, N_FEAT)).astype(np.float32)
    valid = np.ones((N_FIRMS, T), bool)
    panel = Panel(
        features=feats, valid=valid,
        targets=np.zeros((N_FIRMS, T), np.float32),
        target_valid=valid.copy(),
        returns=np.zeros((N_FIRMS, T), np.float32),
        dates=np.arange(T, dtype=np.int32),
        firm_ids=np.arange(N_FIRMS, dtype=np.int32),
        feature_names=[f"f{i}" for i in range(N_FEAT)],
    )
    dev = device_panel(panel, raw=False, lane_pad=True)
    assert dev["xm"].shape[1] % 8 == 0
    assert dev["xm"].shape[2] % 128 == 0
    # Phantom months are invalid (zero validity column).
    assert not np.asarray(dev["xm"][:, T:, N_FEAT]).any()
    # resolve_gather_impl must agree that the padded panel is usable
    # (it only returns "pallas" on a real TPU, but must not trip on the
    # aligned-span check for any T residue).
    assert resolve_gather_impl("auto", None, panel, W) in ("xla", "pallas")


def test_resolve_gather_auto_refuses_f32_on_tpu(monkeypatch):
    """The f32 DMA gather is the standing tunnel-wedge suspect
    (scripts/diag_c1.py): until the on-chip diagnosis clears it, "auto"
    must route f32 panels to the XLA gather even on TPU, while bf16
    keeps the fast path and an explicit "pallas" is always honored (the
    diagnosis itself needs the override)."""
    import jax

    import lfm_quant_tpu.data.windows as win

    T = 240
    valid = np.ones((N_FIRMS, T), bool)
    panel = Panel(
        features=np.zeros((N_FIRMS, T, N_FEAT), np.float32), valid=valid,
        targets=np.zeros((N_FIRMS, T), np.float32),
        target_valid=valid.copy(),
        returns=np.zeros((N_FIRMS, T), np.float32),
        dates=np.arange(T, dtype=np.int32),
        firm_ids=np.arange(N_FIRMS, dtype=np.int32),
        feature_names=[f"f{i}" for i in range(N_FEAT)],
    )
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert resolve_gather_impl("auto", None, panel, W, bf16=True) == "pallas"
    assert resolve_gather_impl("auto", None, panel, W, bf16=False) == "xla"
    assert resolve_gather_impl("pallas", None, panel, W,
                               bf16=False) == "pallas"
    # Fails closed: a caller that doesn't state the dtype gets XLA.
    assert win.resolve_gather_impl("auto", None, panel, W) == "xla"


def test_vmap_folds_seeds_into_one_kernel():
    """vmap over per-seed index batches (the ensemble) must fold seeds
    into the kernel's date grid axis — ONE pallas_call, no lax.scan
    serialization — and match the per-seed results exactly."""
    import jax

    T, S, D, Bf = 72, 4, 3, 8
    xm = _packed_panel(T)
    rng = np.random.default_rng(7)
    fi = jnp.asarray(rng.integers(0, N_FIRMS, (S, D, Bf)).astype(np.int32))
    ti = jnp.asarray(rng.integers(W - 3, T - 1, (S, D)).astype(np.int32))

    def g(a, b):
        return gather_windows_pallas(xm, a, b, window=W, interpret=True)

    jaxpr = str(jax.make_jaxpr(jax.vmap(g))(fi, ti))
    assert jaxpr.count("pallas_call") == 1
    assert " scan[" not in jaxpr

    x, m = jax.vmap(g)(fi, ti)
    for s in range(S):
        xr, mr = g(fi[s], ti[s])
        np.testing.assert_array_equal(np.asarray(x[s]), np.asarray(xr))
        np.testing.assert_array_equal(np.asarray(m[s]), np.asarray(mr))

    # Shared firm indices with per-seed anchors (mixed batching).
    x2, m2 = jax.vmap(lambda b: g(fi[0], b))(ti)
    xr, mr = g(fi[0], ti[2])
    np.testing.assert_array_equal(np.asarray(x2[2]), np.asarray(xr))
    np.testing.assert_array_equal(np.asarray(m2[2]), np.asarray(mr))
