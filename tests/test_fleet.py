"""Fleet-serving lane (``-m fleet``): coordinator-scoped routing,
health-aware failover, store-bootstrapped member join (DESIGN.md §22).

Pins, in order of importance:

* **Zero incorrect responses through a member crash** — SIGKILLing a
  REAL subprocess member mid-traffic yields failover responses
  BIT-EQUAL to the pre-kill reference (every member restored from one
  verified store artifact), zero steady-state recompiles on the
  survivor (scrape-measured), and a replacement member joining from
  the ZooStore at zero restore compiles.
* **Reroute, not error** — an open-circuit or dead-batcher member is
  routed around with zero client errors; it goes OUT after
  ``LFM_FLEET_BREAKER`` failures and is readmitted only through a
  half-open probe after the cooldown.
* **The degenerate fleet** — one member behind the router is
  bit-identical to the direct single-process path.
* **The promotion gate** — a member whose restore report is
  probe-unverified or behind the store fence is REFUSED, never routed
  to; a fleet-wide publish propagates through the journaled manifest
  fence (``sync_from_store`` pulls only newer generations).
* **Non-interference** — ``LFM_FLEET`` unset is an exact no-op: a
  warm fit with the fleet module imported pays zero jit traces, zero
  panel H2D, one host sync per epoch.

Module named early in the alphabet on purpose: it must sort before the
tier-1 timebox cut (ROADMAP tier-1 notes).
"""

import json
import os
import signal
import time

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import clear_panel_cache
from lfm_quant_tpu.serve import (
    FleetCoordinator,
    FleetRouter,
    HttpMember,
    LocalMember,
    MemberJoinRefused,
    ScoringService,
    ZooStore,
)
from lfm_quant_tpu.serve import errors as serrors
from lfm_quant_tpu.serve import fleet
from lfm_quant_tpu.train import reuse
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.utils import faults, metrics, telemetry
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(seed=0, epochs=1, name="fleet_t"):
    return RunConfig(
        name=name,
        data=DataConfig(n_firms=48, n_months=140, n_features=4,
                        window=6, dates_per_batch=4, firms_per_date=24),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (8,)}),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=2,
                          loss="mse"),
        seed=seed,
    )


def _universe(seed=0, panel_seed=5):
    panel = synthetic_panel(n_firms=48, n_months=140, n_features=4,
                            seed=panel_seed)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(seed=seed), splits)
    tr.state = tr.init_state()
    return tr, splits


def _service(store_dir=None, **kw):
    kw.setdefault("max_rows", 2)
    kw.setdefault("max_wait_ms", 0.5)
    return ScoringService(persist_dir=store_dir, **kw)


def _simulate_process_death():
    reuse.clear_program_cache()
    clear_panel_cache()


@pytest.fixture(autouse=True)
def _fleet_hygiene(monkeypatch):
    """No fleet/persist/fault knobs leaking in or out."""
    for k in ("LFM_FLEET", "LFM_FLEET_REPLICAS", "LFM_FLEET_RETRIES",
              "LFM_FLEET_BREAKER", "LFM_FLEET_COOLDOWN_MS",
              "LFM_FLEET_HEALTH_TTL_MS", "LFM_FLEET_TIMEOUT_MS",
              "LFM_ZOO_PERSIST", "LFM_FAULTS"):
        monkeypatch.delenv(k, raising=False)
    faults.configure("")
    yield
    faults.configure("")


class _FakeMember:
    """Registry-only member for routing tests: no service behind it."""

    remote = False

    def __init__(self, name, universes):
        self.name = name
        self._universes = dict(universes)

    def join_report(self):
        return {"member": self.name, "universes": dict(self._universes)}

    def universes(self):
        return dict(self._universes)

    def close(self):
        pass


# ---- knobs / non-interference --------------------------------------------


def test_fleet_knob_routing(monkeypatch):
    assert fleet.fleet_members_default() == 0
    assert not fleet.fleet_enabled()
    monkeypatch.setenv("LFM_FLEET", "3")
    assert fleet.fleet_members_default() == 3
    assert fleet.fleet_enabled()
    monkeypatch.setenv("LFM_FLEET", "nope")
    with pytest.raises(ValueError, match="LFM_FLEET"):
        fleet.fleet_members_default()
    monkeypatch.delenv("LFM_FLEET")
    assert fleet.replicas_default() == 2
    monkeypatch.setenv("LFM_FLEET_REPLICAS", "4")
    assert fleet.replicas_default() == 4
    assert fleet.retries_default() == 2
    assert fleet.breaker_default() == 2
    assert fleet.cooldown_ms_default() == 1000.0
    assert fleet.health_ttl_ms_default() == 500.0
    assert fleet.member_timeout_ms_default() == 15000.0


def test_fleet_unset_is_measured_noop(monkeypatch):
    """The non-interference contract: with LFM_FLEET unset (and the
    fleet module imported — it is, at the top of this file and of
    serve/__init__), a warm fit pays zero jit traces, zero panel H2D
    and one host sync per epoch — the reuse/pipeline lane numbers,
    unchanged."""
    monkeypatch.delenv("LFM_FLEET", raising=False)
    assert not fleet.fleet_enabled()
    panel = synthetic_panel(n_firms=48, n_months=140, n_features=4,
                            seed=5)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(epochs=2), splits)
    tr.fit()  # cold: compiles + panel transfer
    snap = REUSE_COUNTERS.snapshot()
    tr.rebind()
    out = tr.fit()  # warm
    d = REUSE_COUNTERS.delta(snap)
    assert d.get("jit_traces", 0) == 0, d
    assert d.get("panel_transfers", 0) == 0, d
    assert d.get("host_syncs", 0) == out["epochs_run"], d


# ---- routing determinism -------------------------------------------------


def test_routing_deterministic_replicated_and_order_free():
    names = ["alpha", "beta", "gamma", "delta"]
    unis = {"ua": 0, "ub": 3}

    def build(order):
        coord = FleetCoordinator(replicas=2)
        for n in order:
            coord.add_member(_FakeMember(n, unis), verify=False)
        return coord

    a = build(names)
    b = build(list(reversed(names)))
    ra = a.route("ua")
    assert ra == b.route("ua")  # registration order never matters
    assert ra == a.route("ua")  # stable across calls
    assert sorted(ra) == sorted(names)  # replica set + last-resort tail
    # Distinct universes hash to distinct primaries at least sometimes
    # (deterministic, not a distribution claim: these fixed names do).
    assert a.route("ua")[0] != a.route("ub")[0] or \
        a.route("ua")[1] != a.route("ub")[1]
    # Month spread stays INSIDE the replica set; the tail is unchanged.
    r = a.replicas("ua")
    base = set(ra[:r])
    for month in (199001, 199002, 199007, 200012):
        rm = a.route("ua", month)
        assert set(rm[:r]) == base
        assert rm[r:] == ra[r:]
        assert rm == a.route("ua", month)  # deterministic per month
    # Hot-universe replication override widens the replica set.
    a.set_replicas("ua", 3)
    assert a.replicas("ua") == 3 and a.replicas("ub") == 2
    with pytest.raises(KeyError, match="not served"):
        a.route("nope")


# ---- the degenerate one-member fleet -------------------------------------


def test_one_member_fleet_bit_identical_and_503_when_out():
    svc = _service()
    try:
        tr, _ = _universe()
        svc.register("us", tr)
        months = svc.serveable_months("us")[:4]
        refs = {m: svc.score("us", m).scores.copy() for m in months}
        coord = FleetCoordinator.local(svc)
        router = FleetRouter(coord, retries=1, cooldown_ms=100)
        assert router.universes() == ["us"]
        assert router.serveable_months("us") == \
            svc.serveable_months("us")
        snap = REUSE_COUNTERS.snapshot()
        for m in months:
            r = router.score("us", m)
            np.testing.assert_array_equal(r.scores, refs[m])
            assert r.generation == 0
        d = REUSE_COUNTERS.delta(snap)
        # The router adds NO device work: steady state stays zero/zero.
        assert d.get("jit_traces", 0) == 0, d
        assert d.get("panel_transfers", 0) == 0, d
        assert router.health()["ok"]
        # Client/data errors keep the single-process taxonomy — and do
        # NOT feed the member breaker (the member answered).
        with pytest.raises(KeyError):
            router.score("us", 999999)
        assert coord.slot("m0").state == "in"
    finally:
        svc.close()
    # Every member gone ⇒ MemberUnavailableError: 503 + retry-after,
    # the fleet twin of CircuitOpenError.
    with pytest.raises(serrors.MemberUnavailableError) as ei:
        router.score("us", months[0])
    assert serrors.http_status(ei.value) == 503
    assert ei.value.retry_after_s > 0


def test_member_retryable_taxonomy():
    assert not fleet.member_retryable(KeyError("u"))
    assert not fleet.member_retryable(ValueError("v"))
    assert not fleet.member_retryable(
        serrors.DeadlineError("u", 199001, 0.1))
    assert fleet.member_retryable(serrors.ShedError(4))
    assert fleet.member_retryable(serrors.CircuitOpenError(0.2))
    assert fleet.member_retryable(
        serrors.BatcherDeadError(RuntimeError("x")))
    assert fleet.member_retryable(faults.TransientFault("serve_dispatch", 0))
    assert fleet.member_retryable(
        fleet.MemberCallError("m0", "connection refused"))
    e = serrors.MemberUnavailableError("us", tried=2, retry_after_s=0.5)
    assert isinstance(e, serrors.ServeError)
    assert e.http_status == 503 and e.retry_after_s == 0.5


# ---- health-aware reroute + half-open readmission ------------------------


def test_open_breaker_reroute_and_half_open_readmission():
    """An open-circuit member costs a REROUTE, not an error: the router
    consumes the member's /healthz breaker surface, takes it out, and
    readmits it only through a half-open probe after the cooldown."""
    svc_a = _service(breaker_cooldown_ms=100.0)
    svc_b = _service(breaker_cooldown_ms=100.0)
    try:
        tr_a, _ = _universe()
        tr_b, _ = _universe()
        svc_a.register("us", tr_a)
        svc_b.register("us", tr_b)
        months = svc_a.serveable_months("us")[:4]
        refs = {m: svc_a.score("us", m).scores.copy() for m in months}
        # Same cfg/seed/panel ⇒ bit-equal params ⇒ bit-equal scores:
        # the reroute-correctness premise, asserted not assumed.
        for m in months:
            np.testing.assert_array_equal(
                svc_b.score("us", m).scores, refs[m])
        coord = FleetCoordinator(replicas=2)
        coord.add_member(LocalMember("m0", svc_a), verify=False)
        coord.add_member(LocalMember("m1", svc_b), verify=False)
        router = FleetRouter(coord, breaker=1, cooldown_ms=150,
                             health_ttl_ms=0, retries=2)
        primary = coord.route("us")[0]
        victim = {"m0": svc_a, "m1": svc_b}[primary]
        snap = telemetry.COUNTERS.snapshot()
        # Trip the victim's OWN circuit breaker (4 consecutive failed
        # dispatches — the PR 10 machinery) without any traffic.
        for _ in range(4):
            victim.batcher._dispatch_fail()
        assert not victim.health()["ok"]
        # Every request during the outage succeeds bit-equal: the
        # router sees the open circuit on the health surface and
        # reroutes BEFORE paying a failed call.
        for m in months:
            np.testing.assert_array_equal(
                router.score("us", m).scores, refs[m])
        assert coord.slot(primary).state == "out"
        # Readmission: after the victim's breaker cooldown its
        # half-open probe can close it; after the ROUTER cooldown the
        # fleet half-open probe routes one live request back.
        deadline = time.perf_counter() + 10.0
        while (coord.slot(primary).state != "in"
               and time.perf_counter() < deadline):
            time.sleep(0.03)
            np.testing.assert_array_equal(
                router.score("us", months[0]).scores, refs[months[0]])
        assert coord.slot(primary).state == "in"
        d = telemetry.COUNTERS.delta(snap)
        assert d.get("fleet_member_out", 0) >= 1, d
        assert d.get("fleet_probes", 0) >= 1, d
        assert d.get("fleet_readmissions", 0) >= 1, d
        assert d.get("fleet_unroutable", 0) == 0, d
        # Post-readmission the member serves again (probe dispatched
        # through it closed its breaker).
        assert victim.health()["ok"]
    finally:
        svc_a.close()
        svc_b.close()


def test_dead_member_is_reroute_not_error():
    """A dead batcher thread on one member (the §18 BatcherDeadError
    path) never reaches a fleet client: fast-fail → failover."""
    svc_a = _service()
    svc_b = _service()
    try:
        tr_a, _ = _universe()
        tr_b, _ = _universe()
        svc_a.register("us", tr_a)
        svc_b.register("us", tr_b)
        m = svc_a.serveable_months("us")[5]
        ref = svc_a.score("us", m).scores.copy()
        coord = FleetCoordinator(replicas=2)
        coord.add_member(LocalMember("m0", svc_a), verify=False)
        coord.add_member(LocalMember("m1", svc_b), verify=False)
        router = FleetRouter(coord, breaker=1, cooldown_ms=60_000,
                             health_ttl_ms=60_000, retries=2)
        primary = coord.route("us", m)[0]
        victim = {"m0": svc_a, "m1": svc_b}[primary]
        # Warm the router's health cache while the victim is healthy
        # (TTL 60 s): the kill below is then INVISIBLE to the health
        # surface, so the router must discover it the hard way — one
        # failed call, failover, member out.
        np.testing.assert_array_equal(router.score("us", m).scores, ref)
        boom = RuntimeError("boom in _next_batch")
        victim.batcher._next_batch = \
            lambda: (_ for _ in ()).throw(boom)
        # The loop thread is parked inside the REAL _next_batch; one
        # request flushes it through so its NEXT call hits the boom
        # (the test_durable death-guard idiom — both orderings of that
        # race are the guard working).
        try:
            victim.score("us", m)
        except serrors.BatcherDeadError:
            pass
        deadline = time.perf_counter() + 5.0
        while victim.batcher._dead is None \
                and time.perf_counter() < deadline:
            time.sleep(0.001)
        # Health is TTL-cached as fresh-and-ok, so the router pays ONE
        # failed call (BatcherDeadError — member-retryable), fails
        # over, and takes the member out.
        r = router.score("us", m)
        np.testing.assert_array_equal(r.scores, ref)
        assert coord.slot(primary).state == "out"
        assert router.stats()["failovers"] >= 1
    finally:
        telemetry.COUNTERS.set("serve_batcher_dead", 0)
        svc_a.close()
        svc_b.close()


# ---- store-bootstrapped join / promotion gate ----------------------------


def test_store_bootstrap_join_syncs_and_pays_zero_compiles(tmp_path):
    store_dir = str(tmp_path / "store")
    svc = _service(store_dir)
    tr, _ = _universe()
    svc.register("us", tr)
    months = svc.serveable_months("us")[:3]
    refs = {m: svc.score("us", m).scores.copy() for m in months}
    svc.close()
    _simulate_process_death()

    # A fresh "process": read-only store attach, EMPTY zoo — the join
    # gate sees it behind the fence and pulls gen 0 through sync(),
    # verified like a restore, at zero jit traces (AOT executables).
    svc2 = _service(store_dir, persist_readonly=True)
    try:
        coord = FleetCoordinator(store=ZooStore(store_dir,
                                                readonly=True))
        snap = REUSE_COUNTERS.snapshot()
        rep = coord.add_member(LocalMember("m0", svc2))
        d = REUSE_COUNTERS.delta(snap)
        assert d.get("jit_traces", 0) == 0, d
        assert rep["universes"] == {} or "us" in rep["universes"]
        assert coord.slot("m0").universes == {"us": 0}
        assert coord.fence() == {"us": 0}
        router = FleetRouter(coord)
        for m in months:
            np.testing.assert_array_equal(
                router.score("us", m).scores, refs[m])
        assert telemetry.COUNTERS.get("fleet_joins") >= 1
    finally:
        svc2.close()


def test_join_gate_refuses_unverified_and_behind_fence(tmp_path):
    store_dir = str(tmp_path / "store")
    svc = _service(store_dir)
    tr, _ = _universe()
    svc.register("us", tr)
    svc.close()
    _simulate_process_death()

    coord = FleetCoordinator(store=ZooStore(store_dir, readonly=True))

    class _Unverified(_FakeMember):
        def join_report(self):
            return {"member": self.name,
                    "universes": {"us": 0},
                    "restore": [{"universe": "us", "generation": 0,
                                 "probe": "quarantined"}]}

    snap = telemetry.COUNTERS.snapshot()
    with pytest.raises(MemberJoinRefused, match="probe != bit_equal"):
        coord.add_member(_Unverified("bad", {"us": 0}))
    assert "bad" not in coord.members()  # never routed to

    class _Behind(_FakeMember):
        def join_report(self):
            return {"member": self.name, "universes": {}}

        def sync(self):
            raise RuntimeError("store unreachable")

    with pytest.raises(MemberJoinRefused, match="sync failed"):
        coord.add_member(_Behind("stale", {}))
    assert coord.members() == []
    d = telemetry.COUNTERS.delta(snap)
    assert d.get("fleet_refusals", 0) == 2, d


def test_join_gate_active_probe_refuses_imposter(tmp_path):
    """The promotion criterion is ACTIVE, not self-reported: a member
    at the right generation whose params are its OWN (never restored
    from the store — restore report absent) is caught by the gate
    scoring the store's publish-time probe month through it."""
    store_dir = str(tmp_path / "store")
    svc = _service(store_dir)
    tr, _ = _universe(seed=0)
    svc.register("us", tr)
    svc.close()
    _simulate_process_death()
    imposter = _service()  # storeless: trained its own generation 0
    try:
        tr2, _ = _universe(seed=9)
        imposter.register("us", tr2)
        coord = FleetCoordinator(store=ZooStore(store_dir,
                                                readonly=True))
        with pytest.raises(MemberJoinRefused,
                           match="parity probe mismatch"):
            coord.add_member(LocalMember("imposter", imposter))
        assert coord.members() == []  # never routed to
    finally:
        imposter.close()


def test_publish_fence_propagates_fleet_wide(tmp_path):
    """An atomic generation publish on the writer propagates to every
    member through the store-manifest fence: sync_from_store pulls
    ONLY the newer generation, verified, and both members serve it."""
    store_dir = str(tmp_path / "store")
    svc_w = _service(store_dir)
    svc_r = _service(store_dir, persist_readonly=True)
    try:
        tr0, _ = _universe(seed=0)
        svc_w.register("us", tr0)
        svc_r.restore()
        assert svc_r.zoo.generation("us") == 0
        coord = FleetCoordinator(store=svc_w.store, replicas=2)
        coord.add_member(LocalMember("w", svc_w))
        coord.add_member(LocalMember("r", svc_r))
        # The publish: a NEW generation on the writer (different params
        # — different seed), committed to the store before the swap.
        tr1, _ = _universe(seed=9)
        svc_w.register("us", tr1)
        m = svc_w.serveable_months("us")[5]
        ref1 = svc_w.score("us", m)
        assert ref1.generation == 1
        assert coord.fence() == {"us": 1}
        # Reader is behind the fence until the propagation pass.
        assert svc_r.zoo.generation("us") == 0
        out = coord.sync_members()
        assert out["members"]["w"]["up_to_date"]
        assert out["members"]["r"]["up_to_date"]
        assert out["members"]["r"]["synced"] == 1
        assert svc_r.zoo.generation("us") == 1
        r = svc_r.score("us", m)
        assert r.generation == 1
        np.testing.assert_array_equal(r.scores, ref1.scores)
        # Idempotent: a second pass syncs nothing.
        out2 = coord.sync_members()
        assert out2["members"]["r"]["synced"] == 0
    finally:
        svc_w.close()
        svc_r.close()


# ---- member identity / metrics aggregation -------------------------------


def test_member_identity_in_snapshot_and_scrape():
    svc = _service()
    try:
        tr, _ = _universe()
        svc.register("us", tr)
        info = telemetry.build_info()
        snap = svc.snapshot()
        assert snap["stats"]["member"] == {"host": info["host"],
                                           "pid": info["pid"]}
        prom = metrics.parse_prometheus(svc.metrics_text())
        rows = prom.get("lfm_build_info")
        assert rows, "lfm_build_info missing from the scrape"
        labels = rows[0][0]
        assert labels.get("host") == str(info["host"])
        assert labels.get("pid") == str(info["pid"])
    finally:
        svc.close()


def test_relabel_scrape_and_fleet_aggregation():
    text = ('# HELP x y\n# TYPE lfm_a counter\n'
            'lfm_a_total 3\n'
            'lfm_b{universe="us",width="64"} 2.5\n'
            'lfm_c{} 1\n')
    out = fleet.relabel_scrape(text, "m7")
    prom = metrics.parse_prometheus(out)
    assert prom["lfm_a_total"] == [({"member": "m7"}, 3.0)]
    assert prom["lfm_b"] == [({"member": "m7", "universe": "us",
                               "width": "64"}, 2.5)]
    assert prom["lfm_c"] == [({"member": "m7"}, 1.0)]
    # End to end: the one-member local fleet's aggregate carries the
    # router's own counters (in-process members share the registry).
    svc = _service()
    try:
        tr, _ = _universe()
        svc.register("us", tr)
        coord = FleetCoordinator.local(svc)
        router = FleetRouter(coord)
        router.score("us", svc.serveable_months("us")[5])
        agg = metrics.parse_prometheus(router.metrics_text())
        assert any(v >= 1 for _, v in
                   agg.get("lfm_fleet_requests_total", []))
        h = router.health()
        assert h["ok"] and h["members_in"] == 1
    finally:
        svc.close()


# ---- trace_report fleet section ------------------------------------------


def test_fleet_section_in_trace_report(tmp_path):
    run_dir = str(tmp_path / "run")
    svc_a = _service()
    svc_b = _service()
    try:
        tr_a, _ = _universe()
        tr_b, _ = _universe()
        svc_a.register("us", tr_a)
        svc_b.register("us", tr_b)
        months = svc_a.serveable_months("us")[:3]
        with telemetry.run_scope(run_dir, extra={"entry": "test_fleet"}):
            coord = FleetCoordinator(replicas=2)
            coord.add_member(LocalMember("m0", svc_a), verify=False)
            coord.add_member(LocalMember("m1", svc_b), verify=False)
            router = FleetRouter(coord, breaker=1, cooldown_ms=60_000,
                                 health_ttl_ms=0, retries=2)
            primary = coord.route("us")[0]
            victim = {"m0": svc_a, "m1": svc_b}[primary]
            for _ in range(4):
                victim.batcher._dispatch_fail()
            for m in months:
                router.score("us", m)
            with open(os.path.join(run_dir, "fleet.prom"), "w") as fh:
                fh.write(router.metrics_text())
    finally:
        svc_a.close()
        svc_b.close()

    from lfm_quant_tpu.serve.stats import load_trace_report

    tr_mod = load_trace_report(REPO)
    rep = tr_mod.build_report(tr_mod.load_run(run_dir))
    fl = rep.get("fleet")
    assert fl is not None
    assert fl["requests"] == len(months)
    assert fl["member_outs"] >= 1
    assert fl["mismatches"] == []
    assert primary in fl["timeline"]
    events = [e["event"] for e in fl["timeline"][primary]]
    assert "member_joined" in events and "member_out" in events
    # A forged/torn scrape is LOUD: a lifetime total can never show
    # FEWER events than the run recorded (direction-aware 1%
    # discipline — lifetime may exceed a single run's deltas on a
    # long-lived router, so only the impossible direction is flagged).
    import re

    with open(os.path.join(run_dir, "fleet.prom")) as fh:
        forged = re.sub(r"^lfm_fleet_requests_total .*$",
                        "lfm_fleet_requests_total 0",
                        fh.read(), flags=re.M)
    with open(os.path.join(run_dir, "fleet.prom"), "w") as fh:
        fh.write(forged)
    rep2 = tr_mod.build_report(tr_mod.load_run(run_dir))
    assert rep2["fleet"]["mismatches"], "forged fleet scrape not loud"


# ---- the acceptance pin: SIGKILL a subprocess member ---------------------


def test_sigkill_member_failover_subprocess(tmp_path):
    """The acceptance pin: a 2-subprocess-member fleet under traffic.
    SIGKILLing one member yields ZERO incorrect responses (every
    failover response bit-equal to the pre-kill reference), zero
    steady-state recompiles on the survivor (scrape-measured), and a
    replacement member joins from the store at zero restore compiles
    through the promotion gate."""
    store_dir = str(tmp_path / "store")
    svc = _service(store_dir)
    tr, _ = _universe()
    svc.register("us", tr)
    months = svc.serveable_months("us")[:6]
    refs = {m: svc.score("us", m).scores.copy() for m in months}
    svc.close()
    _simulate_process_death()

    env = {"JAX_PLATFORMS": "cpu"}
    procs, rfs = [], []
    try:
        for k in range(2):
            rf = str(tmp_path / f"ready{k}.json")
            procs.append(fleet.spawn_member(store_dir, ready_file=rf,
                                            env=env))
            rfs.append(rf)
        infos = [fleet.wait_member_ready(p, rf, 240)
                 for p, rf in zip(procs, rfs)]
        # Store-bootstrapped members at ZERO restore compiles, probe
        # bit_equal — the join gate admits them.
        coord = FleetCoordinator(store=ZooStore(store_dir,
                                                readonly=True))
        members = []
        for k, info in enumerate(infos):
            assert info["restore_compiles"] == 0, info
            assert all(r["probe"] == "bit_equal"
                       for r in info["restore"])
            hm = HttpMember(f"m{k}",
                            f"http://127.0.0.1:{info['port']}",
                            pid=info["pid"])
            coord.add_member(hm)
            members.append(hm)
        router = FleetRouter(coord, breaker=1, cooldown_ms=300,
                             retries=3)
        # Warm pass: every month bit-equal through the router.
        for m in months:
            np.testing.assert_array_equal(
                router.score("us", m).scores, refs[m])

        def traces_total(member):
            prom = metrics.parse_prometheus(member.metrics_text())
            vals = prom.get("lfm_jit_traces_total") or [({}, 0.0)]
            return sum(v for _, v in vals)

        victim_name = coord.route("us")[0]
        vk = int(victim_name[1:])
        survivor = members[1 - vk]
        survivor_traces0 = traces_total(survivor)
        os.kill(procs[vk].pid, signal.SIGKILL)
        # Mid-traffic kill: ZERO incorrect responses, ZERO errors.
        for _ in range(3):
            for m in months:
                r = router.score("us", m)
                np.testing.assert_array_equal(r.scores, refs[m])
        assert coord.slot(victim_name).state == "out"
        assert router.stats()["failovers"] >= 1
        assert router.health()["ok"]  # one member down ≠ outage
        # Zero steady-state recompiles on the survivor, measured from
        # its own scrape (ReuseCounters ride the absorbed counters).
        assert traces_total(survivor) == survivor_traces0
        # Replacement member: store-bootstrapped join, zero compiles.
        rf2 = str(tmp_path / "ready2.json")
        p2 = fleet.spawn_member(store_dir, ready_file=rf2, env=env)
        procs.append(p2)
        info2 = fleet.wait_member_ready(p2, rf2, 240)
        assert info2["restore_compiles"] == 0, info2
        hm2 = HttpMember("m2", f"http://127.0.0.1:{info2['port']}",
                         pid=info2["pid"])
        coord.add_member(hm2)
        assert "m2" in coord.route("us")
        r2 = hm2.score("us", months[0], timeout_s=15)
        np.testing.assert_array_equal(r2.scores, refs[months[0]])
    finally:
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
