"""Universal stacked-run engine (train/stacked.py): config-sweep parity,
per-run-operand hyperparameters, run-axis microbatching, and the
degrade-to-sequential accounting. (The program-key family-separation
test lives with the other key-collision suites in tests/test_buckets.py,
carrying this lane's marker.)

The engine's contract is the foldstack lane's, one level up: stacking
reorders WORK, never results. A stacked LR × weight-decay config sweep
must reproduce each config's sequential run — epoch histories, best
epochs, early-stop epochs, restored best params — BIT-identically on the
unsharded (``LFM_STACK_SHARDS=0``) stack across the LFM_ASYNC knob
matrix, with exactly ONE counted host sync per stacked epoch and (warm)
zero jit traces / zero panel H2D. The fold-mesh stack gets the same
last-ulp reduction-order tolerance policy as every sharded path, with
decisions still exact.

All tests carry the ``stacked`` marker — the fast CI guard
(``pytest -m stacked``) against a refactor that quietly breaks the
stacked/sequential numerical identity or re-bakes a per-run operand
into a traced constant."""

import json
import os

import jax
import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.train.stacked import (
    HYPER_KEYS,
    StackUnavailable,
    parse_sweep_grid,
    run_config_sweep,
)

pytestmark = pytest.mark.stacked

#: History fields that must match across execution modes (timing fields
#: — ts, firm_months_per_sec — legitimately differ). Same policy as the
#: foldstack lane: val_mse's month-sum reassociates under the run vmap,
#: so it gets last-ulp tolerance even on the "exact" lane.
_DET_FIELDS = ("epoch", "train_loss", "grad_norm", "val_ic", "val_mse")
_ULP_FIELDS = ("val_mse",)
_GRID = "lr=1e-3,3e-4;weight_decay=1e-4,0"


def _cfg(tmp, epochs=3, patience=99, optimizer="adamw"):
    return RunConfig(
        name="cswp",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=5,
                          loss="mse", early_stop_patience=patience,
                          optimizer=optimizer),
        seed=0,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)


def _sweep(tmp, panel, monkeypatch, *, stacked, name, grid=_GRID,
           async_on=True, **cfg_kw):
    monkeypatch.setenv("LFM_ASYNC", "1" if async_on else "0")
    monkeypatch.setenv("LFM_ASYNC_CKPT", "1" if async_on else "0")
    out = str(tmp / name)
    summary = run_config_sweep(_cfg(tmp, **cfg_kw), parse_sweep_grid(grid),
                               panel=panel, out_dir=out, stacked=stacked)
    return summary, out


def _histories(out_dir, n):
    return [
        [json.loads(l) for l in
         open(os.path.join(out_dir, f"config_{i:03d}", "metrics.jsonl"))]
        for i in range(n)
    ]


def _assert_parity(seq, stk, exact, check_params=False, panel=None):
    """Per-config records, histories and (optionally) best params
    restored from each config dir's ckpt/best line. ``exact`` pins
    bit-identity; otherwise float fields get last-ulp tolerance while
    every DECISION (epochs run, best epoch, early-stop epoch) stays
    exact."""
    sum_s, d_s = seq
    sum_k, d_k = stk
    n = sum_s["n_configs"]
    assert (sum_k.get("stacked") or {}).get("enabled") is True
    assert sum_s.get("stacked") is None
    for rs, rk in zip(sum_s["runs"], sum_k["runs"]):
        assert rs["epochs_run"] == rk["epochs_run"], rs["config"]
        assert rs["best_epoch"] == rk["best_epoch"], rs["config"]
        np.testing.assert_allclose(rk["best_val_ic"], rs["best_val_ic"],
                                   rtol=0 if exact else 2e-5)
    assert sum_s["best_index"] == sum_k["best_index"]
    for i, (a, b) in enumerate(zip(_histories(d_s, n), _histories(d_k, n))):
        assert [r["epoch"] for r in a] == [r["epoch"] for r in b], i
        for ra, rb in zip(a, b):
            for f in _DET_FIELDS:
                if f not in ra:
                    continue
                if exact and f not in _ULP_FIELDS:
                    assert ra[f] == rb[f], (i, ra["epoch"], f, ra[f], rb[f])
                else:
                    np.testing.assert_allclose(
                        rb[f], ra[f], rtol=1e-6 if exact else 2e-5,
                        err_msg=f"config {i} {f}")
    if not check_params:
        return
    from lfm_quant_tpu.train.loop import load_trainer

    for i in range(n):
        ps = jax.tree.leaves(load_trainer(
            os.path.join(d_s, f"config_{i:03d}"), panel=panel)[0].state.params)
        pk = jax.tree.leaves(load_trainer(
            os.path.join(d_k, f"config_{i:03d}"), panel=panel)[0].state.params)
        for a, b in zip(ps, pk):
            if exact:
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            else:
                np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                           atol=5e-6, rtol=1e-4)


def test_unsharded_sweep_bit_identical(panel, tmp_path, monkeypatch):
    """LFM_STACK_SHARDS=0 (pure vmap over the config axis): per-config
    histories and restored best params are BIT-identical to sequential
    per-config fits — the per-run-operand optimizer mirror reproduces
    each config's baked optax chain to the bit, across the LFM_ASYNC
    knob matrix."""
    monkeypatch.setenv("LFM_STACK_SHARDS", "0")
    for async_on in (False, True):
        tag = "a" if async_on else "s"
        seq = _sweep(tmp_path, panel, monkeypatch, stacked=False,
                     async_on=async_on, name=f"seq_{tag}")
        stk = _sweep(tmp_path, panel, monkeypatch, stacked=True,
                     async_on=async_on, name=f"stk_{tag}")
        assert stk[0]["stacked"]["stack_mesh"] is None
        assert stk[0]["stacked"]["hyper"] == list(HYPER_KEYS)
        _assert_parity(seq, stk, exact=True, check_params=async_on,
                       panel=panel)


def test_lamb_unsharded_sweep_bit_identical(panel, tmp_path, monkeypatch):
    """The lamb branch of the per-run-operand mirror (scale_by_adam with
    eps=1e-6 + trust ratio + the 4-element chain-state reindex) holds
    the same bit-identity contract as adamw — a chain-order or
    state-index mistake there would otherwise ship with no failing
    lane."""
    monkeypatch.setenv("LFM_STACK_SHARDS", "0")
    kw = dict(epochs=2, optimizer="lamb", grid="lr=1e-3,3e-4")
    seq = _sweep(tmp_path, panel, monkeypatch, stacked=False,
                 name="lamb_seq", **kw)
    stk = _sweep(tmp_path, panel, monkeypatch, stacked=True,
                 name="lamb_stk", **kw)
    _assert_parity(seq, stk, exact=True, check_params=True, panel=panel)


def test_divergent_early_stop_parity(panel, tmp_path, monkeypatch):
    """Configs stopping at DIFFERENT epochs (patience=1, a 30× LR
    spread): per-config early-stop and best epochs must match the
    sequential fits exactly — the masked device-side control reproduces
    each config's FitHarness decisions while its neighbors keep
    training."""
    monkeypatch.setenv("LFM_STACK_SHARDS", "0")
    kw = dict(epochs=8, patience=1, grid="lr=1e-3,1e-4,3e-5")
    seq = _sweep(tmp_path, panel, monkeypatch, stacked=False,
                 name="es_seq", **kw)
    stk = _sweep(tmp_path, panel, monkeypatch, stacked=True,
                 name="es_stk", **kw)
    epochs_seq = [r["epochs_run"] for r in seq[0]["runs"]]
    assert epochs_seq == [r["epochs_run"] for r in stk[0]["runs"]]
    assert min(epochs_seq) < 8, "at least one config must early-stop"
    assert len(set(epochs_seq)) > 1, \
        "config stop epochs must diverge for this test to bite"
    _assert_parity(seq, stk, exact=True)


def test_stack_mesh_decisions_exact(panel, tmp_path, monkeypatch):
    """Default shards (the stack axis actually sharded on the 8-device
    CPU platform): histories within last-ulp reduction-order tolerance,
    every early-stop/best decision exact — the same policy as every
    sharded path in this repo."""
    seq = _sweep(tmp_path, panel, monkeypatch, stacked=False, name="m_seq")
    stk = _sweep(tmp_path, panel, monkeypatch, stacked=True, name="m_stk")
    if jax.device_count() > 1:
        assert dict(stk[0]["stacked"]["stack_mesh"])["stack"] > 1
    _assert_parity(seq, stk, exact=False)


def test_stack_block_bit_identical(panel, tmp_path, monkeypatch):
    """LFM_STACK_BLOCK=2 (run-axis microbatching, the seed_block move
    one axis up): blocking the 4-run stack into 2-run scan blocks is a
    pure re-batching — bit-identical to the unblocked stack."""
    monkeypatch.setenv("LFM_STACK_SHARDS", "0")
    blocked = {}
    for blk in ("0", "2"):
        monkeypatch.setenv("LFM_STACK_BLOCK", blk)
        summary, out = _sweep(tmp_path, panel, monkeypatch, stacked=True,
                              name=f"blk_{blk}")
        assert summary["stacked"]["stack_block"] == int(blk)
        blocked[blk] = _histories(out, summary["n_configs"])
    for i, (a, b) in enumerate(zip(blocked["0"], blocked["2"])):
        for ra, rb in zip(a, b):
            for f in ("train_loss", "grad_norm", "val_ic"):
                assert ra[f] == rb[f], (i, ra["epoch"], f)


def test_non_dividing_stack_block_degrades_unblocked(panel, tmp_path,
                                                     monkeypatch):
    """A block that does not divide the per-shard run count must warn
    and run unblocked — never truncate or crash the stack."""
    monkeypatch.setenv("LFM_STACK_SHARDS", "0")
    monkeypatch.setenv("LFM_STACK_BLOCK", "3")
    with pytest.warns(UserWarning, match="does not divide"):
        summary, _ = _sweep(tmp_path, panel, monkeypatch, stacked=True,
                            name="blk_bad")
    assert summary["stacked"]["stack_block"] == 0


@pytest.mark.reuse
def test_warm_sweep_zero_traces_zero_transfers(panel, tmp_path,
                                               monkeypatch):
    """The reuse lane's contract for config sweeps: a SECOND stacked
    sweep binds the first one's stacked executables and resident panel —
    zero new jit traces, zero panel H2D (200 configs, one compiled
    program: the tentpole's whole point) — and the stacked fit pays
    exactly ONE counted blocking host sync per stacked epoch (the PR 3
    pipeline contract through the stacked driver)."""
    from lfm_quant_tpu.data.windows import clear_panel_cache
    from lfm_quant_tpu.train import reuse
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    reuse.clear_program_cache()
    clear_panel_cache()
    try:
        _sweep(tmp_path, panel, monkeypatch, stacked=True, name="warmup")
        snap = REUSE_COUNTERS.snapshot()
        summary, _ = _sweep(tmp_path, panel, monkeypatch, stacked=True,
                            name="warm")
        d = REUSE_COUNTERS.delta(snap)
        assert d["jit_traces"] == 0, d
        assert d["panel_transfers"] == 0, d
        stack = summary["stacked"]
        epochs = max(r["epochs_run"] for r in summary["runs"])
        assert stack["reuse"]["host_syncs"] == epochs, stack["reuse"]
    finally:
        reuse.clear_program_cache()
        clear_panel_cache()


def test_degrade_to_sequential_is_loud(panel, tmp_path, monkeypatch):
    """A grid whose configs differ beyond the per-run-operand axes
    (here: epochs) cannot stack — the sweep must warn, bump the
    ``stack_degrades`` counter, land a ``stack_degraded`` telemetry
    instant, and still produce the sequential results."""
    import dataclasses

    from lfm_quant_tpu.utils import telemetry

    cfg = _cfg(tmp_path, epochs=2)
    grid = [{"lr": 1e-3}, {"lr": 3e-4}]
    run_cfgs_bad = [
        dataclasses.replace(cfg, optim=dataclasses.replace(
            cfg.optim, lr=g["lr"], epochs=2 + i))
        for i, g in enumerate(grid)
    ]
    from lfm_quant_tpu.data.panel import PanelSplits
    from lfm_quant_tpu.train.stacked import StackedRuns

    dates = panel.dates
    splits = PanelSplits.by_date(panel, int(dates[int(len(dates) * 0.7)]),
                                 int(dates[int(len(dates) * 0.85)]))
    with pytest.raises(StackUnavailable, match="beyond the per-run axes"):
        StackedRuns(run_cfgs_bad, [splits, splits], panel, kind="config")

    # The driver-level degrade: monkeypatch the engine to refuse, then
    # check warning + counter + sequential results all land.
    before = telemetry.COUNTERS.get("stack_degrades")
    import lfm_quant_tpu.train.stacked as stacked_mod

    def refuse(*a, **kw):
        raise StackUnavailable("forced for the degrade test")

    monkeypatch.setattr(stacked_mod, "StackedRuns", refuse)
    with pytest.warns(UserWarning, match="stacked config sweep "
                                         "unavailable"):
        summary = run_config_sweep(cfg, grid, panel=panel,
                                   out_dir=str(tmp_path / "deg"),
                                   stacked=True)
    assert summary["stacked"] is None
    assert len(summary["runs"]) == 2
    assert telemetry.COUNTERS.get("stack_degrades") == before + 1


def test_foldstack_degrade_bumps_counter(panel, tmp_path):
    """The fold adapter's degrade path (no rolling window → sequential
    walk-forward) now shares the loud-degrade accounting: warning AND
    counter, so trace_report can surface it from a run dir alone."""
    from lfm_quant_tpu.train.walkforward import run_walkforward
    from lfm_quant_tpu.utils import telemetry

    before = telemetry.COUNTERS.get("stack_degrades")
    with pytest.warns(UserWarning, match="fold-stacking unavailable"):
        run_walkforward(_cfg(tmp_path, epochs=2), panel,
                        out_dir=str(tmp_path / "fsdeg"), foldstack=True,
                        start=198001, step_months=12, val_months=24,
                        n_folds=2)
    assert telemetry.COUNTERS.get("stack_degrades") == before + 1


def test_parse_sweep_grid():
    """CLI grid spec → cartesian product; unknown axes fail loudly at
    parse time (a typo'd axis must die before any device work)."""
    grid = parse_sweep_grid("lr=1e-3,5e-4;weight_decay=1e-4,0")
    assert len(grid) == 4
    assert grid[0] == {"lr": 1e-3, "weight_decay": 1e-4}
    assert grid[-1] == {"lr": 5e-4, "weight_decay": 0.0}
    assert parse_sweep_grid("lr=1e-3") == [{"lr": 1e-3}]
    for bad in ("dropout=0.1", "lr", "", "lr=;", "lr=1e-3;lr=1e-4"):
        with pytest.raises(ValueError):
            parse_sweep_grid(bad)


def test_sweep_summary_ranks_and_dirs_load(panel, tmp_path, monkeypatch):
    """sweep_summary.json ranks the grid (best_index/best_config agree
    with the per-run records) and every config dir is a standalone
    loadable run dir — config.json pins the swept hyperparameters, so
    ``load_trainer`` rebuilds the exact per-config trainer."""
    summary, out = _sweep(tmp_path, panel, monkeypatch, stacked=True,
                          name="rank")
    on_disk = json.load(open(os.path.join(out, "sweep_summary.json")))
    assert on_disk["best_index"] == summary["best_index"]
    best = max(summary["runs"], key=lambda r: r["best_val_ic"])
    assert summary["best_config"] == best["config"]
    i = summary["best_index"]
    cfg_json = json.load(open(os.path.join(
        out, f"config_{i:03d}", "config.json")))
    assert cfg_json["optim"]["lr"] == summary["best_config"]["lr"]
    from lfm_quant_tpu.train.loop import load_trainer

    trainer, _ = load_trainer(os.path.join(out, f"config_{i:03d}"),
                              panel=panel)
    assert trainer.cfg.optim.lr == summary["best_config"]["lr"]
