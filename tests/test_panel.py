"""Panel store (L1) tests: schema invariants, planted signal, splits, IO."""

import numpy as np
import pytest

from lfm_quant_tpu.data import PanelSplits, load_panel, synthetic_panel

pytestmark = pytest.mark.fast  # whole module is smoke-lane cheap


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=200, n_months=180, n_features=5, seed=7)


def test_shapes_and_invariants(panel):
    panel.validate()
    assert panel.n_firms == 200
    assert panel.n_months == 180
    assert panel.n_features == 5
    # Invalid cells are zero-filled.
    assert np.all(panel.features[~panel.valid] == 0.0)
    assert np.all(panel.targets[~panel.target_valid] == 0.0)


def test_dates_are_consecutive_months(panel):
    d = panel.dates
    y, m = d // 100, d % 100
    assert np.all((m >= 1) & (m <= 12))
    lin = y * 12 + (m - 1)
    assert np.all(np.diff(lin) == 1)


def test_ragged_histories_exist(panel):
    # Not all firms live the whole panel; all have >= min_history months.
    counts = panel.valid.sum(axis=1)
    assert counts.min() >= 60
    assert counts.max() <= 180
    assert len(np.unique(counts)) > 10


def test_target_needs_lookahead(panel):
    # A target can never be observable in the last `horizon` months of a
    # firm's life: target_valid implies valid at t+horizon.
    n, t = panel.valid.shape
    h = panel.horizon
    tv = panel.target_valid[:, : t - h]
    future_valid = panel.valid[:, h:]
    assert np.all(~tv | future_valid)
    assert not panel.target_valid[:, t - h :].any()


def test_planted_signal_is_recoverable(panel):
    # Cross-sectional correlation between the true current features and the
    # future target must be materially positive (the signal exists) —
    # a sanity check on the generator, not on any model.
    mask = panel.target_valid
    x = panel.features[..., 0][mask]
    y = panel.targets[mask]
    r = np.corrcoef(x, y)[0, 1]
    assert r > 0.3, f"planted signal too weak: corr={r:.3f}"


def test_returns_reward_good_forecasts(panel):
    # Ranking firms by the *true* target should earn positive next-month
    # returns on average (the backtest alpha the framework must recover).
    mask = panel.target_valid & (panel.returns != 0)
    ic = np.corrcoef(panel.targets[mask], panel.returns[mask])[0, 1]
    assert ic > 0.05, f"returns not loaded on signal: corr={ic:.3f}"


def test_date_slice(panel):
    d0 = int(panel.dates[0])
    sl = panel.date_slice(d0, 198001)
    assert int(sl.dates[-1]) < 198001
    assert sl.n_firms == panel.n_firms


def test_splits_are_anchor_ranges(panel):
    splits = PanelSplits.by_date(panel, train_end=198001, val_end=198201)
    assert splits.panel is panel  # shared, not sliced
    lo, hi = splits.train_range
    assert lo == 0
    # Training anchors are embargoed `horizon` months before train_end.
    assert int(panel.dates[hi + panel.horizon - 1]) < 198001
    vlo, vhi = splits.val_range
    assert int(panel.dates[vlo]) >= 198001
    # Val anchors are embargoed too: last val target realized before test.
    assert int(panel.dates[vhi + panel.horizon - 1]) < 198201
    tlo, thi = splits.test_range
    assert int(panel.dates[tlo]) >= 198201 and thi == panel.n_months
    assert splits.range_of("val") == splits.val_range
    with pytest.raises(ValueError, match="unknown split"):
        splits.range_of("holdout")
    with pytest.raises(ValueError, match="strictly inside"):
        PanelSplits.by_date(panel, 196001, 198001)
    # Periods shorter than the horizon cannot host embargoed anchors.
    with pytest.raises(ValueError, match="horizon"):
        PanelSplits.by_date(panel, 198001, 198006)


def test_save_load_roundtrip(tmp_path, panel):
    panel.save(str(tmp_path))
    loaded = load_panel(str(tmp_path))
    np.testing.assert_array_equal(loaded.features, panel.features)
    np.testing.assert_array_equal(loaded.valid, panel.valid)
    np.testing.assert_array_equal(loaded.dates, panel.dates)
    assert list(loaded.feature_names) == list(panel.feature_names)
    assert loaded.horizon == panel.horizon


def test_generator_is_deterministic():
    a = synthetic_panel(n_firms=50, n_months=100, seed=3)
    b = synthetic_panel(n_firms=50, n_months=100, seed=3)
    np.testing.assert_array_equal(a.features, b.features)
    c = synthetic_panel(n_firms=50, n_months=100, seed=4)
    assert not np.array_equal(a.features, c.features)


def test_het_noise_default_keeps_legacy_stream_and_scales_spread():
    """het_noise=0.0 must reproduce the legacy generator BYTE-IDENTICALLY
    (every seeded fixture in the suite depends on it); het_noise>0 widens
    the cross-firm spread of realized target variability — the
    uncertainty stack's testbed."""
    a = synthetic_panel(n_firms=60, n_months=100, seed=7)
    b = synthetic_panel(n_firms=60, n_months=100, seed=7, het_noise=0.0)
    for f in ("features", "targets", "returns", "valid", "target_valid"):
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f))
    h = synthetic_panel(n_firms=60, n_months=100, seed=7, het_noise=1.0)

    def spread(p):
        # target_valid, not valid: outside it targets are zero-filled
        # placeholders that would contaminate the realized spread.
        s = np.nanstd(np.where(p.target_valid, p.targets, np.nan), axis=1)
        s = s[np.isfinite(s) & (s > 0)]
        return float(s.max() / s.min())

    assert spread(h) > 1.5 * spread(a)
