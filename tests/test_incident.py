"""Incident lane (``-m incident``): request tracing, the black-box
flight recorder, and automatic incident capture (DESIGN.md §21).

* **Request-scoped tracing** — every request gets (or propagates, via
  ``X-Request-Id`` / ``traceparent``) a trace id that rides submit →
  queue → coalesced batch → dispatch → response, with an O(1)
  queue/batch/retry/dispatch phase breakdown echoed in the response,
  the ``serve_request`` span, the access log and the histogram
  exemplars — pinned end to end including a REAL HTTP round trip.
* **Flight recorder** (``utils/flight.py``) — always-on, lock-guarded,
  allocation-bounded ring of the last ~N structured events; hammered
  from many threads (exact counts, no torn lines), dumped crash-safely,
  fed by ``telemetry.instant`` with NO active run (the black-box
  property).
* **Automatic incident capture** (``serve/incident.py``) — forced
  triggers through the ``LFM_FAULTS`` harness (breaker open, snapshot
  quarantine) each produce EXACTLY ONE rate-limited bundle under the
  cooldown, containing the ring, a valid ``/metrics`` scrape, ≥1
  slow-request trace with phases, and host/build identity —
  ``scripts/trace_report.py`` parses it loudly-clean.
* **Non-interference re-measured** with the recorder fully on: a warm
  fit pays zero jit traces / zero panel H2D / one host sync per epoch,
  and serving steady state pays zero/zero.

Module named early in the alphabet on purpose: it must sort before the
tier-1 timebox cut (ROADMAP tier-1 notes).
"""

import json
import os
import threading
import time
import urllib.request
import warnings

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.data.windows import clear_panel_cache
from lfm_quant_tpu.serve import ScoringService
from lfm_quant_tpu.serve import incident as incident_mod
from lfm_quant_tpu.serve.batcher import clean_request_id, new_request_id
from lfm_quant_tpu.train import reuse
from lfm_quant_tpu.train.loop import Trainer
from lfm_quant_tpu.utils import faults, flight, telemetry
from lfm_quant_tpu.utils.metrics import METRICS
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

pytestmark = pytest.mark.incident

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(n_firms=48, window=6, seed=0, epochs=1, name="incident_t"):
    return RunConfig(
        name=name,
        data=DataConfig(n_firms=n_firms, n_months=140, n_features=4,
                        window=window, dates_per_batch=4,
                        firms_per_date=24),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (8,)}),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=2,
                          loss="mse"),
        seed=seed,
    )


def _universe(seed=0, panel_seed=5, fit=False):
    panel = synthetic_panel(n_firms=48, n_months=140, n_features=4,
                            seed=panel_seed)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(seed=seed), splits)
    if fit:
        tr.fit()
    else:
        tr.state = tr.init_state()
    return tr


def _trace_report():
    """Import scripts/trace_report.py the stats.py way (no package)."""
    from lfm_quant_tpu.serve.stats import load_trace_report

    return load_trace_report(REPO)


@pytest.fixture(autouse=True)
def _incident_hygiene(monkeypatch):
    """Default knob state, fresh ring/registry/caches — in AND out (the
    chaos-lane hygiene pattern)."""
    for knob in ("LFM_FLIGHT", "LFM_INCIDENT_DIR",
                 "LFM_INCIDENT_COOLDOWN_S", "LFM_ACCESS_LOG",
                 "LFM_FAULTS", "LFM_METRICS"):
        monkeypatch.delenv(knob, raising=False)
    faults.configure("")
    flight.configure()
    METRICS.reset()
    reuse.clear_program_cache()
    clear_panel_cache()
    yield
    faults.configure("")
    flight.configure()
    METRICS.reset()
    reuse.clear_program_cache()
    clear_panel_cache()


# ---- flight recorder -----------------------------------------------------


def test_flight_ring_bounded_ordered_and_dumpable(tmp_path):
    rec = flight.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("ev", cat="t", i=i)
    snap = rec.snapshot()
    assert len(snap) == 8                       # bounded
    assert [e["i"] for e in snap] == list(range(12, 20))  # newest kept
    assert [e["seq"] for e in snap] == list(range(13, 21))
    st = rec.stats()
    assert st["total_seen"] == 20 and st["dropped"] == 12
    # Crash-safe dump: strict JSON lines, atomic replace (non-finite
    # floats nulled — the spans.jsonl policy).
    rec.record("weird", cat="t", bad=float("nan"))
    path = str(tmp_path / "flight.jsonl")
    n = rec.dump(path)
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert len(lines) == n == 8
    assert lines[-1]["kind"] == "weird" and lines[-1]["bad"] is None


def test_flight_knob_routing(monkeypatch):
    assert flight.flight_capacity() == flight.DEFAULT_CAPACITY
    monkeypatch.setenv("LFM_FLIGHT", "0")
    assert flight.configure() is None
    assert not flight.enabled()
    flight.record("nope")                        # exact no-op
    assert flight.snapshot() == []
    monkeypatch.setenv("LFM_FLIGHT", "64")
    rec = flight.configure()
    assert rec is not None and rec.capacity == 64
    monkeypatch.setenv("LFM_FLIGHT", "bogus")
    with pytest.raises(ValueError, match="LFM_FLIGHT"):
        flight.configure()
    # Clean BEFORE the hygiene teardown re-reads the env (its
    # configure() would re-raise on the planted garbage).
    monkeypatch.delenv("LFM_FLIGHT")
    flight.configure()


def test_flight_multithreaded_hammer_exact_counts_no_torn_lines(tmp_path):
    """N writer threads × M events each: every event lands exactly once
    (a capacity above N×M), the ring never exceeds its bound under a
    small capacity, and a dump mid-hammer parses line-for-line."""
    n_threads, n_events = 8, 400
    rec = flight.FlightRecorder(capacity=n_threads * n_events + 1)

    def writer(tid):
        for k in range(n_events):
            rec.record("hammer", cat="t", tid=tid, k=k)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = rec.snapshot()
    assert len(snap) == n_threads * n_events
    seen = {(e["tid"], e["k"]) for e in snap}
    assert len(seen) == n_threads * n_events     # exact, no loss
    assert [e["seq"] for e in snap] == sorted(e["seq"] for e in snap)
    # Bounded ring under the same hammer: capacity is the hard cap.
    small = flight.FlightRecorder(capacity=64)
    threads = [threading.Thread(target=lambda t=t: [
        small.record("h", tid=t, k=k) for k in range(n_events)])
        for t in range(n_threads)]
    for t in threads:
        t.start()
    dumped = []
    for _ in range(5):                           # dump DURING the hammer
        p = str(tmp_path / "mid.jsonl")
        small.dump(p)
        dumped.append([json.loads(x)
                       for x in open(p).read().splitlines()])
    for t in threads:
        t.join()
    assert len(small.snapshot()) == 64
    for lines in dumped:                         # no torn lines, ever
        assert len(lines) <= 64
        assert all("kind" in e and "seq" in e for e in lines)


def test_instants_land_in_ring_without_active_run():
    """The black-box property: breaker transitions / fault injections /
    publishes are telemetry INSTANTS, and instants feed the ring even
    when no telemetry run dir is attached (where PR 4 spans go
    nowhere)."""
    assert telemetry._ACTIVE is None
    rec = flight.configure()
    telemetry.instant("circuit_open", cat="serve", streak=3)
    kinds = [e["kind"] for e in rec.snapshot()]
    assert "circuit_open" in kinds
    ev = rec.snapshot()[-1]
    assert ev["streak"] == 3 and ev["cat"] == "serve"


# ---- request-scoped tracing ---------------------------------------------


def test_request_id_hygiene():
    assert len(new_request_id()) == 32
    assert new_request_id() != new_request_id()
    assert clean_request_id(None) is None
    assert clean_request_id("") is None
    assert clean_request_id("  ok-id_1.2  ") == "ok-id_1.2"
    # Hostile header: control/quote/shell characters stripped (only
    # alnum and -_. survive), length capped at 64.
    assert clean_request_id('x"\n;rm -rf<y>' + "z" * 100) == \
        "xrm-rfy" + "z" * 57
    assert len(clean_request_id("a" * 500)) == 64


def test_request_ids_and_phase_breakdown_end_to_end(tmp_path):
    """Trace identity + phases through the REAL service: propagated and
    minted ids echo in the response, the span record, the slow-trace
    tracker and the histogram exemplars; phases sum to ~latency; and
    serving steady state stays zero-trace/zero-H2D with the recorder
    and tracing fully on."""
    run_dir = str(tmp_path / "run")
    assert telemetry._ACTIVE is None
    svc = ScoringService(max_rows=4, max_wait_ms=1.0)
    try:
        svc.register("u0", _universe())
        months = svc.serveable_months("u0")
        svc.score("u0", months[0])               # warm D2H paths
        snap = REUSE_COUNTERS.snapshot()
        with telemetry.run_scope(run_dir, extra={"entry": "test"}):
            r = svc.score("u0", months[1], request_id="trace-me-7")
            auto = svc.score("u0", months[2])
        d = REUSE_COUNTERS.delta(snap)
        assert d.get("jit_traces", 0) == 0, d
        assert d.get("panel_transfers", 0) == 0, d
        assert r.request_id == "trace-me-7"
        assert len(auto.request_id) == 32        # minted
        for resp in (r, auto):
            p = resp.phases
            for k in ("queue_ms", "batch_ms", "retry_ms", "dispatch_ms",
                      "retries", "width"):
                assert k in p, p
            total = (p["queue_ms"] + p["batch_ms"] + p["retry_ms"]
                     + p["dispatch_ms"])
            assert total == pytest.approx(resp.latency_ms, abs=1.0)
            assert p["retries"] == 0
        # The slow-trace tracker holds both, with their ids and phases.
        slow = svc.batcher.slow_traces()
        by_id = {t["request_id"]: t for t in slow}
        assert "trace-me-7" in by_id
        assert by_id["trace-me-7"]["dispatch_ms"] >= 0
        # Exemplars: some latency bucket points at a real trace id.
        ex = METRICS.exemplar_snapshot("serve_latency_ms")
        ids = {e["trace_id"] for v in ex.values() for e in v}
        assert "trace-me-7" in ids or auto.request_id in ids
        # The span record carries the same id + phases (the waterfall's
        # source), and trace_report surfaces the slowest table.
        spans = [json.loads(x) for x in
                 open(os.path.join(run_dir, "spans.jsonl"))]
        req_spans = [s for s in spans if s.get("name") == "serve_request"]
        args = {s["args"]["request_id"]: s["args"] for s in req_spans}
        assert "trace-me-7" in args
        assert args["trace-me-7"]["queue_ms"] >= 0
    finally:
        svc.close()
    tr = _trace_report()
    rep = tr.build_report(tr.load_run(run_dir))
    slowest = rep["serve"]["slowest"]
    assert slowest and "trace-me-7" in {a["request_id"] for a in slowest}
    for a in slowest:
        for k in ("queue_ms", "batch_ms", "retry_ms", "dispatch_ms"):
            assert a[k] is not None


def test_http_header_round_trip_and_access_log(tmp_path, monkeypatch):
    """A REAL HTTP round trip: X-Request-Id propagates into the served
    response (header + body), traceparent's trace-id field is
    extracted, and the knob-gated access log writes one strict-JSON
    line per request with id, routing, status and phases."""
    import serve as serve_mod

    log_path = str(tmp_path / "access.jsonl")
    monkeypatch.setenv("LFM_ACCESS_LOG", log_path)
    svc = ScoringService(max_rows=4, max_wait_ms=1.0)
    httpd = None
    try:
        svc.register("u0", _universe())
        m = svc.serveable_months("u0")[3]
        httpd = serve_mod.make_http_server(svc, 0)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score?universe=u0&month={m}",
            headers={"X-Request-Id": "hdr-rt-1"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers.get("X-Request-Id") == "hdr-rt-1"
            body = json.load(resp)
        assert body["request_id"] == "hdr-rt-1"
        assert body["phases"]["dispatch_ms"] >= 0

        tp = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score?universe=u0&month={m}",
            headers={"traceparent": tp})
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert (resp.headers.get("X-Request-Id")
                    == "0af7651916cd43dd8448eb211c80319c")

        # No header: the service MINTS an id and still echoes it.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/score?universe=u0&month={m}",
                timeout=30) as resp:
            assert len(resp.headers.get("X-Request-Id")) == 32

        lines = [json.loads(x)
                 for x in open(log_path).read().splitlines()]
        assert len(lines) == 3
        assert lines[0]["request_id"] == "hdr-rt-1"
        for rec in lines:
            for k in ("ts", "request_id", "universe", "month", "status",
                      "bucket", "queue_ms", "dispatch_ms", "retries"):
                assert k in rec, rec
            assert rec["status"] == 200
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        svc.close()
    # Knob off (default): not a line is written.
    monkeypatch.delenv("LFM_ACCESS_LOG")
    serve_mod.access_log({"should": "not appear"})
    assert len(open(log_path).read().splitlines()) == 3


# ---- automatic incident capture -----------------------------------------


def test_incident_cooldown_dir_resolution_and_rate_limit(tmp_path):
    svc = ScoringService(max_rows=2, max_wait_ms=0.5)
    try:
        inc = svc.incidents
        # No explicit dir, no env, no active run → capture disabled.
        assert inc.resolve_dir() is None
        assert inc.trigger("breaker_open", sync=True) is False
        inc._dir = str(tmp_path / "inc")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            assert inc.trigger("breaker_open", sync=True, streak=2)
            # Same trigger inside the cooldown: suppressed.
            assert inc.trigger("breaker_open", sync=True) is False
            # A DIFFERENT trigger kind has its own cooldown clock.
            assert inc.trigger("slo_burn", sync=True, max_burn=2.0)
        assert inc.captured == 2 and inc.suppressed == 1
        bundles = incident_mod.find_bundles(str(tmp_path / "inc"))
        assert len(bundles) == 2
        # snapshot() surfaces the tallies (the /stats view).
        assert svc.snapshot()["stats"]["incidents"] == {
            "captured": 2, "suppressed": 1}
    finally:
        svc.close()


def test_forced_breaker_open_produces_exactly_one_bundle(tmp_path):
    """THE acceptance pin: a forced breaker-open (LFM_FAULTS transient
    dispatch schedule, retries exhausted) under load produces exactly
    ONE rate-limited bundle containing the flight ring, a VALID
    /metrics scrape, and ≥1 slow-request trace with the
    queue/batch/dispatch phase breakdown; trace_report parses it
    loudly-clean; a second breaker-open inside the cooldown adds no
    bundle."""
    from concurrent.futures import wait as fwait

    run_dir = str(tmp_path / "run")
    assert telemetry._ACTIVE is None
    with telemetry.run_scope(run_dir, extra={"entry": "test_incident"}):
        svc = ScoringService(max_rows=4, max_wait_ms=1.0, retries=0,
                             breaker_threshold=2,
                             breaker_cooldown_ms=30.0)
        try:
            svc.register("u0", _universe())
            months = svc.serveable_months("u0")
            for m in months[:6]:                 # healthy traffic first
                svc.score("u0", m)
            faults.configure("serve_dispatch:kind=transient,n=2")
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                futs = [svc.submit("u0", months[i % len(months)])
                        for i in range(6)]
                fwait(futs, timeout=30)
                svc.incidents.wait()
            faults.configure("")
            assert svc.batcher.stats()["breaker_opens"] >= 1
            bundles = incident_mod.find_bundles(run_dir)
            assert len(bundles) == 1, bundles
            bdir = bundles[0]

            meta = json.load(open(os.path.join(bdir, "incident.json")))
            assert meta["trigger"] == "breaker_open"
            assert meta["host"]["host"] and meta["host"]["pid"]
            assert meta["host"]["backend"] is not None

            ring = [json.loads(x) for x in
                    open(os.path.join(bdir, "flight.jsonl"))]
            kinds = {e["kind"] for e in ring}
            assert "circuit_open" in kinds       # the causal moment
            assert "fault_injected" in kinds     # ...and its cause
            assert "dispatch" in kinds           # healthy traffic before

            # The scrape is VALID 0.0.4: the package parser and the
            # trace_report twin agree on it, and it carries the serve
            # families.
            doc = open(os.path.join(bdir, "metrics.prom")).read()
            from lfm_quant_tpu.utils.metrics import parse_prometheus

            tr = _trace_report()
            prom_a = parse_prometheus(doc)
            prom_b = tr._parse_prom(doc)
            assert prom_a == prom_b
            assert "lfm_serve_latency_ms_count" in prom_a
            assert "lfm_build_info" in prom_a
            info_labels = prom_a["lfm_build_info"][0][0]
            assert info_labels["backend"] and info_labels["git_sha"]

            slow = json.load(open(os.path.join(bdir,
                                               "slow_requests.json")))
            assert len(slow) >= 1
            for t in slow:
                for k in ("request_id", "queue_ms", "batch_ms",
                          "dispatch_ms", "latency_ms"):
                    assert k in t, t

            # Exemplars point at real trace ids from the slow set's
            # stream (same histogram, same ids).
            ex = json.load(open(os.path.join(bdir, "exemplars.json")))
            assert any(v for v in ex.values())

            # Second forced breaker-open INSIDE the cooldown: the
            # breaker opens again, the capture is suppressed.
            time.sleep(0.1)                      # past the breaker
            faults.configure("serve_dispatch:kind=transient,n=2")
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", RuntimeWarning)
                futs = [svc.submit("u0", months[i % len(months)])
                        for i in range(6)]
                fwait(futs, timeout=30)
                svc.incidents.wait()
            faults.configure("")
            assert svc.batcher.stats()["breaker_opens"] >= 2
            assert len(incident_mod.find_bundles(run_dir)) == 1
            assert svc.incidents.suppressed >= 1
        finally:
            svc.close()
    # trace_report: loudly-clean — one bundle, its trigger, a timeline,
    # and NO mismatch lines (the bundle's mid-run scrape totals are
    # inside the 1% discipline against the run's span-derived counts).
    tr = _trace_report()
    rep = tr.build_report(tr.load_run(run_dir))
    inc = rep["incidents"]
    assert inc["count"] == 1
    assert inc["bundles"][0]["trigger"] == "breaker_open"
    assert inc["bundles"][0]["flight_events"] > 0
    assert inc["bundles"][0]["slow_traces"] >= 1
    assert inc["bundles"][0]["timeline"]
    assert inc["mismatches"] == []
    assert rep["serve"]["breaker_opens"] >= 2
    # Forge the bundle's scrape (a shed total the capture snapshot
    # never recorded): the scrape-integrity cross-check must go LOUD,
    # not quietly average it away.
    forged = os.path.join(incident_mod.find_bundles(run_dir)[0],
                          "metrics.prom")
    doc = open(forged).read().replace(
        "lfm_serve_shed_total", "lfm_ignored_total") \
        + "\nlfm_serve_shed_total 999999\n"
    open(forged, "w").write(doc)
    rep2 = tr.build_report(tr.load_run(run_dir))
    assert any("serve_shed" in m and "forged" in m
               for m in rep2["incidents"]["mismatches"])


def test_quarantine_trigger_produces_exactly_one_bundle(tmp_path):
    """The durable-state trigger: a snapshot failing restore
    verification (tampered params checksum) quarantines AND captures
    exactly one incident bundle."""
    store_dir = str(tmp_path / "store")
    inc_dir = str(tmp_path / "inc")
    svc = ScoringService(max_rows=2, max_wait_ms=0.5,
                         persist_dir=store_dir, incident_dir=inc_dir)
    try:
        svc.register("us", _universe())
    finally:
        svc.close()
    reuse.clear_program_cache()
    clear_panel_cache()
    # Tamper: flip the committed params checksum (the durable-lane
    # idiom) — restore must quarantine, and the quarantine must
    # trigger a capture.
    mpath = os.path.join(store_dir, "manifest.json")
    m = json.load(open(mpath))
    m["universes"]["us"]["generations"][-1]["params_sha256"] = "0" * 64
    json.dump(m, open(mpath, "w"))
    svc2 = ScoringService(max_rows=2, max_wait_ms=0.5,
                          persist_dir=store_dir, incident_dir=inc_dir)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            assert svc2.restore() == []
            svc2.incidents.wait()
        bundles = incident_mod.find_bundles(inc_dir)
        assert len(bundles) == 1
        meta = json.load(open(os.path.join(bundles[0], "incident.json")))
        assert meta["trigger"] == "quarantine"
        assert "reason" in meta["context"]
        # The ring captured the quarantine instant itself.
        ring = [json.loads(x) for x in
                open(os.path.join(bundles[0], "flight.jsonl"))]
        assert "restore_quarantine" in {e["kind"] for e in ring}
    finally:
        svc2.close()


def test_fit_non_interference_with_recorder_fully_on(monkeypatch):
    """The measured contract re-pinned with THIS PR's layer on: flight
    recorder recording, incident manager constructed — a warm fit
    still pays zero jit traces, zero panel H2D, one host sync per
    epoch."""
    assert flight.enabled()
    panel = synthetic_panel(n_firms=48, n_months=140, n_features=4,
                            seed=5)
    splits = PanelSplits.by_date(panel, 197801, 198001)
    tr = Trainer(_cfg(epochs=2), splits)
    tr.fit()                                     # cold
    snap = REUSE_COUNTERS.snapshot()
    ring_before = len(flight.snapshot())
    tr.rebind()
    out = tr.fit()                               # warm
    d = REUSE_COUNTERS.delta(snap)
    assert d.get("jit_traces", 0) == 0, d
    assert d.get("panel_transfers", 0) == 0, d
    assert d.get("host_syncs", 0) == out["epochs_run"], d
    # The recorder was LIVE through the fit (instants land), i.e. the
    # zero-interference numbers above were measured with it on.
    assert flight.recorder() is not None
    assert len(flight.snapshot()) >= ring_before
