"""Sequence parallelism (parallel/ring.py): ring attention vs full
attention, and the sequence-sharded transformer vs the plain one, on the
8-device virtual CPU mesh (conftest).

The reference has no sequence parallelism (SURVEY.md §3) — this is the
framework's long-context capability; correctness is defined against the
un-sharded computation.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from lfm_quant_tpu.models import build_model
from lfm_quant_tpu.parallel import (
    ring_attention,
    seq_mesh,
    sequence_parallel_apply,
)

B, H, W, DH = 3, 2, 32, 8


def _qkvm(seed=0, all_invalid_row=False):
    rng = np.random.default_rng(seed)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, W, DH)), jnp.float32)
               for _ in range(3))
    m = jnp.asarray(rng.random((B, W)) < 0.7)
    if all_invalid_row:
        m = m.at[0].set(False)
    return q, k, v, m


def full_attention(q, k, v, m):
    """Dense masked reference."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (DH ** -0.5)
    s = jnp.where(m[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    # rows with no valid keys: softmax of all -1e30 is uniform garbage —
    # zero them, matching ring_attention's contract
    any_valid = m.any(axis=-1)[:, None, None, None]
    return jnp.where(any_valid, jnp.einsum("bhqk,bhkd->bhqd", p, v), 0.0)


def _ring(q, k, v, m, mesh):
    from lfm_quant_tpu.parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        functools.partial(ring_attention, axis_name="seq"),
        mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3 + (P(None, "seq"),),
        out_specs=P(None, None, "seq", None),
    )
    return fn(q, k, v, m)


@pytest.mark.parametrize("n_dev", [2, pytest.param(8, marks=pytest.mark.nightly)])
def test_ring_matches_full_attention(n_dev):
    mesh = seq_mesh(n_dev)
    q, k, v, m = _qkvm()
    out = _ring(q, k, v, m, mesh)
    ref = full_attention(q, k, v, m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.nightly
def test_ring_grads_match_full_attention():
    mesh = seq_mesh(8)
    q, k, v, m = _qkvm(seed=1)

    def loss(fn, q, k, v):
        return (fn(q, k, v) ** 2).sum()

    g_ring = jax.grad(
        lambda *a: loss(lambda q, k, v: _ring(q, k, v, m, mesh), *a),
        argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(
        lambda *a: loss(lambda q, k, v: full_attention(q, k, v, m), *a),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name}")


@pytest.mark.nightly
def test_ring_empty_key_rows_zero():
    mesh = seq_mesh(8)
    q, k, v, m = _qkvm(all_invalid_row=True)
    out = _ring(q, k, v, m, mesh)
    assert float(jnp.abs(out[0]).max()) == 0.0
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.nightly
def test_sequence_parallel_transformer_matches_plain():
    """Same params, window sharded 8 ways: identical forecasts."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((16, W, 5)), jnp.float32)
    m = jnp.asarray(rng.random((16, W)) < 0.8)
    m = m.at[3].set(False)  # an entirely-invalid history
    mk = dict(dim=16, depth=2, heads=2)
    plain = build_model("transformer", **mk)
    seq = build_model("transformer", seq_axis="seq", **mk)
    params = plain.init(jax.random.key(0), x, m)["params"]

    out_plain = plain.apply({"params": params}, x, m)
    mesh = seq_mesh(8)
    out_seq = sequence_parallel_apply(seq, params, x, m, mesh)
    np.testing.assert_allclose(np.asarray(out_seq), np.asarray(out_plain),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.nightly
def test_sequence_parallel_transformer_grads():
    """Parameter gradients agree between sharded and plain encoders —
    the training-path guarantee for long-context mode."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((8, W, 5)), jnp.float32)
    m = jnp.asarray(rng.random((8, W)) < 0.8)
    y = jnp.asarray(rng.standard_normal((8,)), jnp.float32)
    mk = dict(dim=16, depth=1, heads=2)
    plain = build_model("transformer", **mk)
    seq = build_model("transformer", seq_axis="seq", **mk)
    params = plain.init(jax.random.key(1), x, m)["params"]
    mesh = seq_mesh(8)

    def loss_plain(p):
        return ((plain.apply({"params": p}, x, m) - y) ** 2).mean()

    def loss_seq(p):
        return ((sequence_parallel_apply(seq, p, x, m, mesh) - y) ** 2).mean()

    g_p = jax.grad(loss_plain)(params)
    g_s = jax.grad(loss_seq)(params)
    flat_p = jax.tree_util.tree_leaves_with_path(g_p)
    flat_s = dict(jax.tree_util.tree_leaves_with_path(g_s))
    assert len(flat_p) == len(flat_s)
    for path, leaf in flat_p:
        np.testing.assert_allclose(
            np.asarray(flat_s[path]), np.asarray(leaf), atol=1e-4,
            rtol=1e-3, err_msg=jax.tree_util.keystr(path))


@pytest.mark.nightly
@pytest.mark.parametrize("n_dev", [2, 8])
def test_sequence_parallel_lru_matches_plain(n_dev):
    """The distributed associative scan (models/lru.py) must equal the
    single-device scan: same params, window sharded over the seq axis."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((16, W, 5)), jnp.float32)
    m = jnp.asarray(rng.random((16, W)) < 0.8)
    m = m.at[:, -1].set(True)
    m = m.at[3].set(False)  # an entirely-invalid history
    mk = dict(hidden=16, state_dim=16, layers=2)
    plain = build_model("lru", **mk)
    seq = build_model("lru", seq_axis="seq", **mk)
    params = plain.init(jax.random.key(0), x, m)["params"]

    out_plain = plain.apply({"params": params}, x, m)
    out_seq = sequence_parallel_apply(seq, params, x, m, seq_mesh(n_dev))
    np.testing.assert_allclose(np.asarray(out_seq), np.asarray(out_plain),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.nightly
def test_sequence_parallel_lru_grads():
    """Parameter gradients agree between the sharded and plain LRU —
    the training-path guarantee for the long-context linear recurrence."""
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((8, W, 5)), jnp.float32)
    m = jnp.asarray(rng.random((8, W)) < 0.8)
    y = jnp.asarray(rng.standard_normal((8,)), jnp.float32)
    mk = dict(hidden=16, state_dim=16, layers=1)
    plain = build_model("lru", **mk)
    seq = build_model("lru", seq_axis="seq", **mk)
    params = plain.init(jax.random.key(1), x, m)["params"]
    mesh = seq_mesh(8)

    def loss_plain(p):
        return ((plain.apply({"params": p}, x, m) - y) ** 2).mean()

    def loss_seq(p):
        return ((sequence_parallel_apply(seq, p, x, m, mesh) - y) ** 2).mean()

    # jit is REQUIRED around the sharded grad: eager grad-of-shard_map
    # trips an XLA sharding-override assert on associative_scan's
    # transpose in this JAX version; the training path is always jitted
    # (train/loop.py), so jit-compiled AD is the semantics to pin.
    g_p = jax.tree_util.tree_leaves_with_path(jax.jit(jax.grad(loss_plain))(params))
    g_s = dict(jax.tree_util.tree_leaves_with_path(jax.jit(jax.grad(loss_seq))(params)))
    assert len(g_p) == len(g_s)
    for path, leaf in g_p:
        np.testing.assert_allclose(
            np.asarray(g_s[path]), np.asarray(leaf), atol=1e-4, rtol=1e-3,
            err_msg=str(path))


@pytest.mark.nightly
def test_seq_parallel_training_from_config(tmp_path):
    """Sequence parallelism as a CONFIG-level training mode: a
    transformer trained with n_seq_shards=4 (window sharded over a
    ('seq',) mesh, ring attention inside the step) must reproduce the
    plain full-window run's loss trajectory and recover the signal."""
    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.loop import run_experiment

    panel = synthetic_panel(n_firms=150, n_months=150, n_features=5,
                            seed=13)

    def cfg(n_seq, name):
        return RunConfig(
            name=name,
            data=DataConfig(n_firms=150, n_months=150, n_features=5,
                            window=8, dates_per_batch=4,
                            firms_per_date=32),
            model=ModelConfig(kind="transformer",
                              kwargs={"dim": 16, "depth": 1, "heads": 2}),
            optim=OptimConfig(lr=3e-3, epochs=2, warmup_steps=5,
                              loss="mse"),
            n_seq_shards=n_seq,
            out_dir=str(tmp_path),
        )

    s_plain, _, _ = run_experiment(cfg(1, "sp_plain"), panel=panel)
    s_seq, tr_seq, _ = run_experiment(cfg(4, "sp_seq"), panel=panel)
    assert tr_seq.seq_mesh is not None
    a = [h["train_loss"] for h in s_plain["history"]]
    b = [h["train_loss"] for h in s_seq["history"]]
    np.testing.assert_allclose(b, a, rtol=2e-3)
    assert abs(s_seq["best_val_ic"] - s_plain["best_val_ic"]) < 0.05


@pytest.mark.nightly
def test_seq_parallel_lru_training_from_config(tmp_path):
    """Same config-level mode for the LRU: the distributed associative
    scan replaces ring attention; loss trajectory matches plain."""
    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.loop import run_experiment

    panel = synthetic_panel(n_firms=120, n_months=150, n_features=5,
                            seed=14)

    def cfg(n_seq, name):
        return RunConfig(
            name=name,
            data=DataConfig(n_firms=120, n_months=150, n_features=5,
                            window=8, dates_per_batch=4,
                            firms_per_date=24),
            model=ModelConfig(kind="lru",
                              kwargs={"hidden": 16, "state_dim": 16,
                                      "layers": 1}),
            optim=OptimConfig(lr=3e-3, epochs=2, warmup_steps=5,
                              loss="mse"),
            n_seq_shards=n_seq,
            out_dir=str(tmp_path),
        )

    s_plain, _, _ = run_experiment(cfg(1, "splru_plain"), panel=panel)
    s_seq, _, _ = run_experiment(cfg(4, "splru_seq"), panel=panel)
    a = [h["train_loss"] for h in s_plain["history"]]
    b = [h["train_loss"] for h in s_seq["history"]]
    np.testing.assert_allclose(b, a, rtol=2e-3)


def test_seq_parallel_config_validation(tmp_path):
    """The config-level guards: RNNs can't window-shard; window must
    divide; dropout forbidden; ensembles compose (seed × data × seq)."""
    import pytest as _pytest

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train import Trainer
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    panel = synthetic_panel(n_firms=120, n_months=150, n_features=5,
                            seed=15)
    splits = PanelSplits.by_date(panel, 197901, 198101)

    def cfg(**over):
        base = dict(
            name="spv",
            data=DataConfig(n_firms=120, n_months=150, n_features=5,
                            window=8, dates_per_batch=4,
                            firms_per_date=24),
            model=ModelConfig(kind="transformer",
                              kwargs={"dim": 16, "depth": 1, "heads": 2}),
            optim=OptimConfig(epochs=1),
            n_seq_shards=4,
            out_dir=str(tmp_path),
        )
        base.update(over)
        return RunConfig(**base)

    with _pytest.raises(ValueError, match="window-shardable"):
        Trainer(cfg(model=ModelConfig(kind="lstm",
                                      kwargs={"hidden": 16})), splits)
    with _pytest.raises(ValueError, match="divide"):
        Trainer(cfg(data=DataConfig(n_firms=120, n_months=150,
                                    n_features=5, window=10,
                                    dates_per_batch=4,
                                    firms_per_date=24)), splits)
    with _pytest.raises(ValueError, match="dropout"):
        Trainer(cfg(model=ModelConfig(
            kind="transformer",
            kwargs={"dim": 16, "depth": 1, "heads": 2,
                    "dropout": 0.1})), splits)
    # Ensembles now COMPOSE with the seq axis (seed × data × seq) —
    # construction must succeed and carry the seq mesh axis.
    etr = EnsembleTrainer(cfg(n_seeds=2), splits)
    assert "seq" in dict(etr.mesh.shape)


@pytest.mark.nightly
def test_seq_parallel_resume_and_degrade(tmp_path):
    """Resume re-places restored state on the seq mesh (shard_map needs
    multi-device placement), and an over-wide n_seq_shards degrades to
    the visible device count with a warning instead of refusing to load."""
    import warnings as _warnings

    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.loop import run_experiment

    panel = synthetic_panel(n_firms=120, n_months=150, n_features=5,
                            seed=16)
    cfg = RunConfig(
        name="sp_resume",
        data=DataConfig(n_firms=120, n_months=150, n_features=5,
                        window=8, dates_per_batch=4, firms_per_date=24),
        model=ModelConfig(kind="transformer",
                          kwargs={"dim": 16, "depth": 1, "heads": 2}),
        optim=OptimConfig(lr=3e-3, epochs=3, warmup_steps=5, loss="mse"),
        n_seq_shards=4,
        out_dir=str(tmp_path),
    )
    s1, _, _ = run_experiment(cfg, panel=panel)
    # Resume past the end: restores the checkpoint through _commit_state
    # and exits cleanly (the restored state must be seq-mesh-placeable).
    s2, tr2, _ = run_experiment(cfg, panel=panel, resume=True)
    assert tr2.seq_mesh is not None
    assert np.isfinite(s2["best_val_ic"])

    # 64 > 8 visible devices: degrade with a warning, still trainable.
    import dataclasses

    wide = dataclasses.replace(cfg, name="sp_wide", n_seq_shards=64)
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        s3, tr3, _ = run_experiment(wide, panel=panel)
    assert any("degrading" in str(w.message) for w in rec)
    assert tr3.seq_mesh is not None  # 8 devices → 8-wide seq mesh
    assert dict(tr3.seq_mesh.shape)["seq"] == 8
    assert np.isfinite(s3["best_val_ic"])



@pytest.mark.nightly
def test_seq_parallel_composes_with_data_parallel(tmp_path):
    """SP × DP on one mesh: n_data_shards=2 × n_seq_shards=4 over the 8
    virtual devices — batches shard dates over 'data', each seq shard
    runs its window slice — must reproduce the plain run's losses (the
    grads psum over both axes; the num/den seq duplication cancels)."""
    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.loop import run_experiment

    panel = synthetic_panel(n_firms=150, n_months=150, n_features=5,
                            seed=17)

    def cfg(n_data, n_seq, name):
        return RunConfig(
            name=name,
            data=DataConfig(n_firms=150, n_months=150, n_features=5,
                            window=8, dates_per_batch=4,
                            firms_per_date=32),
            model=ModelConfig(kind="transformer",
                              kwargs={"dim": 16, "depth": 1, "heads": 2}),
            optim=OptimConfig(lr=3e-3, epochs=2, warmup_steps=5,
                              loss="mse"),
            n_data_shards=n_data, n_seq_shards=n_seq,
            out_dir=str(tmp_path),
        )

    s_plain, _, _ = run_experiment(cfg(1, 1, "comp_plain"), panel=panel)
    s_comp, tr, _ = run_experiment(cfg(2, 4, "comp_dp_sp"), panel=panel)
    assert dict(tr.mesh.shape) == {"seed": 1, "data": 2, "seq": 4}
    a = [h["train_loss"] for h in s_plain["history"]]
    b = [h["train_loss"] for h in s_comp["history"]]
    np.testing.assert_allclose(b, a, rtol=2e-3)
    assert abs(s_comp["best_val_ic"] - s_plain["best_val_ic"]) < 0.05


@pytest.mark.nightly
def test_seq_parallel_composes_with_ensemble(tmp_path):
    """The full parallelism matrix: seed × data × seq on one mesh
    (2 seeds × 2 data × 2 seq over the 8 virtual devices). The ensemble's
    per-seed loss traces must match the same ensemble trained without the
    seq axis (seeds/data orders identical; only the window sharding
    changes)."""
    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.train.ensemble import run_ensemble_experiment

    panel = synthetic_panel(n_firms=150, n_months=150, n_features=5,
                            seed=18)

    def cfg(n_seq, name):
        return RunConfig(
            name=name,
            data=DataConfig(n_firms=150, n_months=150, n_features=5,
                            window=8, dates_per_batch=4,
                            firms_per_date=24),
            model=ModelConfig(kind="lru",
                              kwargs={"hidden": 16, "state_dim": 16,
                                      "layers": 1}),
            optim=OptimConfig(lr=3e-3, epochs=2, warmup_steps=5,
                              loss="mse"),
            n_seeds=2, n_data_shards=2, n_seq_shards=n_seq,
            out_dir=str(tmp_path),
        )

    s_plain, tr_p, _ = run_ensemble_experiment(cfg(1, "ens_plain"),
                                               panel=panel)
    s_seq, tr_s, _ = run_ensemble_experiment(cfg(2, "ens_seq"),
                                             panel=panel)
    assert dict(tr_s.mesh.shape) == {"seed": 2, "data": 2, "seq": 2}
    a = [h["train_loss"] for h in s_plain["history"]]
    b = [h["train_loss"] for h in s_seq["history"]]
    np.testing.assert_allclose(b, a, rtol=2e-3)
    # Per-seed params match across the two runs too (seeds independent).
    for x, y in zip(jax.tree.leaves(tr_p.state.params),
                    jax.tree.leaves(tr_s.state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-2, atol=2e-4)


@pytest.mark.nightly
def test_seq_fully_degraded_ensemble_still_constructs(tmp_path):
    """When seed×data consume every device, the seq axis degrades to 1
    and the ensemble must construct and train with the plain full-window
    model — NOT crash (the pod-trained-config-on-small-host contract)."""
    import warnings as _warnings

    import numpy as np

    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer

    panel = synthetic_panel(n_firms=120, n_months=150, n_features=5,
                            seed=19)
    splits = PanelSplits.by_date(panel, 197901, 198101)
    cfg = RunConfig(
        name="sp_degraded",
        data=DataConfig(n_firms=120, n_months=150, n_features=5,
                        window=8, dates_per_batch=4, firms_per_date=16),
        model=ModelConfig(kind="lru",
                          kwargs={"hidden": 16, "state_dim": 16,
                                  "layers": 1}),
        optim=OptimConfig(lr=3e-3, epochs=1, warmup_steps=2, loss="mse"),
        n_seeds=4, n_data_shards=2, n_seq_shards=2,  # 4*2 = all 8 devices
        out_dir=str(tmp_path),
    )
    with _warnings.catch_warnings(record=True) as rec:
        _warnings.simplefilter("always")
        tr = EnsembleTrainer(cfg, splits)
    assert any("degrading" in str(w.message) for w in rec)
    assert "seq" not in dict(tr.mesh.shape)  # fully degraded: 2-axis mesh
    state = tr.init_state()
    arrays = tr._stacked_batch([s.epoch(0) for s in tr.samplers])
    state, ms = tr._jit_step(state, tr.dev, *arrays)
    assert np.isfinite(float(np.asarray(ms["loss"]).mean()))
