"""Pod-shape dry runs: the 64-seed ensemble axis at its REAL width on 64
virtual CPU devices (BASELINE.json:11 — the c5 64-seed geometry), without
a pod. Subprocesses because the device count must be fixed before backend
init (conftest pins the main process to 8).

Covers: 64×1 seed mesh and 8×8 seed×data mesh construction, the stacked
64-seed train state sharded over each, one train step + eval, and a
stacked-checkpoint save/restore at pod shape.
"""

import subprocess
import sys
import textwrap

import pytest

# ~1.5 min of 64-device subprocesses: out of the fast lane (slow) AND the
# default lane (nightly); full-suite runs keep it.
pytestmark = [pytest.mark.slow, pytest.mark.nightly]

_POD = textwrap.dedent("""
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 64)
    except AttributeError:  # jax 0.4.x — legacy spelling (see conftest.py)
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=64").strip()
    import numpy as np
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import PanelSplits, synthetic_panel
    from lfm_quant_tpu.train.ensemble import EnsembleTrainer
    from lfm_quant_tpu.train.loop import TrainState, restore_state_dict
    from lfm_quant_tpu.train.checkpoint import CheckpointManager

    assert jax.device_count() == 64
    panel = synthetic_panel(n_firms=96, n_months=120, n_features=4, seed=0,
                            min_history=60)
    splits = PanelSplits.by_date(panel, 197706, 197901)

    def run(n_seeds, n_data, tag, n_seq=1, kind="lstm"):
        kwargs = ({"hidden": 8} if kind == "lstm"
                  else {"hidden": 8, "state_dim": 8, "layers": 1})
        cfg = RunConfig(
            name=f"pod_{tag}",
            data=DataConfig(n_firms=96, n_months=120, n_features=4,
                            window=8, dates_per_batch=max(2, n_data),
                            firms_per_date=8),
            model=ModelConfig(kind=kind, kwargs=kwargs),
            optim=OptimConfig(lr=1e-3, epochs=1, warmup_steps=1,
                              loss="mse"),
            n_seeds=n_seeds, n_data_shards=n_data, n_seq_shards=n_seq,
        )
        tr = EnsembleTrainer(cfg, splits)
        assert tr.mesh is not None
        want = {"seed": min(n_seeds, 64 // (n_data * n_seq)),
                "data": n_data}
        if n_seq > 1:
            want["seq"] = n_seq
        assert dict(tr.mesh.shape) == want, tr.mesh.shape
        state = tr.init_state()
        # The stacked state's seed axis must actually shard over the mesh:
        # spec pins axis 0 to 'seed', and the leaf spans the full mesh
        # (sharded over seed, replicated over data).
        leaf = jax.tree.leaves(state.params)[0]
        assert leaf.sharding.spec[0] == "seed", (tag, leaf.sharding)
        assert len(leaf.sharding.device_set) == tr.mesh.devices.size, (
            tag, leaf.sharding)
        arrays = tr._stacked_batch([s.epoch(0) for s in tr.samplers])
        state, ms = tr._jit_step(state, tr.dev, *arrays)
        loss = float(np.asarray(ms["loss"]).mean())
        assert np.isfinite(loss), (tag, loss)
        val = tr.evaluate(state.params)
        assert np.isfinite(val["ic_mean"]), tag
        print(f"{tag} OK mesh={dict(tr.mesh.shape)} loss={loss:.4f}",
              flush=True)
        return tr, state

    # 64-wide seed mesh: one member per device — the c5 pod layout.
    tr64, state64 = run(64, 1, "seed64x1")
    # 8 x 8 two-axis mesh: 8-seed blocks x 8-way data parallelism.
    run(8, 8, "seed8x8")
    # Full parallelism matrix at pod width: 4 seeds x 4 data x 4 seq
    # (the LRU's distributed scan carries the window sharding).
    run(4, 4, "seed4x4x4", n_seq=4, kind="lru")

    # Stacked checkpoint at pod width: save the 64-seed state, restore,
    # re-place on the mesh, and step again. Written under the cwd (the
    # pytest tmp_path) so the run leaves nothing behind.
    import os
    mgr = CheckpointManager(os.path.abspath("ck"))
    mgr.save(1, state64._asdict(), wait=True)
    restored = restore_state_dict(mgr, tr64.init_state()._asdict())
    mgr.close()
    rstate = tr64._commit_state(TrainState(**restored))
    for a, b in zip(jax.tree.leaves(state64.params),
                    jax.tree.leaves(rstate.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    arrays = tr64._stacked_batch([s.epoch(1) for s in tr64.samplers])
    rstate, ms = tr64._jit_step(rstate, tr64.dev, *arrays)
    assert np.isfinite(float(np.asarray(ms["loss"]).mean()))
    print("ckpt64 OK", flush=True)
""")


def test_pod_shape_64_devices(tmp_path):
    """The 64-seed axis at 64: meshes, sharded stacked state, step, eval,
    checkpoint roundtrip — all at the c5 pod's real seed width."""
    script = tmp_path / "pod.py"
    script.write_text(_POD)
    proc = subprocess.run(
        [sys.executable, str(script)], cwd=str(tmp_path),
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": ":".join(sys.path)},
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for tag in ("seed64x1 OK", "seed8x8 OK", "seed4x4x4 OK", "ckpt64 OK"):
        assert tag in proc.stdout, proc.stdout
