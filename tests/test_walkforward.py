"""Walk-forward retraining (train/walkforward.py): fold schedule math,
no-lookahead stitching, ensemble stacking, and the CLI round-trip into
backtest.py --forecast-npz."""

import dataclasses

import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.train.walkforward import (
    month_add,
    run_walkforward,
    walkforward_folds,
)


def _cfg(tmp, n_seeds=1):
    return RunConfig(
        name="wf",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=2, warmup_steps=5, loss="mse"),
        seed=0,
        n_seeds=n_seeds,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)


def test_month_add():
    assert month_add(197001, 12) == 197101
    assert month_add(197011, 3) == 197102
    assert month_add(197001, -1) == 196912
    assert month_add(199912, 1) == 200001


def test_fold_schedule_tiles_without_overlap(panel):
    folds = walkforward_folds(panel, start=198001, step_months=12,
                              val_months=24)
    assert len(folds) >= 2
    prev_hi = None
    for train_end, val_end, (lo, hi) in folds:
        assert month_add(train_end, 24) == val_end
        assert lo < hi
        if prev_hi is not None:
            assert lo == prev_hi  # windows tile exactly
        prev_hi = hi
    # The schedule covers the gradeable period: the next fold would start
    # inside the final horizon months (no realized targets there).
    usable = panel.n_months - panel.horizon
    assert folds[-1][2][1] <= panel.n_months
    next_lo = folds[-1][2][1]
    assert next_lo >= usable or next_lo == panel.n_months


def test_fold_schedule_rejects_empty(panel):
    with pytest.raises(ValueError, match="no walk-forward folds"):
        walkforward_folds(panel, start=299001, step_months=12, val_months=24)


def test_walkforward_stitches_oos_only(panel, tmp_path):
    cfg = _cfg(tmp_path)
    fc, valid, summary = run_walkforward(
        cfg, panel, start=198001, step_months=12, val_months=24, n_folds=2,
        out_dir=str(tmp_path / "wf"))
    assert fc.shape == (panel.n_firms, panel.n_months)
    assert summary["n_folds"] == 2
    # Valid cells only inside the stitched out-of-sample month range.
    dates = panel.dates
    lo = int(np.searchsorted(dates, month_add(198001, 24)))
    hi = int(np.searchsorted(dates, month_add(198001, 24 + 2 * 12)))
    assert valid[:, lo:hi].any()
    assert not valid[:, :lo].any() and not valid[:, hi:].any()
    # Forecasts exist exactly where valid.
    assert (fc[~valid] == 0).all()
    # Artifacts on disk.
    data = np.load(tmp_path / "wf" / "walkforward.npz")
    np.testing.assert_array_equal(data["forecast"], fc)
    assert (tmp_path / "wf" / "summary.json").exists()
    assert (tmp_path / "wf" / "config.json").exists()


def test_walkforward_warm_start_carries_params(panel, tmp_path):
    """warm_start=True must initialize fold k>0 from fold k-1's best
    state: prove it by running with epochs so small the carried weights
    dominate — fold 1's warm model must equal neither a fresh-init fold
    nor drift far from fold 0's solution — and by the per-fold records."""
    cfg = _cfg(tmp_path)
    fc_w, valid_w, summary_w = run_walkforward(
        cfg, panel, start=198001, step_months=12, val_months=24, n_folds=2,
        out_dir=str(tmp_path / "warm"), warm_start=True)
    assert summary_w["warm_start"] is True
    assert [r["warm_started"] for r in summary_w["folds"]] == [False, True]

    # The stitched forecasts differ from the cold protocol's (same seeds,
    # same schedule — only the fold-1 init changed).
    fc_c, valid_c, summary_c = run_walkforward(
        cfg, panel, start=198001, step_months=12, val_months=24, n_folds=2,
        out_dir=str(tmp_path / "cold"))
    assert [r["warm_started"] for r in summary_c["folds"]] == [False, False]
    np.testing.assert_array_equal(valid_w, valid_c)
    fold1_months = valid_w.copy()
    hi = int(np.searchsorted(panel.dates, month_add(198001, 36)))
    fold1_months[:, :] = False
    fold1_months[:, hi:] = valid_w[:, hi:]  # fold 1's prediction window
    assert fold1_months.any()
    assert not np.array_equal(fc_w[fold1_months], fc_c[fold1_months])
    # Fold 0 predates any carry: identical under both protocols.
    fold0_months = valid_w & ~fold1_months
    np.testing.assert_array_equal(fc_w[fold0_months], fc_c[fold0_months])


def test_walkforward_fold_dirs_are_loadable_and_forecastable(panel, tmp_path):
    """Every fold run dir must stand alone for load_trainer (config.json
    pins the FOLD's split boundaries), and forecast.py must resolve the
    wf ROOT to the last completed fold — the production live-trading
    flow."""
    import forecast as forecast_cli
    from lfm_quant_tpu.train.loop import load_trainer

    cfg = _cfg(tmp_path)
    wf_dir = tmp_path / "wf"
    run_walkforward(cfg, panel, start=198001, step_months=12, val_months=24,
                    n_folds=2, out_dir=str(wf_dir))
    # Fold 1's reload reconstructs the fold's exact split boundaries.
    tr, splits = load_trainer(str(wf_dir / "fold_1"), panel=panel)
    assert splits.train_end_idx == int(
        np.searchsorted(panel.dates, month_add(198001, 12)))
    assert splits.val_end_idx == int(
        np.searchsorted(panel.dates, month_add(198001, 12 + 24)))
    # The wf root resolves to fold_1 (the most recently trained model).
    csv = tmp_path / "live.csv"
    rc = forecast_cli.main(["--run-dir", str(wf_dir), "--csv", str(csv)])
    assert rc == 0
    assert len(csv.read_text().splitlines()) > 1


def test_warm_start_fit_rejects_mismatched_params(panel, tmp_path):
    """A warm start across different model configs must fail loudly, not
    deep inside a jit trace."""
    from lfm_quant_tpu.data.panel import PanelSplits
    from lfm_quant_tpu.train.loop import Trainer

    splits = PanelSplits.by_date(panel, 198001, 198201)
    small = Trainer(_cfg(tmp_path / "a"), splits)
    big_cfg = _cfg(tmp_path / "b")
    big_cfg = dataclasses.replace(
        big_cfg, model=dataclasses.replace(big_cfg.model,
                                           kwargs={"hidden": (32,)}))
    big = Trainer(big_cfg, splits)
    with pytest.raises(ValueError, match="does not match"):
        big.fit(init_params=small.init_state().params)


def test_walkforward_ensemble_stacks_seeds(panel, tmp_path):
    cfg = _cfg(tmp_path, n_seeds=2)
    fc, valid, summary = run_walkforward(
        cfg, panel, start=198001, step_months=12, val_months=24, n_folds=2)
    assert fc.shape == (2, panel.n_firms, panel.n_months)
    # Members differ where predictions exist (ensemble diversity).
    assert float(fc.std(axis=0)[valid].max()) > 0.0


def test_cli_roundtrip_backtest_forecast_npz(tmp_path):
    import json

    import backtest as bt_cli

    from lfm_quant_tpu.train.loop import resolve_panel

    cfg = _cfg(tmp_path)
    # The panel MUST come from the config (resolve_panel) so backtest.py
    # regenerates the identical panel from the saved config.json —
    # exactly what train.py --walk-forward does.
    panel = resolve_panel(cfg.data)
    run_walkforward(cfg, panel, start=198001, step_months=12, val_months=24,
                    n_folds=2, out_dir=str(tmp_path / "wf"))
    # resolve_panel must rebuild the same synthetic panel from the config.
    cfg_json = json.load(open(tmp_path / "wf" / "config.json"))
    assert cfg_json["data"]["n_firms"] == 100
    rc = bt_cli.main(["--forecast-npz", str(tmp_path / "wf"),
                      "--quantile", "0.3",
                      "--json-out", str(tmp_path / "rep.json")])
    assert rc == 0
    rep = json.load(open(tmp_path / "rep.json"))
    assert rep["n_months"] > 0


def test_walkforward_resume_skips_completed_folds(panel, tmp_path):
    cfg = _cfg(tmp_path)
    out = str(tmp_path / "wfres")
    fc1, v1, s1 = run_walkforward(
        cfg, panel, start=198001, step_months=12, val_months=24, n_folds=1,
        out_dir=out)
    # Resume with one more fold: fold 0 must be taken from the snapshot.
    fc2, v2, s2 = run_walkforward(
        cfg, panel, start=198001, step_months=12, val_months=24, n_folds=2,
        out_dir=out, resume=True)
    assert s2["n_folds"] == 2
    assert s2["folds"][0] == s1["folds"][0]
    # Fold-0 forecasts carried over bit-identically; fold 1 added.
    np.testing.assert_array_equal(fc2[..., v1], fc1[..., v1])
    assert v2.sum() > v1.sum()


def test_walkforward_resume_rejects_schedule_mismatch(panel, tmp_path):
    cfg = _cfg(tmp_path)
    out = str(tmp_path / "wfmm")
    run_walkforward(cfg, panel, start=198001, step_months=12, val_months=24,
                    n_folds=1, out_dir=out)
    with pytest.raises(ValueError, match="schedule mismatch"):
        run_walkforward(cfg, panel, start=198101, step_months=12,
                        val_months=24, n_folds=2, out_dir=out, resume=True)


def test_walkforward_rejects_bad_step(panel):
    with pytest.raises(ValueError, match="step_months"):
        walkforward_folds(panel, start=198001, step_months=0, val_months=24)
    with pytest.raises(ValueError, match="step_months"):
        walkforward_folds(panel, start=198001, step_months=-12, val_months=24)


def test_walkforward_nll_stitches_variances_and_total_std(tmp_path):
    """Heteroscedastic walk-forward: variances land in walkforward.npz
    and backtest.py --mode mean_minus_total_std consumes the file."""
    import backtest as bt_cli

    from lfm_quant_tpu.train.loop import resolve_panel

    cfg = _cfg(tmp_path, n_seeds=2)
    cfg = dataclasses.replace(
        cfg, optim=dataclasses.replace(cfg.optim, loss="nll"))
    panel = resolve_panel(cfg.data)
    fc, valid, _ = run_walkforward(cfg, panel, start=198001, step_months=12,
                                   val_months=24, n_folds=2,
                                   out_dir=str(tmp_path / "wf"))
    data = np.load(tmp_path / "wf" / "walkforward.npz")
    assert "variance" in data
    assert data["variance"].shape == fc.shape == (2, 100, 200)
    assert (data["variance"][:, valid] > 0).all()
    rc = bt_cli.main(["--forecast-npz", str(tmp_path / "wf"),
                      "--quantile", "0.3", "--mode", "mean_minus_total_std"])
    assert rc == 0


@pytest.mark.nightly
def test_walkforward_with_sequence_parallelism(panel, tmp_path):
    """Walk-forward retraining composes with n_seq_shards: each fold's
    trainer rebuilds the (data × seq) mesh and the stitched forecasts
    stay strictly out of sample."""
    cfg = dataclasses.replace(
        _cfg(tmp_path),
        model=ModelConfig(kind="transformer",
                          kwargs={"dim": 16, "depth": 1, "heads": 2}),
        n_seq_shards=4,
    )
    # A degrade warning would mean the seq axis silently collapsed and
    # this test stopped exercising the composition — treat it as failure.
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.filterwarnings("error", message=".*degrading.*")
        fc, valid, summary = run_walkforward(
            cfg, panel=panel, start=198001, step_months=12, val_months=24,
            n_folds=2)
    assert summary["n_folds"] == 2
    assert valid.any()
    assert np.isfinite(fc[valid]).all()
    # Strictly out of sample: valid cells only inside the stitched OOS
    # range (boundary math as in test_walkforward_stitches_oos_only).
    dates = panel.dates
    lo = int(np.searchsorted(dates, month_add(198001, 24)))
    hi = int(np.searchsorted(dates, month_add(198001, 24 + 2 * 12)))
    assert valid[:, lo:hi].any()
    assert not valid[:, :lo].any() and not valid[:, hi:].any()
    # The seq-sharded transformer folds must still find signal OOS.
    ic = np.corrcoef(fc[valid], panel.targets[valid])[0, 1]
    assert ic > 0.0, ic
