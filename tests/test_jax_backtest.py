"""numpy ↔ JAX backtest parity: the fused device-resident scoring path
(backtest/jax_engine.py) against the numpy reference engine.

The fused engine is an OPTIMIZATION, never a numerics change: portfolios
are bit-identical (stable-sort tie-break + host-precomputed k-table),
per-month series match within float32 tolerance, and the report summary
math is literally shared (``engine.assemble_report``). These tests are
the ``backtest`` marker lane (``pytest -m backtest -q``) — the fast CI
guard that a core refactor can't silently diverge the two engines.
"""

import numpy as np
import pytest

from lfm_quant_tpu.backtest import (
    jax_backtest_enabled,
    resolve_backtest,
    run_backtest,
)
from lfm_quant_tpu.backtest.engine import aggregate_ensemble, mode_label
from lfm_quant_tpu.backtest.jax_engine import (
    aggregate_scores_device,
    run_backtest_jax,
    run_scoring_pipeline,
)
from lfm_quant_tpu.data.panel import Panel

pytestmark = [pytest.mark.backtest, pytest.mark.fast]

# float32-tolerance contract: returns/bench/profile are sums of a few
# hundred float32 terms; ICs additionally square rank magnitudes (~n²),
# so they get the loosest bound.
TOL = dict(ret=2e-6, ic=5e-4, profile=2e-6, turn=1e-6)


def random_panel(n=80, t=90, seed=0, ragged=True):
    """Adversarial panel: ragged live spans, vendor gaps, unobserved
    targets, delisting-censored forward returns."""
    rng = np.random.default_rng(seed)
    feats = rng.standard_normal((n, t, 2)).astype(np.float32)
    valid = np.ones((n, t), bool)
    if ragged:
        lo = rng.integers(0, t // 3, n)
        hi = rng.integers(2 * t // 3, t + 1, n)
        cols = np.arange(t)
        valid = (cols >= lo[:, None]) & (cols < hi[:, None])
        valid &= rng.random((n, t)) > 0.05
    tv = valid & (rng.random((n, t)) > 0.3)
    targets = np.where(tv, rng.standard_normal((n, t)), 0.0).astype(np.float32)
    returns = np.where(valid, 0.02 * rng.standard_normal((n, t)),
                       0.0).astype(np.float32)
    ret_valid = valid.copy()
    ret_valid &= rng.random((n, t)) > 0.1
    y, m = 2000 + np.arange(t) // 12, np.arange(t) % 12 + 1
    return Panel(feats, targets, tv, valid, returns,
                 (y * 100 + m).astype(np.int32),
                 np.arange(1, n + 1, dtype=np.int32), ["a", "b"],
                 horizon=1, ret_valid=ret_valid)


def assert_reports_match(a, b):
    """Field-by-field parity of the numpy (a) and fused (b) reports."""
    assert a.n_months == b.n_months
    assert a.n_skipped_months == b.n_skipped_months
    np.testing.assert_array_equal(a.dates, b.dates)
    np.testing.assert_allclose(a.monthly_returns, b.monthly_returns,
                               atol=TOL["ret"])
    np.testing.assert_allclose(a.monthly_bench, b.monthly_bench,
                               atol=TOL["ret"])
    np.testing.assert_allclose(a.monthly_ic, b.monthly_ic, atol=TOL["ic"])
    np.testing.assert_allclose(a.quantile_profile, b.quantile_profile,
                               atol=TOL["profile"])
    np.testing.assert_allclose(a.turnover, b.turnover, atol=TOL["turn"])
    np.testing.assert_allclose(a.mean_ic, b.mean_ic, atol=TOL["ic"])
    np.testing.assert_allclose(a.mean_ret_ic, b.mean_ret_ic, atol=TOL["ic"])
    np.testing.assert_allclose(a.cagr, b.cagr, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(a.sharpe_ann, b.sharpe_ann, rtol=1e-3,
                               atol=1e-4)


def test_parity_property_random_panels():
    """Property-style sweep over random ragged panels × engine configs,
    forecasts quantized to force TIES across the portfolio boundary —
    the case an unstable sort order would silently diverge on."""
    for seed in range(4):
        panel = random_panel(seed=seed)
        rng = np.random.default_rng(100 + seed)
        fc = rng.standard_normal(panel.targets.shape).astype(np.float32)
        fc = np.round(fc * 3) / 3  # heavy ties
        fc_valid = panel.valid & (rng.random(fc.shape) > 0.2)
        fc_valid[:, 7] = False  # an empty month
        for kw in (dict(min_universe=10),
                   dict(min_universe=10, long_short=True, costs_bps=25.0),
                   dict(min_universe=10, quantile=0.25, rf_monthly=0.002),
                   dict(min_universe=40)):  # short universes skip months
            a = run_backtest(fc, fc_valid, panel, **kw)
            b = run_backtest_jax(fc, fc_valid, panel, **kw)
            assert_reports_match(a, b)


def test_parity_all_invalid_target_months():
    """Months whose universe has no observable target define IC = 0 on
    both engines (not NaN, not dropped)."""
    panel = random_panel(seed=9)
    panel.target_valid[:, 20:30] = False
    rng = np.random.default_rng(1)
    fc = rng.standard_normal(panel.targets.shape).astype(np.float32)
    a = run_backtest(fc, panel.valid, panel, min_universe=10)
    b = run_backtest_jax(fc, panel.valid, panel, min_universe=10)
    assert_reports_match(a, b)
    # The blinded months really hit the IC=0 branch on both engines.
    blinded = (a.dates >= a.dates.min()) & np.isin(
        a.dates, panel.dates[20:30].astype(a.dates.dtype))
    assert blinded.any() and np.all(a.monthly_ic[blinded] == 0.0)
    assert np.all(b.monthly_ic[blinded] == 0.0)


def test_parity_thin_and_tiny_universes():
    """Universes below profile_buckets exercise the thin-month bucket
    mapping; min_universe=1 keeps them in the simulation."""
    panel = random_panel(n=8, t=60, seed=3, ragged=False)
    rng = np.random.default_rng(2)
    fc = rng.standard_normal(panel.targets.shape).astype(np.float32)
    a = run_backtest(fc, panel.valid, panel, min_universe=1, quantile=0.2)
    b = run_backtest_jax(fc, panel.valid, panel, min_universe=1,
                         quantile=0.2)
    assert_reports_match(a, b)


def test_jax_engine_raises_when_no_month_qualifies():
    panel = random_panel(seed=5)
    fc = np.zeros(panel.targets.shape, np.float32)
    with pytest.raises(ValueError, match="no month"):
        run_backtest_jax(fc, np.zeros(fc.shape, bool), panel)


def test_aggregate_scores_device_matches_numpy():
    """All modes from one stacked tensor ≡ the numpy per-mode aggregate,
    including the aleatoric total-std mode and per-seed validity."""
    rng = np.random.default_rng(4)
    fc = rng.standard_normal((5, 30, 24)).astype(np.float32)
    avar = rng.random((5, 30, 24)).astype(np.float32)
    pv = np.ones((5, 30, 24), bool)
    pv[2, 4, 4] = False
    modes = [("mean", 1.0), ("mean_minus_std", 0.5),
             ("mean_minus_std", 2.0), ("mean_minus_total_std", 1.0)]
    scores, valid, specs = aggregate_scores_device(fc, pv, modes,
                                                   aleatoric_var=avar)
    scores = np.asarray(scores)
    assert scores.shape == (4, 30, 24)
    for g, (mode, lam) in enumerate(specs):
        ref, ref_valid = aggregate_ensemble(
            fc, pv, mode, lam,
            aleatoric_var=avar if mode == "mean_minus_total_std" else None)
        np.testing.assert_array_equal(valid, ref_valid)
        np.testing.assert_allclose(scores[g], ref, atol=1e-5)
    with pytest.raises(ValueError, match="aleatoric_var"):
        aggregate_scores_device(fc, pv, ["mean_minus_total_std"])
    with pytest.raises(ValueError, match="unknown ensemble mode"):
        aggregate_scores_device(fc, pv, ["median"])


def test_scoring_pipeline_matches_per_mode_numpy_path():
    """The fused mode-sweep (one aggregate dispatch + one backtest
    dispatch for ALL modes) ≡ numpy aggregate_ensemble → run_backtest
    per mode."""
    panel = random_panel(seed=6)
    rng = np.random.default_rng(7)
    stack = rng.standard_normal((4,) + panel.targets.shape).astype(np.float32)
    modes = [("mean", 1.0), ("mean_minus_std", 0.5), ("mean_minus_std", 2.0)]
    reports = run_scoring_pipeline(stack, panel.valid, panel, modes=modes,
                                   min_universe=10)
    assert list(reports) == [mode_label(m, lam) for m, lam in modes]
    for (mode, lam), (label, rep) in zip(modes, reports.items()):
        fc, v = aggregate_ensemble(stack, panel.valid, mode, lam)
        assert_reports_match(run_backtest(fc, v, panel, min_universe=10),
                             rep)


def test_mode_sweep_shares_one_compiled_core():
    """Compile-once contract: after the first dispatch, same-shape calls
    with different λs, costs, quantiles or long/short flags pay ZERO new
    traces (those knobs are traced arguments, not trace constants)."""
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    panel = random_panel(seed=8)
    rng = np.random.default_rng(8)
    stack = rng.standard_normal((3,) + panel.targets.shape).astype(np.float32)
    modes = [("mean", 1.0), ("mean_minus_std", 1.0), ("mean_minus_std", 2.0)]
    run_scoring_pipeline(stack, panel.valid, panel, modes=modes,
                         min_universe=10)
    snap = REUSE_COUNTERS.snapshot()
    run_scoring_pipeline(stack, panel.valid, panel,
                         modes=[("mean", 1.0), ("mean_minus_std", 0.25),
                                ("mean_minus_std", 4.0)],
                         min_universe=10, quantile=0.2, costs_bps=10.0,
                         long_short=True)
    assert REUSE_COUNTERS.delta(snap)["jit_traces"] == 0


def test_engine_dispatch_knob(monkeypatch):
    """LFM_JAX_BACKTEST routes the serving path: fused by default, the
    numpy reference when 0."""
    assert jax_backtest_enabled()
    assert resolve_backtest() is run_backtest_jax
    monkeypatch.setenv("LFM_JAX_BACKTEST", "0")
    assert not jax_backtest_enabled()
    assert resolve_backtest() is run_backtest
    monkeypatch.delenv("LFM_JAX_BACKTEST")
    assert resolve_backtest() is run_backtest_jax


def test_walkforward_score_stitched_fused_matches_numpy(monkeypatch):
    """run_walkforward's end-of-sweep scoring hook: the fused path and
    the LFM_JAX_BACKTEST=0 numpy path produce the same digests."""
    from lfm_quant_tpu.train.walkforward import score_stitched

    panel = random_panel(seed=11)
    rng = np.random.default_rng(11)
    stack = rng.standard_normal((2,) + panel.targets.shape).astype(np.float32)
    modes = ["mean", ("mean_minus_std", 0.5)]
    fused = score_stitched(stack, panel.valid, panel, modes,
                           min_universe=10)
    monkeypatch.setenv("LFM_JAX_BACKTEST", "0")
    host = score_stitched(stack, panel.valid, panel, modes, min_universe=10)
    assert list(fused) == list(host) == ["mean", "mean_minus_std@0.5"]
    for label in fused:
        for k, v in fused[label].items():
            if isinstance(v, float):
                assert v == pytest.approx(host[label][k], rel=1e-3,
                                          abs=2e-4), (label, k)
