"""Integration tests for the campaign shell machinery itself.

The round's on-chip evidence is collected by `scripts/chip_campaign.sh`
running UNATTENDED (fired by the recovery watcher at whatever hour the
tunnel heals), so the shell logic — resume guards, step ordering, abort
behavior, one-shot attempt markers — is load-bearing in a way unit tests
on the Python helpers cannot cover. These tests run the REAL script in a
stub repo: every measurement step is replaced by a tiny stand-in that
writes the same ledger tags the real harness writes (backend=tpu,
resolved impls, geometry extras) and bumps a per-step invocation
counter, so a second pass proves exactly which steps the guards skip.

Marked slow: each of the ~25 step/probe subprocesses imports jax.
"""

import json
import os
import subprocess
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

BENCH_STUB = textwrap.dedent("""\
    import json, os, sys, time
    def persist_row(rec):
        row = dict(rec)
        row.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        path = os.environ.get("LFM_BENCH_ROWS") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_ROWS.jsonl")
        with open(path, "a") as fh:
            fh.write(json.dumps(row) + "\\n")
    def _count(name):
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "calls.log"), "a") as fh:
            fh.write(name + "\\n")
    if __name__ == "__main__":
        _count("bench")
        persist_row({"metric": "train_throughput_c2_lstm", "value": 1.0,
                     "unit": "fm/s", "backend": "tpu", "n_reps": 3})
        persist_row({"metric": "train_throughput_c5_ensemble", "value": 1.0,
                     "unit": "fm/s", "backend": "tpu", "n_seeds": 16,
                     "n_reps": 3})
""")

LADDER_STUB = textwrap.dedent("""\
    import os, sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import persist_row, _count
    name = sys.argv[1]
    gi = os.environ.get("LFM_BENCH_GATHER_IMPL") or "pallas"
    extras = {"gather_impl": gi}
    if os.environ.get("LFM_BENCH_DATES"):
        extras["dates_per_batch"] = int(os.environ["LFM_BENCH_DATES"])
    if name == "c5":
        extras["n_seeds"] = int(os.environ.get("LFM_BENCH_SEEDS", "16"))
        sb = int(os.environ.get("LFM_BENCH_SEED_BLOCK", "0"))
        if sb:
            extras["seed_block"] = sb
    _count("ladder-" + name + "-" + gi + "-" + str(extras.get("n_seeds", ""))
           + "-" + str(extras.get("seed_block", ""))
           + "-" + str(extras.get("dates_per_batch", "")))
    if os.environ.get("STUB_FAIL_FOR") == name:
        sys.exit(124)  # timeout-killed mid-step: NO rows banked
    persist_row({"metric": f"train_throughput_{name}", "value": 2.0,
                 "unit": "fm/s", "backend": "tpu", "n_reps": 3, **extras})
    persist_row({"metric": f"eval_throughput_{name}", "value": 3.0,
                 "unit": "fm/s", "backend": "tpu", "n_reps": 3,
                 "lane_pad": gi == "pallas", **extras})
""")

SWEEP_STUB = textwrap.dedent("""\
    import os, sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import persist_row, _count
    _count("sweep")
    for bb in ("default", 256, 512, 1024, 2048):
        persist_row({"metric": "sweep_c2_block_b", "block_b": bb,
                     "value": 4.0, "unit": "fm/s", "backend": "tpu",
                     "scan_impl": "pallas_fused"})
    for bb in ("default", 256, 512, 1024, 2048, 4096):
        persist_row({"metric": "sweep_c2_eval_block_b", "block_b": bb,
                     "value": 4.0, "unit": "fm/s", "backend": "tpu",
                     "scan_impl": "pallas_fused"})
""")

DIAG_STUB = textwrap.dedent("""\
    import os, sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import persist_row, _count
    impl = sys.argv[1]
    _count("diag-" + impl)
    persist_row({"metric": "diag_c1", "impl": impl, "value": 5.0,
                 "unit": "fm/s", "backend": "tpu"})
""")

HBM_STUB = textwrap.dedent("""\
    import os, sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import _count
    _count("hbm" + ("-blocked" if "--seed-block" in sys.argv else ""))
""")


def _make_stub_repo(tmp_path: Path) -> Path:
    repo = tmp_path / "repo"
    (repo / "scripts").mkdir(parents=True)
    for name in ("chip_campaign.sh", "ledger_has.py", "regen_baseline.py"):
        (repo / "scripts" / name).write_text(
            (REPO / "scripts" / name).read_text())
    (repo / "bench.py").write_text(BENCH_STUB)
    (repo / "scripts" / "bench_ladder.py").write_text(LADDER_STUB)
    (repo / "scripts" / "sweep_rnn_blocks.py").write_text(SWEEP_STUB)
    (repo / "scripts" / "diag_c1.py").write_text(DIAG_STUB)
    (repo / "scripts" / "hbm_probe.py").write_text(HBM_STUB)
    (repo / "BASELINE.md").write_text("# stub baseline\n")
    (repo / "calls.log").write_text("")
    # Force EVERY python the campaign spawns onto the CPU backend before
    # any jax use: the axon PJRT plugin overrides JAX_PLATFORMS, so a
    # bare env var would let the script's probe/mark subprocesses claim
    # the REAL tunneled chip — hanging the test while it is wedged and
    # contending with the real campaign when it is not.
    shim = tmp_path / "shim"
    shim.mkdir()
    # LAZY hook, not an eager `import jax`: a campaign pass spawns ~100
    # interpreters but only the probe/mark ones touch jax — an eager
    # import would add minutes of pure overhead to every guard/regen
    # process and flake the subprocess timeout on slow machines.
    (shim / "sitecustomize.py").write_text(textwrap.dedent("""\
        import builtins
        import sys

        _orig_import = builtins.__import__

        def _cpu_pin_import(name, *args, **kwargs):
            mod = _orig_import(name, *args, **kwargs)
            if name == "jax" or name.startswith("jax."):
                j = sys.modules.get("jax")
                if j is not None and not getattr(j, "_lfm_cpu_set", False):
                    try:
                        j.config.update("jax_platforms", "cpu")
                        j._lfm_cpu_set = True
                    except Exception:
                        pass
            return mod

        builtins.__import__ = _cpu_pin_import
    """))
    return repo


def _run(repo: Path, **env_over) -> subprocess.CompletedProcess:
    # Scrub EVERY harness knob from the ambient shell (a developer's
    # exported LFM_BENCH_GATHER_IMPL/SEEDS/DATES would re-tag stub rows
    # and silently break the guard assertions), then apply the test's
    # own overrides.
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("LFM_BENCH_") and k != "STUB_FAIL_FOR"}
    env.update(env_over)
    shim = str(repo.parent / "shim")
    env["PYTHONPATH"] = shim + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        ["bash", str(repo / "scripts" / "chip_campaign.sh"),
         str(repo / "campaign.log")],
        capture_output=True, text=True, timeout=600, env=env)


def _calls(repo: Path):
    return (repo / "calls.log").read_text().split()


def _rows(repo: Path):
    out = []
    for line in (repo / "BENCH_ROWS.jsonl").read_text().splitlines():
        out.append(json.loads(line))
    return out


@pytest.mark.slow
@pytest.mark.nightly
def test_campaign_full_pass_then_full_skip(tmp_path):
    """Pass 1 on an empty ledger runs every step and banks every row;
    pass 2 must skip every measuring step (zero new stub invocations
    except probes) — the resume property the heal-cycle design relies
    on."""
    repo = _make_stub_repo(tmp_path)
    p1 = _run(repo)
    assert p1.returncode == 0, p1.stdout + p1.stderr
    calls1 = _calls(repo)
    rows1 = _rows(repo)
    metrics = {r["metric"] for r in rows1}
    for want in ("train_throughput_c2_lstm", "train_throughput_c5_ensemble",
                 "train_throughput_c2", "eval_throughput_c2",
                 "train_throughput_c3", "train_throughput_c4",
                 "train_throughput_lru", "train_throughput_c5",
                 "train_throughput_lru64", "train_throughput_lc",
                 "sweep_c2_block_b", "diag_c1", "train_throughput_c1",
                 "eval_throughput_c1"):
        assert want in metrics, f"pass 1 never banked {want}"
    # Both gather legs of the c2 A/B ran.
    assert "ladder-c2-pallas--" in " ".join(calls1)
    assert "ladder-c2-xla--" in " ".join(calls1)
    # The 64-seed full and blocked variants both ran.
    c5_rows = [r for r in rows1 if r["metric"] == "eval_throughput_c5"]
    assert {r.get("n_seeds") for r in c5_rows} == {16, 64}
    assert any(r.get("seed_block") == 16 for r in c5_rows)
    # c3 ran at BOTH geometries (D=1 and full-D).
    c3_rows = [r for r in rows1 if r["metric"] == "eval_throughput_c3"]
    assert {r.get("dates_per_batch") for r in c3_rows} == {1, None}

    p2 = _run(repo)
    assert p2.returncode == 0, p2.stdout + p2.stderr
    calls2 = _calls(repo)[len(calls1):]
    assert calls2 == [], f"resume pass re-ran steps: {calls2}"
    # Zero new rows: every measuring step (and every one-shot marker,
    # whose guarded block is superseded by its banked measurement) skips.
    assert len(_rows(repo)) == len(rows1)


@pytest.mark.slow
@pytest.mark.nightly
def test_campaign_attempt_markers_suppress_wedge_triggers(tmp_path):
    """A one-shot attempt marker (tpu-backed, written by a prior pass
    whose risky step then WEDGED, leaving no measurement) must keep the
    wedge trigger from re-running on every heal-cycle — the mechanism
    bounding the heal→wedge loop. Pre-seed a ledger holding every
    measurement EXCEPT the two marker-guarded one-shots, plus their
    attempt markers; the pass must then run nothing at all."""
    repo = _make_stub_repo(tmp_path)
    p0 = _run(repo)  # bank everything once
    assert p0.returncode == 0, p0.stdout + p0.stderr
    rows = _rows(repo)
    keep = [r for r in rows
            if not (r["metric"] == "diag_c1" and r.get("impl") == "pallas")
            and not (r["metric"] == "eval_throughput_c3"
                     and r.get("dates_per_batch") is None)
            and not (r["metric"] == "train_throughput_c3"
                     and r.get("dates_per_batch") is None)]
    assert len(keep) < len(rows)  # the one-shots are genuinely pruned
    keep.append({"metric": "diag_c1_attempt", "impl": "pallas",
                 "backend": "tpu", "unit": "attempt"})
    keep.append({"metric": "c3_fullD_attempt", "backend": "tpu",
                 "unit": "attempt"})
    (repo / "BENCH_ROWS.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in keep))
    n_calls = len(_calls(repo))

    p1 = _run(repo)
    assert p1.returncode == 0, p1.stdout + p1.stderr
    calls = _calls(repo)[n_calls:]
    assert calls == [], f"marker-guarded one-shots re-ran: {calls}"

    # Control: WITHOUT the markers the pruned one-shots do re-run.
    (repo / "BENCH_ROWS.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in keep
                if not r["metric"].endswith("_attempt")))
    p2 = _run(repo)
    assert p2.returncode == 0, p2.stdout + p2.stderr
    calls = _calls(repo)[n_calls:]
    assert any(c == "diag-pallas" for c in calls), calls
    assert any(c == "ladder-c3-pallas---" for c in calls), calls


@pytest.mark.slow
@pytest.mark.nightly
def test_campaign_aborts_on_nonrisky_failure_and_resumes(tmp_path):
    """A non-risky step failing (tunnel re-wedge signature) aborts the
    pass, keeping already-banked rows; the next pass skips those rows
    and picks up at the failed step."""
    repo = _make_stub_repo(tmp_path)
    p1 = _run(repo, STUB_FAIL_FOR="c4")
    assert p1.returncode != 0
    metrics = {r["metric"] for r in _rows(repo)}
    assert "eval_throughput_c2" in metrics      # banked before the abort
    assert "train_throughput_c4" not in metrics  # the failed step
    assert "train_throughput_lru" not in metrics  # never reached

    n_calls_p1 = len(_calls(repo))
    p2 = _run(repo)  # healed: no forced failure
    assert p2.returncode == 0, p2.stdout + p2.stderr
    calls2 = _calls(repo)[n_calls_p1:]
    # Banked steps must NOT re-run: the c2 legs and c3@D=1 (its call tag
    # ends in the dates_per_batch=1 marker). c3-fullD — a DIFFERENT,
    # never-banked geometry — legitimately runs at its dead-last slot.
    assert not any(c.startswith("ladder-c2") or c == "ladder-c3-pallas---1"
                   for c in calls2), calls2
    assert any(c.startswith("ladder-c4") for c in calls2)
    metrics2 = {r["metric"] for r in _rows(repo)}
    assert "train_throughput_c4" in metrics2
    assert "train_throughput_lc" in metrics2


@pytest.mark.fast
def test_bench_fake_wedge_dry_run_is_parseable_and_fast():
    """Round-4 verdict (Weak #5 / ask 9): the driver capture must stay
    parseable even if its timebox shrinks below the probe window. The
    contract: bench.py puts a schema-shaped JSON record on stdout FIRST
    (provisional), then a structured terminal record, with zero chip
    contact and the whole run bounded well under the smallest observed
    driver timebox. The fake-wedge hook exercises exactly the real
    wedged-tunnel code path minus the subprocess probes."""
    import sys
    import time

    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        env={**os.environ, "LFM_BENCH_FAKE_WEDGE": "1",
             "LFM_BENCH_NO_PERSIST": "1"},
        capture_output=True, text=True, timeout=30)
    took = time.monotonic() - t0
    assert took < 10, f"dry run took {took:.1f}s (must be <10s)"
    assert proc.returncode == 1
    recs = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    assert len(recs) >= 2
    # First record: the provisional, emitted before anything can hang.
    assert recs[0]["metric"] == "bench_status"
    assert recs[0]["status"] == "no_capture"
    # Last record (what the driver parses): the structured wedge status.
    assert recs[-1]["metric"] == "bench_status"
    assert recs[-1]["status"] == "tunnel_wedged"
    for rec in recs:  # every record is schema-shaped for the driver
        assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)


@pytest.mark.fast
def test_ledger_has_presence_guard(tmp_path):
    """`--has KEY` matches on field presence with ANY value — the resume
    guard for spread-carrying rows, rep-count-agnostic by design (an
    operator's LFM_BENCH_OUTER_REPS=1/5 choice must still satisfy it)."""
    import sys

    ledger = tmp_path / "rows.jsonl"
    rows = [
        {"metric": "m", "value": 1.0, "unit": "u", "backend": "tpu"},
        {"metric": "m", "value": 2.0, "unit": "u", "backend": "tpu",
         "n_reps": 1},
    ]
    ledger.write_text("".join(json.dumps(r) + "\n" for r in rows))
    env = {**os.environ, "LFM_BENCH_ROWS": str(ledger)}

    def has(*args):
        return subprocess.run(
            [sys.executable, str(REPO / "scripts" / "ledger_has.py"),
             *args], env=env).returncode == 0

    assert has("metric=m")
    assert has("metric=m", "--has", "n_reps")          # any rep count
    assert not has("metric=m", "--has", "spread_pct")  # truly absent
    assert not has("metric=absent", "--has", "n_reps")
