"""Unified run telemetry (utils/telemetry.py): spans, counters, ledger.

The telemetry layer's contract is that it OBSERVES the training path
without perturbing it: spans nest and order correctly with per-span
counter deltas, the emitted Chrome-trace JSON is valid (Perfetto-
loadable), the program ledger records compile costs, trace_report rolls
a run dir up from files alone — and the disabled path (LFM_TELEMETRY=0,
or simply no active run) emits zero spans while the training loop's
measured sync/trace counts stay IDENTICAL to the instrumented run
(telemetry must never add a device round-trip; the reuse/pipeline
lanes' zero-trace / one-sync-per-epoch contracts hold in both knob
states). All tests carry the ``telemetry`` marker — the fast CI lane
(``pytest -m telemetry``)."""

import json
import math
import os
import subprocess
import sys
import warnings

import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.data.panel import PanelSplits
from lfm_quant_tpu.utils import telemetry
from lfm_quant_tpu.utils.logging import MetricsLogger
from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS, StepTimer

pytestmark = pytest.mark.telemetry


def _cfg(tmp, epochs=2):
    return RunConfig(
        name="tele",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=1e-3, epochs=epochs, warmup_steps=5,
                          loss="mse", early_stop_patience=99),
        seed=0,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)


@pytest.fixture(scope="module")
def splits(panel):
    return PanelSplits.by_date(panel, 198001, 198201)


@pytest.fixture(autouse=True)
def _no_leaked_run(monkeypatch):
    """Telemetry activation is process-global; tests must not leak it.
    The knob is pinned ON here so this lane tests what it claims even
    under an outer LFM_TELEMETRY=0 (tests of the disabled path set the
    env themselves, which overrides this default)."""
    monkeypatch.setenv("LFM_TELEMETRY", "1")
    assert telemetry._ACTIVE is None
    yield
    if telemetry._ACTIVE is not None:  # a failed test left a run open
        telemetry._ACTIVE.finish()


def _spans(run_dir):
    with open(os.path.join(run_dir, "spans.jsonl")) as fh:
        return [json.loads(line) for line in fh]


# ---- span tracer ---------------------------------------------------------


def test_span_nesting_ordering_and_deltas(tmp_path):
    """Nested spans carry parent/depth; the jsonl stream is in CLOSING
    order; counter bumps inside a child are attributed to the child AND
    every enclosing span, and to no sibling."""
    with telemetry.run_scope(str(tmp_path)):
        with telemetry.span("outer", cat="test") as sp:
            with telemetry.span("child"):
                telemetry.COUNTERS.bump("tele_test_counter", 3)
            with telemetry.span("sibling"):
                pass
            sp.set(result="done")
    recs = {r["name"]: r for r in _spans(str(tmp_path))}
    assert list(r["name"] for r in _spans(str(tmp_path))) == [
        "child", "sibling", "outer", "run"]  # closing order, run last
    assert recs["child"]["parent"] == "outer"
    assert recs["child"]["depth"] == 1
    assert recs["outer"]["depth"] == 0
    assert recs["child"]["d"]["tele_test_counter"] == 3
    assert recs["outer"]["d"]["tele_test_counter"] == 3  # hierarchical
    assert "d" not in recs["sibling"] or \
        "tele_test_counter" not in recs["sibling"].get("d", {})
    assert recs["outer"]["args"]["result"] == "done"
    # Durations nest: the child fits inside the parent.
    assert recs["child"]["dur_s"] <= recs["outer"]["dur_s"]


def test_chrome_trace_is_valid_and_async_spans_pair(tmp_path):
    """trace.json is strict JSON in Chrome trace-event format: every
    event has name/ph/ts/pid/tid, "X" events carry dur, and async
    ("b"/"e") pairs share name+id — what Perfetto needs to render the
    pipeline's overlapping epochs."""
    with telemetry.run_scope(str(tmp_path)):
        with telemetry.span("work", cat="test", bad=float("nan")):
            h0 = telemetry.begin_async("epoch", epoch=0)
            h1 = telemetry.begin_async("epoch", epoch=1)  # overlapping
            h0.end()
            h1.end(stop=True, val_ic=float("inf"))  # non-finite args
        telemetry.instant("marker", note="hi")
    raw = open(os.path.join(str(tmp_path), "trace.json")).read()
    trace = json.loads(raw)  # strict JSON — json.loads rejects NaN? no,
    assert "NaN" not in raw and "Infinity" not in raw
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    for e in events:
        assert {"name", "ph", "pid"} <= set(e), e
        if e["ph"] in ("X", "b", "e", "i"):
            assert "ts" in e and "tid" in e, e
        if e["ph"] == "X":
            assert "dur" in e and e["dur"] >= 0, e
    begins = [e for e in events if e["ph"] == "b"]
    ends = [e for e in events if e["ph"] == "e"]
    assert len(begins) == len(ends) == 2
    assert ({(e["name"], e["id"]) for e in begins}
            == {(e["name"], e["id"]) for e in ends})
    assert len({e["id"] for e in begins}) == 2  # distinct overlap ids
    assert any(e["ph"] == "i" for e in events)


def test_disabled_knob_emits_nothing(tmp_path, monkeypatch):
    """LFM_TELEMETRY=0: run_scope is a no-op — no manifest, no spans, no
    trace — and span() returns the shared null span."""
    monkeypatch.setenv("LFM_TELEMETRY", "0")
    with telemetry.run_scope(str(tmp_path / "off")) as run:
        assert run is None
        s = telemetry.span("x")
        assert s is telemetry._NULL
        with s:
            s.set(a=1)
    assert not (tmp_path / "off").exists()


def test_no_active_run_emits_nothing(tmp_path):
    """Default-on telemetry WITHOUT an attached run dir (the library
    path every test/bench run takes): spans are null, zero files."""
    assert telemetry.active_run() is None
    assert telemetry.span("x") is telemetry._NULL
    assert telemetry.begin_async("x") is telemetry._NULL


def test_telemetry_adds_no_syncs_or_traces_to_training(splits, tmp_path,
                                                       monkeypatch):
    """The acceptance contract, measured: a fit with telemetry ACTIVE
    (spans + ledger + analysis) pays exactly the same counted host
    syncs per epoch and the same warm-path jit traces as a fit with no
    run attached and one with LFM_TELEMETRY=0 — the layer observes the
    loop, never adds a device round-trip. (Analysis re-lowering runs
    under suspend_trace_counting, so even COLD trace counts match.)"""
    from lfm_quant_tpu.train.loop import Trainer

    # Warm the shared program cache first so every measured pass binds
    # the same executables — the comparison is then pure telemetry
    # overhead, not cold-compile ordering.
    Trainer(_cfg(tmp_path, epochs=2), splits, run_dir=None).fit()
    results = {}
    for label, env, attach in (("active", "1", True),
                               ("inactive", "1", False),
                               ("off", "0", False)):
        monkeypatch.setenv("LFM_TELEMETRY", env)
        snap = REUSE_COUNTERS.snapshot()
        scope = (telemetry.run_scope(str(tmp_path / label)) if attach
                 else telemetry.run_scope(None))
        with scope:
            t = Trainer(_cfg(tmp_path, epochs=2), splits, run_dir=None)
            s = t.fit()
        d = REUSE_COUNTERS.delta(snap)
        results[label] = (s["epochs_run"], d["host_syncs"],
                          d["jit_traces"])
    # Programs are warm after the first pass (shared program cache), so
    # all three must agree: one sync per epoch, zero extra traces.
    assert results["active"] == results["inactive"] == results["off"]
    assert results["active"][1] == results["active"][0]  # syncs == epochs


def test_run_manifest_contents(tmp_path):
    cfg = _cfg(tmp_path)
    with telemetry.run_scope(str(tmp_path), cfg, extra={"entry": "test"}):
        pass
    m = json.load(open(os.path.join(str(tmp_path), "manifest.json")))
    assert m["entry"] == "test"
    assert m["config"]["name"] == "tele"
    assert m["jax"]["jax_version"]
    assert m["jax"]["device_count"] >= 1
    assert isinstance(m["env_lfm"], dict)
    assert m["knobs"]["telemetry"] is True
    assert "async_pipeline" in m["knobs"]


def test_program_ledger_and_trace_report_cli(splits, tmp_path):
    """End to end: a fit under an active run writes spans.jsonl +
    ledger.jsonl + trace.json; the trace_report CLI rolls them up from
    the run dir alone with epochs/hour and idle fraction computed by
    the same formulas bench.py epoch_pipeline uses."""
    from lfm_quant_tpu.train import reuse
    from lfm_quant_tpu.train.loop import Trainer

    reuse.clear_program_cache()  # cold programs → ledger entries
    run_dir = str(tmp_path / "run")
    with telemetry.run_scope(run_dir, _cfg(tmp_path)):
        t = Trainer(_cfg(tmp_path, epochs=3), splits, run_dir=None)
        summary = t.fit()
    led = [json.loads(line)
           for line in open(os.path.join(run_dir, "ledger.jsonl"))]
    assert {e["program"] for e in led} >= {"multi_step", "forward"}
    assert all(e["compile_s"] > 0 for e in led)
    out = subprocess.run(
        [sys.executable, os.path.join(os.getcwd(), "scripts",
                                      "trace_report.py"), run_dir,
         "--json"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-2000:]
    rep = json.loads(out.stdout)
    assert rep["n_fits"] == 1
    assert rep["n_epochs"] == summary["epochs_run"] == 3
    assert rep["epochs_per_hour"] > 0
    assert rep["idle_frac"] is not None
    assert rep["host_syncs"] == 3 and rep["syncs_per_epoch"] == 1.0
    assert rep["compile_s_total"] > 0
    assert any(p["program"] == "multi_step" for p in rep["programs"])
    assert rep["has_trace_json"]
    # The rollup's epochs/hour is the bench formula on the fit span.
    fit = [r for r in _spans(run_dir) if r["name"] == "fit"][0]
    assert rep["epochs_per_hour"] == pytest.approx(
        3600.0 * 3 / fit["dur_s"], rel=0.01)


# ---- satellite regressions ----------------------------------------------


def test_metrics_logger_nonfinite_floats_stay_valid_json(tmp_path):
    """json.dumps(float('nan')) emits a bare NaN token — invalid JSON
    that would corrupt the metrics.jsonl line crash-resume reads. The
    logger must serialize non-finite values as null (and keep the
    in-memory record's real floats)."""
    with MetricsLogger(str(tmp_path)) as log:
        rec = log.log(1, val_ic=float("nan"), loss=float("inf"),
                      ok=1.5, neg=float("-inf"),
                      per_seed=[0.1, float("nan")],  # nested containers
                      nested={"a": float("inf"), "b": 2.0})
    assert math.isnan(rec["val_ic"])  # caller's record untouched
    line = open(os.path.join(str(tmp_path), "metrics.jsonl")).read()
    assert "NaN" not in line and "Infinity" not in line
    parsed = json.loads(line)  # strict-parses
    assert parsed["val_ic"] is None
    assert parsed["loss"] is None
    assert parsed["neg"] is None
    assert parsed["ok"] == 1.5
    assert parsed["per_seed"] == [0.1, None]
    assert parsed["nested"] == {"a": None, "b": 2.0}


def test_steptimer_stop_without_start_warns_not_raises():
    t = StepTimer()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        dt = t.stop(firm_months=10.0)
    assert dt == 0.0
    assert t.steps == 0 and t.seconds == 0.0 and t.firm_months == 0.0
    assert any("start()" in str(x.message) for x in w)
    # A proper start/stop afterwards still works.
    t.start()
    assert t.stop(firm_months=1.0) >= 0.0
    assert t.steps == 1


def test_reuse_counters_view_and_float_fields():
    """ReuseCounters is a view over telemetry.COUNTERS: bumps through
    either surface agree, and the float fields (host_sync_s,
    device_idle_s) round-trip as floats through snapshot/delta."""
    snap = REUSE_COUNTERS.snapshot()
    REUSE_COUNTERS.jit_traces += 1
    telemetry.COUNTERS.bump("host_sync_s", 0.25)
    d = REUSE_COUNTERS.delta(snap)
    assert d["jit_traces"] == 1
    assert isinstance(d["host_sync_s"], float)
    assert d["host_sync_s"] == pytest.approx(0.25)
    assert telemetry.COUNTERS.get("jit_traces") == snap["jit_traces"] + 1


# ---- serving thread-safety (serve satellite audit) -----------------------
#
# The scoring service bumps counters and emits spans from request
# threads, the micro-batcher thread and a refresh fit concurrently; the
# registry takes a lock per mutation and the span streams serialize
# under the run lock. These hammers pin "no lost increments" and "no
# interleaved-corrupt spans.jsonl lines".


def test_counter_registry_hammer_loses_no_increments():
    """8 threads × 5000 bumps on shared int and float counters — the
    totals must be EXACT (the pre-lock dict read-modify-write loses
    increments under exactly this load), and peak() must record the
    true maximum."""
    import threading

    reg = telemetry.CounterRegistry()
    n_threads, n_bumps = 8, 5000

    def worker(tid):
        for i in range(n_bumps):
            reg.bump("ints")
            reg.bump("floats", 0.5)
            reg.peak("peak", tid * n_bumps + i)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.get("ints") == n_threads * n_bumps
    assert reg.get("floats") == pytest.approx(n_threads * n_bumps * 0.5)
    assert reg.get("peak") == n_threads * n_bumps - 1
    # snapshot/delta under concurrent writers never corrupts shape.
    snap = reg.snapshot()
    assert set(snap) == {"ints", "floats", "peak"}


def test_concurrent_span_emission_no_torn_lines(tmp_path):
    """6 threads × 40 spans (sync + async, with counter bumps inside)
    emitted into one live run: every spans.jsonl line must strict-parse
    and every span must be present — a torn/interleaved write corrupts
    the line this test would fail on."""
    import threading

    n_threads, n_spans = 6, 40
    with telemetry.run_scope(str(tmp_path)):
        def worker(tid):
            for i in range(n_spans):
                if i % 3 == 0:
                    h = telemetry.begin_async("hammer_async", tid=tid, i=i)
                    telemetry.COUNTERS.bump("hammer_counter")
                    h.end(done=True)
                else:
                    with telemetry.span("hammer_sync", tid=tid, i=i):
                        telemetry.COUNTERS.bump("hammer_counter")

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    recs = _spans(str(tmp_path))  # json.loads on every line — strict
    names = [r["name"] for r in recs]
    assert names.count("hammer_sync") + names.count("hammer_async") \
        == n_threads * n_spans
    assert telemetry.COUNTERS.get("hammer_counter") >= n_threads * n_spans
    # The trace stream survived the same load as valid JSON.
    trace = json.load(open(os.path.join(str(tmp_path), "trace.json")))
    assert isinstance(trace["traceEvents"], list)
