"""Fold-vectorized walk-forward (train/foldstack.py): parity + freezing.

The fold-stack's contract is that stacking reorders WORK, never results:
with ``LFM_FOLDSTACK`` on, every fold's epoch history, best epoch,
early-stop epoch and restored best params must match its sequential run
— across the LFM_FOLDSTACK × LFM_ASYNC knob matrix — a stopped fold's
params must stay bit-frozen while other folds train, and the reuse
lane's zero-warm-trace / zero-H2D contract must hold with fold-stacking
ON. Tolerance policy: the UNSHARDED stack (``LFM_FOLDSTACK_SHARDS=0``)
is pinned bit-identical; the fold-MESH stack is pinned to last-ulp
reduction-order tolerance (the same caveat every sharded path in this
repo states) with epochs/best-epoch decisions still exact.

All tests carry the ``foldstack`` marker — the fast CI guard
(``pytest -m foldstack``) against a refactor that quietly breaks the
stacked/sequential numerical identity.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.config import DataConfig, ModelConfig, OptimConfig, RunConfig
from lfm_quant_tpu.data import synthetic_panel
from lfm_quant_tpu.train.walkforward import run_walkforward, walkforward_folds

pytestmark = pytest.mark.foldstack

#: History fields that must match across execution modes (timing fields
#: — ts, firm_months_per_sec — legitimately differ). val_mse is compared
#: with last-ulp tolerance even on the "exact" lane: its month-sum
#: reassociates under the fold vmap (a logged diagnostic — no control
#: decision reads it; val_ic, the early-stop input, stays bit-exact).
_DET_FIELDS = ("epoch", "train_loss", "grad_norm", "val_ic", "val_mse",
               "val_ic_std")
_ULP_FIELDS = ("val_mse",)
_WF_KW = dict(start=198001, step_months=12, val_months=24, n_folds=3,
              train_months=72)


def _cfg(tmp, epochs=3, patience=99, lr=1e-3, n_seeds=1):
    return RunConfig(
        name="fstk",
        data=DataConfig(n_firms=100, n_months=200, n_features=5, window=12,
                        dates_per_batch=4, firms_per_date=32),
        model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
        optim=OptimConfig(lr=lr, epochs=epochs, warmup_steps=5, loss="mse",
                          early_stop_patience=patience),
        seed=0,
        n_seeds=n_seeds,
        out_dir=str(tmp),
    )


@pytest.fixture(scope="module")
def panel():
    return synthetic_panel(n_firms=100, n_months=200, n_features=5, seed=5)


def _wf(tmp, panel, monkeypatch, *, stacked, async_on=True, name,
        **cfg_kw):
    monkeypatch.setenv("LFM_ASYNC", "1" if async_on else "0")
    monkeypatch.setenv("LFM_ASYNC_CKPT", "1" if async_on else "0")
    out = str(tmp / name)
    fc, valid, summary = run_walkforward(
        _cfg(tmp, **cfg_kw), panel, out_dir=out, foldstack=stacked,
        **_WF_KW)
    return fc, valid, summary, out


def _histories(out_dir, n_folds):
    return [
        [json.loads(l) for l in
         open(os.path.join(out_dir, f"fold_{k}", "metrics.jsonl"))]
        for k in range(n_folds)
    ]


def _det(history):
    return [tuple((f, r[f]) for f in _DET_FIELDS
                  if f in r and f not in _ULP_FIELDS)
            for r in history]


def _best_params(out_dir, k, panel):
    from lfm_quant_tpu.train.forecast import is_ensemble_run_dir

    run_dir = os.path.join(out_dir, f"fold_{k}")
    if is_ensemble_run_dir(run_dir):
        from lfm_quant_tpu.train.ensemble import load_ensemble

        trainer, _ = load_ensemble(run_dir, panel=panel)
    else:
        from lfm_quant_tpu.train.loop import load_trainer

        trainer, _ = load_trainer(run_dir, panel=panel)
    return trainer.state.params


def _assert_parity(seq, stk, panel, exact, n_folds=3, check_params=False):
    """Shared contract: records, histories, stitched forecasts and (for
    the key lanes) best params restored from each fold's ckpt/best line.
    ``exact`` pins bit-identity; otherwise float history fields and
    forecasts get last-ulp tolerance while every DECISION (epochs run,
    best epoch, early-stop epoch) stays exact."""
    fc_s, v_s, sum_s, d_s = seq
    fc_k, v_k, sum_k, d_k = stk
    assert sum_k.get("foldstack", {}).get("enabled") is True
    assert "foldstack" not in sum_s
    np.testing.assert_array_equal(v_s, v_k)
    for rs, rk in zip(sum_s["folds"], sum_k["folds"]):
        assert rs["epochs_run"] == rk["epochs_run"], rs["fold"]
        assert rs["best_epoch"] == rk["best_epoch"], rs["fold"]
        np.testing.assert_allclose(rk["best_val_ic"], rs["best_val_ic"],
                                   rtol=0 if exact else 1e-5)
    hs, hk = _histories(d_s, n_folds), _histories(d_k, n_folds)
    for k, (a, b) in enumerate(zip(hs, hk)):
        assert [r["epoch"] for r in a] == [r["epoch"] for r in b], k
        if exact:
            assert _det(a) == _det(b), f"fold {k} history diverged"
            for ra, rb in zip(a, b):
                for f in _ULP_FIELDS:
                    if f in ra:
                        np.testing.assert_allclose(rb[f], ra[f], rtol=1e-6,
                                                   err_msg=f"fold {k} {f}")
        else:
            for ra, rb in zip(a, b):
                for f in _DET_FIELDS:
                    if f in ra:
                        np.testing.assert_allclose(rb[f], ra[f], rtol=2e-5,
                                                   err_msg=f"fold {k} {f}")
    if exact:
        np.testing.assert_array_equal(fc_s, fc_k)
    else:
        np.testing.assert_allclose(fc_k, fc_s, atol=5e-6, rtol=1e-4)
    if not check_params:
        return
    for k in range(n_folds):
        ps = jax.tree.leaves(_best_params(d_s, k, panel))
        pk = jax.tree.leaves(_best_params(d_k, k, panel))
        for a, b in zip(ps, pk):
            if exact:
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            else:
                np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                           atol=5e-6, rtol=1e-4)


def test_unsharded_stack_bit_identical(panel, tmp_path, monkeypatch):
    """LFM_FOLDSTACK_SHARDS=0 (pure vmap over the fold axis): histories,
    stitched forecasts and restored best params are BIT-identical to
    sequential execution — stacking is a pure re-batching."""
    monkeypatch.setenv("LFM_FOLDSTACK_SHARDS", "0")
    seq = _wf(tmp_path, panel, monkeypatch, stacked=False, name="seq")
    stk = _wf(tmp_path, panel, monkeypatch, stacked=True, name="stk")
    assert stk[2]["foldstack"]["fold_mesh"] is None
    _assert_parity(seq, stk, panel, exact=True, check_params=True)


def test_fold_mesh_parity_matrix(panel, tmp_path, monkeypatch):
    """The LFM_FOLDSTACK × LFM_ASYNC knob matrix under the (default)
    fold mesh: per-fold histories/forecasts within last-ulp
    reduction-order tolerance, every early-stop/best decision exact.
    The fold axis must actually shard (this suite pins an 8-device CPU
    platform)."""
    for async_on in (False, True):
        tag = "a" if async_on else "s"
        seq = _wf(tmp_path, panel, monkeypatch, stacked=False,
                  async_on=async_on, name=f"mseq_{tag}")
        stk = _wf(tmp_path, panel, monkeypatch, stacked=True,
                  async_on=async_on, name=f"mstk_{tag}")
        if jax.device_count() > 1:
            assert dict(stk[2]["foldstack"]["fold_mesh"])["fold"] > 1
        _assert_parity(seq, stk, panel, exact=False)


def test_divergent_early_stop_parity(panel, tmp_path, monkeypatch):
    """Folds stopping at DIFFERENT epochs (patience=1): per-fold
    early-stop epochs and best epochs must match sequential execution
    exactly — the masking-based device-side control reproduces the
    FitHarness decisions fold by fold, with live folds continuing after
    their neighbors froze."""
    kw = dict(epochs=10, patience=1)
    seq = _wf(tmp_path, panel, monkeypatch, stacked=False, name="es_seq",
              **kw)
    stk = _wf(tmp_path, panel, monkeypatch, stacked=True, name="es_stk",
              **kw)
    epochs_seq = [r["epochs_run"] for r in seq[2]["folds"]]
    assert epochs_seq == [r["epochs_run"] for r in stk[2]["folds"]]
    assert max(epochs_seq) < 10, "geometry must actually early-stop"
    assert len(set(epochs_seq)) > 1, \
        "fold stop epochs must diverge for this test to bite"
    _assert_parity(seq, stk, panel, exact=False, check_params=True)


def test_stopped_fold_is_bit_frozen(panel, tmp_path, monkeypatch):
    """Drive the stacked epoch program directly with a forged live mask:
    the dead folds' ENTIRE TrainState (params, optimizer moments, step
    counter, dropout stream) must come back bit-identical while the live
    fold's state moves — the masking contract that makes divergent early
    stopping safe."""
    monkeypatch.setenv("LFM_ASYNC", "1")
    from lfm_quant_tpu.train.foldstack import StackedWalkforward

    cfg = _cfg(tmp_path)
    folds = walkforward_folds(panel, _WF_KW["start"],
                              _WF_KW["step_months"], _WF_KW["val_months"],
                              _WF_KW["n_folds"])
    sw = StackedWalkforward(cfg, panel, folds,
                            train_months=_WF_KW["train_months"])
    state, best, ctrl = sw.init_carry()
    live = jnp.asarray([True, False, False])
    ctrl = ctrl._replace(live=jax.device_put(
        live, ctrl.live.sharding) if hasattr(ctrl.live, "sharding")
        else live)
    before = jax.device_get(state._asdict())  # host copy pre-donation
    args, _ = sw.build_epoch(0)
    (state2, _, ctrl2), _ = sw.dispatch_epoch((state, best, ctrl), args)
    after = jax.device_get(state2._asdict())
    for key in before:
        for a, b in zip(jax.tree.leaves(before[key]),
                        jax.tree.leaves(after[key])):
            a, b = np.asarray(a), np.asarray(b)
            np.testing.assert_array_equal(a[1:], b[1:],
                                          err_msg=f"dead folds moved: {key}")
    moved = any(
        not np.array_equal(np.asarray(a)[0], np.asarray(b)[0])
        for a, b in zip(jax.tree.leaves(before["params"]),
                        jax.tree.leaves(after["params"])))
    assert moved, "the live fold's params did not train"
    # Dead folds never re-enter the live set; the live fold keeps going.
    live_out = np.asarray(jax.device_get(ctrl2.live))
    assert not live_out[1] and not live_out[2]


@pytest.mark.reuse
def test_foldstack_warm_run_zero_traces_zero_transfers(panel, tmp_path,
                                                       monkeypatch):
    """The reuse lane's contract with fold-stacking ON: a SECOND stacked
    sweep binds the first one's fold-stacked executables and resident
    panel — zero new jit traces, zero panel H2D — and the stacked fit
    pays exactly ONE counted blocking host sync per stacked epoch (the
    PR 3 pipeline contract through the fold-stack driver)."""
    from lfm_quant_tpu.data.windows import clear_panel_cache
    from lfm_quant_tpu.train import reuse
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    reuse.clear_program_cache()
    clear_panel_cache()
    try:
        _wf(tmp_path, panel, monkeypatch, stacked=True, name="warmup")
        snap = REUSE_COUNTERS.snapshot()
        _, _, summary, _ = _wf(tmp_path, panel, monkeypatch, stacked=True,
                               name="warm")
        d = REUSE_COUNTERS.delta(snap)
        assert d["jit_traces"] == 0, d
        assert d["panel_transfers"] == 0, d
        stack = summary["foldstack"]
        epochs = max(r["epochs_run"] for r in summary["folds"])
        assert stack["reuse"]["host_syncs"] == epochs, stack["reuse"]
    finally:
        reuse.clear_program_cache()
        clear_panel_cache()


def test_no_out_dir_predicts_last_epoch_state_like_sequential(
        panel, tmp_path, monkeypatch):
    """out_dir=None parity: sequential folds have no ckpt/best line to
    restore, so they predict from the last RECORDED epoch's state — the
    stacked path must mirror that (its device-tracked best params serve
    only runs that checkpoint), or LFM_FOLDSTACK would silently flip
    forecasts for non-checkpointing callers. patience=1 makes best and
    last epoch genuinely differ."""
    monkeypatch.setenv("LFM_FOLDSTACK_SHARDS", "0")  # bit-exact lane
    monkeypatch.setenv("LFM_ASYNC", "1")
    cfg = _cfg(tmp_path, epochs=10, patience=1)
    out = {}
    for stacked in (False, True):
        fc, _, summary = run_walkforward(panel=panel, cfg=cfg,
                                         out_dir=None, foldstack=stacked,
                                         **_WF_KW)
        out[stacked] = (fc, summary)
    assert any(r["best_epoch"] != r["epochs_run"] - 1
               for r in out[True][1]["folds"]
               if "best_epoch" in r), "best must differ from last epoch"
    np.testing.assert_array_equal(out[False][0], out[True][0])


def test_env_knob_enables_foldstack(panel, tmp_path, monkeypatch):
    """LFM_FOLDSTACK=1 routes run_walkforward through the stacked path
    without the explicit argument (the --wf-foldstack CLI equivalent)."""
    monkeypatch.setenv("LFM_FOLDSTACK", "1")
    _, _, summary, _ = _wf(tmp_path, panel, monkeypatch, stacked=None,
                           name="env")
    assert summary["foldstack"]["enabled"] is True
    assert all(r["foldstack"] for r in summary["folds"])


def test_foldstack_without_rolling_window_degrades(panel, tmp_path,
                                                   monkeypatch):
    """No train_months (expanding window → fold-varying shapes): the
    stacked mode must WARN and fall back to the sequential sweep with
    identical results — a data-dependent precondition failure never
    kills a sweep the sequential path handles."""
    kw = {**_WF_KW}
    kw.pop("train_months")
    cfg = _cfg(tmp_path, epochs=2)
    with pytest.warns(UserWarning, match="fold-stacking unavailable"):
        fc_k, v_k, sum_k = run_walkforward(
            cfg, panel, out_dir=str(tmp_path / "fb_stk"), foldstack=True,
            **kw)
    assert "foldstack" not in sum_k
    fc_s, v_s, _ = run_walkforward(
        cfg, panel, out_dir=str(tmp_path / "fb_seq"), foldstack=False,
        **kw)
    np.testing.assert_array_equal(fc_s, fc_k)
    np.testing.assert_array_equal(v_s, v_k)


def test_foldstack_rejects_resume_and_warm_start(panel, tmp_path):
    """resume/warm_start are inherently serial (per-epoch checkpoint
    lines; predecessor-fold carry) — the stacked mode refuses them
    loudly instead of silently changing their semantics."""
    cfg = _cfg(tmp_path, epochs=2)
    for kw in (dict(resume=True), dict(warm_start=True)):
        with pytest.raises(ValueError, match="foldstack is incompatible"):
            run_walkforward(cfg, panel, out_dir=str(tmp_path / "rej"),
                            foldstack=True, **_WF_KW, **kw)


def test_ensemble_coprime_seeds_fold_only_mesh(panel, tmp_path,
                                               monkeypatch):
    """n_seeds coprime to the device count (3 on an 8-device host): the
    inner ensemble mesh degrades to None, so the stack runs over a
    fold-ONLY mesh — the batch specs must not name absent seed/data
    axes (this crashed before the spec guard), and parity still holds."""
    kw = dict(n_seeds=3, epochs=2)
    seq = _wf(tmp_path, panel, monkeypatch, stacked=False, name="cp_seq",
              **kw)
    stk = _wf(tmp_path, panel, monkeypatch, stacked=True, name="cp_stk",
              **kw)
    mesh = stk[2]["foldstack"]["fold_mesh"]
    if jax.device_count() > 1:
        assert dict(mesh or []).get("seed") is None
    _assert_parity(seq, stk, panel, exact=False)


def test_ensemble_foldstack_parity(panel, tmp_path, monkeypatch):
    """The seed-vmapped ensemble under the fold stack: the fold axis
    composes OUTSIDE the seed (× data) mesh axes, and per-fold ensemble
    histories (train_loss, mean/std val IC), best epochs and stitched
    stacked forecasts match the sequential ensemble sweep."""
    kw = dict(n_seeds=2, epochs=2)
    seq = _wf(tmp_path, panel, monkeypatch, stacked=False, name="ens_seq",
              **kw)
    stk = _wf(tmp_path, panel, monkeypatch, stacked=True, name="ens_stk",
              **kw)
    mesh = stk[2]["foldstack"]["fold_mesh"]
    if jax.device_count() > 1:
        assert dict(mesh)["seed"] == 2
    # check_params also proves the stacked ensemble fold dirs RESTORE
    # (the [S]-shaped step leaf must round-trip through load_ensemble).
    _assert_parity(seq, stk, panel, exact=False, check_params=True)
