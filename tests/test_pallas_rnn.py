"""Pallas fused recurrence (ops/pallas_rnn.py) vs the lax.scan reference.

Runs in Pallas interpret mode on the CPU test platform (rnn_scan auto-
selects it off-TPU), so CI needs no TPU; the same kernels compile via
Mosaic on a real chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lfm_quant_tpu.models import build_model
from lfm_quant_tpu.ops.pallas_rnn import rnn_scan, rnn_scan_reference

CELLS = ["lstm", "gru"]
GATES = {"lstm": 4, "gru": 3}


def _inputs(cell, B=13, T=6, H=12, seed=0, mask_p=0.75):
    rng = np.random.default_rng(seed)
    G = GATES[cell] * H
    xw = jnp.asarray(rng.standard_normal((B, T, G)).astype(np.float32))
    wh = jnp.asarray(0.3 * rng.standard_normal((H, G)).astype(np.float32))
    m = jnp.asarray(rng.random((B, T)) < mask_p)
    return xw, wh, m


@pytest.mark.parametrize("cell", CELLS)
def test_forward_matches_reference(cell):
    xw, wh, m = _inputs(cell)
    out = rnn_scan(cell, xw, wh, m)
    ref = rnn_scan_reference(cell, xw, wh, m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


@pytest.mark.parametrize("cell", CELLS)
def test_multi_block_grid_matches_reference(cell):
    """B > block_b: exercises the cross-block machinery every real batch
    uses — per-block scratch re-zeroing and dW_h accumulation across the
    batch-block grid dimension into the shared output block."""
    xw, wh, m = _inputs(cell, B=20, seed=5)
    kw = dict(block_b=8)
    out = rnn_scan(cell, xw, wh, m, **kw)
    ref = rnn_scan_reference(cell, xw, wh, m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
    g = jax.grad(lambda a, b: (rnn_scan(cell, a, b, m, **kw) ** 2).sum(),
                 (0, 1))(xw, wh)
    gr = jax.grad(lambda a, b: (rnn_scan_reference(cell, a, b, m) ** 2).sum(),
                  (0, 1))(xw, wh)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(gr[0]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(gr[1]), atol=1e-5)


@pytest.mark.parametrize("cell", CELLS)
def test_gradients_match_reference(cell):
    xw, wh, m = _inputs(cell, seed=1)
    up = jnp.asarray(
        np.random.default_rng(2).standard_normal(
            xw.shape[:2] + (wh.shape[0],)).astype(np.float32))

    def loss(fn, a, b):
        return (fn(cell, a, b, m) * up).sum()

    g_pal = jax.grad(lambda a, b: loss(rnn_scan, a, b), (0, 1))(xw, wh)
    g_ref = jax.grad(lambda a, b: loss(rnn_scan_reference, a, b), (0, 1))(
        xw, wh)
    np.testing.assert_allclose(np.asarray(g_pal[0]), np.asarray(g_ref[0]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(g_pal[1]), np.asarray(g_ref[1]),
                               atol=1e-5)


def test_all_invalid_rows_stay_zero():
    # A firm with no valid months must carry the zero init state through.
    xw, wh, m = _inputs("lstm")
    m = m.at[0].set(False)
    out = rnn_scan("lstm", xw, wh, m)
    assert float(jnp.abs(out[0]).max()) == 0.0


@pytest.mark.parametrize("cell", CELLS)
def test_model_pallas_equals_xla(cell):
    """RNNModel(scan_impl=pallas) must be interchangeable with the default
    XLA scan — identical parameter tree AND identical outputs."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((9, 8, 5)).astype(np.float32))
    m = jnp.asarray(rng.random((9, 8)) < 0.8)
    mk = dict(hidden=12, layers=2)
    xla = build_model(cell, **mk)
    pal = build_model(cell, scan_impl="pallas", **mk)
    params = xla.init(jax.random.key(0), x, m)["params"]
    p2 = pal.init(jax.random.key(0), x, m)["params"]
    assert jax.tree.structure(params) == jax.tree.structure(p2)
    out_x = xla.apply({"params": params}, x, m)
    out_p = pal.apply({"params": params}, x, m)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               atol=1e-5)


def test_model_pallas_bf16():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((8, 8, 5)).astype(np.float32))
    m = jnp.asarray(rng.random((8, 8)) < 0.9)
    pal = build_model("lstm", hidden=16, scan_impl="pallas",
                      dtype=jnp.bfloat16)
    xla = build_model("lstm", hidden=16, dtype=jnp.bfloat16)
    params = pal.init(jax.random.key(0), x, m)["params"]
    out_p = pal.apply({"params": params}, x, m)
    out_x = xla.apply({"params": params}, x, m)
    assert out_p.dtype == out_x.dtype
    # bf16 compute: allow a few ULP between kernel and scan orderings.
    np.testing.assert_allclose(np.asarray(out_p, np.float32),
                               np.asarray(out_x, np.float32),
                               atol=0.05, rtol=0.05)


# ---------------------------------------------------------------------------
# vmap over the kernels (the ensemble's seed axis). The custom_vmap rules
# dispatch stacked operands onto the kernels' native seed grid axis; JAX's
# generic pallas batching rule would produce a TPU-illegal block layout
# (squeezed mid-array block for the recurrent weights), so these tests pin
# the dispatch path's numerics for every in_batched combination the
# trainers produce.
# ---------------------------------------------------------------------------


def _stacked_inputs(cell, S=3, B=9, T=7, H=8, seed=5, mask_p=0.8):
    rng = np.random.default_rng(seed)
    G = GATES[cell] * H
    xw = jnp.asarray(rng.standard_normal((S, B, T, G)).astype(np.float32))
    wh = jnp.asarray(0.3 * rng.standard_normal((S, H, G)).astype(np.float32))
    m = jnp.asarray(rng.random((S, B, T)) < mask_p)
    return xw, wh, m


@pytest.mark.parametrize("cell", CELLS)
def test_vmap_forward_matches_reference(cell):
    """vmap over (xw, wh, m) — the ensemble train step's batching."""
    xw, wh, m = _stacked_inputs(cell)
    out = jax.vmap(lambda a, b, c: rnn_scan(cell, a, b, c))(xw, wh, m)
    ref = jnp.stack([rnn_scan_reference(cell, xw[s], wh[s], m[s])
                     for s in range(xw.shape[0])])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("cell", CELLS)
def test_vmap_shared_data_per_seed_weights(cell):
    """vmap over wh only (shared batch) — the ensemble eval forward's
    batching; exercises the rule's broadcast of unbatched operands."""
    xw, wh, m = _stacked_inputs(cell)
    out = jax.vmap(lambda b: rnn_scan(cell, xw[0], b, m[0]))(wh)
    ref = jnp.stack([rnn_scan_reference(cell, xw[0], wh[s], m[0])
                     for s in range(wh.shape[0])])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("cell", CELLS)
def test_vmap_grad_matches_reference(cell):
    """jit(vmap(grad(...))) — the exact transform stack of the vmapped
    ensemble train step — against per-seed reference gradients."""
    xw, wh, m = _stacked_inputs(cell)
    mf = m.astype(jnp.float32)

    def loss(xw, wh, m):
        return (rnn_scan(cell, xw, wh, m) ** 2).sum()

    def loss_ref(xw, wh, m):
        return (rnn_scan_reference(cell, xw, wh, m) ** 2).sum()

    g = jax.jit(jax.vmap(jax.grad(loss, argnums=(0, 1))))(xw, wh, mf)
    gr = jax.jit(jax.vmap(jax.grad(loss_ref, argnums=(0, 1))))(xw, wh, mf)
    for got, want in zip(g, gr):
        scale = float(jnp.abs(want).max()) + 1e-9
        np.testing.assert_allclose(np.asarray(got) / scale,
                                   np.asarray(want) / scale, atol=1e-5)


def test_vmap_multi_block_batch():
    """Seed axis × a batch big enough for multiple grid blocks."""
    cell = "lstm"
    xw, wh, m = _stacked_inputs(cell, S=2, B=20, T=5, H=8)
    out = jax.vmap(lambda a, b, c: rnn_scan(cell, a, b, c, block_b=8))(
        xw, wh, m)
    ref = jnp.stack([rnn_scan_reference(cell, xw[s], wh[s], m[s])
                     for s in range(2)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# ---------------------------------------------------------------------------
# Fused-projection variant (rnn_scan_fused / scan_impl="pallas_fused"):
# identical parameter tree, the gate input projection computed in-kernel.
# ---------------------------------------------------------------------------

from lfm_quant_tpu.ops.pallas_rnn import rnn_scan_fused  # noqa: E402


@pytest.mark.parametrize("cell", CELLS)
def test_fused_matches_reference(cell):
    rng = np.random.default_rng(11)
    B, T, H = 13, 6, 8
    G = GATES[cell] * H
    hin = jnp.asarray(rng.standard_normal((B, T, H)).astype(np.float32))
    wx = jnp.asarray(0.3 * rng.standard_normal((H, G)).astype(np.float32))
    b = jnp.asarray(0.1 * rng.standard_normal((G,)).astype(np.float32))
    wh = jnp.asarray(0.3 * rng.standard_normal((H, G)).astype(np.float32))
    m = jnp.asarray(rng.random((B, T)) < 0.75)
    out = rnn_scan_fused(cell, hin, wx, b, wh, m)
    ref = rnn_scan_reference(cell, hin @ wx + b, wh, m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("cell", CELLS)
def test_fused_gradients_match_reference(cell):
    rng = np.random.default_rng(12)
    B, T, H = 9, 5, 8
    G = GATES[cell] * H
    hin = jnp.asarray(rng.standard_normal((B, T, H)).astype(np.float32))
    wx = jnp.asarray(0.3 * rng.standard_normal((H, G)).astype(np.float32))
    b = jnp.asarray(0.1 * rng.standard_normal((G,)).astype(np.float32))
    wh = jnp.asarray(0.3 * rng.standard_normal((H, G)).astype(np.float32))
    m = jnp.asarray((rng.random((B, T)) < 0.75).astype(np.float32))

    def loss(hin, wx, b, wh, m):
        return (rnn_scan_fused(cell, hin, wx, b, wh, m) ** 2).sum()

    def loss_ref(hin, wx, b, wh, m):
        return (rnn_scan_reference(cell, hin @ wx + b, wh, m) ** 2).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))(hin, wx, b, wh, m)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))(hin, wx, b, wh, m)
    for got, want in zip(g, gr):
        scale = float(jnp.abs(want).max()) + 1e-9
        np.testing.assert_allclose(np.asarray(got) / scale,
                                   np.asarray(want) / scale, atol=1e-5)


@pytest.mark.parametrize("cell", CELLS)
def test_fused_vmap_grad_matches_reference(cell):
    """jit(vmap(grad(...))) over all operands — the ensemble composition."""
    rng = np.random.default_rng(13)
    S, B, T, H = 3, 7, 5, 8
    G = GATES[cell] * H
    hin = jnp.asarray(rng.standard_normal((S, B, T, H)).astype(np.float32))
    wx = jnp.asarray(0.3 * rng.standard_normal((S, H, G)).astype(np.float32))
    b = jnp.asarray(0.1 * rng.standard_normal((S, G)).astype(np.float32))
    wh = jnp.asarray(0.3 * rng.standard_normal((S, H, G)).astype(np.float32))
    m = jnp.asarray((rng.random((S, B, T)) < 0.75).astype(np.float32))

    def loss(hin, wx, b, wh, m):
        return (rnn_scan_fused(cell, hin, wx, b, wh, m) ** 2).sum()

    def loss_ref(hin, wx, b, wh, m):
        return (rnn_scan_reference(cell, hin @ wx + b, wh, m) ** 2).sum()

    g = jax.jit(jax.vmap(jax.grad(loss, argnums=(1, 2, 3))))(
        hin, wx, b, wh, m)
    gr = jax.jit(jax.vmap(jax.grad(loss_ref, argnums=(1, 2, 3))))(
        hin, wx, b, wh, m)
    for got, want in zip(g, gr):
        scale = float(jnp.abs(want).max()) + 1e-9
        np.testing.assert_allclose(np.asarray(got) / scale,
                                   np.asarray(want) / scale, atol=1e-5)


@pytest.mark.parametrize("cell", CELLS)
def test_model_fused_equals_xla(cell):
    """RNNModel(scan_impl='pallas_fused') must share the XLA path's exact
    parameter tree and outputs — checkpoint interchange both ways."""
    rng = np.random.default_rng(14)
    x = jnp.asarray(rng.standard_normal((9, 8, 5)).astype(np.float32))
    m = jnp.asarray(rng.random((9, 8)) < 0.8)
    mk = dict(hidden=12, layers=2)
    xla = build_model(cell, **mk)
    fused = build_model(cell, scan_impl="pallas_fused", **mk)
    params = xla.init(jax.random.key(0), x, m)["params"]
    p2 = fused.init(jax.random.key(0), x, m)["params"]
    assert jax.tree.structure(params) == jax.tree.structure(p2)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
        assert a.shape == b.shape and a.dtype == b.dtype
    out_x = xla.apply({"params": params}, x, m)
    out_f = fused.apply({"params": params}, x, m)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_x),
                               atol=1e-5)


def test_fused_multi_block_and_padding():
    """Fused variant across multiple batch grid blocks + a non-multiple
    batch (padded rows must not pollute dWx/dWh/db)."""
    cell = "lstm"
    rng = np.random.default_rng(15)
    B, T, H = 21, 5, 8  # block_b=8 → 3 blocks, 3 padded rows
    G = GATES[cell] * H
    hin = jnp.asarray(rng.standard_normal((B, T, H)).astype(np.float32))
    wx = jnp.asarray(0.3 * rng.standard_normal((H, G)).astype(np.float32))
    b = jnp.asarray(0.1 * rng.standard_normal((G,)).astype(np.float32))
    wh = jnp.asarray(0.3 * rng.standard_normal((H, G)).astype(np.float32))
    m = jnp.asarray((rng.random((B, T)) < 0.75).astype(np.float32))

    def loss(hin, wx, b, wh, m):
        return (rnn_scan_fused(cell, hin, wx, b, wh, m, block_b=8) ** 2).sum()

    def loss_ref(hin, wx, b, wh, m):
        return (rnn_scan_reference(cell, hin @ wx + b, wh, m) ** 2).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))(hin, wx, b, wh, m)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))(hin, wx, b, wh, m)
    for got, want in zip(g, gr):
        scale = float(jnp.abs(want).max()) + 1e-9
        np.testing.assert_allclose(np.asarray(got) / scale,
                                   np.asarray(want) / scale, atol=1e-5)
