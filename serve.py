#!/usr/bin/env python
"""Always-on scoring service entry point (L7 — lfm_quant_tpu/serve/).

Stands up a persistent :class:`ScoringService`: a model zoo resident in
HBM (one entry per universe, LRU-evicted, atomically swapped on
refresh), a request micro-batcher coalescing concurrent queries into
the compiled scoring core through padded request-shape buckets (zero
jit traces and zero panel H2D in steady state), and per-request latency
telemetry (``scripts/trace_report.py`` rolls the run dir up).

Demo/smoke mode (default): builds ``--universes`` toy universes with
distinct cross-section sizes and lookbacks, trains each briefly
(``--train-epochs``; 0 = fresh init, shape-only), warms every bucket,
drives ``--requests`` mixed queries from ``--threads`` client threads
(one ``--refresh`` swap mid-stream if asked) and prints the stats
rollup. With ``--http PORT`` it additionally exposes the service on a
stdlib JSON endpoint until interrupted:

    GET /score?universe=u0&month=199001   → scores for the month
                                            (propagates X-Request-Id /
                                            traceparent; echoes the
                                            trace id + phase breakdown)
    GET /stats                            → the stats() rollup (+ts)
    GET /healthz                          → 200 ok | 503 + reason
                                            (+ SLO-burn/drift detail)
    GET /metrics                          → Prometheus text exposition
                                            (live histograms, rates,
                                            gauges, counters — §19;
                                            lfm_build_info identity)

Request-scoped observability (DESIGN.md §21): every request carries a
trace id (inbound ``X-Request-Id``/``traceparent`` header, else
minted) and a queue/batch/retry/dispatch phase breakdown; the
knob-gated ``LFM_ACCESS_LOG`` emits one structured JSON line per
request; and when a degradation trigger fires (breaker open, SLO
burn, drift veto, quarantine, shed spike) the service writes one
rate-limited incident bundle (flight-recorder ring + scrape +
snapshot + slowest traces) under ``LFM_INCIDENT_DIR`` or the run dir.

``/stats`` and ``/healthz`` share ONE ``service.snapshot()`` call per
request (single locked read per owning structure, same scrape ``ts`` in
both) instead of re-deriving state per field — the pre-metrics handlers
could observe a torn view across a concurrent refresh/breaker
transition.

Failure semantics (the degradation layer, DESIGN.md §18 — mapping in
lfm_quant_tpu/serve/errors.py, pinned by tests/test_chaos.py):

    shed (queue at LFM_SERVE_QUEUE_MAX)     → 429 + Retry-After
    circuit open (consecutive failures)     → 503 + Retry-After
    deadline expired / client timed out     → 504
    batcher thread dead (service unready)   → 503
    unknown universe / month                → 404
    /healthz degraded                       → 503 + {"ok": false, reason}

Durable serving state (DESIGN.md §20): with ``--persist DIR`` (or
``LFM_ZOO_PERSIST=DIR``) every published generation is journaled to a
crash-consistent store — params snapshot + checksum, panel, drift
reference sketch, a bit-exact parity probe, and serialized lowered
executables where jax supports AOT export. ``--restore`` then stands
the service back up from that store: every universe re-registered and
VERIFIED (checksum + probe bit-equality; corrupt snapshots are
quarantined loudly and degrade to fresh retrain), the warm ladder
rebuilt with zero compiles when the executable artifacts load.

Fleet mode (DESIGN.md §22): ``--fleet N`` (or ``LFM_FLEET=N``)
publishes the universes to the durable store, spawns N subprocess
members that each bootstrap from it (read-only attach, verified
restore, zero compiles), and serves through the health-aware failover
router — the same front door, one member's death is a reroute. The
fleet ``/healthz``/``/metrics`` aggregate member snapshots; ``/fleet``
shows topology + the publish fence; ``/sync`` (on a member) pulls
newer generations from the store.

Usage:
    python serve.py --universes 3 --requests 200 --run-dir runs/serve
    python serve.py --train-epochs 2 --http 8777
    python serve.py --persist runs/zoo_store --train-epochs 1
    python serve.py --persist runs/zoo_store --restore --requests 100
    python serve.py --persist runs/zoo_store --fleet 2 --requests 200
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def extract_request_id(headers) -> str | None:
    """Inbound trace identity (DESIGN.md §21): ``X-Request-Id`` wins
    (opaque, echoed verbatim after sanitizing), else the W3C
    ``traceparent`` header's 32-hex trace-id field
    (``00-<trace-id>-<span-id>-<flags>``) — so a request entering from
    any tracing fabric keeps its identity through submit → batch →
    dispatch → response. None means the batcher mints a fresh id."""
    rid = headers.get("X-Request-Id")
    if rid:
        return rid
    tp = headers.get("traceparent")
    if tp:
        parts = tp.strip().split("-")
        if len(parts) >= 3 and len(parts[1]) == 32:
            return parts[1]
    return None


def access_log_dest() -> str:
    """``LFM_ACCESS_LOG``: unset/``0`` = off (default), ``1``/
    ``stdout`` = one JSON line per request to stdout, anything else =
    a file path appended to (line-buffered)."""
    return os.environ.get("LFM_ACCESS_LOG", "").strip()


_ACCESS_LOCK = threading.Lock()
_ACCESS_FH = None
_ACCESS_PATH = None


def access_log(record: dict) -> None:
    """Emit one structured access-log line (strict JSON). Knob-gated,
    default OFF; the write happens under a lock so concurrent client
    threads can never tear a line. Never raises — logging must not be
    able to fail a request that already succeeded."""
    global _ACCESS_FH, _ACCESS_PATH
    dest = access_log_dest()
    if not dest or dest == "0":
        return
    try:
        line = json.dumps(record, default=str)
        with _ACCESS_LOCK:
            if dest in ("1", "stdout"):
                print(line, flush=True)
                return
            if _ACCESS_FH is None or _ACCESS_PATH != dest:
                if _ACCESS_FH is not None:
                    _ACCESS_FH.close()
                _ACCESS_FH = open(dest, "a", buffering=1)
                _ACCESS_PATH = dest
            _ACCESS_FH.write(line + "\n")
    except OSError:
        pass


def _access_record(universe, month, status, request_id=None,
                   resp=None, error=None) -> dict:
    """The one access-line shape (both the HTTP front door and the
    demo driver emit it): request identity, routing, outcome, and the
    per-request phase breakdown when the request completed."""
    rec = {
        "ts": round(time.time(), 6),
        "request_id": request_id,
        "universe": universe,
        "month": month,
        "status": status,
    }
    if resp is not None:
        rec.update(request_id=resp.request_id,
                   generation=resp.generation,
                   bucket=(resp.phases or {}).get("width"),
                   latency_ms=resp.latency_ms,
                   n_scores=int(resp.scores.size),
                   **(resp.phases or {}))
    if error is not None:
        rec["error"] = f"{type(error).__name__}: {error}"
    return rec


def build_universes(n: int, train_epochs: int, echo: bool = False,
                    only=None):
    """N toy universes with DISTINCT geometries (cross-section width
    and lookback window), each a fitted/initialized Trainer — the
    mixed-shape traffic the bucket ladder exists for. ``only`` (a set
    of names) restricts construction to those universes — the partial-
    restore path retrains just the ones whose snapshots failed."""
    from lfm_quant_tpu.config import (DataConfig, ModelConfig, OptimConfig,
                                      RunConfig)
    from lfm_quant_tpu.data import synthetic_panel
    from lfm_quant_tpu.data.panel import PanelSplits
    from lfm_quant_tpu.train.loop import Trainer

    out = {}
    for k in range(n):
        if only is not None and f"u{k}" not in only:
            continue
        n_firms = 60 + 60 * k           # distinct universe sizes
        window = 6 + 3 * k              # distinct lookbacks
        cfg = RunConfig(
            name=f"serve_u{k}",
            data=DataConfig(n_firms=n_firms, n_months=200, n_features=5,
                            window=window, dates_per_batch=4,
                            firms_per_date=32),
            model=ModelConfig(kind="mlp", kwargs={"hidden": (16,)}),
            optim=OptimConfig(lr=1e-3, epochs=max(1, train_epochs),
                              warmup_steps=5, loss="mse"),
            seed=k,
        )
        panel = synthetic_panel(n_firms=n_firms, n_months=200,
                                n_features=5, seed=100 + k)
        splits = PanelSplits.by_date(panel, 198001, 198201)
        trainer = Trainer(cfg, splits, run_dir=None, echo=echo)
        if train_epochs > 0:
            trainer.fit()
        else:
            trainer.state = trainer.init_state()
        out[f"u{k}"] = (trainer, splits)
    return out


def drive_load(service, n_requests: int, n_threads: int,
               refresh_mid: bool = False):
    """Closed-loop mixed-shape load: each client thread round-robins
    universes and months. Returns (wall_s, errors, refreshed_gen)."""
    import numpy as np

    universes = (service.universes() if hasattr(service, "universes")
                 else service.zoo.universes())
    months = {u: service.serveable_months(u) for u in universes}
    done = [0]
    errors = []
    lock = threading.Lock()

    def client(cid: int):
        rng = np.random.default_rng(cid)
        while True:
            with lock:
                if done[0] >= n_requests:
                    return
                done[0] += 1
            u = universes[int(rng.integers(len(universes)))]
            m = months[u][int(rng.integers(len(months[u])))]
            try:
                r = service.score(u, m)
                access_log(_access_record(u, m, 200, resp=r))
            except Exception as e:  # noqa: BLE001 — tallied, not fatal
                errors.append(f"{u}/{m}: {type(e).__name__}: {e}")
                access_log(_access_record(u, m, _status_of(e), error=e))

    t0 = time.perf_counter()
    refreshed = None
    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(n_threads)]
    for t in threads:
        t.start()
    if refresh_mid and universes:
        # One mid-stream refresh of the first universe: same split
        # boundaries re-posed as "new month arrived" at toy scale (a
        # real deployment advances them) — the point is the warm
        # retrain + atomic swap under live traffic.
        u = universes[0]
        splits = service.zoo.current(u).trainer.splits
        refreshed = service.refresh(u, splits, epochs=1).generation
    for t in threads:
        t.join()
    return time.perf_counter() - t0, errors, refreshed


def _status_of(exc) -> int:
    from lfm_quant_tpu.serve.errors import http_status

    return http_status(exc)


def make_http_server(service, port: int):
    """Build (but do not run) the stdlib JSON front door — split from
    :func:`run_http` so tests can bind port 0 and drive real HTTP
    round trips (the header-propagation contract needs actual headers
    on the wire). Returns the ``ThreadingHTTPServer``."""
    from concurrent.futures import TimeoutError as FutureTimeout
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from urllib.parse import parse_qs, urlparse

    from lfm_quant_tpu.serve.batcher import clean_request_id
    from lfm_quant_tpu.serve.errors import ServeError, http_status
    from lfm_quant_tpu.utils import telemetry

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload, retry_after_s=None,
                  request_id=None):
            body = json.dumps(payload, default=str).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if request_id:
                # Echo the trace identity (propagated or minted) so
                # the caller — and every proxy between — can correlate
                # this response with the span/access-log/exemplar
                # records carrying the same id (DESIGN.md §21).
                self.send_header("X-Request-Id", str(request_id))
            if retry_after_s is not None:
                # HTTP Retry-After is whole seconds; never advertise 0
                # (clients would hot-loop the open circuit).
                self.send_header("Retry-After",
                                 str(max(1, int(retry_after_s + 0.999))))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str, content_type: str):
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            url = urlparse(self.path)
            try:
                if url.path in ("/healthz", "/stats"):
                    # ONE snapshot call per request (DESIGN.md §19):
                    # both views derive from the same locked reads and
                    # carry the same scrape ts — no torn state across a
                    # concurrent refresh/breaker transition, no
                    # per-field re-derivation.
                    snap = service.snapshot()
                    if url.path == "/stats":
                        return self._send(200, snap["stats"])
                    # REAL readiness (DESIGN.md §18): 503 + reason when
                    # the batcher is dead or the circuit is open — a
                    # load balancer must stop routing here, which the
                    # old constant {"ok": true} prevented. SLO-burn and
                    # score-drift DETAIL rides along (§19) without
                    # flipping ok.
                    h = snap["health"]
                    return self._send(200 if h.get("ok") else 503, h,
                                      retry_after_s=h.get("retry_after_s"))
                if url.path == "/metrics":
                    # Prometheus text exposition (utils/metrics.py §19):
                    # live histograms/rates/gauges plus the absorbed
                    # telemetry counters. text/plain; version=0.0.4 is
                    # the format's registered content type.
                    return self._send_text(
                        200, service.metrics_text(),
                        "text/plain; version=0.0.4; charset=utf-8")
                if url.path == "/fleet":
                    # Fleet topology / join report (DESIGN.md §22): a
                    # router answers with its member registry + fence;
                    # a member answers with the join report the
                    # coordinator's promotion gate verifies (identity,
                    # served generations, restore verdicts, counted
                    # restore compiles, serveable months).
                    if hasattr(service, "fleet_info"):
                        return self._send(200, service.fleet_info())
                    zsnap = service.zoo.snapshot()
                    return self._send(200, {
                        "build": telemetry.build_info(),
                        "universes": zsnap["universes"],
                        "months": {u: service.serveable_months(u)
                                   for u in zsnap["universes"]},
                        "restore": getattr(service, "last_restore",
                                           None),
                        "restore_compiles": getattr(
                            service, "last_restore_compiles", None),
                        "restore_panel_h2d": getattr(
                            service, "last_restore_panel_h2d", None),
                    })
                if url.path == "/sync":
                    # Fleet publish propagation (DESIGN.md §22): pull
                    # newer-than-served generations from the durable
                    # store (the journaled manifest generation is the
                    # fence), verified like a restore.
                    if getattr(service, "store", None) is None:
                        return self._send(
                            404, {"error": "no durable store attached "
                                           "(LFM_ZOO_PERSIST/--persist)"})
                    synced = service.sync_from_store()
                    return self._send(200, {
                        "synced": synced,
                        "universes": service.zoo.snapshot()["universes"],
                    })
                if url.path == "/score":
                    q = parse_qs(url.query)
                    u, m = q["universe"][0], int(q["month"][0])
                    # Sanitize ONCE at the front door: the error-path
                    # access-log line below must carry the same bounded
                    # id the span/exemplars will (a raw hostile header
                    # would land unsanitized in every degraded-request
                    # log line — exactly the ones incidents care about).
                    rid_in = clean_request_id(
                        extract_request_id(self.headers))
                    try:
                        r = service.score(u, m, request_id=rid_in)
                    except Exception as e:  # noqa: BLE001 — logged+reraised
                        access_log(_access_record(
                            u, m, _status_of(e), request_id=rid_in,
                            error=e))
                        raise
                    access_log(_access_record(u, m, 200, resp=r))
                    return self._send(200, {
                        "universe": r.universe, "month": r.month,
                        "generation": r.generation,
                        "request_id": r.request_id,
                        "latency_ms": r.latency_ms,
                        "phases": r.phases,
                        "firm_idx": r.firm_idx.tolist(),
                        "scores": r.scores.tolist()},
                        request_id=r.request_id)
                return self._send(404, {"error": "unknown path"})
            except KeyError as e:
                return self._send(404, {"error": str(e)})
            except FutureTimeout:
                return self._send(504, {"error": "scoring timed out"})
            except ServeError as e:
                # The failure-semantics table (module docstring): shed →
                # 429, open circuit / dead batcher → 503, expired
                # deadline → 504 — each with Retry-After when known.
                return self._send(http_status(e),
                                  {"error": f"{type(e).__name__}: {e}"},
                                  retry_after_s=e.retry_after_s)
            except Exception as e:  # noqa: BLE001 — a request must answer
                return self._send(500, {"error": f"{type(e).__name__}: {e}"})

    return ThreadingHTTPServer(("127.0.0.1", port), Handler)


def run_http(service, port: int):
    """Minimal stdlib JSON front door (demo-grade: one service, GET
    only; a production deployment would sit behind a real gateway)."""
    httpd = make_http_server(service, port)
    print(f"[serve] http on 127.0.0.1:{httpd.server_address[1]} "
          f"(/score?universe=u0&month=YYYYMM, /stats, /healthz)",
          flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--universes", type=int, default=2,
                    help="toy universes to register (distinct sizes + "
                         "lookbacks; default 2)")
    ap.add_argument("--train-epochs", type=int, default=1,
                    help="epochs to fit each universe before serving "
                         "(0 = fresh init — shape demo only)")
    ap.add_argument("--requests", type=int, default=100,
                    help="demo load: total requests to drive (default 100)")
    ap.add_argument("--threads", type=int, default=4,
                    help="demo load: concurrent client threads")
    ap.add_argument("--refresh", action="store_true",
                    help="perform one warm refresh + zoo swap mid-stream")
    ap.add_argument("--run-dir", default=None,
                    help="attach telemetry (spans/manifest/trace) here; "
                         "roll up with scripts/trace_report.py")
    ap.add_argument("--http", type=int, default=0, metavar="PORT",
                    help="after the demo load, serve a stdlib JSON "
                         "endpoint on this port until interrupted")
    ap.add_argument("--echo", action="store_true",
                    help="echo training metrics while fitting universes")
    ap.add_argument("--persist", default=None, metavar="DIR",
                    help="durable zoo store directory (DESIGN.md §20); "
                         "every published generation is journaled there "
                         "(falls back to LFM_ZOO_PERSIST)")
    ap.add_argument("--restore", action="store_true",
                    help="stand the service up from the durable store "
                         "instead of retraining: verified snapshots, "
                         "re-stamped drift references, warm ladder from "
                         "serialized executables (universes that fail "
                         "verification degrade to fresh retrain)")
    ap.add_argument("--fleet", type=int, default=None, metavar="N",
                    help="fleet mode (DESIGN.md §22; default LFM_FLEET, "
                         "unset/0 = single process): publish the "
                         "universes to the durable store, spawn N "
                         "subprocess members that each bootstrap from "
                         "it, and serve through the health-aware "
                         "failover router (requires --persist / "
                         "LFM_ZOO_PERSIST)")
    args = ap.parse_args(argv)
    if args.restore and not args.persist \
            and os.environ.get("LFM_ZOO_PERSIST", "") in ("", "0"):
        ap.error("--restore needs --persist DIR (or LFM_ZOO_PERSIST)")
    fleet_n = args.fleet
    if fleet_n is None:
        from lfm_quant_tpu.serve.fleet import fleet_members_default

        fleet_n = fleet_members_default()
    if fleet_n and not args.persist \
            and os.environ.get("LFM_ZOO_PERSIST", "") in ("", "0"):
        ap.error("--fleet needs --persist DIR (or LFM_ZOO_PERSIST) — "
                 "members bootstrap from the durable store")
    if fleet_n and args.refresh:
        ap.error("--refresh is not supported with --fleet yet: the "
                 "mid-stream refresh drives the parent service's zoo, "
                 "which stops serving once the members take over "
                 "(fleet publishes propagate via the store fence — "
                 "see DESIGN.md §22)")
    if fleet_n:
        # Reflect CLI-selected fleet mode in the env knob so the run
        # manifest's `fleet` probe records the mode that actually ran.
        os.environ["LFM_FLEET"] = str(fleet_n)

    from lfm_quant_tpu.serve import ScoringService
    from lfm_quant_tpu.utils import telemetry
    from lfm_quant_tpu.utils.profiling import REUSE_COUNTERS

    with telemetry.run_scope(args.run_dir, extra={"entry": "serve"}):
        service = ScoringService(persist_dir=args.persist)
        restored = []
        if args.restore:
            t0 = time.perf_counter()
            restored = service.restore()
            wall = time.perf_counter() - t0
            for info in restored:
                print(f"[serve] restored {info['universe']}: gen "
                      f"{info['generation']}, execs loaded "
                      f"{info['execs_loaded']} / recompiled "
                      f"{info['execs_recompiled']}, probe {info['probe']}",
                      flush=True)
            print(f"[serve] restore: {len(restored)} universe(s) in "
                  f"{wall:.2f}s", flush=True)
        # Cold start, or the degrade-to-fresh-retrain path: build every
        # requested universe the restore did NOT recover (a quarantined
        # snapshot must cost a retrain, never a missing universe).
        missing = ({f"u{k}" for k in range(args.universes)}
                   - {info["universe"] for info in restored})
        if missing:
            print(f"[serve] building {len(missing)} universe(s)…",
                  flush=True)
            for name, (trainer, _) in build_universes(
                    args.universes, args.train_epochs,
                    echo=args.echo, only=missing).items():
                entry = service.register(name, trainer)
                print(f"[serve] registered {name}: gen {entry.generation}, "
                      f"{len(entry.serveable_months())} serveable months, "
                      f"widths {entry.widths()}", flush=True)
        # Fleet mode (DESIGN.md §22): the registrations above committed
        # every generation to the durable store; the parent stops
        # serving, spawns N members that bootstrap from the store, and
        # becomes the health-aware failover router — the fleet front
        # door shares this same entry point, error taxonomy and
        # observability surface with the single-process deploy (which
        # is exactly the degenerate one-member fleet).
        front = service
        router = None
        fleet_procs = []
        fleet_tmpdir = None
        try:
            if fleet_n:
                import tempfile

                from lfm_quant_tpu.serve import fleet as fleet_mod

                store = service.store
                service.close()
                # Ready files + member logs live under the run dir
                # when there is one (the logs are diagnostic evidence
                # worth keeping beside the spans); else ONE tempdir,
                # removed in the finally below — repeated fleet runs
                # must not accumulate /tmp debris.
                if args.run_dir:
                    fleet_dir = os.path.join(args.run_dir, "fleet")
                    os.makedirs(fleet_dir, exist_ok=True)
                else:
                    fleet_dir = tempfile.mkdtemp(prefix="lfm_fleet_")
                    fleet_tmpdir = fleet_dir
                specs = []
                for k in range(fleet_n):
                    rf = os.path.join(fleet_dir, f"ready_m{k}.json")
                    # Track the proc the instant it exists: a later
                    # spawn/join/drive failure must still terminate
                    # every member in the finally below.
                    proc = fleet_mod.spawn_member(store.root,
                                                  ready_file=rf)
                    fleet_procs.append(proc)
                    specs.append((proc, rf))
                coord = fleet_mod.FleetCoordinator(store=store)
                for k, (proc, rf) in enumerate(specs):
                    info = fleet_mod.wait_member_ready(proc, rf)
                    rep = coord.add_member(fleet_mod.HttpMember(
                        f"m{k}", f"http://127.0.0.1:{info['port']}",
                        pid=info.get("pid")))
                    print(f"[serve] fleet member m{k}: pid {info['pid']} "
                          f"port {info['port']}, restore compiles "
                          f"{rep.get('restore_compiles')}", flush=True)
                router = fleet_mod.FleetRouter(coord)
                front = router
            snap = REUSE_COUNTERS.snapshot()
            wall, errors, refreshed = drive_load(
                front, args.requests, args.threads,
                refresh_mid=args.refresh)
            d = REUSE_COUNTERS.delta(snap)
            stats = front.stats()
            stats.update(
                wall_s=round(wall, 3),
                requests_per_sec=(round(args.requests / wall, 1)
                                  if wall else None),
                errors=len(errors),
                refreshed_generation=refreshed,
                # Steady-state compile accounting is a PER-PROCESS
                # measurement: in fleet mode all scoring runs in the
                # member subprocesses, so the router's counters would
                # print a vacuous 0/0 — report None (unmeasured here;
                # each member's scrape carries its own
                # lfm_jit_traces_total, and the join reports carry the
                # counted restore compiles).
                steady_jit_traces=(None if router is not None
                                   else d.get("jit_traces", 0)),
                steady_panel_h2d=(None if router is not None
                                  else d.get("panel_transfers", 0)),
            )
            print(json.dumps(stats, indent=2, default=str))
            for e in errors[:5]:
                print(f"[serve] ERROR {e}", file=sys.stderr)
            if args.run_dir:
                # Save the final /metrics scrape beside the spans so
                # scripts/trace_report.py can cross-check the live
                # metrics plane against the span-derived numbers (its
                # `metrics` section — same 1% contract as the stats()
                # twins). Fleet runs save the AGGREGATED scrape as
                # fleet.prom (router counters + member-labeled member
                # series) for the fleet section's cross-check.
                if router is not None:
                    with open(os.path.join(args.run_dir, "fleet.prom"),
                              "w") as fh:
                        fh.write(router.metrics_text())
                else:
                    with open(os.path.join(args.run_dir,
                                           "metrics.prom"), "w") as fh:
                        fh.write(service.metrics_text())
                print(f"[serve] telemetry in {args.run_dir} — "
                      f"python scripts/trace_report.py {args.run_dir}")
            if args.http:
                run_http(front, args.http)
        finally:
            if router is not None:
                router.close()
            if fleet_procs:
                for p in fleet_procs:
                    p.terminate()
                for p in fleet_procs:
                    try:
                        p.wait(timeout=10)
                    except Exception:  # noqa: BLE001 — last resort
                        p.kill()
            if fleet_tmpdir is not None:
                import shutil

                shutil.rmtree(fleet_tmpdir, ignore_errors=True)
            if router is None:
                service.close()
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
